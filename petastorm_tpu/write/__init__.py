"""petastorm_tpu.write — the Spark-free distributed write plane.

Four layers (docs/write.md):

1. **Fleet-ETL writer** (:mod:`.writer`): ``DistributedDatasetWriter``
   shards encode+write across any worker pool (thread/process/service
   fleet) with exactly-once tmp+rename publication and a commit
   manifest; ``pool=None`` is the degenerate local backend.
2. **Read-optimized layout** (:mod:`.layout`): row-groups sized to the
   readahead window, statistics-rich footers, and the post-write
   ``self_check`` that reads the output back through the pushdown /
   readahead planners.
3. **Compaction** (:mod:`.compact`): fold small-file ingest into
   readahead-friendly parts under an atomic manifest swap.
4. **Bounded-staleness append** (:mod:`.append`): monotonic manifest
   generations; followers pick up rows written seconds ago.
"""

from petastorm_tpu.write.append import AppendFollower, follow_dataset
from petastorm_tpu.write.compact import (
    CompactionDaemon, compact_dataset, plan_compaction,
)
from petastorm_tpu.write.layout import self_check, target_rowgroup_bytes
from petastorm_tpu.write.manifest import (
    ManifestError, gc_superseded, load as load_manifest, staleness_s,
)
from petastorm_tpu.write.writer import (
    DistributedDatasetWriter, WriteShardWorker, write_dataset_distributed,
)

__all__ = [
    'AppendFollower', 'CompactionDaemon', 'DistributedDatasetWriter',
    'ManifestError', 'WriteShardWorker', 'compact_dataset',
    'follow_dataset', 'gc_superseded', 'load_manifest', 'plan_compaction',
    'self_check', 'staleness_s', 'target_rowgroup_bytes',
    'write_dataset_distributed',
]
