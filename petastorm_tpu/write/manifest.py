"""The commit manifest: atomic, monotonic dataset generations.

A manifest-bearing dataset has ONE commit point: ``_manifest.json`` at
the dataset root, swapped atomically (tmp + rename) by every writer
commit, compaction fold and append. The manifest is the committed truth
about which part files ARE the dataset:

* **Exactly-once publication** — part files are written under invisible
  ``.tmp.`` names and renamed into place before the manifest swap; a
  writer SIGKILL mid-write leaves only tmp litter (purged by the next
  commit) and the previous manifest generation committed. Readers
  (:class:`petastorm_tpu.etl.dataset_metadata.ParquetDatasetInfo`
  consults the manifest before falling back to a directory walk) can
  never observe a torn dataset.
* **Determinism** — the manifest carries NO wall-clock state: the same
  rows committed through any retry/failover path serialize to
  byte-identical manifest JSON (the crash-safety contract the chaos
  drill asserts). Staleness questions are answered from the manifest
  *file's* mtime, not embedded timestamps.
* **Monotonic generations** — every commit bumps ``generation`` by one;
  an append adds file entries, a compaction replaces entries
  (``replaces`` names the folded files, left on disk for in-flight
  readers until :func:`gc_superseded`). Bounded-staleness followers
  (:mod:`petastorm_tpu.write.append`) diff generations to deliver only
  new rows.
"""

import json
import logging
import posixpath
import time

from petastorm_tpu import faults
from petastorm_tpu.telemetry import get_registry, metrics_disabled

logger = logging.getLogger(__name__)

MANIFEST_NAME = '_manifest.json'
MANIFEST_VERSION = 1

#: committed-generation gauge (docs/telemetry.md): the latest generation
#: this process published (writer commits, compaction folds)
MANIFEST_GENERATION = 'petastorm_tpu_write_manifest_generation'
WRITE_COMMITS = 'petastorm_tpu_write_commits_total'

#: invisible-name prefix of in-flight part files and manifest swaps;
#: leading '.' keeps them out of every discovery walk
TMP_PREFIX = '.tmp.'

#: tmp litter older than this is presumed orphaned by a dead writer and
#: purged at the next commit (the decoded cache's age rule)
_TMP_PURGE_AGE_S = 3600.0


class ManifestError(RuntimeError):
    """A manifest that exists but cannot be trusted (unparseable,
    wrong version, non-monotonic swap attempt)."""


def manifest_path(root_path):
    return posixpath.join(root_path, MANIFEST_NAME)


def file_entry(path, rows, row_groups, nbytes, source='append',
               replaces=()):
    """One committed part file. ``path`` is dataset-root-relative."""
    return {'path': path, 'rows': int(rows), 'row_groups': int(row_groups),
            'bytes': int(nbytes), 'source': source,
            'replaces': sorted(replaces)}


def build_manifest(files, generation=0, sort_key=None):
    """A manifest dict, entries sorted by path (deterministic bytes)."""
    return {
        'version': MANIFEST_VERSION,
        'generation': int(generation),
        'sort_key': sort_key,
        'files': sorted(files, key=lambda e: e['path']),
    }


def dumps(manifest):
    """Canonical manifest bytes: sorted keys, fixed separators — the
    byte-identical-across-retries contract."""
    return json.dumps(manifest, sort_keys=True,
                      separators=(',', ':')).encode('utf-8')


def load(fs, root_path):
    """The committed manifest at ``root_path``, or None when the dataset
    carries none (plain parquet store)."""
    path = manifest_path(root_path)
    try:
        if not fs.exists(path):
            return None
        with fs.open(path, 'rb') as f:
            raw = f.read()
    except (OSError, ValueError):
        return None
    try:
        manifest = json.loads(raw.decode('utf-8'))
    except (ValueError, UnicodeDecodeError) as e:
        raise ManifestError('Unparseable manifest at %r: %s' % (path, e))
    if manifest.get('version') != MANIFEST_VERSION:
        raise ManifestError('Unsupported manifest version %r at %r'
                            % (manifest.get('version'), path))
    return manifest


def staleness_s(fs, root_path):
    """Age in seconds of the committed manifest (None without one) —
    the reader-side ``max_staleness_s`` evidence. Filesystem mtime, not
    embedded time: the manifest bytes stay deterministic."""
    path = manifest_path(root_path)
    try:
        info = fs.info(path)
    except (OSError, FileNotFoundError, ValueError):
        return None
    mtime = info.get('mtime')
    if mtime is None:
        return None
    if hasattr(mtime, 'timestamp'):
        mtime = mtime.timestamp()
    return max(0.0, time.time() - float(mtime))


def publish(fs, root_path, manifest):
    """Atomically swap the committed manifest (tmp + rename) after
    proving the swap monotonic against the generation on storage."""
    current = load(fs, root_path)
    if current is not None and manifest['generation'] <= current['generation']:
        raise ManifestError(
            'Manifest swap is not monotonic: committed generation %d, '
            'attempted %d' % (current['generation'], manifest['generation']))
    path = manifest_path(root_path)
    tmp = posixpath.join(root_path, TMP_PREFIX + MANIFEST_NAME)
    if faults.ARMED:
        faults.fault_hit('io.write', key='%s#manifest' % path)
    with fs.open(tmp, 'wb') as f:
        f.write(dumps(manifest))
    fs.mv(tmp, path)
    if not metrics_disabled():
        registry = get_registry()
        registry.counter(WRITE_COMMITS).inc()
        registry.gauge(MANIFEST_GENERATION).set(manifest['generation'])
    logger.debug('write: committed manifest generation %d (%d files)',
                 manifest['generation'], len(manifest['files']))
    return manifest


def committed_paths(manifest, root_path):
    """Absolute paths of the manifest's committed part files."""
    return [posixpath.join(root_path, e['path']) for e in manifest['files']]


def row_group_counts(manifest):
    """``{relative path: row-group count}`` for the metadata footer —
    the commit already knows every count, so the footer write pays zero
    re-scans."""
    return {e['path']: e['row_groups'] for e in manifest['files']}


def purge_stale_tmp(fs, root_path, max_age_s=_TMP_PURGE_AGE_S):
    """Remove ``.tmp.`` litter orphaned by dead writers. Age-gated so a
    concurrent live writer's in-flight tmp is never yanked; purged count
    returned (best-effort: a racing delete is not an error)."""
    purged = 0
    try:
        listing = fs.ls(root_path, detail=True)
    except (OSError, FileNotFoundError, ValueError):
        return 0
    now = time.time()
    for entry in listing:
        name = posixpath.basename(entry.get('name', ''))
        if not name.startswith(TMP_PREFIX):
            continue
        mtime = entry.get('mtime')
        if hasattr(mtime, 'timestamp'):
            mtime = mtime.timestamp()
        if mtime is not None and now - float(mtime) < max_age_s:
            continue
        try:
            fs.rm(entry['name'])
            purged += 1
        except (OSError, FileNotFoundError, ValueError):
            pass
    if purged:
        logger.info('write: purged %d stale tmp file(s) under %s',
                    purged, root_path)
    return purged


def gc_superseded(fs, root_path, grace_s=0.0):
    """Delete data files on disk that the committed manifest no longer
    references (compaction leftovers), once they are at least
    ``grace_s`` seconds older than the manifest — in-flight readers
    that opened the previous generation keep their files until the
    grace window passes. Returns the removed paths."""
    manifest = load(fs, root_path)
    if manifest is None:
        return []
    committed = {e['path'] for e in manifest['files']}
    manifest_age = staleness_s(fs, root_path)
    removed = []
    try:
        listing = fs.find(root_path, detail=True)
    except TypeError:
        listing = {p: fs.info(p) for p in fs.find(root_path)}
    for path, entry in sorted(listing.items()):
        rel = posixpath.relpath(path, root_path.rstrip('/'))
        segments = rel.split('/')
        if any(seg.startswith(('.', '_')) for seg in segments):
            continue
        if rel in committed:
            continue
        if grace_s > 0:
            mtime = entry.get('mtime')
            if hasattr(mtime, 'timestamp'):
                mtime = mtime.timestamp()
            age_past_swap = (None if mtime is None or manifest_age is None
                             else (time.time() - float(mtime)) - manifest_age)
            if age_past_swap is None or age_past_swap < grace_s:
                continue
        try:
            fs.rm(path)
            removed.append(rel)
        except (OSError, FileNotFoundError, ValueError):
            pass
    if removed:
        logger.info('write: garbage-collected %d superseded file(s) '
                    'under %s', len(removed), root_path)
    return removed
