"""The commit manifest: atomic, monotonic dataset generations.

A manifest-bearing dataset has ONE commit point: ``_manifest.json`` at
the dataset root, swapped atomically (tmp + rename) by every writer
commit, compaction fold and append. The manifest is the committed truth
about which part files ARE the dataset:

* **Exactly-once publication** — part files are written under invisible
  ``.tmp.`` names and renamed into place before the manifest swap; a
  writer SIGKILL mid-write leaves only tmp litter (purged by the next
  commit) and the previous manifest generation committed. Readers
  (:class:`petastorm_tpu.etl.dataset_metadata.ParquetDatasetInfo`
  consults the manifest before falling back to a directory walk) can
  never observe a torn dataset.
* **Determinism** — the manifest carries NO wall-clock state: the same
  rows committed through any retry/failover path serialize to
  byte-identical manifest JSON (the crash-safety contract the chaos
  drill asserts). Staleness questions are answered from the manifest
  *file's* mtime, not embedded timestamps.
* **Monotonic generations** — every commit bumps ``generation`` by one;
  an append adds file entries, a compaction replaces entries
  (``replaces`` names the folded files, left on disk for in-flight
  readers until :func:`gc_superseded`). Bounded-staleness followers
  (:mod:`petastorm_tpu.write.append`) diff generations to deliver only
  new rows.
* **Serialized commits** — every load→swap critical section holds the
  ``_manifest.lock`` lease (:class:`CommitLock`), so concurrent
  committers (append writer vs. compaction daemon) rebase onto each
  other instead of the last rename silently dropping the loser's files.
"""

import json
import logging
import posixpath
import time

from petastorm_tpu import faults
from petastorm_tpu.telemetry import get_registry, metrics_disabled

logger = logging.getLogger(__name__)

MANIFEST_NAME = '_manifest.json'
MANIFEST_VERSION = 1

#: committed-generation gauge (docs/telemetry.md): the latest generation
#: this process published (writer commits, compaction folds)
MANIFEST_GENERATION = 'petastorm_tpu_write_manifest_generation'
WRITE_COMMITS = 'petastorm_tpu_write_commits_total'

#: invisible-name prefix of in-flight part files and manifest swaps;
#: leading '.' keeps them out of every discovery walk
TMP_PREFIX = '.tmp.'

#: tmp litter older than this is presumed orphaned by a dead writer and
#: purged at the next commit (the decoded cache's age rule)
_TMP_PURGE_AGE_S = 3600.0


class ManifestError(RuntimeError):
    """A manifest that exists but cannot be trusted (unparseable,
    wrong version, non-monotonic swap attempt)."""


def manifest_path(root_path):
    return posixpath.join(root_path, MANIFEST_NAME)


def file_entry(path, rows, row_groups, nbytes, source='append',
               replaces=()):
    """One committed part file. ``path`` is dataset-root-relative."""
    return {'path': path, 'rows': int(rows), 'row_groups': int(row_groups),
            'bytes': int(nbytes), 'source': source,
            'replaces': sorted(replaces)}


def build_manifest(files, generation=0, sort_key=None):
    """A manifest dict, entries sorted by path (deterministic bytes)."""
    return {
        'version': MANIFEST_VERSION,
        'generation': int(generation),
        'sort_key': sort_key,
        'files': sorted(files, key=lambda e: e['path']),
    }


def dumps(manifest):
    """Canonical manifest bytes: sorted keys, fixed separators — the
    byte-identical-across-retries contract."""
    return json.dumps(manifest, sort_keys=True,
                      separators=(',', ':')).encode('utf-8')


def load(fs, root_path):
    """The committed manifest at ``root_path``, or None when the dataset
    carries none (plain parquet store). Only a *missing* manifest maps
    to None — a transient IO error propagates, so callers never silently
    degrade to the torn directory-walk view (or restart at generation 1)
    just because storage hiccuped."""
    path = manifest_path(root_path)
    try:
        if not fs.exists(path):
            return None
        with fs.open(path, 'rb') as f:
            raw = f.read()
    except FileNotFoundError:
        return None
    try:
        manifest = json.loads(raw.decode('utf-8'))
    except (ValueError, UnicodeDecodeError) as e:
        raise ManifestError('Unparseable manifest at %r: %s' % (path, e))
    if manifest.get('version') != MANIFEST_VERSION:
        raise ManifestError('Unsupported manifest version %r at %r'
                            % (manifest.get('version'), path))
    return manifest


def staleness_s(fs, root_path):
    """Age in seconds of the committed manifest (None without one) —
    the reader-side ``max_staleness_s`` evidence. Filesystem mtime, not
    embedded time: the manifest bytes stay deterministic."""
    path = manifest_path(root_path)
    try:
        info = fs.info(path)
    except (OSError, FileNotFoundError, ValueError):
        return None
    mtime = info.get('mtime')
    if mtime is None:
        return None
    if hasattr(mtime, 'timestamp'):
        mtime = mtime.timestamp()
    return max(0.0, time.time() - float(mtime))


#: lease file serializing manifest commits; underscore prefix keeps it
#: out of every discovery walk (and the gc sweep)
LOCK_NAME = '_manifest.lock'
_LOCK_STALE_S = 60.0
_LOCK_TIMEOUT_S = 120.0
_LOCK_POLL_S = 0.05


class CommitLock:
    """Lease file serializing manifest commits under one dataset root.

    Without it, two concurrent committers (an append writer racing the
    compaction daemon, or two appenders) can both load generation G,
    both pass the monotonic check and both swap G+1 — the last rename
    wins and the loser's files silently leave the manifest, to be
    deleted by :func:`gc_superseded` (durable loss of acknowledged
    writes). :func:`publish` and the read-modify-write commit sections
    in the writer and compactor hold this lease across load→swap, so
    racers serialize and rebase instead.

    The lease is taken with exclusive create (``xb``); a lease older
    than ``stale_s`` is presumed orphaned by a dead committer and
    broken. Acquisition past ``timeout_s`` raises :class:`ManifestError`
    rather than waiting forever.
    """

    def __init__(self, fs, root_path, timeout_s=_LOCK_TIMEOUT_S,
                 stale_s=_LOCK_STALE_S):
        self._fs = fs
        self._path = posixpath.join(root_path, LOCK_NAME)
        self._timeout_s = timeout_s
        self._stale_s = stale_s
        self._held = False

    def _try_create(self):
        try:
            with self._fs.open(self._path, 'xb') as f:
                f.write(b'petastorm_tpu commit lease')
            return True
        except FileExistsError:
            return False
        except (ValueError, NotImplementedError):
            # no exclusive-create on this filesystem: degrade to
            # check-then-create (window shrinks to one fs call)
            if self._fs.exists(self._path):
                return False
            with self._fs.open(self._path, 'wb') as f:
                f.write(b'petastorm_tpu commit lease')
            return True

    def _break_if_stale(self):
        try:
            info = self._fs.info(self._path)
        except (OSError, ValueError):
            return
        mtime = info.get('mtime')
        if hasattr(mtime, 'timestamp'):
            mtime = mtime.timestamp()
        if mtime is None or time.time() - float(mtime) < self._stale_s:
            return
        logger.warning('write: breaking stale commit lease %s (older than '
                       '%.0fs)', self._path, self._stale_s)
        try:
            self._fs.rm(self._path)
        except (OSError, FileNotFoundError, ValueError):
            pass

    def acquire(self):
        deadline = time.monotonic() + self._timeout_s
        while True:
            if self._try_create():
                self._held = True
                return self
            self._break_if_stale()
            if time.monotonic() >= deadline:
                raise ManifestError(
                    'Commit lease %r held past the %.1fs timeout — another '
                    'committer is live (or died inside the stale window)'
                    % (self._path, self._timeout_s))
            time.sleep(_LOCK_POLL_S)

    def release(self):
        if not self._held:
            return
        self._held = False
        try:
            self._fs.rm(self._path)
        except (OSError, FileNotFoundError, ValueError):
            pass

    def __enter__(self):
        return self.acquire()

    def __exit__(self, exc_type, exc_val, exc_tb):
        self.release()


def commit_lock(fs, root_path, **kwargs):
    """The commit lease for ``root_path`` (see :class:`CommitLock`)."""
    return CommitLock(fs, root_path, **kwargs)


def publish(fs, root_path, manifest, locked=False, lock_timeout_s=None):
    """Atomically swap the committed manifest (tmp + rename) after
    proving the swap monotonic against the generation on storage.

    Load, check and swap run under the commit lease — pass
    ``locked=True`` only when the caller already holds it (the writer's
    and compactor's read-modify-write commit sections do, so their
    rebase and the swap are one critical section)."""
    if not locked:
        kwargs = ({} if lock_timeout_s is None
                  else {'timeout_s': lock_timeout_s})
        with CommitLock(fs, root_path, **kwargs):
            return publish(fs, root_path, manifest, locked=True)
    current = load(fs, root_path)
    if current is not None and manifest['generation'] <= current['generation']:
        raise ManifestError(
            'Manifest swap is not monotonic: committed generation %d, '
            'attempted %d' % (current['generation'], manifest['generation']))
    path = manifest_path(root_path)
    tmp = posixpath.join(root_path, TMP_PREFIX + MANIFEST_NAME)
    if faults.ARMED:
        faults.fault_hit('io.write', key='%s#manifest' % path)
    with fs.open(tmp, 'wb') as f:
        f.write(dumps(manifest))
    fs.mv(tmp, path)
    if not metrics_disabled():
        registry = get_registry()
        registry.counter(WRITE_COMMITS).inc()
        registry.gauge(MANIFEST_GENERATION).set(manifest['generation'])
    logger.debug('write: committed manifest generation %d (%d files)',
                 manifest['generation'], len(manifest['files']))
    return manifest


def committed_paths(manifest, root_path):
    """Absolute paths of the manifest's committed part files."""
    return [posixpath.join(root_path, e['path']) for e in manifest['files']]


def row_group_counts(manifest):
    """``{relative path: row-group count}`` for the metadata footer —
    the commit already knows every count, so the footer write pays zero
    re-scans."""
    return {e['path']: e['row_groups'] for e in manifest['files']}


def purge_stale_tmp(fs, root_path, max_age_s=_TMP_PURGE_AGE_S):
    """Remove ``.tmp.`` litter orphaned by dead writers. Age-gated so a
    concurrent live writer's in-flight tmp is never yanked; purged count
    returned (best-effort: a racing delete is not an error)."""
    purged = 0
    try:
        listing = fs.ls(root_path, detail=True)
    except (OSError, FileNotFoundError, ValueError):
        return 0
    now = time.time()
    for entry in listing:
        name = posixpath.basename(entry.get('name', ''))
        if not name.startswith(TMP_PREFIX):
            continue
        mtime = entry.get('mtime')
        if hasattr(mtime, 'timestamp'):
            mtime = mtime.timestamp()
        if mtime is not None and now - float(mtime) < max_age_s:
            continue
        try:
            fs.rm(entry['name'])
            purged += 1
        except (OSError, FileNotFoundError, ValueError):
            pass
    if purged:
        logger.info('write: purged %d stale tmp file(s) under %s',
                    purged, root_path)
    return purged


def gc_superseded(fs, root_path, grace_s=0.0):
    """Delete data files on disk that the committed manifest no longer
    references (compaction leftovers), once the grace window has passed.

    The window is measured from the **manifest swap** — the manifest
    file's mtime IS the moment the files became superseded — so nothing
    is deleted until the swap itself is at least ``grace_s`` old: a
    reader that resolved the previous generation seconds before the
    swap keeps every file it may hold, no matter how long ago those
    files were *written*. Each candidate must additionally be
    ``grace_s`` old itself, which protects parts an in-flight writer
    has renamed but not yet committed. Returns the removed paths."""
    committed_manifest = load(fs, root_path)
    if committed_manifest is None:
        return []
    committed = {e['path'] for e in committed_manifest['files']}
    if grace_s > 0:
        swap_age = staleness_s(fs, root_path)
        if swap_age is None or swap_age < grace_s:
            # the swap that superseded these files is younger than the
            # grace window: in-flight readers may still hold them
            return []
    removed = []
    try:
        listing = fs.find(root_path, detail=True)
    except TypeError:
        listing = {p: fs.info(p) for p in fs.find(root_path)}
    now = time.time()
    for path, entry in sorted(listing.items()):
        rel = posixpath.relpath(path, root_path.rstrip('/'))
        segments = rel.split('/')
        if any(seg.startswith(('.', '_')) for seg in segments):
            continue
        if rel in committed:
            continue
        if grace_s > 0:
            mtime = entry.get('mtime')
            if hasattr(mtime, 'timestamp'):
                mtime = mtime.timestamp()
            if mtime is None or now - float(mtime) < grace_s:
                continue
        try:
            fs.rm(path)
            removed.append(rel)
        except (OSError, FileNotFoundError, ValueError):
            pass
    if removed:
        logger.info('write: garbage-collected %d superseded file(s) '
                    'under %s', len(removed), root_path)
    return removed


def merge_footer_counts(fs, root_path, counts, previous):
    """Row-group counts for the ``_common_metadata`` restamp: the new
    generation's ``counts`` merged over the ``previous`` stamped map.

    A reader holding the previous generation's file list (or opening
    between the footer restamp and the manifest swap) resolves
    superseded files — dropping their counts would fail its
    ``load_row_groups`` with a missing-count error. Stale keys are
    pruned once their backing file leaves the disk (``gc_superseded``),
    keeping the map bounded."""
    merged = dict(previous or {})
    merged.update(counts)
    for rel in list(merged):
        if rel in counts:
            continue
        try:
            if not fs.exists(posixpath.join(root_path, rel)):
                del merged[rel]
        except (OSError, ValueError):
            pass
    return merged
