"""Compaction / re-shard: fold small-file ingest into readahead-friendly
part files, atomically.

Streaming ingest (the bounded-staleness append path) lands many small
part files — each a full footer read, a tiny coalesce window and a
request-per-file on real storage. :func:`compact_dataset` folds them:

* **Arrow-level fold** — source parts are read as arrow tables
  (``pq.read_table``), concatenated and rewritten with the layout
  target's row-group size. Cells stay codec-encoded bytes throughout, so
  Unischema fidelity is structural, not re-encoded; the footer schema
  JSON is untouched.
* **Atomic swap** — folded files are published tmp+rename like every
  write, then ONE manifest swap replaces the source entries with the
  folded ones. A reader that resolved the previous generation keeps
  reading the old files (left on disk until
  :func:`~petastorm_tpu.write.manifest.gc_superseded`); a reader that
  resolves after the swap sees only folded files. No interleaving —
  concurrent reads stay multiset-exact. The commit section holds the
  manifest lease, so a racing append commit rebases instead of being
  lost.
* **Standing service** — :class:`CompactionDaemon` rides the PR 13
  daemon pattern: a background thread re-plans on an interval and folds
  whenever at least ``PETASTORM_TPU_COMPACT_MIN_FILES`` parts undershoot
  the ``PETASTORM_TPU_COMPACT_TARGET_MB`` target.
"""

import logging
import posixpath
import threading

import pyarrow as pa
import pyarrow.parquet as pq

from petastorm_tpu import faults
from petastorm_tpu.fs import get_filesystem_and_path_or_paths, normalize_dir_url
from petastorm_tpu.telemetry import get_registry, knobs, metrics_disabled, span
from petastorm_tpu.write import layout, manifest
from petastorm_tpu.write.manifest import TMP_PREFIX

logger = logging.getLogger(__name__)

COMPACT_RUNS = 'petastorm_tpu_compact_runs_total'
COMPACT_FILES_FOLDED = 'petastorm_tpu_compact_files_folded_total'

_MB = 1024 * 1024


def target_file_bytes():
    """Compaction fold target: ``PETASTORM_TPU_COMPACT_TARGET_MB``
    (default 64 MB — a few readahead-window-sized row-groups per file,
    so footer count drops without single-file hotspotting)."""
    return knobs.get_int('PETASTORM_TPU_COMPACT_TARGET_MB', 64, floor=1) * _MB


def min_files():
    return knobs.get_int('PETASTORM_TPU_COMPACT_MIN_FILES', 4, floor=2)


def plan_compaction(committed, target_bytes=None, minimum=None):
    """Groups of manifest entries worth folding: runs of sub-target
    files (manifest path order — adjacent in sort-key order when the
    dataset declares one) packed greedily up to ``target_bytes`` per
    folded output. Returns ``[[entry, ...], ...]``; empty when the
    dataset is already readahead-friendly."""
    target = target_bytes or target_file_bytes()
    floor_count = minimum or min_files()
    small = [e for e in committed['files'] if e['bytes'] < target]
    if len(small) < floor_count:
        return []
    groups = []
    group = []
    group_bytes = 0
    for entry in small:
        if group and group_bytes + entry['bytes'] > target:
            groups.append(group)
            group, group_bytes = [], 0
        group.append(entry)
        group_bytes += entry['bytes']
    if len(group) >= 2:
        groups.append(group)
    return [g for g in groups if len(g) >= 2]


def _fold_group(fs, root_path, group, generation, group_id, rowgroup_bytes,
                sort_key=None):
    """Rewrite one group's rows into a single tmp part, rename it, and
    return its manifest entry (with ``replaces`` naming the sources)."""
    with span('compact'):
        tables = []
        for entry in group:
            with fs.open(posixpath.join(root_path, entry['path']), 'rb') as f:
                tables.append(pq.read_table(f))
        folded = pa.concat_tables(tables)
        sorting = None
        if sort_key is not None and sort_key in folded.schema.names:
            # fold preserves (and re-establishes, when appends interleaved
            # key ranges) the declared order, and restamps the
            # sorted-column footer metadata the writer promised
            folded = folded.sort_by(sort_key)
            sorting = [pq.SortingColumn(
                folded.schema.get_field_index(sort_key))]
        final_name = 'part-g%04d-c%05d-00000.parquet' % (generation, group_id)
        final_path = posixpath.join(root_path, final_name)
        tmp_path = posixpath.join(root_path, TMP_PREFIX + final_name)
        if faults.ARMED:
            faults.fault_hit('io.write', key='%s#part' % final_path)
        # row-group re-chunk: rows sized so each row-group lands near the
        # layout byte target (readahead-window aligned)
        bytes_per_row = max(1, folded.nbytes // max(1, folded.num_rows))
        rows_per_group = max(1, rowgroup_bytes // bytes_per_row)
        with fs.open(tmp_path, 'wb') as sink:
            pq.write_table(folded, sink, row_group_size=rows_per_group,
                           write_statistics=True, sorting_columns=sorting)
        if faults.ARMED:
            faults.fault_hit('io.write', key='%s#rename' % final_path)
        try:
            fs.mv(tmp_path, final_path)
        except FileExistsError:
            fs.rm(final_path)
            fs.mv(tmp_path, final_path)
    with fs.open(final_path, 'rb') as f:
        meta = pq.read_metadata(f)
    return manifest.file_entry(
        final_name, meta.num_rows, meta.num_row_groups,
        int(fs.info(final_path)['size']), source='compact',
        replaces=[e['path'] for e in group])


def compact_dataset(dataset_url, storage_options=None, target_bytes=None,
                    minimum=None, gc_grace_s=None):
    """One compaction pass. Returns the new committed manifest, or None
    when there was nothing to fold (or no manifest to fold under).

    The fold (the data rewrite) runs lock-free; the commit section —
    rebase onto the latest committed manifest, footer restamp, manifest
    swap — holds the commit lease, so an append commit that landed
    mid-fold keeps its files and a fold whose sources were concurrently
    replaced is dropped instead of resurrecting folded-away rows.

    Source files are NOT deleted here — they back any reader that
    resolved the previous generation. Pass ``gc_grace_s`` to also sweep
    superseded files once the swap outlives the grace window (a
    standing daemon's second pass does this)."""
    url = normalize_dir_url(dataset_url)
    fs, root_path = get_filesystem_and_path_or_paths(url, storage_options)
    committed = manifest.load(fs, root_path)
    if committed is None:
        return None
    groups = plan_compaction(committed, target_bytes, minimum)
    if not groups:
        return None
    generation = committed['generation'] + 1
    rowgroup_bytes = layout.target_rowgroup_bytes()
    folded_entries = []
    for group_id, group in enumerate(groups):
        folded_entries.append(_fold_group(
            fs, root_path, group, generation, group_id, rowgroup_bytes,
            sort_key=committed.get('sort_key')))
    with manifest.commit_lock(fs, root_path):
        latest = manifest.load(fs, root_path) or committed
        latest_paths = {e['path'] for e in latest['files']}
        surviving_folds = []
        for entry in folded_entries:
            if all(p in latest_paths for p in entry['replaces']):
                surviving_folds.append(entry)
                continue
            # a concurrent committer already replaced some source of
            # this fold: publishing it would resurrect folded-away rows
            try:
                fs.rm(posixpath.join(root_path, entry['path']))
            except (OSError, FileNotFoundError, ValueError):
                pass
        if not surviving_folds:
            return None
        generation = latest['generation'] + 1
        replaced = {p for e in surviving_folds for p in e['replaces']}
        survivors = [e for e in latest['files'] if e['path'] not in replaced]
        new_manifest = manifest.build_manifest(
            survivors + surviving_folds, generation=generation,
            sort_key=latest.get('sort_key'))
        _restamp_footer(url, fs, root_path, new_manifest, storage_options)
        published = manifest.publish(fs, root_path, new_manifest,
                                     locked=True)
    if not metrics_disabled():
        registry = get_registry()
        registry.counter(COMPACT_RUNS).inc()
        registry.counter(COMPACT_FILES_FOLDED).inc(len(replaced))
    logger.info('compact: folded %d file(s) into %d under %s '
                '(generation %d)', len(replaced), len(surviving_folds),
                root_path, generation)
    if gc_grace_s is not None:
        manifest.gc_superseded(fs, root_path, grace_s=gc_grace_s)
    return published


def _restamp_footer(url, fs, root_path, new_manifest, storage_options):
    """Refresh the row-group counts in ``_common_metadata`` for the new
    file set, merged over the previously stamped counts: a reader
    holding the pre-swap generation's file list still finds counts for
    the superseded files it resolves (they stay on disk until
    ``gc_superseded``). The schema entries are preserved as-is (fold is
    arrow-level: Unischema fidelity is untouched)."""
    import json

    from petastorm_tpu.etl.dataset_metadata import (
        LEGACY_ROW_GROUPS_PER_FILE_KEY, ROW_GROUPS_PER_FILE_KEY,
        ParquetDatasetInfo, update_dataset_metadata,
        _row_group_counts_from_common_metadata,
    )
    info = ParquetDatasetInfo(url, storage_options, validate=False)
    info.file_paths = sorted(manifest.committed_paths(new_manifest,
                                                      root_path))
    try:
        previous = _row_group_counts_from_common_metadata(info)
    except (OSError, ValueError):
        previous = None
    counts = manifest.merge_footer_counts(
        fs, root_path, manifest.row_group_counts(new_manifest), previous)
    counts_json = json.dumps(counts, sort_keys=True).encode('utf-8')
    entries = {ROW_GROUPS_PER_FILE_KEY: counts_json}
    if info.common_metadata is not None and info.common_metadata.metadata \
            and LEGACY_ROW_GROUPS_PER_FILE_KEY in info.common_metadata.metadata:
        entries[LEGACY_ROW_GROUPS_PER_FILE_KEY] = counts_json
    update_dataset_metadata(info, entries)


class CompactionDaemon:
    """Standing compaction job: re-plans on an interval, folds when the
    small-file count crosses the floor, gc-sweeps superseded files after
    a grace window. One daemon per dataset; idempotent start/stop.

    Mounts on the process's observability endpoint like Reader/JaxLoader
    and the service daemon do: with ``PETASTORM_TPU_OBS_PORT`` armed,
    ``/health`` carries a ``compaction-daemon`` component showing the
    last published generation, folds completed, files gc-swept and the
    latest self-check warnings — a standing job is only operable when
    its progress is visible without reading logs."""

    def __init__(self, dataset_url, interval_s=30.0, gc_grace_s=300.0,
                 storage_options=None):
        self._url = dataset_url
        self._interval_s = interval_s
        self._gc_grace_s = gc_grace_s
        self._storage_options = storage_options
        self._stop = threading.Event()
        self._thread = None
        self._mount = None
        self.runs = 0
        self.generation = None  #: last generation this daemon published
        self.gc_files = 0       #: superseded files swept by this daemon
        self.last_warnings = []  #: latest fold's self-check warnings

    def start(self):
        if self._thread is not None:
            return
        from petastorm_tpu.telemetry import obs_server
        self._stop.clear()
        self._mount = obs_server.mount('compaction-daemon',
                                       health=self.health)
        self._thread = threading.Thread(target=self._run,
                                        name='pt-compactd', daemon=True)
        self._thread.start()

    def health(self):
        """The ``/health`` component section."""
        return {
            'dataset_url': self._url,
            'interval_s': self._interval_s,
            'runs': self.runs,
            'generation': self.generation,
            'gc_files': self.gc_files,
            'self_check_warnings': list(self.last_warnings),
        }

    def _run(self):
        while not self._stop.wait(self._interval_s):
            try:
                published = compact_dataset(
                    self._url, storage_options=self._storage_options)
                if published is not None:
                    self.runs += 1
                    self.generation = published['generation']
                    self._self_check(published)
                # the gc sweep runs every pass (not only fold passes):
                # files superseded by an EARLIER fold age out of their
                # grace window during quiet intervals too
                fs, root_path = get_filesystem_and_path_or_paths(
                    normalize_dir_url(self._url), self._storage_options)
                removed = manifest.gc_superseded(fs, root_path,
                                                 grace_s=self._gc_grace_s)
                self.gc_files += len(removed)
            except Exception:  # noqa: BLE001 - a standing job never dies
                logger.exception('compaction daemon: pass failed for %s',
                                 self._url)

    def _self_check(self, published):
        """Refresh the health section's warnings from a post-fold layout
        self-check (footer-only analysis; knob-gated like the writer's)."""
        if knobs.is_disabled('PETASTORM_TPU_WRITE_SELF_CHECK'):
            return
        try:
            report = layout.self_check(
                self._url, sort_key=published.get('sort_key'),
                storage_options=self._storage_options)
            self.last_warnings = list(report.get('warnings') or [])
        except Exception:  # noqa: BLE001 - analysis must not kill the job
            logger.exception('compaction daemon: self-check failed for %s',
                             self._url)

    def stop(self):
        self._stop.set()
        thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=30)
        if self._mount is not None:
            self._mount.close()
            self._mount = None
