"""Unischema: a single schema definition usable across Parquet, numpy, JAX, TF and Torch.

This is a from-scratch, TPU-first re-design of the reference's schema system
(``petastorm/unischema.py``). The public surface intentionally matches the
reference — ``UnischemaField`` (:50), ``Unischema`` (:174), ``create_schema_view``
(:199), ``from_arrow_schema`` (:302), ``match_unischema_fields`` (:437),
``insert_explicit_nulls`` (:409) — so that users of the reference can migrate,
but the implementation differs where TPU ingest wants it to:

* The on-disk schema serialization is **versioned JSON**, not a Python pickle
  (the reference pickles the schema into the Parquet footer and calls that
  fragile itself, ``etl/dataset_metadata.py:201-202``). Legacy pickled schemas
  are still readable via :mod:`petastorm_tpu.etl.legacy`.
* Column-major first: a schema can render itself to an Arrow schema directly
  (``as_arrow_schema``); Spark is an optional add-on instead of a core
  dependency.
* ``make_namedtuple`` identity is cached per (schema-name, field-names) so the
  tf.data bridge sees a stable structure type across reader restarts
  (reference: ``_NamedtupleCache``, ``unischema.py:88``).
"""

import re
from collections import OrderedDict, namedtuple
from decimal import Decimal

import numpy as np
import pyarrow as pa

# ---------------------------------------------------------------------------
# numpy <-> arrow type mapping
# ---------------------------------------------------------------------------

_NUMPY_TO_ARROW = {
    np.bool_: pa.bool_(),
    np.int8: pa.int8(),
    np.uint8: pa.uint8(),
    np.int16: pa.int16(),
    np.uint16: pa.uint16(),
    np.int32: pa.int32(),
    np.uint32: pa.uint32(),
    np.int64: pa.int64(),
    np.uint64: pa.uint64(),
    np.float16: pa.float16(),
    np.float32: pa.float32(),
    np.float64: pa.float64(),
    np.str_: pa.string(),
    np.bytes_: pa.binary(),
    np.datetime64: pa.timestamp('ns'),
    Decimal: pa.string(),
}

# Arrow type (by id) -> numpy dtype. Mirrors the mapping table at
# ``petastorm/unischema.py:467-501`` but is arrow-first instead of spark-first.
_ARROW_TO_NUMPY = {
    pa.bool_(): np.bool_,
    pa.int8(): np.int8,
    pa.uint8(): np.uint8,
    pa.int16(): np.int16,
    pa.uint16(): np.uint16,
    pa.int32(): np.int32,
    pa.uint32(): np.uint32,
    pa.int64(): np.int64,
    pa.uint64(): np.uint64,
    pa.float16(): np.float16,
    pa.float32(): np.float32,
    pa.float64(): np.float64,
    pa.string(): np.str_,
    pa.large_string(): np.str_,
    pa.binary(): np.bytes_,
    pa.large_binary(): np.bytes_,
    pa.date32(): np.datetime64,
    pa.date64(): np.datetime64,
}


def arrow_to_numpy_dtype(arrow_type):
    """Map an arrow DataType to the numpy dtype class used in UnischemaField."""
    if arrow_type in _ARROW_TO_NUMPY:
        return _ARROW_TO_NUMPY[arrow_type]
    if pa.types.is_timestamp(arrow_type):
        return np.datetime64
    if pa.types.is_decimal(arrow_type):
        return Decimal
    if pa.types.is_dictionary(arrow_type):
        return arrow_to_numpy_dtype(arrow_type.value_type)
    raise ValueError('Cannot map arrow type %s to a numpy dtype' % arrow_type)


def numpy_to_arrow_type(numpy_dtype):
    """Map a numpy dtype (class or instance) to an arrow DataType."""
    key = np.dtype(numpy_dtype).type if numpy_dtype is not Decimal else Decimal
    if key in _NUMPY_TO_ARROW:
        return _NUMPY_TO_ARROW[key]
    raise ValueError('Cannot map numpy dtype %s to an arrow type' % numpy_dtype)


# ---------------------------------------------------------------------------
# UnischemaField
# ---------------------------------------------------------------------------

class UnischemaField:
    """A single typed field of a :class:`Unischema`.

    Attributes: ``name``, ``numpy_dtype``, ``shape`` (tuple; ``None`` entries
    are wildcard dims), ``codec`` (or None for plain-parquet columns),
    ``nullable``.

    Equality and hashing intentionally ignore the codec, matching the
    reference semantics (``petastorm/unischema.py:39-47``): two fields that
    produce the same in-memory value are "the same field" even if stored
    differently.
    """

    __slots__ = ('name', 'numpy_dtype', 'shape', 'codec', 'nullable')

    def __init__(self, name, numpy_dtype, shape=(), codec=None, nullable=False):
        if not isinstance(shape, tuple):
            raise ValueError('shape must be a tuple, got %r' % (shape,))
        object.__setattr__(self, 'name', name)
        object.__setattr__(self, 'numpy_dtype', numpy_dtype)
        object.__setattr__(self, 'shape', shape)
        object.__setattr__(self, 'codec', codec)
        object.__setattr__(self, 'nullable', nullable)

    def __setattr__(self, key, value):
        raise AttributeError('UnischemaField is immutable')

    def __reduce__(self):
        # Immutability breaks pickle's default slot restore (it uses setattr);
        # reconstruct through __init__ instead. Needed for the process pool.
        return (UnischemaField,
                (self.name, self.numpy_dtype, self.shape, self.codec,
                 self.nullable))

    def _key(self):
        return (self.name, self.numpy_dtype, self.shape, self.nullable)

    def __eq__(self, other):
        if not isinstance(other, UnischemaField):
            return NotImplemented
        return self._key() == other._key()

    def __ne__(self, other):
        result = self.__eq__(other)
        return result if result is NotImplemented else not result

    def __hash__(self):
        return hash(self._key())

    def __repr__(self):
        return ('UnischemaField(name=%r, numpy_dtype=%r, shape=%r, codec=%r, nullable=%r)'
                % (self.name, self.numpy_dtype, self.shape, self.codec, self.nullable))

    # -- structural helpers -------------------------------------------------

    @property
    def is_scalar(self):
        return len(self.shape) == 0

    def is_shape_compliant(self, value_shape):
        """True when ``value_shape`` matches ``self.shape`` with None wildcards.

        Reference: ``petastorm/codecs.py:274-294`` (``_is_compliant_shape``).
        """
        if len(value_shape) != len(self.shape):
            return False
        return all(want is None or want == got
                   for want, got in zip(self.shape, value_shape))

    def arrow_storage_type(self):
        """The arrow type this field occupies in a materialized Parquet file."""
        if self.codec is not None:
            return self.codec.arrow_type(self)
        if self.shape:
            return pa.list_(numpy_to_arrow_type(self.numpy_dtype))
        return numpy_to_arrow_type(self.numpy_dtype)

    # -- JSON (de)serialization for the dataset footer ----------------------

    def to_json_dict(self):
        from petastorm_tpu.codecs import codec_to_json
        if self.numpy_dtype is Decimal:
            dtype_name = 'decimal'
        else:
            dtype_name = np.dtype(self.numpy_dtype).name if self.numpy_dtype not in (np.str_, np.bytes_) \
                else ('str' if self.numpy_dtype is np.str_ else 'bytes')
        return {
            'name': self.name,
            'numpy_dtype': dtype_name,
            'shape': list(self.shape),
            'codec': codec_to_json(self.codec),
            'nullable': bool(self.nullable),
        }

    @classmethod
    def from_json_dict(cls, d):
        from petastorm_tpu.codecs import codec_from_json
        dtype_name = d['numpy_dtype']
        if dtype_name == 'decimal':
            numpy_dtype = Decimal
        elif dtype_name == 'str':
            numpy_dtype = np.str_
        elif dtype_name == 'bytes':
            numpy_dtype = np.bytes_
        else:
            numpy_dtype = np.dtype(dtype_name).type
        shape = tuple(None if s is None else int(s) for s in d['shape'])
        return cls(d['name'], numpy_dtype, shape, codec_from_json(d['codec']),
                   bool(d['nullable']))


# ---------------------------------------------------------------------------
# Stable namedtuple cache
# ---------------------------------------------------------------------------

class _NamedtupleRegistry:
    """Returns the *same* namedtuple class for the same (name, fields) pair.

    tf.data compares structure types by class identity; recreating a reader
    must therefore yield the identical namedtuple class
    (reference: ``petastorm/unischema.py:88-113``).
    """

    _instances = {}

    @classmethod
    def get(cls, type_name, field_names):
        key = (type_name, tuple(field_names))
        if key not in cls._instances:
            cls._instances[key] = namedtuple(type_name, field_names)
        return cls._instances[key]


# ---------------------------------------------------------------------------
# Unischema
# ---------------------------------------------------------------------------

class Unischema:
    """An ordered collection of :class:`UnischemaField`.

    Fields are exposed as attributes (``schema.field_name``) and via the
    ``fields`` OrderedDict. Field order is the declaration order
    (the reference's ``'preserve_input_order'`` mode, ``unischema.py:33-36`` —
    the legacy alphabetical mode is not carried forward).
    """

    def __init__(self, name, fields):
        self._name = name
        self._fields = OrderedDict((f.name, f) for f in fields)
        if len(self._fields) != len(fields):
            seen, dupes = set(), []
            for f in fields:
                if f.name in seen:
                    dupes.append(f.name)
                seen.add(f.name)
            raise ValueError('Duplicate field names in schema %r: %s' % (name, dupes))
        for f in fields:
            if hasattr(self, f.name):
                raise ValueError('Field name %r collides with a Unischema attribute' % f.name)
            setattr(self, f.name, f)

    # -- basic accessors ----------------------------------------------------

    @property
    def fields(self):
        return self._fields

    def __iter__(self):
        return iter(self._fields.values())

    def __len__(self):
        return len(self._fields)

    def __repr__(self):
        lines = ['%s(%s: [' % (type(self).__name__, self._name)]
        lines.extend('  %r,' % f for f in self)
        lines.append('])')
        return '\n'.join(lines)

    # -- views and matching -------------------------------------------------

    def create_schema_view(self, fields):
        """A new Unischema with a subset of fields.

        ``fields`` may contain :class:`UnischemaField` instances or regex
        pattern strings (reference: ``unischema.py:199-241``).
        """
        regexes = [f for f in fields if isinstance(f, str)]
        explicit = [f for f in fields if not isinstance(f, str)]
        for f in explicit:
            # Match by NAME only and substitute this schema's own field: a
            # passed instance may carry stale codec/shape info (e.g. obtained
            # from another view or an older schema version) — same rationale
            # as the reference (``unischema.py:221-236``).
            if f.name not in self._fields:
                raise ValueError('Field %r does not belong to schema %r'
                                 % (f.name, self._name))
        matched = set(f.name for f in match_unischema_fields(self, regexes)) if regexes else set()
        keep = matched | set(f.name for f in explicit)
        view_fields = [f for f in self if f.name in keep]
        return Unischema('%s_view' % self._name, view_fields)

    # -- rendering ----------------------------------------------------------

    def as_arrow_schema(self):
        """Arrow schema of the *materialized* (encoded) representation."""
        return pa.schema([pa.field(f.name, f.arrow_storage_type(), nullable=f.nullable)
                          for f in self])

    def as_spark_schema(self):
        """Spark StructType of the materialized representation (optional dep).

        Reference: ``petastorm/unischema.py:264-280``.
        """
        from pyspark.sql.types import StructField, StructType  # optional dependency
        from petastorm_tpu.codecs import arrow_to_spark_type
        struct_fields = []
        for f in self:
            spark_type = (f.codec.spark_dtype(f) if f.codec is not None
                          else arrow_to_spark_type(f.arrow_storage_type()))
            struct_fields.append(StructField(f.name, spark_type, f.nullable))
        return StructType(struct_fields)

    def make_namedtuple(self, **kwargs):
        """Build one row instance of this schema's namedtuple (None-filled)."""
        cls = self.namedtuple
        values = {k: kwargs.get(k) for k in self._fields}
        return cls(**values)

    def make_namedtuple_tf(self, **kwargs):
        cls = self.namedtuple
        return cls(**{k: kwargs[k] for k in self._fields})

    @property
    def namedtuple(self):
        """Stable namedtuple class for rows of this schema."""
        return _NamedtupleRegistry.get('%s_row' % self._name, list(self._fields))

    # -- serialization ------------------------------------------------------

    def to_json_dict(self):
        return {
            'version': 1,
            'name': self._name,
            'fields': [f.to_json_dict() for f in self],
        }

    @classmethod
    def from_json_dict(cls, d):
        if d.get('version') != 1:
            raise ValueError('Unsupported unischema JSON version: %r' % d.get('version'))
        return cls(d['name'], [UnischemaField.from_json_dict(fd) for fd in d['fields']])

    # -- inference from plain parquet ---------------------------------------

    @classmethod
    def from_arrow_schema(cls, arrow_schema, omit_unsupported_fields=True,
                          partition_columns=(), partition_types=None,
                          name='inferred'):
        """Infer a Unischema from a plain (non-petastorm) arrow schema.

        list<primitive> columns become 1-d wildcard arrays; nested
        list<list<...>> columns are skipped (with the same silent-skip
        semantics as ``petastorm/unischema.py:337-342``) unless
        ``omit_unsupported_fields`` is False, in which case they raise.
        """
        fields = []
        for arrow_field in arrow_schema:
            atype = arrow_field.type
            try:
                if pa.types.is_list(atype) or pa.types.is_large_list(atype):
                    value_type = atype.value_type
                    if pa.types.is_nested(value_type):
                        raise ValueError('Nested list field %r is not supported' % arrow_field.name)
                    fields.append(UnischemaField(arrow_field.name,
                                                 arrow_to_numpy_dtype(value_type),
                                                 (None,), None, arrow_field.nullable))
                else:
                    fields.append(UnischemaField(arrow_field.name,
                                                 arrow_to_numpy_dtype(atype),
                                                 (), None, arrow_field.nullable))
            except ValueError:
                if not omit_unsupported_fields:
                    raise
        for part in partition_columns:
            if part not in {f.name for f in fields}:
                dtype = (partition_types or {}).get(part, np.str_)
                fields.append(UnischemaField(part, dtype, (), None, False))
        return cls(name, fields)


def match_unischema_fields(schema, field_regexes):
    """Return fields of ``schema`` whose names fully match any of the regexes.

    Uses ``re.fullmatch`` semantics, like the reference's current behavior
    (``petastorm/unischema.py:437-465``; the legacy prefix-``match`` fallback
    and its warning are deliberately not reproduced).
    """
    if not field_regexes:
        return []
    compiled = [re.compile(p) for p in field_regexes]
    return [f for f in schema if any(c.fullmatch(f.name) for c in compiled)]


def dict_to_encoded_row(schema, row_dict):
    """Validate and codec-encode a row dict into parquet-storable values.

    The write-path equivalent of the reference's ``dict_to_spark_row``
    (``petastorm/unischema.py:359-406``) minus the Spark Row wrapper: returns a
    plain dict whose values are encoded (bytes for codec'd ndarrays, python
    scalars/lists for the rest) ready for an arrow table.
    """
    if not isinstance(row_dict, dict):
        raise TypeError('row must be a dict, got %s' % type(row_dict))
    unknown = set(row_dict.keys()) - set(schema.fields.keys())
    if unknown:
        raise ValueError('Attempt to write fields not in schema %s: %s'
                         % (schema._name, sorted(unknown)))
    encoded = {}
    for field in schema:
        value = row_dict.get(field.name)
        if value is None:
            if not field.nullable:
                raise ValueError('Field %r is not nullable but got None' % field.name)
            encoded[field.name] = None
        elif field.codec is not None:
            encoded[field.name] = field.codec.encode(field, value)
        else:
            encoded[field.name] = _encode_plain(field, value)
    return encoded


def _encode_plain(field, value):
    """Encode a codec-less field into an arrow-friendly python value.

    Only scalars and 1-d arrays (stored as list<primitive>) are supported
    without a codec; for >=2-d data the shape would be unrecoverable from the
    flat parquet list, so it must use an ndarray codec (the reference rejects
    all non-scalar codec-less fields, ``unischema.py:166``).
    """
    if field.shape:
        if len(field.shape) > 1:
            raise ValueError(
                'Field %r: %d-dimensional data cannot be stored without a '
                'codec (the flat parquet list loses the shape). Use '
                'NdarrayCodec/CompressedNdarrayCodec.' % (field.name, len(field.shape)))
        arr = np.asarray(value)
        if not field.is_shape_compliant(arr.shape):
            raise ValueError('Field %r: value shape %s does not match %s'
                             % (field.name, arr.shape, field.shape))
        return arr.ravel().tolist()
    if isinstance(value, np.generic):
        return value.item()
    return value


def insert_explicit_nulls(schema, row_dict):
    """Add explicit ``None`` entries for nullable fields missing from the dict.

    Raises for missing non-nullable fields. Reference:
    ``petastorm/unischema.py:409-434``.
    """
    for field in schema:
        if field.name in row_dict:
            continue
        if field.nullable:
            row_dict[field.name] = None
        else:
            raise ValueError('Field %r is not found in row and is not nullable' % field.name)
    return row_dict
