"""Exception types for petastorm_tpu.

Parity target: ``petastorm/errors.py:16`` (``NoDataAvailableError``) plus the
metadata error types from ``petastorm/etl/dataset_metadata.py:38-49``.
"""


class PetastormTpuError(Exception):
    """Base class for all framework-specific errors."""


class NoDataAvailableError(PetastormTpuError):
    """Raised when a reader ends up with zero work items.

    The most common cause is requesting more shards than there are row-groups
    in the dataset (reference: ``petastorm/reader.py:547-549``).
    """


class MetadataError(PetastormTpuError):
    """Dataset metadata is missing or malformed (``dataset_metadata.py:38``)."""


class MetadataGenerationError(MetadataError):
    """Metadata could not be generated (``dataset_metadata.py:45``)."""


class DecodeFieldError(PetastormTpuError):
    """A field value failed codec decode (``petastorm/utils.py:48``)."""
