"""Exception types for petastorm_tpu.

Parity target: ``petastorm/errors.py:16`` (``NoDataAvailableError``) plus the
metadata error types from ``petastorm/etl/dataset_metadata.py:38-49``.
"""


class PetastormTpuError(Exception):
    """Base class for all framework-specific errors."""


class NoDataAvailableError(PetastormTpuError):
    """Raised when a reader ends up with zero work items.

    The most common cause is requesting more shards than there are row-groups
    in the dataset (reference: ``petastorm/reader.py:547-549``).
    """


class MetadataError(PetastormTpuError):
    """Dataset metadata is missing or malformed (``dataset_metadata.py:38``)."""


class MetadataGenerationError(MetadataError):
    """Metadata could not be generated (``dataset_metadata.py:45``)."""


class DecodeFieldError(PetastormTpuError):
    """A field value failed codec decode (``petastorm/utils.py:48``)."""


class RowGroupPoisonedError(PetastormTpuError):
    """A service work item exhausted its retry budget and was quarantined
    (docs/service.md, "Failure semantics").

    Raised by :class:`~petastorm_tpu.service.service_pool.ServicePool`
    under ``poison_policy='raise'`` when the dispatcher quarantines an
    item whose failures carried no worker exception (a row-group that
    *kills* its workers rather than erroring). ``info`` is the
    dispatcher's quarantine descriptor (item id, attempts, reason)."""

    def __init__(self, message, info=None):
        super().__init__(message)
        self.info = info or {}


class ServiceWedgedError(PetastormTpuError):
    """A service consumer read made no progress for the configured
    deadline while work was outstanding (``PETASTORM_TPU_SERVICE_READ_
    DEADLINE_S``) — the diagnosable replacement for wedging forever.
    ``fleet`` carries the dispatcher's live fleet view at raise time
    (per-worker liveness, in-flight loads, queue state), so the failure
    mode is in the traceback, not lost with the hung process."""

    def __init__(self, message, fleet=None):
        super().__init__(message)
        self.fleet = fleet or {}
