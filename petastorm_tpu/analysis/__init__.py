"""pipecheck: AST-level contract & concurrency analyzer for the pipeline.

The last several PRs grew a concurrency-heavy surface — thread/process/
service pools, a ZMQ dispatcher, a slot-ring staging engine — whose
correctness rests on contracts no runtime test sees on every path:
canonical stage/metric/event names, ``PETASTORM_TPU_*`` knobs going
through one registry, threads that are daemonized or joined, no blocking
calls under a lock, pickle-safe payloads across process boundaries. This
package verifies those contracts statically, on every commit::

    python -m petastorm_tpu.analysis petastorm_tpu   # CI gate: exit 0
    make analyze                                     # same, via make

Library API::

    from petastorm_tpu.analysis import analyze_paths, analyze_source
    findings = analyze_paths(['petastorm_tpu'])      # [] on a clean tree

Six composable passes (eight rules) — see
:data:`~petastorm_tpu.analysis.core.RULE_DESCRIPTIONS` and the rule
reference table in docs/development.md. Passes are per-module
(``run(module)``), whole-program (``run_project(modules)`` — the pipesan
``buffer-escape``/``buffer-write`` ownership pass and the call-graph half
of ``lock-order``, both over :mod:`~petastorm_tpu.analysis.callgraph`),
or both. Findings are structured ``(path, line, rule, message)``; a
``# pipecheck: disable=<rule>`` comment on the offending line suppresses
a finding (use sparingly, with a justification comment), and a
``# pipesan: owns`` annotation records an intentional buffer-ownership
transfer. The canonical name sets and borrow-source registries live in
:mod:`~petastorm_tpu.analysis.contracts`, imported by the telemetry
subsystem and the runtime sanitizer (:mod:`petastorm_tpu.sanitizer`) at
runtime and by this checker statically — one source of truth, enforced
from both sides. The CLI's ``--baseline``/``--fail-on-new`` let a new
rule gate strictly on new code before its backlog hits zero.

Stdlib-only by design: the analyzer must run on a bare TPU image (no
flake8/mypy there), inside ``tests/test_analysis.py`` in tier-1, and in
CI, all from the same code.
"""

from petastorm_tpu.analysis import contracts  # noqa: F401

#: public API, resolved lazily (PEP 562): telemetry imports
#: ``analysis.contracts`` on every production import path (knob registry,
#: stage/event sets), and that must load ONLY the contracts data — never
#: drag the whole ast/tokenize analyzer into reader/worker/service
#: processes that will never run it.
_CORE_API = ('ALL_RULES', 'PASSES', 'RULE_DESCRIPTIONS', 'analyze_paths',
             'analyze_source', 'iter_python_files', 'run_passes')
_FINDINGS_API = ('Finding', 'SourceModule')

__all__ = ('contracts',) + _CORE_API + _FINDINGS_API


def __getattr__(name):
    if name in _CORE_API:
        from petastorm_tpu.analysis import core
        return getattr(core, name)
    if name in _FINDINGS_API:
        from petastorm_tpu.analysis import findings
        return getattr(findings, name)
    raise AttributeError('module %r has no attribute %r'
                         % (__name__, name))
