"""``buffer-escape`` / ``buffer-write`` pass: whole-program ownership
analysis of the zero-copy pipeline's borrowed buffer views.

Every recent perf win hands out BORROWED memory: ``np.frombuffer`` views
over ZMQ receive frames (``recv_multipart(copy=False)``), decoded-cache
columns aliasing an Arrow IPC mmap (``read_entry``), staging-arena slot
views recycled once the slot's next transfer retires, ``astype(...,
copy=False)`` aliases of a decoder's scratch buffer. One escaped view or
stray in-place write is *silent batch corruption* — the bit-exact
reproducibility failure mode, invisible until a loss curve diverges.

The pass taints values born from the borrow sources registered in
:mod:`~petastorm_tpu.analysis.contracts` (``BORROW_CALLS`` /
``BORROW_CALL_KWARGS`` / ``BORROW_ATTRS`` — the single source of truth
the runtime sanitizer guards dynamically) and walks each function
flow-sensitively, in statement order, flagging a tainted value that:

* is stored into object/class/module state (``self.x = view``,
  ``obj.attr = view``, a ``global``-declared name) — rule
  ``buffer-escape``;
* is put on a queue (``q.put(view)``) or appended onto object state —
  ``buffer-escape``;
* is captured by a nested ``def``/``lambda`` (the closure outlives the
  owner's frame) — ``buffer-escape``;
* is returned (past the owner's documented lifetime) — ``buffer-escape``;
* is written through (``view[...] = x``, ``view += x``,
  ``np.copyto(dst=view)``) — rule ``buffer-write``.

An explicit ``# pipesan: owns`` annotation on the line records the
transfer as intentional and silences the finding; on a ``return`` it
asserts the CALLER owns the result (the view's base chain carries the
memory), so taint does not propagate — a function whose callers genuinely
*borrow* belongs in ``BORROW_CALLS`` instead. Two precision exemptions
keep honest code clean: ``frombuffer`` over a *call expression* and
``astype(copy=False)`` on a *call expression* receiver build views over
fresh anonymous temporaries whose only reference becomes the array's
``.base`` — owned by construction. A registered borrow source returning
its borrowed views (``read_entry`` handing out mmap columns) is its
documented contract, not a finding.

Whole-program: a project function whose return value is tainted (and not
``owns``-annotated) becomes a borrow source for its (conservatively
resolved) callers, via the shared
:mod:`~petastorm_tpu.analysis.callgraph`, iterated to fixpoint. Analysis
is function-scoped and flow-sensitive but path-insensitive: branch bodies
are walked in source order and reassignment from an untainted value kills
taint — the right precision for this codebase's straight-line decode
paths. Taint distinguishes a direct *view* from a *container* that
absorbed one: writing a new key into a dict of borrowed columns is fine;
writing through the view itself is not.
"""

import ast

from petastorm_tpu.analysis.callgraph import (
    _MAX_FIXPOINT_ROUNDS, build_graph,
)
from petastorm_tpu.analysis.contracts import (
    BORROW_ATTRS, BORROW_CALL_KWARGS, BORROW_CALLS,
)
from petastorm_tpu.analysis.findings import call_name, dotted_text

ESCAPE_RULE = 'buffer-escape'
WRITE_RULE = 'buffer-write'
RULES = (ESCAPE_RULE, WRITE_RULE)

#: calls that move their argument onto a channel another scope drains
_QUEUE_CALLS = frozenset(['put', 'put_nowait'])

#: container mutators: a tainted argument taints a local receiver, and
#: escapes through an attribute receiver (object state)
_CONTAINER_CALLS = frozenset(['append', 'extend', 'add', 'appendleft'])

#: ndarray methods whose RESULT owns its memory even on a borrowed
#: receiver — deep copies, materializations, and reductions. A call to
#: one of these launders taint correctly (``view.copy()`` is the
#: canonical fix for an escape finding); plain ``astype`` copies by
#: default (the aliasing ``copy=False`` spelling is caught earlier as a
#: registered borrow kwarg).
_OWNING_METHODS = frozenset([
    'copy', 'tobytes', 'tolist', 'item', 'astype', 'dump', 'dumps',
    'sum', 'mean', 'std', 'var', 'prod', 'min', 'max', 'all', 'any',
    'argmin', 'argmax', 'nonzero', 'round', 'cumsum', 'cumprod',
])

_FIX_HINT = ("copy it (e.g. np.array(view)) or annotate an intentional "
             "transfer with '# pipesan: owns'")

#: ndarray attributes that are scalar metadata, not aliasing views —
#: ``view.nbytes`` / ``view.shape[0]`` cannot leak the buffer
_SCALAR_ATTRS = frozenset([
    'nbytes', 'shape', 'size', 'ndim', 'dtype', 'itemsize', 'strides',
    'flags',
])


def _kw_equals(call, kw, value):
    for k in call.keywords:
        if k.arg == kw and isinstance(k.value, ast.Constant) \
                and k.value.value is value:
            return True
    return False


class _FnScanner:
    """Flow-sensitive taint walk over one function body."""

    def __init__(self, info, graph, borrowed_fns):
        self.info = info
        self.module = info.module
        self.graph = graph
        self.borrowed_fns = borrowed_fns
        self.findings = []
        self.returns_borrowed = False
        self.tainted = {}            # local name -> source description
        self.globals_declared = set()

    # -- reporting -----------------------------------------------------------

    def _flag(self, rule, node, message):
        if self.module.owned(node):
            return
        finding = self.module.finding(rule, node, message)
        if finding is not None:
            self.findings.append(finding)

    # -- borrow sources ------------------------------------------------------

    def _borrow_call(self, call):
        """Source description when the call births a borrowed view."""
        name = call_name(call)
        if name in BORROW_CALLS:
            if name == 'frombuffer' and call.args \
                    and isinstance(call.args[0], ast.Call):
                return None  # fresh anonymous temporary: owned via .base
            return '%s()' % name
        if name in BORROW_CALL_KWARGS:
            kw, value = BORROW_CALL_KWARGS[name]
            if _kw_equals(call, kw, value):
                if name == 'astype' \
                        and isinstance(call.func, ast.Attribute) \
                        and isinstance(call.func.value, ast.Call):
                    return None  # fresh temporary receiver: owned
                return '%s(%s=%r)' % (name, kw, value)
        target = self.graph.resolve(self.info.modname,
                                    self.info.class_name, call)
        if target is not None and target in self.borrowed_fns:
            return '%s()' % target
        return None

    def _taint_source(self, expr):
        """``(source description, kind)`` when the expression's value may
        be a borrowed view (kind ``'view'``) or a container holding one
        (kind ``'container'``), else None."""
        if expr is None:
            return None
        if isinstance(expr, ast.Call):
            source = self._borrow_call(expr)
            if source is not None:
                # recv_multipart returns a caller-owned LIST of frames:
                # mutating the list is fine, the frames inside are the
                # borrowed views (container taint — indexing yields one)
                kind = ('container' if source.startswith('recv_multipart')
                        else 'view')
                return (source, kind)
            # view-producing method chain on a tainted receiver
            # (view.reshape(...), view[...].ravel()) — except the owning
            # methods (copies/reductions), whose results are fresh; a
            # call on an untainted callee launders taint by design
            if isinstance(expr.func, ast.Attribute):
                if expr.func.attr in _OWNING_METHODS:
                    return None
                return self._taint_source(expr.func.value)
            return None
        if isinstance(expr, ast.Name):
            return self.tainted.get(expr.id)
        if isinstance(expr, ast.Attribute):
            dotted = dotted_text(expr)
            if dotted in BORROW_ATTRS:
                # slot.buffers is a dict of slot arrays — a container
                return (dotted, 'container')
            if expr.attr in _SCALAR_ATTRS:
                return None  # scalar metadata cannot alias the buffer
            return self._taint_source(expr.value)
        if isinstance(expr, ast.Subscript):
            taint = self._taint_source(expr.value)
            if taint is None:
                return None
            # indexing a tainted container/view yields the borrowed view
            return (taint[0], 'view')
        if isinstance(expr, ast.Starred):
            return self._taint_source(expr.value)
        if isinstance(expr, (ast.Tuple, ast.List, ast.Set)):
            for elt in expr.elts:
                taint = self._taint_source(elt)
                if taint is not None:
                    return (taint[0], 'container')
            return None
        if isinstance(expr, ast.Dict):
            for value in expr.values:
                taint = self._taint_source(value)
                if taint is not None:
                    return (taint[0], 'container')
            return None
        if isinstance(expr, ast.IfExp):
            return self._taint_source(expr.body) \
                or self._taint_source(expr.orelse)
        if isinstance(expr, (ast.ListComp, ast.SetComp, ast.GeneratorExp)):
            return self._comprehension_source(expr, expr.elt)
        if isinstance(expr, ast.DictComp):
            return self._comprehension_source(expr, expr.value)
        if isinstance(expr, ast.NamedExpr):
            return self._taint_source(expr.value)
        return None

    def _comprehension_source(self, comp, elt):
        """A comprehension yielding borrowed views taints the container it
        builds (``[np.frombuffer(b) for b in frames]``). The element
        expression is evaluated with the comprehension variables bound to
        their iterated taint, so laundering still works —
        ``[v.copy() for v in views]`` and ``[len(v) for v in views]``
        build containers that OWN their elements."""
        saved = self.tainted
        self.tainted = dict(saved)
        try:
            for gen in comp.generators:
                taint = self._taint_source(gen.iter)
                if taint is not None:
                    for node in ast.walk(gen.target):
                        if isinstance(node, ast.Name):
                            self.tainted[node.id] = (taint[0], 'view')
            taint = self._taint_source(elt)
            return (taint[0], 'container') if taint is not None else None
        finally:
            self.tainted = saved

    # -- statement walk ------------------------------------------------------

    def scan(self):
        self._scan_body(self.info.node.body)

    def _scan_body(self, body):
        for stmt in body:
            self._scan_stmt(stmt)

    def _scan_stmt(self, stmt):
        if isinstance(stmt, ast.Global):
            self.globals_declared.update(stmt.names)
            return
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            self._check_closure(stmt)
            return
        if isinstance(stmt, ast.ClassDef):
            return
        if isinstance(stmt, ast.Return):
            self._check_closures_in(stmt.value)
            taint = self._taint_source(stmt.value)
            if taint is not None:
                if self.info.node.name in BORROW_CALLS:
                    return  # a registered borrow source returning its
                    # borrowed views IS its documented contract
                if self.module.owned(stmt):
                    return  # caller owns the result: no propagation
                self.returns_borrowed = True
                self._flag(ESCAPE_RULE, stmt,
                           'borrowed buffer view (from %s) returned past '
                           'its owning scope; %s' % (taint[0], _FIX_HINT))
            return
        if isinstance(stmt, ast.Assign):
            self._check_closures_in(stmt.value)
            targets = stmt.targets
            if (len(targets) == 1
                    and isinstance(targets[0], (ast.Tuple, ast.List))
                    and isinstance(stmt.value, (ast.Tuple, ast.List))
                    and len(targets[0].elts) == len(stmt.value.elts)
                    and not any(isinstance(e, ast.Starred)
                                for e in targets[0].elts)):
                # literal unpack matches elementwise: `size, owned =
                # view.nbytes, view.copy()` must not smear the tuple's
                # aggregated taint onto the untainted elements
                for t, v in zip(targets[0].elts, stmt.value.elts):
                    self._assign_target(t, self._taint_source(v), stmt)
                return
            taint = self._taint_source(stmt.value)
            for target in targets:
                self._assign_target(target, taint, stmt)
            return
        if isinstance(stmt, ast.AnnAssign):
            if stmt.value is not None:
                self._check_closures_in(stmt.value)
                self._assign_target(stmt.target,
                                    self._taint_source(stmt.value), stmt)
            return
        if isinstance(stmt, ast.AugAssign):
            self._aug_assign(stmt)
            return
        if isinstance(stmt, ast.Expr):
            self._check_closures_in(stmt.value)
            if isinstance(stmt.value, ast.Call):
                self._expr_call(stmt.value)
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                taint = self._taint_source(item.context_expr)
                if item.optional_vars is not None:
                    self._assign_target(item.optional_vars, taint, stmt)
            self._scan_body(stmt.body)
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._assign_target(stmt.target,
                                self._taint_source(stmt.iter), stmt)
            self._scan_body(stmt.body)
            self._scan_body(stmt.orelse)
            return
        if isinstance(stmt, (ast.If, ast.While)):
            self._scan_body(stmt.body)
            self._scan_body(stmt.orelse)
            return
        if isinstance(stmt, ast.Try):
            self._scan_body(stmt.body)
            for handler in stmt.handlers:
                self._scan_body(handler.body)
            self._scan_body(stmt.orelse)
            self._scan_body(stmt.finalbody)
            return
        if isinstance(stmt, ast.Delete):
            for target in stmt.targets:
                if isinstance(target, ast.Name):
                    self.tainted.pop(target.id, None)
            return

    def _assign_target(self, target, taint, stmt):
        if isinstance(target, ast.Name):
            if taint is None:
                self.tainted.pop(target.id, None)  # reassignment kills
                return
            if target.id in self.globals_declared:
                self._flag(ESCAPE_RULE, stmt,
                           'borrowed buffer view (from %s) stored into '
                           'module state (global %s) — escapes its owning '
                           'scope; %s' % (taint[0], target.id, _FIX_HINT))
                return
            self.tainted[target.id] = taint
            return
        if isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._assign_target(elt, taint, stmt)
            return
        if isinstance(target, ast.Starred):
            self._assign_target(target.value, taint, stmt)
            return
        if isinstance(target, ast.Attribute):
            if taint is not None:
                self._flag(ESCAPE_RULE, stmt,
                           'borrowed buffer view (from %s) stored into '
                           'object/class state (%s) — escapes its owning '
                           'scope; %s'
                           % (taint[0], dotted_text(target) or 'attribute',
                              _FIX_HINT))
            return
        if isinstance(target, ast.Subscript):
            base = self._taint_source(target.value)
            if base is not None and base[1] == 'view':
                # writing INTO a tainted dict/list of views is a normal
                # container store; writing through the view itself is the
                # silent-corruption hazard
                self._flag(WRITE_RULE, stmt,
                           'write through a borrowed buffer view (from '
                           '%s): in-place mutation corrupts the shared '
                           'backing memory; %s' % (base[0], _FIX_HINT))
                return
            if taint is None:
                return
            if base is None and isinstance(target.value, ast.Name):
                # a local container absorbing a borrowed view carries it
                self.tainted[target.value.id] = (taint[0], 'container')
            elif base is None and isinstance(target.value, ast.Attribute):
                self._flag(ESCAPE_RULE, stmt,
                           'borrowed buffer view (from %s) stored into '
                           'object state (%s[...]) — escapes its owning '
                           'scope; %s'
                           % (taint[0],
                              dotted_text(target.value) or 'attr',
                              _FIX_HINT))

    def _aug_assign(self, stmt):
        target = stmt.target
        source = None
        if isinstance(target, ast.Name):
            taint = self.tainted.get(target.id)
            if taint is not None and taint[1] == 'view':
                source = taint[0]
        elif isinstance(target, ast.Subscript):
            # d[k] += x and view[i] += x both mutate the element in place
            taint = self._taint_source(target.value)
            if taint is not None:
                source = taint[0]
        elif isinstance(target, ast.Attribute):
            dotted = dotted_text(target)
            if dotted in BORROW_ATTRS:
                source = dotted
        if source is not None:
            self._flag(WRITE_RULE, stmt,
                       'augmented write through a borrowed buffer view '
                       '(from %s): in-place mutation corrupts the shared '
                       'backing memory; %s' % (source, _FIX_HINT))

    def _expr_call(self, call):
        name = call_name(call)
        if name in _QUEUE_CALLS:
            for arg in call.args:
                taint = self._taint_source(arg)
                if taint is not None:
                    self._flag(ESCAPE_RULE, call,
                               'borrowed buffer view (from %s) put on a '
                               'queue — the consumer outlives the owning '
                               'scope; %s' % (taint[0], _FIX_HINT))
                    return
            return
        if name == 'copyto':
            dst = call.args[0] if call.args else None
            for k in call.keywords:
                if k.arg == 'dst':
                    dst = k.value
            taint = self._taint_source(dst)
            if taint is not None and taint[1] == 'view':
                self._flag(WRITE_RULE, call,
                           'np.copyto into a borrowed buffer view (from '
                           '%s): in-place mutation corrupts the shared '
                           'backing memory; %s' % (taint[0], _FIX_HINT))
            return
        if name in _CONTAINER_CALLS \
                and isinstance(call.func, ast.Attribute):
            for arg in call.args:
                taint = self._taint_source(arg)
                if taint is None:
                    continue
                receiver = call.func.value
                if isinstance(receiver, ast.Name):
                    self.tainted[receiver.id] = (taint[0], 'container')
                elif isinstance(receiver, ast.Attribute):
                    self._flag(ESCAPE_RULE, call,
                               'borrowed buffer view (from %s) appended '
                               'onto object state (%s) — escapes its '
                               'owning scope; %s'
                               % (taint[0],
                                  dotted_text(receiver) or 'attribute',
                                  _FIX_HINT))
                return

    # -- closures ------------------------------------------------------------

    def _check_closures_in(self, expr):
        if expr is None:
            return
        for node in ast.walk(expr):
            if isinstance(node, (ast.Lambda, ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                self._check_closure(node)

    def _check_closure(self, fn_node):
        captured = sorted(_free_names(fn_node) & set(self.tainted))
        for name in captured:
            self._flag(ESCAPE_RULE, fn_node,
                       'borrowed buffer view %r (from %s) captured by a '
                       'closure — the closure outlives the owning scope; '
                       '%s' % (name, self.tainted[name][0], _FIX_HINT))


def _free_names(fn_node):
    """Names a nested function reads from the enclosing scope."""
    args = fn_node.args
    bound = {a.arg for a in (args.posonlyargs + args.args
                             + args.kwonlyargs)}
    if args.vararg:
        bound.add(args.vararg.arg)
    if args.kwarg:
        bound.add(args.kwarg.arg)
    body = fn_node.body if isinstance(fn_node.body, list) else [fn_node.body]
    loaded, stored = set(), set()
    for stmt in body:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Name):
                if isinstance(node.ctx, ast.Load):
                    loaded.add(node.id)
                else:
                    stored.add(node.id)
    return loaded - bound - stored


def run_project(modules):
    """Whole-program driver: fixpoint the set of borrow-returning project
    functions over the call graph. The converged (no-change) round's
    findings ARE the result — the borrowed set was stable throughout it,
    so a separate emit pass would just recompute them."""
    graph = build_graph(modules)
    borrowed = set()
    findings = []
    for _ in range(_MAX_FIXPOINT_ROUNDS):
        changed = False
        findings = []
        for info in graph.functions.values():
            scanner = _FnScanner(info, graph, borrowed)
            scanner.scan()
            findings.extend(scanner.findings)
            if scanner.returns_borrowed and info.qname not in borrowed:
                borrowed.add(info.qname)
                changed = True
        if not changed:
            break
    return findings
