"""Finding records, parsed source modules, and suppression comments.

The pieces every pass shares: a :class:`Finding` is one structured
``(path, line, rule, message)`` result; a :class:`SourceModule` is one
parsed file (source text, AST, and its ``# pipecheck: disable=...``
comment map). Stdlib only — the analyzer must run on a bare TPU image.
"""

import ast
import io
import re
import tokenize

#: suppression comment syntax: ``# pipecheck: disable=rule[,rule...]``
#: on any line the finding's node spans (``all`` silences every rule).
_SUPPRESS_RE = re.compile(r'pipecheck:\s*disable=([A-Za-z0-9_,\- ]+)')


class Finding:
    """One structured analyzer result."""

    __slots__ = ('path', 'line', 'rule', 'message')

    def __init__(self, path, line, rule, message):
        self.path = path
        self.line = line
        self.rule = rule
        self.message = message

    def __str__(self):
        return '%s:%d: [%s] %s' % (self.path, self.line, self.rule,
                                   self.message)

    def __repr__(self):
        return 'Finding(%r, %r, %r, %r)' % (self.path, self.line,
                                            self.rule, self.message)

    def as_dict(self):
        return {'path': self.path, 'line': self.line, 'rule': self.rule,
                'message': self.message}

    def sort_key(self):
        return (self.path, self.line, self.rule, self.message)


def parse_suppressions(source):
    """``{line: set(rule_ids)}`` of every ``pipecheck: disable=`` comment
    (comments only — a disable token inside a string literal is inert)."""
    out = {}
    try:
        tokens = tokenize.generate_tokens(io.StringIO(source).readline)
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            match = _SUPPRESS_RE.search(tok.string)
            if match is None:
                continue
            rules = {r.strip() for r in match.group(1).split(',') if r.strip()}
            out.setdefault(tok.start[0], set()).update(rules)
    except tokenize.TokenError:
        pass  # findings still apply; only suppressions are best-effort
    return out


class SourceModule:
    """One parsed Python file handed to every pass."""

    def __init__(self, path, source=None, relpath=None):
        if source is None:
            with tokenize.open(path) as f:  # honors coding declarations
                source = f.read()
        self.path = path
        self.relpath = relpath or path
        self.source = source
        self.tree = ast.parse(source, filename=path)
        self.suppressions = parse_suppressions(source)

    def suppressed(self, rule, node_or_line):
        """True when a ``disable=`` comment for ``rule`` (or ``all``) sits
        on any line the node spans."""
        if isinstance(node_or_line, int):
            lines = (node_or_line,)
        else:
            start = getattr(node_or_line, 'lineno', 0)
            end = getattr(node_or_line, 'end_lineno', start) or start
            lines = range(start, end + 1)
        for line in lines:
            rules = self.suppressions.get(line)
            if rules and (rule in rules or 'all' in rules):
                return True
        return False

    def finding(self, rule, node_or_line, message):
        """A :class:`Finding` anchored at the node, or None when a
        suppression comment covers it."""
        if self.suppressed(rule, node_or_line):
            return None
        line = (node_or_line if isinstance(node_or_line, int)
                else getattr(node_or_line, 'lineno', 0))
        return Finding(self.relpath, line, rule, message)


def call_name(node):
    """Terminal callable name of a Call node ('get' for ``q.get(...)``,
    'span' for ``span(...)``); None for exotic callees."""
    func = node.func
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


def dotted_text(expr):
    """Source-ish dotted name of a Name/Attribute chain ('self._lock');
    None for anything else (calls, subscripts)."""
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Attribute):
        base = dotted_text(expr.value)
        return None if base is None else '%s.%s' % (base, expr.attr)
    return None


def literal_str(node):
    """The str value of a Constant node, else None."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def module_constants(tree):
    """``{NAME: 'literal'}`` for module-level string-constant assigns —
    how passes resolve ``registry.counter(SERVICE_REVENTILATED)`` back to
    the literal the constant holds."""
    consts = {}
    for stmt in tree.body:
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Name):
            value = literal_str(stmt.value)
            if value is not None:
                consts[stmt.targets[0].id] = value
        elif isinstance(stmt, ast.AnnAssign) \
                and isinstance(stmt.target, ast.Name):
            value = literal_str(stmt.value) if stmt.value else None
            if value is not None:
                consts[stmt.target.id] = value
    return consts


def resolve_str(node, consts):
    """Literal string of ``node``: a Constant directly, or a module-level
    constant Name; None when not statically resolvable."""
    value = literal_str(node)
    if value is not None:
        return value
    if isinstance(node, ast.Name):
        return consts.get(node.id)
    return None
