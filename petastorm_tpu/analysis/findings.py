"""Finding records, parsed source modules, and suppression comments.

The pieces every pass shares: a :class:`Finding` is one structured
``(path, line, rule, message)`` result; a :class:`SourceModule` is one
parsed file (source text, AST, and its ``# pipecheck: disable=...``
comment map). Stdlib only — the analyzer must run on a bare TPU image.
"""

import ast
import io
import re
import tokenize

from petastorm_tpu.analysis.contracts import OWNS_ANNOTATION_RE

#: suppression comment syntax: ``# pipecheck: disable=rule[,rule...]``
#: on any line the finding's node spans (``all`` silences every rule).
_SUPPRESS_RE = re.compile(r'pipecheck:\s*disable=([A-Za-z0-9_,\- ]+)')

#: ownership-transfer annotation: ``# pipesan: owns`` (contracts.py is the
#: one owner of the token spelling) — silences buffer-ownership findings
#: on the lines it covers while recording an explicit, greppable transfer
_OWNS_RE = re.compile(OWNS_ANNOTATION_RE)


class Finding:
    """One structured analyzer result."""

    __slots__ = ('path', 'line', 'rule', 'message')

    def __init__(self, path, line, rule, message):
        self.path = path
        self.line = line
        self.rule = rule
        self.message = message

    def __str__(self):
        return '%s:%d: [%s] %s' % (self.path, self.line, self.rule,
                                   self.message)

    def __repr__(self):
        return 'Finding(%r, %r, %r, %r)' % (self.path, self.line,
                                            self.rule, self.message)

    def as_dict(self):
        return {'path': self.path, 'line': self.line, 'rule': self.rule,
                'message': self.message}

    def sort_key(self):
        return (self.path, self.line, self.rule, self.message)


def _scan_comments(source):
    """``[(lineno, text)]`` of every comment token — ONE tokenizer pass
    per module, shared by the suppression and owns-annotation scans."""
    out = []
    try:
        tokens = tokenize.generate_tokens(io.StringIO(source).readline)
        for tok in tokens:
            if tok.type == tokenize.COMMENT:
                out.append((tok.start[0], tok.string))
    except tokenize.TokenError:
        pass
    return out


def _suppressions_from(comments):
    """``{line: set(rule_ids)}`` of every ``pipecheck: disable=`` comment
    (comments only — a disable token inside a string literal is inert)."""
    out = {}
    for line, text in comments:
        match = _SUPPRESS_RE.search(text)
        if match is None:
            continue
        rules = {r.strip() for r in match.group(1).split(',') if r.strip()}
        out.setdefault(line, set()).update(rules)
    return out


def _owns_from(comments):
    """Line numbers carrying a ``# pipesan: owns`` ownership-transfer
    annotation."""
    return {line for line, text in comments if _OWNS_RE.search(text)}


def parse_suppressions(source):
    """Suppression map from raw source (one-off callers; SourceModule
    shares a single :func:`_scan_comments` pass for both scans)."""
    return _suppressions_from(_scan_comments(source))


def parse_owns(source):
    """Owns-annotation lines from raw source (one-off callers)."""
    return _owns_from(_scan_comments(source))


class SourceModule:
    """One parsed Python file handed to every pass."""

    def __init__(self, path, source=None, relpath=None):
        if source is None:
            with tokenize.open(path) as f:  # honors coding declarations
                source = f.read()
        self.path = path
        self.relpath = relpath or path
        self.source = source
        self.tree = ast.parse(source, filename=path)
        comments = _scan_comments(source)
        self.suppressions = _suppressions_from(comments)
        self.owns_lines = _owns_from(comments)

    def suppressed(self, rule, node_or_line):
        """True when a ``disable=`` comment for ``rule`` (or ``all``) sits
        on any line the node spans."""
        if isinstance(node_or_line, int):
            lines = (node_or_line,)
        else:
            start = getattr(node_or_line, 'lineno', 0)
            end = getattr(node_or_line, 'end_lineno', start) or start
            lines = range(start, end + 1)
        for line in lines:
            rules = self.suppressions.get(line)
            if rules and (rule in rules or 'all' in rules):
                return True
        return False

    def owned(self, node_or_line):
        """True when a ``# pipesan: owns`` annotation sits on any line the
        node spans — or on the line directly above it (the justified
        annotation-block style) — the buffer-ownership passes treat the
        transfer as explicit and emit no finding."""
        if isinstance(node_or_line, int):
            lines = (node_or_line - 1, node_or_line)
        else:
            start = getattr(node_or_line, 'lineno', 0)
            end = getattr(node_or_line, 'end_lineno', start) or start
            lines = range(start - 1, end + 1)
        return any(line in self.owns_lines for line in lines)

    def finding(self, rule, node_or_line, message):
        """A :class:`Finding` anchored at the node, or None when a
        suppression comment covers it."""
        if self.suppressed(rule, node_or_line):
            return None
        line = (node_or_line if isinstance(node_or_line, int)
                else getattr(node_or_line, 'lineno', 0))
        return Finding(self.relpath, line, rule, message)


def call_name(node):
    """Terminal callable name of a Call node ('get' for ``q.get(...)``,
    'span' for ``span(...)``); None for exotic callees."""
    func = node.func
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


def dotted_text(expr):
    """Source-ish dotted name of a Name/Attribute chain ('self._lock');
    None for anything else (calls, subscripts)."""
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Attribute):
        base = dotted_text(expr.value)
        return None if base is None else '%s.%s' % (base, expr.attr)
    return None


def literal_str(node):
    """The str value of a Constant node, else None."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def module_constants(tree):
    """``{NAME: 'literal'}`` for module-level string-constant assigns —
    how passes resolve ``registry.counter(SERVICE_REVENTILATED)`` back to
    the literal the constant holds."""
    consts = {}
    for stmt in tree.body:
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Name):
            value = literal_str(stmt.value)
            if value is not None:
                consts[stmt.targets[0].id] = value
        elif isinstance(stmt, ast.AnnAssign) \
                and isinstance(stmt.target, ast.Name):
            value = literal_str(stmt.value) if stmt.value else None
            if value is not None:
                consts[stmt.target.id] = value
    return consts


def resolve_str(node, consts):
    """Literal string of ``node``: a Constant directly, or a module-level
    constant Name; None when not statically resolvable."""
    value = literal_str(node)
    if value is not None:
        return value
    if isinstance(node, ast.Name):
        return consts.get(node.id)
    return None
