"""Lightweight project-wide call/assignment graph for whole-program passes.

pipecheck's original passes are per-module by design; the pipesan passes
(``buffer-escape``/``buffer-write`` in :mod:`pass_buffers`, the
whole-program half of ``lock-order`` in :mod:`pass_locks`) need to see
across files: *does this function's return value alias a borrowed
buffer?*, *which locks does this call chain eventually acquire?*. This
module builds the one shared structure both answer from:

* a **function table** — every module-level function and every method of
  every class, keyed by a stable qualified name
  (``module.func`` / ``module.Class.method``);
* per function, the **calls** it makes (with the lock set lexically held
  at each call site), the **locks** it acquires, and its **return
  expressions**;
* a conservative **call resolver**: ``self.method()`` resolves within the
  class, bare names resolve through the module's project-internal
  ``from``-imports then to same-module functions, ``alias.func()``
  resolves through ``import``-aliases. Anything else (attribute calls on
  unknown objects, dynamic dispatch) stays unresolved — whole-program
  conclusions are drawn only from edges that are certainly real, which is
  what keeps the passes baseline-zero-able on a live tree.

Lock identity is globalized so cross-module nesting compares equal:
``self._lock`` inside ``class C`` of module ``m`` becomes ``m.C._lock``;
a module-level ``_IO_LOCK`` becomes ``m._IO_LOCK``. Stdlib-only, like
everything under :mod:`petastorm_tpu.analysis`.
"""

import ast
import os

from petastorm_tpu.analysis.pass_locks import _lock_name as _local_lock_name

#: project package prefix: imports outside it are external and unresolved
_PACKAGE = 'petastorm_tpu'

#: interprocedural fixpoints are bounded; real call chains converge in 2-3
_MAX_FIXPOINT_ROUNDS = 10


def module_name(path):
    """Stable dotted module name for a file path: rooted at the package
    directory when the file lives under one (``petastorm_tpu.jax.staging``),
    else the bare stem (fixture files, snippets)."""
    parts = os.path.normpath(path).replace('\\', '/').split('/')
    stem = parts[-1][:-3] if parts[-1].endswith('.py') else parts[-1]
    if _PACKAGE in parts:
        rooted = parts[parts.index(_PACKAGE):-1] + [stem]
        name = '.'.join(rooted)
        return name[:-len('.__init__')] if name.endswith('.__init__') \
            else name
    return stem


class FunctionInfo:
    """One function/method in the graph."""

    __slots__ = ('qname', 'module', 'modname', 'class_name', 'node',
                 'calls', 'acquires', 'lexical_pairs', 'returns')

    def __init__(self, qname, module, modname, class_name, node):
        self.qname = qname
        self.module = module            # the owning SourceModule
        self.modname = modname
        self.class_name = class_name    # None for module-level functions
        self.node = node
        #: [(call_node, line, tuple(held global lock names))]
        self.calls = []
        #: [(global lock name, line)]
        self.acquires = []
        #: [(outer, inner, line)] — lock nestings lexical to this function
        self.lexical_pairs = []
        #: [ast.Return nodes]
        self.returns = []


class CallGraph:
    """Function table + import maps + the conservative call resolver."""

    def __init__(self, modules):
        #: qname -> FunctionInfo
        self.functions = {}
        #: modname -> {local name: qname} (module-level functions)
        self._module_funcs = {}
        #: modname -> {class: {method: qname}}
        self._class_methods = {}
        #: modname -> {local alias: (source modname, source func or None)}
        self._imports = {}
        # same-stem files from different scanned directories must not
        # merge into (and overwrite) one function table — the first
        # keeps the resolvable name, later ones get a path-qualified key
        # imports cannot reach (conservative: unresolved, never wrong)
        self._modules = {}
        named = []
        for m in modules:
            name = module_name(m.path)
            if name in self._modules:
                name = '%s<%s>' % (name, m.path)
            self._modules[name] = m
            named.append((name, m))
        for name, m in named:
            self._index_module(m, name)
        for info in self.functions.values():
            _FunctionScanner(self, info).scan()

    # -- indexing ------------------------------------------------------------

    def _index_module(self, module, modname):
        funcs = self._module_funcs.setdefault(modname, {})
        methods = self._class_methods.setdefault(modname, {})
        imports = self._imports.setdefault(modname, {})
        for stmt in module.tree.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qname = '%s.%s' % (modname, stmt.name)
                funcs[stmt.name] = qname
                self.functions[qname] = FunctionInfo(
                    qname, module, modname, None, stmt)
            elif isinstance(stmt, ast.ClassDef):
                table = methods.setdefault(stmt.name, {})
                for item in stmt.body:
                    if isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        qname = '%s.%s.%s' % (modname, stmt.name, item.name)
                        table[item.name] = qname
                        self.functions[qname] = FunctionInfo(
                            qname, module, modname, stmt.name, item)
            elif isinstance(stmt, ast.ImportFrom) and stmt.module:
                source = self._project_module(stmt.module)
                if source is not None:
                    for alias in stmt.names:
                        imports[alias.asname or alias.name] = (source,
                                                               alias.name)
            elif isinstance(stmt, ast.Import):
                for alias in stmt.names:
                    source = self._project_module(alias.name)
                    if source is not None:
                        local = alias.asname or alias.name.split('.')[-1]
                        imports[local] = (source, None)

    def _project_module(self, dotted):
        """The analyzed-modules key a ``from X import`` names, or None for
        external modules. Fixture snippets import siblings by bare stem."""
        if dotted in self._modules:
            return dotted
        tail = dotted.split('.')[-1]
        if tail in self._modules and '.' not in dotted:
            return tail
        return None

    # -- resolution ----------------------------------------------------------

    def resolve(self, modname, class_name, call):
        """Qualified name of the project function a Call certainly targets,
        else None. Conservative on purpose: unresolved edges are dropped,
        never guessed."""
        func = call.func
        if isinstance(func, ast.Name):
            imported = self._imports.get(modname, {}).get(func.id)
            if imported is not None:
                source, name = imported
                if name is not None:
                    return self._module_funcs.get(source, {}).get(name)
                return None
            return self._module_funcs.get(modname, {}).get(func.id)
        if isinstance(func, ast.Attribute) and isinstance(func.value,
                                                          ast.Name):
            base = func.value.id
            if base in ('self', 'cls') and class_name is not None:
                return self._class_methods.get(modname, {}) \
                    .get(class_name, {}).get(func.attr)
            imported = self._imports.get(modname, {}).get(base)
            if imported is not None and imported[1] is None:
                return self._module_funcs.get(imported[0], {}).get(func.attr)
        return None

    # -- lock-reachability fixpoint ------------------------------------------

    def eventually_acquires(self):
        """``{qname: frozenset(global lock names)}`` — locks a call into
        the function can end up holding, transitively through resolved
        project-internal edges."""
        state = {q: {name for name, _ in f.acquires}
                 for q, f in self.functions.items()}
        for _ in range(_MAX_FIXPOINT_ROUNDS):
            changed = False
            for qname, info in self.functions.items():
                for call, _, _ in info.calls:
                    target = self.resolve(info.modname, info.class_name,
                                          call)
                    if target is not None and target != qname:
                        extra = state.get(target, ()) - state[qname]
                        if extra:
                            state[qname].update(extra)
                            changed = True
            if not changed:
                break
        return {q: frozenset(s) for q, s in state.items()}


class _FunctionScanner:
    """Populates one FunctionInfo: calls with held-lock context, acquires,
    lexical lock pairs, return statements. Mirrors the statement-walking
    discipline of :mod:`pass_locks` (nested ``def``/``lambda`` bodies run
    later, not here — their calls are not attributed to this function)."""

    def __init__(self, graph, info):
        self.graph = graph
        self.info = info

    def scan(self):
        self.scan_body(self.info.node.body, ())

    def _globalize(self, dotted):
        if dotted.startswith('self.') or dotted.startswith('cls.'):
            if self.info.class_name is None:
                return '%s.%s' % (self.info.modname,
                                  dotted.split('.', 1)[1])
            return '%s.%s.%s' % (self.info.modname, self.info.class_name,
                                 dotted.split('.', 1)[1])
        # an IMPORTED lock must globalize to its DEFINING module, or the
        # two sides of a cross-module nesting would never compare equal
        # (``from mod_b import _FLUSH_LOCK`` used under mod_a's lock)
        imports = self.graph._imports.get(self.info.modname, {})
        head, _, rest = dotted.partition('.')
        imported = imports.get(head)
        if imported is not None:
            source, name = imported
            if name is None:
                # import X as head; head.rest
                return '%s.%s' % (source, rest) if rest else source
            return '%s.%s%s' % (source, name, '.' + rest if rest else '')
        return '%s.%s' % (self.info.modname, dotted)

    def _lock_name(self, expr):
        # the ONE lock-recognition predicate, shared with the per-module
        # scan so the two halves of lock-order agree on what a lock is
        name = _local_lock_name(expr)
        if name is None:
            return None
        return self._globalize(name)

    def _note(self, held, lock, line):
        self.info.acquires.append((lock, line))
        for outer in held:
            if outer != lock:
                self.info.lexical_pairs.append((outer, lock, line))

    def scan_body(self, body, held):
        held = list(held)
        for stmt in body:
            if self._acquire_release(stmt, held):
                continue
            self.scan_stmt(stmt, tuple(held))

    def _acquire_release(self, stmt, held):
        if not isinstance(stmt, ast.Expr) \
                or not isinstance(stmt.value, ast.Call) \
                or not isinstance(stmt.value.func, ast.Attribute):
            return False
        call = stmt.value
        lock = self._lock_name(call.func.value)
        if lock is None:
            return False
        if call.func.attr == 'acquire':
            self._note(held, lock, stmt.lineno)
            held.append(lock)
            return True
        if call.func.attr == 'release':
            if lock in held:
                held.remove(lock)
            return True
        return False

    def scan_stmt(self, stmt, held):
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return  # deferred execution: not this function's calls
        if isinstance(stmt, ast.Return):
            self.info.returns.append(stmt)
            self._collect_calls(stmt.value, held)
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            entered = list(held)
            for item in stmt.items:
                self._collect_calls(item.context_expr, held)
                lock = self._lock_name(item.context_expr)
                if lock is None and item.optional_vars is not None:
                    lock = self._lock_name(item.optional_vars)
                if lock is not None:
                    self._note(entered, lock, stmt.lineno)
                    entered.append(lock)
            self.scan_body(stmt.body, tuple(entered))
            return
        if isinstance(stmt, ast.Try):
            self.scan_body(stmt.body, held)
            for handler in stmt.handlers:
                self.scan_body(handler.body, held)
            self.scan_body(stmt.orelse, held)
            self.scan_body(stmt.finalbody, held)
            return
        if isinstance(stmt, (ast.If, ast.While)):
            self._collect_calls(stmt.test, held)
            self.scan_body(stmt.body, held)
            self.scan_body(stmt.orelse, held)
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._collect_calls(stmt.iter, held)
            self.scan_body(stmt.body, held)
            self.scan_body(stmt.orelse, held)
            return
        self._collect_calls(stmt, held)

    def _collect_calls(self, node, held):
        if node is None:
            return
        for child in ast.walk(node):
            if isinstance(child, (ast.Lambda, ast.FunctionDef,
                                  ast.AsyncFunctionDef)):
                continue  # deferred bodies; ast.walk still descends, so
                # calls inside lambdas are over-collected — acceptable for
                # reachability (a deferred call can still run under the
                # lock when invoked synchronously, e.g. sorted(key=...))
            if isinstance(child, ast.Call):
                self.info.calls.append((child, child.lineno, tuple(held)))


# last (module identity set, graph): pass_buffers and pass_locks both
# call build_graph over the SAME modules list within one analysis run,
# and indexing + scanning every function body twice would double the
# whole-program cost. The cached graph holds strong refs to its modules,
# so the ids in the key cannot be recycled while the entry is alive;
# core.run_project_passes clears the cache when the run ends so a
# long-lived process does not pin the last repo's parse state.
_last_graph = None


def build_graph(modules):
    """The :class:`CallGraph` over a list of parsed SourceModules
    (memoized for consecutive calls over the same modules)."""
    global _last_graph
    key = tuple(id(m) for m in modules)
    if _last_graph is not None and _last_graph[0] == key:
        return _last_graph[1]
    graph = CallGraph(modules)
    _last_graph = (key, graph)
    return graph


def clear_graph_cache():
    """Drop the memoized graph (end of an analysis run)."""
    global _last_graph
    _last_graph = None
