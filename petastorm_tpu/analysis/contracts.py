"""Canonical name sets: the pipeline's naming contracts in ONE place.

Every name contract the concurrency-heavy surface relies on lives here —
imported at runtime by the telemetry subsystem (stage spans, trace events,
the env-knob registry) AND statically by the :mod:`petastorm_tpu.analysis`
checker plus ``tests/test_hygiene.py``. Before this module existed the
same literals were duplicated between ``telemetry/spans.py``,
``telemetry/tracing.py`` and the hygiene test, where they could (and did)
drift silently; now a typo'd stage name, an unregistered knob or an
undocumented metric is a static-analysis finding, not a runtime mystery.

Dependency-free and import-light by design: this module imports nothing,
so the checker can read the contracts without dragging in numpy/pyarrow,
and ``telemetry`` can import it without cycles (nothing here imports
telemetry back).
"""

#: canonical pipeline stages, ventilator → device (docs/telemetry.md):
#: ``ventilate`` hand item to pool · ``io`` parquet row-group read ·
#: ``decode`` codec decode · ``filter`` predicate/row-mask eval ·
#: ``transform`` TransformSpec · ``queue_wait`` consumer blocked pulling ·
#: ``collate`` re-batch/shuffle-buffer/densify · ``h2d`` host→device
#: staging (pre-arena path) · ``h2d_ready`` staging arena blocked until a
#: slot's previous transfer completed · ``stage_fill`` cast/pad/mask copy
#: into the arena slot · ``h2d_dispatch`` async transfer dispatch ·
#: ``cache_hit_read`` decoded-row-group cache hit served (mmap + column
#: reconstruct) · ``cache_fill`` decoded batch serialized to Arrow IPC +
#: atomically published into the cache · ``decode_fused`` deferred image
#: cells decoded by the staging arena straight into the destination
#: buffer (slot ring or fresh assembly; petastorm_tpu/fused.py) ·
#: ``rowgroup_prune`` plan-time statistics pruning at Reader
#: construction (footer fetch + prover; petastorm_tpu/pushdown.py) ·
#: ``late_materialize`` survivor-only decode of heavy columns after the
#: predicate mask — the late-materialization specialization of
#: ``decode`` (arrow_worker._load_rowgroup) · ``autotune`` one staging
#: autotuner tick: registry snapshot + rollup window close + policy
#: (petastorm_tpu/jax/autotune.py; the loop's own overhead is on the
#: books) · ``readahead_fetch`` one coalesced prefetch of an upcoming
#: row-group's column-chunk ranges on the readahead plane's fetch
#: threads (petastorm_tpu/readahead.py; wall time overlapped with
#: decode — a high share here with low ``io`` share is the plane
#: working) · ``pack`` token-budget sequence packing: variable-length
#: documents folded into fixed ``(seq_len,)`` rows with loss masks and
#: segment ids (petastorm_tpu/mixture/packing.py) · ``encode``
#: write-path codec encode of row dicts into parquet-storable values
#: (etl/dataset_metadata.DatasetWriter, write/writer.py) ·
#: ``write_flush`` one buffered row-group flushed as an arrow table
#: into a parquet part file (etl/dataset_metadata.DatasetWriter._flush)
#: · ``compact`` one compaction group folded: source part files read at
#: the arrow level, re-chunked to readahead-friendly row-groups and
#: rewritten (write/compact.py) · ``peer_fetch`` one finished decoded
#: entry fetched from a peer worker's serve socket instead of decoded
#: locally: request + streamed Arrow IPC bytes + verify + atomic
#: publish into the local disk tier (service/peer_cache.py; wire-priced
#: where ``decode`` would be decode-priced)
STAGES = ('ventilate', 'io', 'decode', 'filter', 'transform', 'queue_wait',
          'collate', 'h2d', 'h2d_ready', 'stage_fill', 'h2d_dispatch',
          'cache_hit_read', 'cache_fill', 'decode_fused',
          'rowgroup_prune', 'late_materialize', 'autotune',
          'readahead_fetch', 'pack', 'encode', 'write_flush', 'compact',
          'peer_fetch')

#: every trace-event name the package records outside the canonical stage
#: spans (docs/telemetry.md, tracing section)
EVENT_NAMES = frozenset([
    'attempt',          # one worker-side processing of one item (X event)
    'ventilate',        # recorded via the ventilator's stage span
    'dispatch',         # dispatcher assigned the item to a worker (instant)
    'reventilate',      # heartbeat lapse sent the item back to pending
    'retry',            # failed attempt rescheduled with backoff (instant)
    'poisoned',         # retry budget exhausted; item quarantined
    'done',             # the item's single delivered completion
    'duplicate_done',   # a raced second completion, deduped (dropped)
    # standing-service supervision (service/supervisor.py + daemon.py):
    # every scaling/repair action on the fleet is an instant on the
    # 'supervisor'/'daemon' track, so a Perfetto export shows WHY the
    # fleet changed size or membership
    'worker_spawn',     # supervisor started a worker-server process
    'worker_release',   # supervisor drained + released an idle worker
    'breaker_open',     # crash-looping slot tripped its circuit breaker
    'breaker_close',    # a breaker's respawned worker proved stable
    'job_register',     # daemon admitted a client job into the registry
    'job_gone',         # a job left the registry (goodbye or lease GC)
    # high availability + QoS (service/standby.py + dispatcher.py):
    'standby_promote',  # standby detected a primary lapse; promoting
    'endpoint_takeover',  # promoted standby bound the primary's endpoint
    'job_preempt',      # priority preemption cordoned a worker for a job
    # staging autotuner (jax/autotune.py): one instant per knob
    # adjustment on the 'autotuner' track, so a Perfetto export shows
    # WHY throughput changed shape mid-run
    'autotune_decision',
    # streaming mixture engine (mixture/engine.py): one complete event
    # per source-reader batch pull on that source's track, so a traced
    # mixture run shows which source each document lifeline came from
    'mixture_pull',
])

#: every metric series name the package exports — the registry namespace
#: (``petastorm_tpu_*``). Dashboards are built from docs/telemetry.md's
#: metric reference; the hygiene test holds that table and this set equal,
#: and the canonical-name analysis pass holds every
#: ``registry.counter/gauge/histogram`` call in the package to this set.
METRIC_NAMES = frozenset([
    # stage spans (telemetry/spans.py)
    'petastorm_tpu_stage_seconds_total',
    'petastorm_tpu_stage_calls_total',
    'petastorm_tpu_stage_duration_seconds',
    # stall wait clocks (telemetry/__init__.py)
    'petastorm_tpu_stall_producer_wait_seconds_total',
    'petastorm_tpu_stall_consumer_wait_seconds_total',
    # staging arena (jax/staging.py)
    'petastorm_tpu_h2d_bytes_total',
    # staging autotuner (jax/autotune.py)
    'petastorm_tpu_staging_autotune_decisions_total',
    # row-group cache (cache.py)
    'petastorm_tpu_cache_hits_total',
    'petastorm_tpu_cache_misses_total',
    'petastorm_tpu_cache_evictions_total',
    'petastorm_tpu_cache_bytes_written_total',
    'petastorm_tpu_cache_bytes_evicted_total',
    'petastorm_tpu_cache_size_bytes',
    # materialized decoded-row-group cache (materialized_cache.py)
    'petastorm_tpu_decoded_cache_hits_total',
    'petastorm_tpu_decoded_cache_misses_total',
    'petastorm_tpu_decoded_cache_mem_hits_total',
    'petastorm_tpu_decoded_cache_evictions_total',
    'petastorm_tpu_decoded_cache_bytes_written_total',
    'petastorm_tpu_decoded_cache_bytes_read_total',
    'petastorm_tpu_decoded_cache_mmap_reads_total',
    'petastorm_tpu_decoded_cache_copy_reads_total',
    'petastorm_tpu_decoded_cache_size_bytes',
    # disaggregated-service fleet health (service/dispatcher.py)
    'petastorm_tpu_service_reventilated_total',
    'petastorm_tpu_service_duplicate_done_total',
    'petastorm_tpu_service_workers_alive',
    'petastorm_tpu_service_workers_registered',
    'petastorm_tpu_service_items_pending',
    'petastorm_tpu_service_items_assigned',
    # failure-domain hardening (service/dispatcher.py, faults.py,
    # telemetry/__init__.py)
    'petastorm_tpu_service_retries_total',
    'petastorm_tpu_service_items_poisoned_total',
    # standing decode service (service/daemon.py + supervisor.py)
    'petastorm_tpu_service_jobs_active',
    'petastorm_tpu_service_workers_spawned_total',
    'petastorm_tpu_service_workers_released_total',
    'petastorm_tpu_service_breaker_open',
    # highly-available decode service: warm-standby failover, QoS
    # preemption, cache-aware placement (service/standby.py +
    # dispatcher.py)
    'petastorm_tpu_service_failovers_total',
    'petastorm_tpu_service_replication_lag_seconds',
    'petastorm_tpu_service_preemptions_total',
    'petastorm_tpu_service_placement_hits_total',
    'petastorm_tpu_service_placement_misses_total',
    'petastorm_tpu_swallowed_errors_total',
    'petastorm_tpu_faults_injected_total',
    # decoded-cache failure domain (materialized_cache.py)
    'petastorm_tpu_decoded_cache_disk_failures_total',
    'petastorm_tpu_decoded_cache_degraded',
    # pipesan runtime zero-copy sanitizer (sanitizer.py)
    'petastorm_tpu_sanitizer_violations_total',
    'petastorm_tpu_sanitizer_views_guarded_total',
    'petastorm_tpu_sanitizer_canary_checks_total',
    # fused batch-native decode (fused.py / jax/staging.py)
    'petastorm_tpu_fused_decode_rows_total',
    'petastorm_tpu_fused_decode_bytes_total',
    'petastorm_tpu_fused_decode_fallbacks_total',
    # live observability plane (telemetry/timeseries.py + obs_server.py)
    'petastorm_tpu_anomaly_events_total',
    'petastorm_tpu_obs_windows_total',
    'petastorm_tpu_obs_scrapes_total',
    # query-shaped reads: statistics pruning + late materialization
    # (pushdown.py, arrow_worker.py, materialized_cache.py)
    'petastorm_tpu_rowgroups_pruned_total',
    'petastorm_tpu_rows_pruned_total',
    'petastorm_tpu_late_materialized_rows_total',
    'petastorm_tpu_decoded_cache_skipped_total',
    # wire-speed I/O plane: coalesced column-chunk readahead
    # (readahead.py)
    'petastorm_tpu_readahead_hits_total',
    'petastorm_tpu_readahead_misses_total',
    'petastorm_tpu_readahead_bytes_total',
    'petastorm_tpu_readahead_coalesced_reads_total',
    'petastorm_tpu_readahead_degraded_total',
    'petastorm_tpu_readahead_pool_bytes',
    # streaming mixture engine: deterministic mixing + sequence packing
    # (mixture/engine.py, mixture/packing.py)
    'petastorm_tpu_mixture_docs_total',
    'petastorm_tpu_pack_rows_total',
    'petastorm_tpu_pack_tokens_total',
    'petastorm_tpu_pack_padding_tokens_total',
    'petastorm_tpu_pack_split_docs_total',
    # distributed write plane: fleet-ETL writer, compaction, append
    # (write/writer.py, write/compact.py, write/manifest.py)
    'petastorm_tpu_write_rows_total',
    'petastorm_tpu_write_bytes_total',
    'petastorm_tpu_write_files_total',
    'petastorm_tpu_write_commits_total',
    'petastorm_tpu_write_manifest_generation',
    'petastorm_tpu_compact_runs_total',
    'petastorm_tpu_compact_files_folded_total',
    # bounded-staleness append reads (write/append.py): observed lag
    # between the latest committed manifest and the follower's delivery
    'petastorm_tpu_append_staleness_s',
    # fleet-wide decoded-cache tier: peer-served entries
    # (service/peer_cache.py + dispatcher.py). Hits/bytes count
    # successful peer fetches on the FETCHING worker; misses carry the
    # degrade reason (no_holder, peer_miss, timeout, budget, corrupt,
    # injected, send) — every miss decodes locally, never errors;
    # evict_hints counts the dispatcher's advisory global-LRU hints
    'petastorm_tpu_peer_cache_hits_total',
    'petastorm_tpu_peer_cache_misses_total',
    'petastorm_tpu_peer_cache_bytes_total',
    'petastorm_tpu_peer_cache_evict_hints_total',
    # SLO plane (telemetry/slo.py): per-target breach windows + the
    # error budget left in the long burn window (1.0 = untouched)
    'petastorm_tpu_slo_breach_windows_total',
    'petastorm_tpu_slo_budget_remaining',
    # critical-path engine (telemetry/critpath.py): decision-quality
    # cross-check against the staging autotuner (verdict=agree|disagree)
    'petastorm_tpu_critpath_agreement_total',
])

#: prefix of every operator-facing environment knob
KNOB_PREFIX = 'PETASTORM_TPU_'

#: every registered environment knob. A ``PETASTORM_TPU_*`` read anywhere
#: but ``telemetry/knobs.py`` — or of a name missing here, or of a name
#: without a row in docs/env_knobs.md — is an ``env-knob`` analysis
#: finding; :mod:`petastorm_tpu.telemetry.knobs` additionally enforces the
#: set at runtime (reading an unregistered knob raises).
KNOWN_KNOBS = frozenset([
    'PETASTORM_TPU_NATIVE',
    'PETASTORM_TPU_JPEG_FANCY',
    'PETASTORM_TPU_JPEG_DCT',            # parsed by native/jpeg_batch.c
    'PETASTORM_TPU_IMAGE_DECODER_THREADS',
    'PETASTORM_TPU_SERVICE_DISPATCHER',
    'PETASTORM_TPU_SERVICE_WORKERS',
    'PETASTORM_TPU_METRICS',
    'PETASTORM_TPU_METRICS_WINDOW_S',
    'PETASTORM_TPU_TRACE',
    'PETASTORM_TPU_TRACE_SAMPLE',
    'PETASTORM_TPU_TRACE_DUMP',
    'PETASTORM_TPU_TRACE_AUTODUMP_WINDOWS',
    'PETASTORM_TPU_STAGING',
    'PETASTORM_TPU_STAGING_SLOTS',
    'PETASTORM_TPU_STAGING_AUTOTUNE',
    'PETASTORM_TPU_STAGING_AUTOTUNE_WINDOW_SEC',
    'PETASTORM_TPU_STAGING_AUTOTUNE_MAX_SLOTS',
    'PETASTORM_TPU_STAGING_AUTOTUNE_MAX_PREFETCH',
    'PETASTORM_TPU_DECODED_CACHE',
    'PETASTORM_TPU_DECODED_CACHE_DIR',
    'PETASTORM_TPU_DECODED_CACHE_MEM_MB',
    'PETASTORM_TPU_DECODED_CACHE_DISK_MB',
    'PETASTORM_TPU_SANITIZE',
    'PETASTORM_TPU_OBS_PORT',
    'PETASTORM_TPU_OBS_HOST',
    'PETASTORM_TPU_OBS_WINDOW_SEC',
    'PETASTORM_TPU_OBS_WINDOWS',
    'PETASTORM_TPU_OBS_COLLAPSE_FRAC',
    'PETASTORM_TPU_OBS_SATURATED_SHARE',
    'PETASTORM_TPU_OBS_FLAP_FLIPS',
    'PETASTORM_TPU_FAULTS',
    'PETASTORM_TPU_SERVICE_MAX_RETRIES',
    'PETASTORM_TPU_SERVICE_RETRY_BACKOFF_S',
    'PETASTORM_TPU_SERVICE_READ_DEADLINE_S',
    'PETASTORM_TPU_SERVICE_DAEMON',
    'PETASTORM_TPU_SERVICE_LEASE_S',
    'PETASTORM_TPU_SERVICE_MAX_JOBS',
    'PETASTORM_TPU_SERVICE_MIN_WORKERS',
    'PETASTORM_TPU_SERVICE_MAX_WORKERS',
    'PETASTORM_TPU_SERVICE_BREAKER_DEATHS',
    'PETASTORM_TPU_SERVICE_BREAKER_WINDOW_S',
    'PETASTORM_TPU_SERVICE_SCALE_WINDOW_S',
    'PETASTORM_TPU_SERVICE_STANDBY_SYNC_S',
    'PETASTORM_TPU_SERVICE_STANDBY_LAPSE_S',
    'PETASTORM_TPU_SERVICE_JOB_WEIGHT',
    'PETASTORM_TPU_SERVICE_JOB_PRIORITY',
    'PETASTORM_TPU_SERVICE_PLACEMENT',
    'PETASTORM_TPU_PUSHDOWN',
    'PETASTORM_TPU_PUSHDOWN_PRUNE',
    'PETASTORM_TPU_PUSHDOWN_WORKERS',
    'PETASTORM_TPU_READAHEAD',
    'PETASTORM_TPU_READAHEAD_DEPTH',
    'PETASTORM_TPU_READAHEAD_MAX_DEPTH',
    'PETASTORM_TPU_READAHEAD_THREADS',
    'PETASTORM_TPU_READAHEAD_POOL_MB',
    'PETASTORM_TPU_READAHEAD_GAP_KB',
    'PETASTORM_TPU_READAHEAD_MAX_RANGE_MB',
    'PETASTORM_TPU_MIXTURE_OPEN_BINS',
    'PETASTORM_TPU_MIXTURE_RESEQ_MAX',
    'PETASTORM_TPU_WRITE_ROWGROUP_MB',
    'PETASTORM_TPU_WRITE_WORKERS',
    'PETASTORM_TPU_WRITE_SHARD_ROWS',
    'PETASTORM_TPU_WRITE_SELF_CHECK',
    'PETASTORM_TPU_COMPACT_TARGET_MB',
    'PETASTORM_TPU_COMPACT_MIN_FILES',
    'PETASTORM_TPU_SLO',
    'PETASTORM_TPU_OBS_LOG_DIR',
    'PETASTORM_TPU_OBS_LOG_MB',
    'PETASTORM_TPU_PEER_CACHE',
    'PETASTORM_TPU_PEER_CACHE_HOST',
    'PETASTORM_TPU_PEER_CACHE_BUDGET_MB',
    'PETASTORM_TPU_PEER_CACHE_TIMEOUT_S',
    'PETASTORM_TPU_PEER_CACHE_COLD_S',
])

#: canonical anomaly event kinds the live observability plane's detector
#: (:mod:`petastorm_tpu.telemetry.timeseries`) may emit, mapped to the
#: docs/troubleshoot.md runbook HEADING that explains each one. The value
#: rides on every emitted event as its ``runbook`` field, and
#: ``tests/test_hygiene.py`` holds (a) every ``record_anomaly`` literal in
#: the package to this set, (b) every kind to a row in docs/telemetry.md's
#: anomaly table, and (c) every heading here to a real ``##`` section of
#: docs/troubleshoot.md — an event that names a missing runbook is a
#: hygiene failure, not an operator dead end.
ANOMALY_KINDS = {
    'throughput_collapse': 'Throughput collapsed mid-epoch',
    'stall_flap': 'Stall verdict flaps between producer- and '
                  'consumer-bound',
    'queue_saturated': 'My pipeline is consumer-bound — is it the '
                       'training step or the H2D link?',
    'heartbeat_gap': 'Stale decode workers after a crash',
    'h2d_starvation': 'My pipeline is consumer-bound — is it the '
                      'training step or the H2D link?',
    'row_group_poisoned': 'A row-group was quarantined '
                          '(row_group_poisoned)',
    'cache_degraded': 'The decoded cache degraded to decode-through',
    'worker_flapping': 'A worker slot is crash-looping (worker_flapping)',
    'job_lease_expired': 'A job lease expired and was reclaimed '
                         '(job_lease_expired)',
    'dispatcher_failover': 'The dispatcher failed over to its standby '
                           '(dispatcher_failover)',
    'slo_breach': 'An SLO error budget is burning too fast (slo_breach)',
}

#: every registered fault-injection site (:mod:`petastorm_tpu.faults`),
#: mapped to a one-line description of the seam it sits on. The
#: ``faultpoint`` analysis pass holds every ``fault_hit()`` literal in
#: the package to this set, an armed hit of an unregistered name raises
#: at runtime, and docs/development.md's authoring guide renders this
#: table — a faultpoint can never exist off the books. ``drop`` is only
#: meaningful at the message-send sites; the data-path sites take the
#: error/oserror/delay modes.
FAULTPOINTS = {
    'io.read': 'parquet row-group read (arrow_worker._load_rowgroup), '
               'the pushdown planner\'s footer-statistics fetch '
               '(pushdown.StatsIndex, keys end in #footer — a footer '
               'fault degrades to unpruned reads, never a wrong answer) '
               'and the readahead plane\'s prefetch reads (readahead.py, '
               'keys end in #readahead — a fetch fault degrades to the '
               'worker\'s blocking read, counted in '
               'petastorm_tpu_readahead_degraded_total)',
    'decode.rowgroup': 'whole row-group decode, incl. the native batch '
                       'decoders (arrow_worker._load_rowgroup)',
    'decode.batch': 'one column batch decode (codecs.'
                    'decode_batch_with_nulls; fused + per-cell paths)',
    'cache.read': 'decoded-cache entry open/mmap (materialized_cache)',
    'cache.write': 'decoded-cache entry publish (materialized_cache)',
    'zmq.recv': 'dispatcher inbound message (drop = lose the frame)',
    'zmq.work': 'dispatcher WORK send (drop = assignment lost in '
                'flight; the consumer-read deadline is the backstop)',
    'zmq.done': 'worker DONE/ERROR send (drop = completion lost)',
    'zmq.heartbeat': 'worker heartbeat send (drop = dispatcher sees a '
                     'lapse and re-ventilates)',
    'zmq.stop': 'dispatcher STOP broadcast (drop = dispatcher dies '
                'without goodbye — the restart/reconnect drill)',
    'staging.h2d': 'staging-arena host->device dispatch (jax/staging)',
    'service.spawn': 'supervisor worker-server process spawn '
                     '(service/supervisor.py; error = the spawn fails, '
                     'feeding the crash-loop circuit breaker — the '
                     'breaker drill without burning real processes)',
    'zmq.replicate': 'the standby replication stream, checked at BOTH '
                     'ends (dispatcher SSTATE send, standby receive; '
                     'drop = the snapshot is lost in flight — sustained, '
                     'the standby\'s mirror goes stale and a later '
                     'promotion is COLD: clients re-register from '
                     'scratch, still exactly-once)',
    'service.promote': 'a standby\'s promotion attempt (service/'
                       'standby.py; error = the attempt fails and is '
                       'retried with backoff inside the promote window '
                       '— the failover drill\'s knob for prolonging the '
                       'blackout deterministically)',
    'zmq.peer_serve': 'a peer-cache serve reply (service/peer_cache.py '
                      'server side; drop = the holding worker never '
                      'answers and the fetcher times out into local '
                      'decode — the peer-loss drill without killing a '
                      'process; error = the serve fails mid-read)',
    'zmq.peer_fetch': 'a peer-cache fetch attempt (service/peer_cache.py '
                      'client side, hit before the request is sent; '
                      'error/drop = the fetch fails and the worker '
                      'degrades to local decode, counted in '
                      'petastorm_tpu_peer_cache_misses_total'
                      '{reason=injected} — never a wrong answer)',
    'io.write': 'the distributed write plane\'s publication seams '
                '(write/writer.py, write/compact.py, write/manifest.py):'
                ' part-file data write/close (keys end in #part), the '
                'tmp->final rename that publishes a part file (keys end '
                'in #rename) and the atomic manifest swap that commits '
                'a generation (keys end in #manifest). A fault before '
                'the rename leaves only an invisible .tmp file; a fault '
                'before the manifest swap leaves the previous generation'
                ' committed — either way readers never see a torn '
                'dataset, and a retried shard republishes byte-identical'
                ' output (the crash-safety chaos drill in '
                'tests/test_write.py)',
}

#: the one knob-truthiness rule for "disable"/"enable" env spellings —
#: shared by every PETASTORM_TPU_* switch so spellings cannot drift
DISABLED_VALUES = ('0', 'false', 'off', 'no')
ENABLED_VALUES = ('1', 'true', 'on', 'yes')

# -- pipesan buffer-ownership contracts ---------------------------------------
#
# The zero-copy fast paths hand out BORROWED views: arrays whose memory is
# owned by someone else with a shorter (or recycled) lifetime — ZMQ receive
# buffers, the decoded-cache mmap, staging-arena slots. The
# ``buffer-escape``/``buffer-write`` analysis pass
# (:mod:`petastorm_tpu.analysis.pass_buffers`) taints values born from the
# sources registered here and flags them escaping their owning scope or
# being written through; the runtime sanitizer (``PETASTORM_TPU_SANITIZE=1``,
# :mod:`petastorm_tpu.sanitizer`) guards the same three boundaries
# dynamically. One registry, checked from both sides.

#: terminal call names whose RESULT is a borrowed buffer view.
#: ``frombuffer`` over a *call expression* (``np.frombuffer(bytes(...))``,
#: ``np.frombuffer(x.encode())``) is exempt: the argument is a fresh
#: anonymous temporary whose only reference becomes the array's ``.base``,
#: so the view owns its memory by construction.
BORROW_CALLS = frozenset([
    'frombuffer',           # numpy view over someone else's buffer
    'read_entry',           # decoded-cache columns alias the entry's mmap
    '_binary_cell_views',   # cells alias the arrow column's data buffer
])

#: call names whose result is borrowed only when the given keyword is
#: passed with the given value. ``astype`` over a *call expression*
#: receiver is exempt for the same fresh-temporary reason as frombuffer.
BORROW_CALL_KWARGS = {
    'recv_multipart': ('copy', False),   # frames view ZMQ receive buffers
    'astype': ('copy', False),           # may alias the source array
}

#: dotted expressions denoting borrowed buffer collections — staging-arena
#: slot memory (recycled after the slot's next transfer retires) and a
#: deferred image column's encoded cell views (zero-copy over the arrow
#: data buffer; valid only while the column object — which carries the
#: owning arrow column — is alive). Any view over them is borrowed.
BORROW_ATTRS = frozenset([
    'slot.buffers',
    'column.cells',
    # a readahead fetch entry's pooled range buffers (readahead.py):
    # recycled when the entry's reference census drains — views over
    # them are pinned only by a served table's finalizer
    'entry.ranges',
])

#: the ownership-transfer annotation: ``# pipesan: owns`` on (any line of)
#: a flagged statement records that the transfer is intentional and the
#: receiver owns (or knowingly borrows) the memory — always pair it with a
#: justification comment. On a ``return`` it asserts the CALLER owns the
#: result, so taint does NOT propagate — a function whose callers
#: genuinely borrow its result belongs in :data:`BORROW_CALLS` instead.
OWNS_ANNOTATION_RE = r'pipesan:\s*owns'
