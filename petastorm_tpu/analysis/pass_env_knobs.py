"""``env-knob`` pass: every ``PETASTORM_TPU_*`` read goes through the
central knob registry (:mod:`petastorm_tpu.telemetry.knobs`) and names a
registered, documented knob.

Three checks:

* a raw ``os.environ`` read (``get``/``getenv``/``[...]``/``in``/
  ``setdefault``/``pop``) of the knob namespace anywhere outside the
  registry module is a finding — call-site parsing drifts (PR 4 found
  three half-compatible truthiness parses before the shared spelling
  tuple existed; this pass makes the registry structurally load-bearing);
* any knob literal handed to the registry API must be a member of
  :data:`~petastorm_tpu.analysis.contracts.KNOWN_KNOBS`;
* every registered knob must carry a row in docs/env_knobs.md
  (:func:`check_docs_coverage`, run once per analysis, not per file).

Writes (``os.environ['PETASTORM_TPU_X'] = v``) are reads' responsibility
to notice via ``telemetry.refresh()``; they are still steered through
``knobs.set_env`` so the name is validated, but a bare env-var *store*
outside the registry is only flagged when it uses ``setdefault`` (which
also reads).
"""

import ast
import os
import re

from petastorm_tpu.analysis.contracts import KNOB_PREFIX, KNOWN_KNOBS
from petastorm_tpu.analysis.findings import Finding, literal_str

RULE = 'env-knob'
RULES = (RULE,)

#: the one module allowed to touch ``os.environ`` for the knob namespace
REGISTRY_SUFFIX = os.path.join('telemetry', 'knobs.py')

_KNOB_API = frozenset(['raw', 'get_str', 'get_int', 'get_float',
                       'is_disabled', 'is_enabled', 'set_env'])

_DOC_NAME_RE = re.compile(r'PETASTORM_TPU_[A-Z0-9_]+')


def _is_environ(expr):
    """True for ``os.environ`` (or a bare ``environ`` from-import)."""
    if isinstance(expr, ast.Attribute) and expr.attr == 'environ' \
            and isinstance(expr.value, ast.Name) and expr.value.id == 'os':
        return True
    return isinstance(expr, ast.Name) and expr.id == 'environ'


def _is_knob(name):
    return name is not None and name.startswith(KNOB_PREFIX)


def _is_registry(module):
    return module.path.replace('\\', os.sep).endswith(REGISTRY_SUFFIX)


def run(module):
    findings = []
    in_registry = _is_registry(module)

    def flag(node, message):
        finding = module.finding(RULE, node, message)
        if finding is not None:
            findings.append(finding)

    for node in ast.walk(module.tree):
        if isinstance(node, ast.Call):
            func = node.func
            if isinstance(func, ast.Attribute):
                # os.environ.get / .setdefault / .pop
                if func.attr in ('get', 'setdefault', 'pop') \
                        and _is_environ(func.value) and node.args:
                    key = literal_str(node.args[0])
                    if _is_knob(key) and not in_registry:
                        flag(node, 'raw os.environ read of %s: go through '
                                   'petastorm_tpu.telemetry.knobs (the one '
                                   'owner of knob parsing)' % key)
                # os.getenv('PETASTORM_TPU_X')
                elif func.attr == 'getenv' \
                        and isinstance(func.value, ast.Name) \
                        and func.value.id == 'os' and node.args:
                    key = literal_str(node.args[0])
                    if _is_knob(key) and not in_registry:
                        flag(node, 'raw os.getenv read of %s: go through '
                                   'petastorm_tpu.telemetry.knobs' % key)
                # knobs.get_str('PETASTORM_TPU_X') — registered name?
                if func.attr in _KNOB_API and node.args:
                    key = literal_str(node.args[0])
                    if _is_knob(key) and key not in KNOWN_KNOBS:
                        flag(node, 'unregistered knob %s: add it to '
                                   'analysis/contracts.py KNOWN_KNOBS and '
                                   'docs/env_knobs.md' % key)
            elif isinstance(func, ast.Name) and func.id in _KNOB_API \
                    and node.args:
                key = literal_str(node.args[0])
                if _is_knob(key) and key not in KNOWN_KNOBS:
                    flag(node, 'unregistered knob %s: add it to '
                               'analysis/contracts.py KNOWN_KNOBS and '
                               'docs/env_knobs.md' % key)
        elif isinstance(node, ast.Subscript) \
                and isinstance(node.ctx, ast.Load) \
                and _is_environ(node.value):
            key = literal_str(node.slice)
            if _is_knob(key) and not in_registry:
                flag(node, 'raw os.environ[%r] read: go through '
                           'petastorm_tpu.telemetry.knobs' % key)
        elif isinstance(node, ast.Compare) and node.comparators \
                and any(_is_environ(c) for c in node.comparators):
            key = literal_str(node.left)
            if _is_knob(key) and not in_registry:
                flag(node, '%r in os.environ membership read: go through '
                           'petastorm_tpu.telemetry.knobs' % key)
    return findings


def check_docs_coverage(docs_path, relpath=None):
    """Project-level half of the rule: every registered knob has a row in
    docs/env_knobs.md, and the docs name no unregistered knobs (stale
    rows read as operational surface that does not exist)."""
    findings = []
    try:
        with open(docs_path) as f:
            documented = set(_DOC_NAME_RE.findall(f.read()))
    except OSError:
        return [Finding(relpath or docs_path, 1, RULE,
                        'docs/env_knobs.md missing or unreadable: every '
                        'registered knob needs a documented row')]
    where = relpath or docs_path
    for name in sorted(KNOWN_KNOBS - documented):
        findings.append(Finding(where, 1, RULE,
                                'registered knob %s has no row in '
                                'docs/env_knobs.md' % name))
    for name in sorted(documented - KNOWN_KNOBS):
        findings.append(Finding(where, 1, RULE,
                                'docs/env_knobs.md documents %s but it is '
                                'not in KNOWN_KNOBS (stale row or missing '
                                'registration)' % name))
    return findings
