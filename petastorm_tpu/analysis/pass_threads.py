"""``thread-lifecycle`` pass: every created thread is reclaimable.

A ``threading.Thread(...)`` must either be daemonized (``daemon=True``
at construction, or ``X.daemon = True`` before ``start()`` in the same
function) or be ``.join()``ed from a teardown path: a non-daemon,
never-joined thread keeps the interpreter alive after ``main`` returns —
the classic "job finished but the process won't exit" hang — while a
joined thread documents who waits for it and when.

"Joined from a teardown path" is checked structurally: a thread bound to
``self.X`` needs a ``*.join(...)`` call inside a method of the same
class whose name suggests teardown (``stop``/``close``/``shutdown``/
``join``/``__exit__``/``__del__``/``abort``/``teardown``); a thread
bound to a local variable needs ``X.join(...)`` later in the same
function.
"""

import ast

from petastorm_tpu.analysis.findings import dotted_text

RULE = 'thread-lifecycle'
RULES = (RULE,)

_TEARDOWN_TOKENS = ('stop', 'close', 'shutdown', 'join', 'exit', 'del',
                    'abort', 'teardown')


def _is_thread_ctor(call):
    func = call.func
    if isinstance(func, ast.Name) and func.id == 'Thread':
        return True
    return (isinstance(func, ast.Attribute) and func.attr == 'Thread'
            and isinstance(func.value, ast.Name)
            and func.value.id == 'threading')


def _daemon_true(call):
    for kw in call.keywords:
        if kw.arg == 'daemon' and isinstance(kw.value, ast.Constant) \
                and kw.value.value is True:
            return True
    return False


def _is_teardown_method(name):
    lowered = name.lower()
    return any(token in lowered for token in _TEARDOWN_TOKENS)


def _join_targets(tree):
    """Dotted names ``X`` for every ``X.join(...)`` call; the bare
    terminal too ('self._thread' -> also '_thread') so locals
    snapshotting the attribute under the lock
    (``thread = self._thread; thread.join()``) still count."""
    names = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and isinstance(node.func,
                                                     ast.Attribute) \
                and node.func.attr == 'join':
            target = dotted_text(node.func.value)
            if target is not None:
                names.add(target)
                names.add(target.rsplit('.', 1)[-1])
    return names


def _class_joins(class_node):
    """Join-call target names inside teardown-named methods of a class."""
    names = set()
    for stmt in class_node.body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and _is_teardown_method(stmt.name):
            names |= _join_targets(stmt)
    return names


def _daemon_assigned(func_node, target):
    """True when ``<target>.daemon = True`` appears in the function."""
    if target is None:
        return False
    for node in ast.walk(func_node):
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            tgt = node.targets[0]
            if isinstance(tgt, ast.Attribute) and tgt.attr == 'daemon' \
                    and dotted_text(tgt.value) in (target,
                                                   target.rsplit('.', 1)[-1]) \
                    and isinstance(node.value, ast.Constant) \
                    and node.value.value is True:
                return True
    return False


def _binding_target(parents, call):
    """Dotted name the Thread(...) result is bound to ('self._thread',
    'worker_thread'), walking up through the statement that contains the
    call; None when unbound."""
    node = call
    while node in parents:
        parent = parents[node]
        if isinstance(parent, ast.Assign) and parent.value is node \
                and len(parent.targets) == 1:
            return dotted_text(parent.targets[0])
        if isinstance(parent, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef, ast.Module)):
            return None
        node = parent
    return None


def run(module):
    findings = []
    parents = {}
    for node in ast.walk(module.tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node

    def enclosing(node, kinds):
        while node in parents:
            node = parents[node]
            if isinstance(node, kinds):
                return node
        return None

    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Call) or not _is_thread_ctor(node):
            continue
        if _daemon_true(node):
            continue
        target = _binding_target(parents, node)
        func_node = enclosing(node, (ast.FunctionDef, ast.AsyncFunctionDef))
        class_node = enclosing(node, (ast.ClassDef,))
        if func_node is not None and _daemon_assigned(func_node, target):
            continue
        joined = set()
        if class_node is not None:
            joined |= _class_joins(class_node)
        if func_node is not None:
            joined |= _join_targets(func_node)
        terminal = target.rsplit('.', 1)[-1] if target else None
        if target is not None and (target in joined or terminal in joined):
            continue
        finding = module.finding(
            RULE, node,
            'Thread without daemon=True and never join()ed from a '
            'stop()/close()/__exit__ path%s' % (
                '' if target is None
                else ' (bound to %s)' % target))
        if finding is not None:
            findings.append(finding)
    return findings
