"""``pickle-payload`` pass: objects crossing a process boundary must be
picklable by reference.

Work items ventilated into the process/service pools, job specs, and
objects framed for ZMQ all round-trip through dill/pickle. Lambdas,
functions defined inside another function, and locally-defined classes
pickle by value or not at all — dill *sometimes* serializes them, but
the result silently captures the enclosing closure (stale state shipped
to every worker) or fails only on the worker side, where the traceback
points at the pool internals rather than the call site. The contract:
anything handed to a ventilation/serialization boundary is module-level.

Flagged boundary calls: ``ventilate(...)``, ``dill.dumps``/
``pickle.dumps``/``cloudpickle.dumps``, ``dump_job_spec``,
``dump_work_item``, ``exec_in_new_process``, ``send_pyobj``. Flagged
arguments: lambda expressions, and names bound to a ``def``/``class``
nested inside an enclosing function (one level of tuple/list/dict
literal is unpacked; deeper structures are runtime's problem).
"""

import ast

from petastorm_tpu.analysis.findings import call_name

RULE = 'pickle-payload'
RULES = (RULE,)

_BOUNDARY_NAMES = frozenset(['ventilate', 'dump_job_spec', 'dump_work_item',
                             'exec_in_new_process', 'send_pyobj'])
_PICKLER_MODULES = frozenset(['dill', 'pickle', 'cloudpickle'])


def _is_boundary(call):
    name = call_name(call)
    if name in _BOUNDARY_NAMES:
        return True
    if name == 'dumps' and isinstance(call.func, ast.Attribute) \
            and isinstance(call.func.value, ast.Name) \
            and call.func.value.id in _PICKLER_MODULES:
        return True
    return False


def _payload_exprs(call):
    """Argument expressions to inspect, unpacking one literal level."""
    exprs = list(call.args) + [kw.value for kw in call.keywords]
    out = []
    for expr in exprs:
        if isinstance(expr, (ast.Tuple, ast.List, ast.Set)):
            out.extend(expr.elts)
        elif isinstance(expr, ast.Dict):
            out.extend(v for v in expr.values if v is not None)
        else:
            out.append(expr)
    return out


class _Scope:
    __slots__ = ('node', 'local_defs')

    def __init__(self, node):
        self.node = node
        self.local_defs = {}  # name -> 'function' | 'class'


class _Visitor(ast.NodeVisitor):
    def __init__(self, module):
        self.module = module
        self.findings = []
        self.scopes = []  # open FUNCTION scopes only

    def _flag(self, node, message):
        finding = self.module.finding(RULE, node, message)
        if finding is not None:
            self.findings.append(finding)

    def _register(self, name, kind):
        if self.scopes:
            self.scopes[-1].local_defs[name] = kind

    def _local_kind(self, name):
        for scope in reversed(self.scopes):
            kind = scope.local_defs.get(name)
            if kind is not None:
                return kind
        return None

    def visit_FunctionDef(self, node):
        self._register(node.name, 'function')
        self.scopes.append(_Scope(node))
        self.generic_visit(node)
        self.scopes.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_ClassDef(self, node):
        self._register(node.name, 'class')
        self.generic_visit(node)

    def visit_Call(self, node):
        if _is_boundary(node):
            boundary = call_name(node)
            for expr in _payload_exprs(node):
                if isinstance(expr, ast.Lambda):
                    self._flag(expr, 'lambda handed to %s(): not '
                                     'pickle-safe across a process '
                                     'boundary' % boundary)
                elif isinstance(expr, ast.Name):
                    kind = self._local_kind(expr.id)
                    if kind is not None:
                        self._flag(expr, 'locally-defined %s %r handed to '
                                         '%s(): not pickle-safe across a '
                                         'process boundary (move it to '
                                         'module level)'
                                         % (kind, expr.id, boundary))
        self.generic_visit(node)


def run(module):
    visitor = _Visitor(module)
    visitor.visit(module.tree)
    return visitor.findings
