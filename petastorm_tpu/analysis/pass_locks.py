"""``blocking-under-lock`` + ``lock-order`` pass: the deadlock-hazard
detector for the dispatcher/staging/recorder code.

Rule ``blocking-under-lock``: a call that can block indefinitely —
``queue.get()``/``put(...)`` without a timeout, ZMQ ``recv``/``send``
without ``NOBLOCK``/``DONTWAIT``, ``Thread.join()``/``wait()`` without a
timeout, ``block_until_ready``, ``subprocess.*``, ``time.sleep`` — must
not execute while a lock is lexically held (a ``with <lock>:`` body, or
between ``<lock>.acquire()`` and ``<lock>.release()``). A blocked holder
stalls every other thread contending for that lock; when the blocked
resource is drained by one of those threads, that is a deadlock (the
tf.data service paper's dispatcher post-mortems are exactly this shape).

Rule ``lock-order``: when two locks are ever nested in both orders
within one module (A then B somewhere, B then A elsewhere), the module
has a lock-inversion hazard — two threads taking the opposite paths
deadlock. Lock identity is the dotted source text (``self._lock``),
which is the right granularity for the single-class modules this
package keeps its locks in.

The per-module half is lexical by design; :func:`run_project` promotes
the same rule to **whole-program**: using the shared
:mod:`~petastorm_tpu.analysis.callgraph`, a call made while a lock is
held inherits every lock the (conservatively resolved) callee can
eventually acquire — so ``with A: helper()`` where ``helper`` (in any
module) takes ``B`` records the nesting ``A → B``, and an opposite-order
chain anywhere in the project is an inversion finding even though no
single module ever shows both orders. Lock names are globalized
(``module.Class._lock``) so cross-module nestings compare equal; pairs
the lexical per-module scan already reports are not re-reported.

Nested ``def``/``lambda`` bodies under a ``with`` are skipped in the
lexical scan — they execute later, not under the lock.
"""

import ast

from petastorm_tpu.analysis.findings import call_name, dotted_text

BLOCK_RULE = 'blocking-under-lock'
ORDER_RULE = 'lock-order'
RULES = (BLOCK_RULE, ORDER_RULE)
#: the subset run_project can emit — ``--select blocking-under-lock``
#: must not pay for call-graph construction it cannot benefit from
PROJECT_RULES = (ORDER_RULE,)

#: ZMQ socket operations that block without an explicit NOBLOCK/DONTWAIT
_ZMQ_OPS = frozenset(['recv', 'recv_multipart', 'recv_pyobj', 'recv_string',
                      'recv_json', 'send', 'send_multipart', 'send_pyobj',
                      'send_string', 'send_json'])

_SUBPROCESS_OPS = frozenset(['run', 'call', 'check_call', 'check_output',
                             'Popen'])


def _lock_name(expr):
    """Dotted name when the expression looks like a lock ('lock'/'mutex'
    in its terminal segment, e.g. ``self._lock``, ``_JPEG_FANCY_LOCK``);
    else None. Conditions (`self._cv`) are deliberately not locks here:
    their ``wait()`` releases the underlying lock by contract."""
    name = dotted_text(expr)
    if name is None:
        return None
    terminal = name.rsplit('.', 1)[-1].lower()
    if 'lock' in terminal or 'mutex' in terminal:
        return name
    return None


def _has_kw(call, kw):
    return any(k.arg == kw for k in call.keywords)


def _kw_is_false(call, kw):
    for k in call.keywords:
        if k.arg == kw and isinstance(k.value, ast.Constant) \
                and k.value.value is False:
            return True
    return False


def _mentions_noblock(call):
    for node in ast.walk(call):
        if isinstance(node, ast.Attribute) \
                and node.attr in ('NOBLOCK', 'DONTWAIT'):
            return True
        if isinstance(node, ast.Name) and node.id in ('NOBLOCK', 'DONTWAIT'):
            return True
    return False


def _blocking_reason(call):
    """Why this call can block indefinitely, or None."""
    func = call.func
    name = call_name(call)
    if isinstance(func, ast.Attribute):
        if name == 'get' and not call.args and not _has_kw(call, 'timeout') \
                and not _kw_is_false(call, 'block'):
            return 'queue get() with no timeout'
        if name == 'put' and not _has_kw(call, 'timeout') \
                and not _kw_is_false(call, 'block'):
            return 'queue put() with no timeout'
        if name in _ZMQ_OPS and not _mentions_noblock(call):
            return 'ZMQ %s() without NOBLOCK/DONTWAIT' % name
        if name in ('join', 'wait') and not call.args \
                and not _has_kw(call, 'timeout'):
            return '%s() with no timeout' % name
        if isinstance(func.value, ast.Name) and func.value.id == 'subprocess' \
                and name in _SUBPROCESS_OPS:
            return 'subprocess.%s()' % name
        if name == 'sleep' and isinstance(func.value, ast.Name) \
                and func.value.id == 'time':
            return 'time.sleep()'
    elif isinstance(func, ast.Name) and name == 'sleep':
        return 'sleep()'
    if name == 'block_until_ready':
        return 'block_until_ready()'
    return None


class _Scanner:
    """Statement walker tracking the lexically-held lock stack."""

    def __init__(self, module):
        self.module = module
        self.findings = []
        # (outer_name, inner_name) -> first line the nesting was seen at
        self.order_pairs = {}

    # -- reporting -----------------------------------------------------------

    def _flag(self, rule, node, message):
        finding = self.module.finding(rule, node, message)
        if finding is not None:
            self.findings.append(finding)

    def _note_nesting(self, held, new_name, node):
        for outer in held:
            pair = (outer, new_name)
            self.order_pairs.setdefault(pair, node.lineno)
            inverse = self.order_pairs.get((new_name, outer))
            if inverse is not None and outer != new_name:
                self._flag(ORDER_RULE, node,
                           'locks %s and %s are nested in both orders in '
                           'this module (opposite order at line %d): '
                           'lock-inversion deadlock hazard'
                           % (outer, new_name, inverse))

    # -- traversal -----------------------------------------------------------

    def scan_body(self, body, held):
        """Walk one statement list; ``held`` is the tuple of lock names
        lexically held on entry. acquire()/release() statements extend or
        shrink the held set for their remaining siblings."""
        held = list(held)
        for stmt in body:
            acquired = self._acquire_release(stmt, held)
            if acquired is not None:
                continue  # the acquire/release call itself is not a block
            self.scan_stmt(stmt, tuple(held))

    def _acquire_release(self, stmt, held):
        """Handle a bare ``<lock>.acquire()`` / ``.release()`` statement;
        returns the lock name when the statement was one, else None."""
        if not isinstance(stmt, ast.Expr) or not isinstance(stmt.value,
                                                            ast.Call):
            return None
        call = stmt.value
        if not isinstance(call.func, ast.Attribute):
            return None
        lock = _lock_name(call.func.value)
        if lock is None:
            return None
        if call.func.attr == 'acquire':
            self._note_nesting(held, lock, stmt)
            held.append(lock)
            return lock
        if call.func.attr == 'release':
            if lock in held:
                held.remove(lock)
            return lock
        return None

    def scan_stmt(self, stmt, held):
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # a def's body runs when called, not where it is defined:
            # fresh scan with no held locks
            self.scan_body(stmt.body, ())
            return
        if isinstance(stmt, ast.ClassDef):
            self.scan_body(stmt.body, ())
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            entered = list(held)
            for item in stmt.items:
                self._check_expr(item.context_expr, held)
                lock = _lock_name(item.context_expr)
                if lock is None and item.optional_vars is not None:
                    # `with open(path) as lock_file:` — an fcntl-style
                    # file lock announced by its as-name
                    lock = _lock_name(item.optional_vars)
                if lock is not None:
                    self._note_nesting(entered, lock, stmt)
                    entered.append(lock)
            self.scan_body(stmt.body, tuple(entered))
            return
        if isinstance(stmt, ast.Try):
            self.scan_body(stmt.body, held)
            for handler in stmt.handlers:
                self.scan_body(handler.body, held)
            self.scan_body(stmt.orelse, held)
            self.scan_body(stmt.finalbody, held)
            return
        if isinstance(stmt, (ast.If, ast.While)):
            self._check_expr(stmt.test, held)
            self.scan_body(stmt.body, held)
            self.scan_body(stmt.orelse, held)
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._check_expr(stmt.iter, held)
            self.scan_body(stmt.body, held)
            self.scan_body(stmt.orelse, held)
            return
        # simple statement: check every call inside it
        self._check_expr(stmt, held)

    def _check_expr(self, node, held):
        """Flag blocking calls in an expression/simple statement; nested
        function/lambda bodies are skipped (deferred execution)."""
        if not held or node is None:
            return
        for child in ast.walk(node):
            if isinstance(child, (ast.Lambda, ast.FunctionDef,
                                  ast.AsyncFunctionDef)):
                continue  # ast.walk is non-recursive per node; see below
            if isinstance(child, ast.Call):
                reason = self._reason_outside_lambda(node, child)
                if reason is not None:
                    self._flag(BLOCK_RULE, child,
                               '%s while holding %s' % (reason,
                                                        ', '.join(held)))

    def _reason_outside_lambda(self, root, call):
        """Blocking reason for ``call`` unless it sits inside a deferred
        body (lambda) under ``root``."""
        reason = _blocking_reason(call)
        if reason is None:
            return None
        for node in ast.walk(root):
            if isinstance(node, ast.Lambda):
                for inner in ast.walk(node.body):
                    if inner is call:
                        return None
        return reason


def run(module):
    scanner = _Scanner(module)
    scanner.scan_body(module.tree.body, ())
    return scanner.findings


def run_project(modules):
    """Whole-program ``lock-order``: inversion pairs only visible through
    the call graph (cross-function / cross-module). Same-module pairs the
    lexical :func:`run` scan reports are excluded here."""
    from petastorm_tpu.analysis.callgraph import build_graph
    graph = build_graph(modules)
    eventually = graph.eventually_acquires()
    # (outer, inner) -> (SourceModule, line, kind); first witness wins
    pairs = {}
    # per-module lexical pair sets: an inversion whose BOTH orders are
    # lexical within one module is the per-module run() scan's report,
    # regardless of which witness kind got recorded into `pairs` first
    lexical_by_module = {}
    for info in graph.functions.values():
        for outer, inner, line in info.lexical_pairs:
            pairs.setdefault((outer, inner), (info.module, line, 'lexical'))
            lexical_by_module.setdefault(id(info.module), set()).add(
                (outer, inner))
        for call, line, held in info.calls:
            if not held:
                continue
            target = graph.resolve(info.modname, info.class_name, call)
            if target is None:
                continue
            for inner in eventually.get(target, ()):
                for outer in held:
                    if outer != inner:
                        pairs.setdefault((outer, inner),
                                         (info.module, line, 'call'))
    findings = []
    reported = set()
    for (outer, inner), witness in sorted(pairs.items()):
        inverse = pairs.get((inner, outer))
        if inverse is None:
            continue
        key = frozenset((outer, inner))
        if key in reported:
            continue
        reported.add(key)
        if any((outer, inner) in s and (inner, outer) in s
               for s in lexical_by_module.values()):
            continue  # the per-module lexical scan owns this report
        module, line, kind = witness
        imodule, iline, ikind = inverse
        if kind != 'call' and ikind == 'call':
            # anchor at the witness only the call graph could see
            module, line, kind = imodule, iline, ikind
            imodule = witness[0]
            outer, inner = inner, outer
        # the inverse witness is named by PATH only: baselines match on
        # (path, rule, message) ignoring line numbers, and embedding the
        # witness's line here would resurrect baselined findings whenever
        # an unrelated edit shifts it
        how = ('through this call chain' if kind == 'call'
               else 'in this nesting')
        finding = module.finding(
            ORDER_RULE, line,
            'whole-program lock order: %s is taken before %s %s, but the '
            'opposite order holds in %s — lock-inversion deadlock hazard'
            % (outer, inner, how, imodule.relpath))
        if finding is not None:
            findings.append(finding)
    return findings
