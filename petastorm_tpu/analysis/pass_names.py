"""``canonical-name`` / ``faultpoint`` passes: recorded stage/event/
metric names — and fault-injection site names — are members of the
canonical sets in :mod:`petastorm_tpu.analysis.contracts`.

A typo'd stage would silently fall out of ``pipeline_report``'s grouping;
a typo'd metric name would export an invisible series no dashboard knows;
an off-contract trace-event name would land on no known timeline track;
an unregistered ``fault_hit()`` site would be a chaos clause no spec can
ever arm (and no docs table describes). The pass resolves first
arguments that are string literals or module-level string constants
(``registry.counter(SERVICE_REVENTILATED)`` resolves through the
constant); dynamic names are runtime's problem and are skipped.
"""

import ast

from petastorm_tpu.analysis.contracts import (
    EVENT_NAMES, FAULTPOINTS, METRIC_NAMES, STAGES,
)
from petastorm_tpu.analysis.findings import (
    call_name, module_constants, resolve_str,
)

RULE = 'canonical-name'
RULE_FAULTPOINT = 'faultpoint'
RULES = (RULE, RULE_FAULTPOINT)

#: calls recording a stage span or trace event; first arg ∈ STAGES ∪
#: EVENT_NAMES (spans share names with the trace timeline's tracks)
_RECORDING_CALLS = frozenset(['span', 'record_complete', 'record_instant'])

#: registry metric constructors/readers; first arg ∈ METRIC_NAMES
_METRIC_CALLS = frozenset(['counter', 'gauge', 'histogram',
                           'counter_value', 'gauge_value'])

_STAGE_OR_EVENT = frozenset(STAGES) | EVENT_NAMES


def run(module):
    findings = []
    consts = module_constants(module.tree)
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Call) or not node.args:
            continue
        name = call_name(node)
        if name in _RECORDING_CALLS:
            value = resolve_str(node.args[0], consts)
            if value is not None and value not in _STAGE_OR_EVENT:
                finding = module.finding(
                    RULE, node,
                    '%s(%r): not a canonical stage/event name (contracts.'
                    'STAGES / contracts.EVENT_NAMES)' % (name, value))
                if finding is not None:
                    findings.append(finding)
        elif name in _METRIC_CALLS and isinstance(node.func, ast.Attribute):
            value = resolve_str(node.args[0], consts)
            if value is not None and value not in METRIC_NAMES:
                finding = module.finding(
                    RULE, node,
                    '%s(%r): not a canonical metric name (contracts.'
                    'METRIC_NAMES; document new series in '
                    'docs/telemetry.md)' % (name, value))
                if finding is not None:
                    findings.append(finding)
        elif name == 'fault_hit':
            value = resolve_str(node.args[0], consts)
            if value is not None and value not in FAULTPOINTS:
                finding = module.finding(
                    RULE_FAULTPOINT, node,
                    'fault_hit(%r): not a registered faultpoint '
                    '(contracts.FAULTPOINTS; describe new sites in '
                    'docs/development.md)' % (value,))
                if finding is not None:
                    findings.append(finding)
    return findings
