"""pipecheck runner: load sources, run every pass, collect findings.

Library API (``tests/test_analysis.py`` and the CLI both sit on it):

* :func:`analyze_paths` — files/directories → sorted Finding list.
* :func:`analyze_source` — one in-memory snippet (fixture tests).
* :data:`ALL_RULES` / :data:`PASSES` — the registry.

The project-level half of the ``env-knob`` rule (docs coverage) runs
once per :func:`analyze_paths` call when a ``docs/env_knobs.md`` is
discoverable above the first analyzed path.
"""

import os

from petastorm_tpu.analysis import (
    callgraph, pass_buffers, pass_env_knobs, pass_locks, pass_names,
    pass_payloads, pass_threads,
)
from petastorm_tpu.analysis.findings import SourceModule

#: the composable passes, in report order. A pass exposes ``run(module)``
#: (per-module), ``run_project(modules)`` (whole-program, over every
#: parsed module at once — the pipesan buffer-ownership pass and the
#: whole-program half of lock-order), or both.
PASSES = (pass_env_knobs, pass_names, pass_locks, pass_threads,
          pass_payloads, pass_buffers)

#: every rule id a pass can emit (suppression tokens)
ALL_RULES = tuple(rule for p in PASSES for rule in p.RULES)

#: rule id -> one-line description (the CLI's --list-rules table and the
#: docs/development.md reference are both rendered from this)
RULE_DESCRIPTIONS = {
    'env-knob':
        'PETASTORM_TPU_* reads go through telemetry.knobs; knobs are '
        'registered in contracts.KNOWN_KNOBS and documented in '
        'docs/env_knobs.md',
    'canonical-name':
        'span()/trace-event/metric name literals are members of the '
        'canonical sets in analysis/contracts.py',
    'faultpoint':
        'every fault_hit() call site names a fault-injection site '
        'registered in contracts.FAULTPOINTS',
    'blocking-under-lock':
        'no indefinitely-blocking call (queue get/put sans timeout, ZMQ '
        'sans NOBLOCK, join()/wait() sans timeout, block_until_ready, '
        'subprocess, sleep) while lexically holding a lock',
    'lock-order':
        'two locks never nest in opposite orders within one module',
    'thread-lifecycle':
        'every threading.Thread is daemon=True or join()ed from a '
        'teardown path',
    'pickle-payload':
        'no lambdas / locally-defined functions or classes handed to '
        'process-boundary calls (ventilate, dill/pickle dumps, '
        'exec_in_new_process, send_pyobj)',
    'buffer-escape':
        'a borrowed zero-copy view (np.frombuffer, recv_multipart('
        'copy=False) frames, read_entry columns, staging slot views, '
        'astype(copy=False)) must not escape its owning scope — object/'
        'module state, queues, closures, returns — without a '
        "'# pipesan: owns' transfer annotation",
    'buffer-write':
        'no in-place write through a borrowed zero-copy view '
        '(view[...] =, +=, np.copyto(dst=view)): it corrupts the shared '
        'backing memory (mmap, wire buffer, arena slot)',
}


def iter_python_files(paths):
    """Every ``.py`` file under the given files/directories, sorted,
    deduplicated; ``__pycache__`` skipped."""
    seen = set()
    for path in paths:
        if os.path.isdir(path):
            for dirpath, dirnames, filenames in os.walk(path):
                dirnames[:] = [d for d in sorted(dirnames)
                               if d != '__pycache__']
                for filename in sorted(filenames):
                    if filename.endswith('.py'):
                        full = os.path.join(dirpath, filename)
                        if full not in seen:
                            seen.add(full)
                            yield full
        elif path.endswith('.py'):
            if path not in seen:
                seen.add(path)
                yield path


def _find_docs(start):
    """Walk up from ``start`` towards the filesystem root looking for
    ``docs/env_knobs.md`` (the repo checkout shape); None when not found
    (analyzing an installed copy: the per-file rules still run)."""
    current = os.path.abspath(start if os.path.isdir(start)
                              else os.path.dirname(start))
    for _ in range(10):
        candidate = os.path.join(current, 'docs', 'env_knobs.md')
        if os.path.isfile(candidate):
            return candidate
        parent = os.path.dirname(current)
        if parent == current:
            break
        current = parent
    return None


def run_passes(module, select=None):
    """All (selected) per-module passes over one :class:`SourceModule`."""
    findings = []
    for p in PASSES:
        if select is not None and not (set(p.RULES) & select):
            continue
        run = getattr(p, 'run', None)
        if run is None:
            continue  # project-level-only pass (pass_buffers)
        found = run(module)
        if select is not None:
            found = [f for f in found if f.rule in select]
        findings.extend(found)
    return findings


def run_project_passes(modules, select=None):
    """Whole-program passes over every parsed module at once. The passes
    share one memoized call graph; it is dropped when the run ends so a
    long-lived process does not pin the parse state."""
    findings = []
    try:
        for p in PASSES:
            project_rules = getattr(p, 'PROJECT_RULES', p.RULES)
            if select is not None and not (set(project_rules) & select):
                continue
            run_project = getattr(p, 'run_project', None)
            if run_project is None:
                continue
            found = run_project(modules)
            if select is not None:
                found = [f for f in found if f.rule in select]
            findings.extend(found)
    finally:
        callgraph.clear_graph_cache()
    return findings


def analyze_source(source, path='<string>', select=None):
    """Analyze one in-memory snippet (fixture tests drive rules here).
    Whole-program passes run over the single module."""
    select = set(select) if select else None
    module = SourceModule(path, source=source)
    findings = run_passes(module, select) \
        + run_project_passes([module], select)
    return sorted(findings, key=lambda f: f.sort_key())


def analyze_paths(paths, select=None, root=None, check_docs=True):
    """Analyze files/directories; returns sorted findings.

    ``root`` anchors the relative paths in findings (default: cwd).
    ``check_docs`` adds the project-level knob-docs coverage check when a
    ``docs/env_knobs.md`` is discoverable.
    """
    select = set(select) if select else None
    root = root or os.getcwd()
    # A gate that silently scans nothing is worse than no gate: a wrong
    # cwd or a renamed package must fail loudly, not exit 0.
    for path in paths:
        if not os.path.exists(path):
            raise FileNotFoundError('analysis path does not exist: %r'
                                    % (path,))
    findings = []
    modules = []
    any_path = None
    for path in iter_python_files(paths):
        any_path = any_path or path
        try:
            rel = os.path.relpath(path, root)
        except ValueError:  # different drive (windows)
            rel = path
        module = SourceModule(path, relpath=rel)
        modules.append(module)
        findings.extend(run_passes(module, select))
    if any_path is None:
        raise FileNotFoundError('no Python files found under: %s'
                                % ', '.join(map(repr, paths)))
    findings.extend(run_project_passes(modules, select))
    if check_docs and any_path is not None \
            and (select is None or 'env-knob' in select):
        docs = _find_docs(any_path)
        if docs is not None:
            try:
                rel = os.path.relpath(docs, root)
            except ValueError:
                rel = docs
            findings.extend(pass_env_knobs.check_docs_coverage(docs, rel))
    return sorted(findings, key=lambda f: f.sort_key())
