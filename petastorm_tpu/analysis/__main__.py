"""CLI: ``python -m petastorm_tpu.analysis [paths...]``.

Exit status 0 when no findings, 1 when findings, 2 on usage errors —
the CI gate shape (``make analyze``). ``--json`` emits one finding per
line for tooling; ``--select`` narrows to specific rules;
``--list-rules`` prints the rule reference.

``--baseline FILE`` (a ``--json`` dump of an earlier run) filters
findings already present in the baseline — matched by ``(path, rule,
message)``, deliberately ignoring line numbers so unrelated edits don't
resurrect accepted debt — letting a new rule land **strict on new code**
before its backlog hits zero. ``--fail-on-new`` names the resulting
contract explicitly (it is the default exit-code behavior once a
baseline filters: only NEW findings fail the gate).
"""

import argparse
import json
import sys

from petastorm_tpu.analysis.core import (
    ALL_RULES, RULE_DESCRIPTIONS, analyze_paths,
)


def build_parser():
    parser = argparse.ArgumentParser(
        prog='python -m petastorm_tpu.analysis',
        description='pipecheck: AST-level contract & concurrency analyzer')
    parser.add_argument('paths', nargs='*', default=['petastorm_tpu'],
                        help='files or directories to analyze '
                             '(default: petastorm_tpu)')
    parser.add_argument('--select', default=None, metavar='RULE[,RULE...]',
                        help='run only these rules (see --list-rules)')
    parser.add_argument('--json', action='store_true',
                        help='one JSON finding per line instead of text')
    parser.add_argument('--baseline', default=None, metavar='FILE',
                        help='known-findings file (a --json dump of an '
                             'earlier run); matching findings are '
                             'filtered so only new ones fail the gate')
    parser.add_argument('--fail-on-new', action='store_true',
                        help='with --baseline: fail only on findings not '
                             'in the baseline (this is already the '
                             'behavior once --baseline is given; the '
                             'flag documents intent in CI command lines)')
    parser.add_argument('--no-docs-check', action='store_true',
                        help='skip the project-level knob-docs coverage '
                             'check')
    parser.add_argument('--list-rules', action='store_true',
                        help='print the rule reference and exit')
    return parser


def _baseline_keys(path):
    """Multiset of ``(path, rule, message)`` keys from a baseline file
    (one JSON finding per line, as ``--json`` emits; blank lines ok)."""
    keys = {}
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            record = json.loads(line)
            key = (record['path'], record['rule'], record['message'])
            keys[key] = keys.get(key, 0) + 1
    return keys


def apply_baseline(findings, keys):
    """Findings minus the baseline multiset; returns (new, matched)."""
    remaining = dict(keys)
    new = []
    matched = 0
    for finding in findings:
        key = (finding.path, finding.rule, finding.message)
        if remaining.get(key):
            remaining[key] -= 1
            matched += 1
        else:
            new.append(finding)
    return new, matched


def main(argv=None):
    args = build_parser().parse_args(argv)
    if args.list_rules:
        for rule in ALL_RULES:
            print('%-20s %s' % (rule, RULE_DESCRIPTIONS.get(rule, '')))
        return 0
    select = None
    if args.select:
        select = {r.strip() for r in args.select.split(',') if r.strip()}
        unknown = select - set(ALL_RULES)
        if unknown:
            print('unknown rule(s): %s (try --list-rules)'
                  % ', '.join(sorted(unknown)), file=sys.stderr)
            return 2
    if args.fail_on_new and not args.baseline:
        print('--fail-on-new requires --baseline FILE', file=sys.stderr)
        return 2
    try:
        findings = analyze_paths(args.paths, select=select,
                                 check_docs=not args.no_docs_check)
    except FileNotFoundError as e:
        # a gate that scanned nothing must not read as a clean pass
        print('error: %s' % e, file=sys.stderr)
        return 2
    if args.baseline:
        try:
            keys = _baseline_keys(args.baseline)
        except (OSError, ValueError, KeyError, TypeError) as e:
            # an unreadable baseline must not silently waive every finding
            print('error: unusable baseline %s: %s' % (args.baseline, e),
                  file=sys.stderr)
            return 2
        findings, matched = apply_baseline(findings, keys)
        if matched:
            print('%d baseline finding(s) suppressed' % matched,
                  file=sys.stderr)
    for finding in findings:
        if args.json:
            print(json.dumps(finding.as_dict(), sort_keys=True))
        else:
            print(finding)
    if findings:
        print('%d finding(s)' % len(findings), file=sys.stderr)
        return 1
    return 0


if __name__ == '__main__':
    sys.exit(main())
