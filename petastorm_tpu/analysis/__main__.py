"""CLI: ``python -m petastorm_tpu.analysis [paths...]``.

Exit status 0 when no findings, 1 when findings, 2 on usage errors —
the CI gate shape (``make analyze``). ``--json`` emits one finding per
line for tooling; ``--select`` narrows to specific rules;
``--list-rules`` prints the rule reference.
"""

import argparse
import json
import sys

from petastorm_tpu.analysis.core import (
    ALL_RULES, RULE_DESCRIPTIONS, analyze_paths,
)


def build_parser():
    parser = argparse.ArgumentParser(
        prog='python -m petastorm_tpu.analysis',
        description='pipecheck: AST-level contract & concurrency analyzer')
    parser.add_argument('paths', nargs='*', default=['petastorm_tpu'],
                        help='files or directories to analyze '
                             '(default: petastorm_tpu)')
    parser.add_argument('--select', default=None, metavar='RULE[,RULE...]',
                        help='run only these rules (see --list-rules)')
    parser.add_argument('--json', action='store_true',
                        help='one JSON finding per line instead of text')
    parser.add_argument('--no-docs-check', action='store_true',
                        help='skip the project-level knob-docs coverage '
                             'check')
    parser.add_argument('--list-rules', action='store_true',
                        help='print the rule reference and exit')
    return parser


def main(argv=None):
    args = build_parser().parse_args(argv)
    if args.list_rules:
        for rule in ALL_RULES:
            print('%-20s %s' % (rule, RULE_DESCRIPTIONS.get(rule, '')))
        return 0
    select = None
    if args.select:
        select = {r.strip() for r in args.select.split(',') if r.strip()}
        unknown = select - set(ALL_RULES)
        if unknown:
            print('unknown rule(s): %s (try --list-rules)'
                  % ', '.join(sorted(unknown)), file=sys.stderr)
            return 2
    try:
        findings = analyze_paths(args.paths, select=select,
                                 check_docs=not args.no_docs_check)
    except FileNotFoundError as e:
        # a gate that scanned nothing must not read as a clean pass
        print('error: %s' % e, file=sys.stderr)
        return 2
    for finding in findings:
        if args.json:
            print(json.dumps(finding.as_dict(), sort_keys=True))
        else:
            print(finding)
    if findings:
        print('%d finding(s)' % len(findings), file=sys.stderr)
        return 1
    return 0


if __name__ == '__main__':
    sys.exit(main())
