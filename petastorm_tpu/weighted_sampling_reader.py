"""Probabilistic multiplexer over several readers.

Parity with ``petastorm/weighted_sampling_reader.py:20-115``: each ``next()``
draws one underlying reader with the given probability and returns its next
item. Readers must agree on output schema/mode; exhaustion of ANY reader ends
the mix (so relative mixing ratios hold throughout).

``deterministic=True`` swaps the RNG draw for the mixture engine's
arithmetic interleave (:class:`petastorm_tpu.mixture.InterleaveSchedule`):
the same surface and the same weights, but the source at position ``p``
becomes a pure function of ``(seed, weights, p)`` — replayable by any
rank and by the readahead plan, with a hard realized-ratio deviation
bound instead of an in-expectation one. Callers who need the full
packed-row mixture should use :mod:`petastorm_tpu.mixture` directly.
"""

import numpy as np


class WeightedSamplingReader:
    """:param readers: list of opened readers (same schema, same
        batched/ngram mode).
    :param probabilities: relative weights, one per reader (normalized
        internally).
    :param seed: RNG seed for reproducible mixing.
    :param deterministic: mix by the arithmetic interleave schedule
        instead of RNG draws (``seed`` then defaults to 0 — there is no
        nondeterministic flavor of an arithmetic schedule).
    """

    def __init__(self, readers, probabilities, seed=None,
                 deterministic=False):
        if len(readers) != len(probabilities):
            raise ValueError('readers and probabilities must have equal '
                             'lengths (%d != %d)'
                             % (len(readers), len(probabilities)))
        if not readers:
            raise ValueError('At least one reader is required')
        if any(p < 0 for p in probabilities) or sum(probabilities) <= 0:
            raise ValueError('probabilities must be non-negative with a '
                             'positive sum')
        first = readers[0]
        for other in readers[1:]:
            if set(other.schema.fields) != set(first.schema.fields):
                raise ValueError(
                    'All readers must share the same output schema; %s != %s'
                    % (sorted(other.schema.fields), sorted(first.schema.fields)))
            if other.batched_output != first.batched_output:
                raise ValueError('All readers must have the same '
                                 'batched_output mode')
            if (other.ngram is None) != (first.ngram is None) or (
                    first.ngram is not None and other.ngram != first.ngram):
                raise ValueError('All readers must use the same NGram spec '
                                 '(or none)')
        self._readers = readers
        self._cum = np.cumsum(np.asarray(probabilities, dtype=np.float64))
        self._cum /= self._cum[-1]
        self._seed = seed
        self._rng = np.random.RandomState(seed)
        self._draws = 0  # mux cursor: DELIVERED draws only
        self._schedule = None
        if deterministic:
            from petastorm_tpu.mixture import InterleaveSchedule
            self._schedule = InterleaveSchedule(
                list(probabilities), seed=0 if seed is None else seed)

    # The mix exposes the shared reader surface.
    @property
    def schema(self):
        return self._readers[0].schema

    @property
    def batched_output(self):
        return self._readers[0].batched_output

    @property
    def ngram(self):
        return self._readers[0].ngram

    @property
    def last_row_consumed(self):
        """True once any underlying reader ran dry (which ends the mix)."""
        return any(getattr(r, 'last_row_consumed', False)
                   for r in self._readers)

    def __iter__(self):
        return self

    def __next__(self):
        # _draws must count only DELIVERED draws: charging before the
        # source's next() means a StopIteration (any source drying ends
        # the mix) leaves an undelivered draw counted, and a checkpoint
        # taken at mix end replays a choice sequence shifted by one on
        # restore. The generator state rewinds on the failure path so
        # BOTH restore flavors (rng_state and legacy seed+draws replay)
        # reflect delivered draws only.
        if self._schedule is not None:
            choice = self._schedule.peek(1)[0]
            item = next(self._readers[choice])
            self._schedule.next()
            self._draws += 1
            return item
        pre = self._rng.get_state()
        choice = int(np.searchsorted(self._cum, self._rng.random_sample(),
                                     side='right'))
        try:
            item = next(self._readers[min(choice, len(self._readers) - 1)])
        except StopIteration:
            self._rng.set_state(pre)
            raise
        self._draws += 1
        return item

    def next(self):
        return self.__next__()

    def state_dict(self):
        """Joint data position of the mix: every source reader's
        row-group-granular state plus the mux RNG cursor, so a restored
        mix continues the SAME choice sequence (beyond the reference,
        whose mix has no checkpoint story — like its readers). Sources
        restore with their own at-least-once semantics; the choice
        sequence continues exactly for ANY mix — ``rng_state`` carries
        the generator state itself, so even ``seed=None`` mixes restore
        onto their actual stream (pre-``rng_state`` checkpoints replay
        ``seed``+``draws`` instead, which needs an explicit seed)."""
        # the Mersenne-Twister state itself (JSON-shaped) makes restore
        # O(1); 'draws' stays as a diagnostic and as the replay cursor
        # for checkpoints written before rng_state existed
        kind, keys, pos, has_gauss, cached = self._rng.get_state()
        state = {'version': 1, 'seed': self._seed, 'draws': self._draws,
                 'rng_state': [kind, [int(k) for k in keys], int(pos),
                               int(has_gauss), float(cached)],
                 'readers': [r.state_dict() for r in self._readers]}
        if self._schedule is not None:
            state['interleave'] = self._schedule.state_dict()
        return state

    def load_state_dict(self, state):
        """Reposition every source and the mux cursor (call before
        iteration starts, like the readers' own ``load_state_dict``)."""
        if len(state['readers']) != len(self._readers):
            raise ValueError(
                'checkpoint has %d reader states, this mix has %d readers'
                % (len(state['readers']), len(self._readers)))
        for reader, sub_state in zip(self._readers, state['readers']):
            reader.load_state_dict(sub_state)
        # Adopt the CHECKPOINT's seed (not the constructor's): a later
        # state_dict of this restored mix must record the stream it is
        # actually on, or a second-generation restore would replay a
        # different choice sequence than the real run took.
        self._seed = state.get('seed', self._seed)
        if self._schedule is not None:
            if 'interleave' in state:
                self._schedule.load_state_dict(state['interleave'])
            else:
                # RNG-mode checkpoint into a deterministic mix: the
                # arithmetic order is a pure function of position, so
                # the delivered-draw count IS the full cursor
                self._schedule.reset()
                for _ in range(int(state['draws'])):
                    self._schedule.next()
            self._draws = state['draws']
            return
        self._rng = np.random.RandomState(self._seed)
        if 'rng_state' in state:
            # O(1) restore: adopt the saved Mersenne-Twister state
            # directly — replaying billions of draws would stall resume
            # for minutes on a long-lived infinite mix
            kind, keys, pos, has_gauss, cached = state['rng_state']
            self._rng.set_state((kind, np.asarray(keys, dtype=np.uint32),
                                 int(pos), int(has_gauss), float(cached)))
        else:
            # pre-rng_state checkpoints: replay the mux RNG to the saved
            # cursor in bounded chunks — one random_sample(draws) call
            # would materialize an 8*draws-byte throwaway array
            remaining = state['draws']
            while remaining > 0:
                chunk = min(remaining, 1_000_000)
                self._rng.random_sample(chunk)
                remaining -= chunk
        self._draws = state['draws']

    def reset(self):
        """Restart the mix for another pass (the consumer contract
        :class:`~petastorm_tpu.jax.JaxLoader` re-iteration relies on — it
        calls ``reader.reset()`` when a fully consumed loader is iterated
        again).

        A probabilistic mix ends when ANY source runs dry
        (:attr:`last_row_consumed`), which necessarily leaves the other
        sources mid-stream. Reset therefore restarts the DRY sources and
        lets the mid-stream ones continue from where they were — sound
        for a mix, whose per-pass row coverage is probabilistic by
        construction (there is no epoch alignment to restore)."""
        for r in self._readers:
            if getattr(r, 'last_row_consumed', False):
                r.reset()

    def stop(self):
        for r in self._readers:
            r.stop()

    def join(self):
        for r in self._readers:
            r.join()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        self.stop()
        self.join()
