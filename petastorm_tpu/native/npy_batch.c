/* Native batched NPY decode: the hot inner loop of NdarrayCodec.
 *
 * decode_npy_batch(cells, out): parse each .npy payload's header (magic,
 * version, dict literal) in C and memcpy the raw data into row i of a
 * preallocated output batch — no per-cell Python object churn, no BytesIO,
 * no np.load. Falls back (returns 0 at the failing index) when a cell's
 * dtype/shape disagrees with the output, so the caller can route that cell
 * through the generic Python path.
 *
 * The framework-level rationale (SURVEY.md section 7.1): the reference's
 * native surface lived in its dependencies (pyarrow C++, OpenCV); this
 * framework owns the decode loop, so the batched inner loop is first-party
 * native code.
 */

#define PY_SSIZE_T_CLEAN
#define NPY_NO_DEPRECATED_API NPY_1_7_API_VERSION
#include <Python.h>
#include <numpy/arrayobject.h>
#include <pthread.h>
#include <string.h>
#include <stdint.h>

/* same internal-pool clamp as jpeg_batch.c / png_batch.c */
#define PT_MAX_THREADS 32

static const char NPY_MAGIC[6] = {'\x93', 'N', 'U', 'M', 'P', 'Y'};

/* Parse an npy header; on success sets *data_offset to the payload start
 * and returns the header dict substring (borrowed pointers into buf). */
static int
parse_npy_header(const unsigned char *buf, Py_ssize_t len,
                 Py_ssize_t *data_offset, const char **header,
                 Py_ssize_t *header_len)
{
    uint32_t hlen;
    if (len < 10 || memcmp(buf, NPY_MAGIC, 6) != 0)
        return -1;
    if (buf[6] == 1) {
        hlen = (uint32_t)buf[8] | ((uint32_t)buf[9] << 8);
        *data_offset = 10 + (Py_ssize_t)hlen;
        *header = (const char *)buf + 10;
    } else if (buf[6] == 2 || buf[6] == 3) {
        if (len < 12)
            return -1;
        hlen = (uint32_t)buf[8] | ((uint32_t)buf[9] << 8)
             | ((uint32_t)buf[10] << 16) | ((uint32_t)buf[11] << 24);
        *data_offset = 12 + (Py_ssize_t)hlen;
        *header = (const char *)buf + 12;
    } else {
        return -1;
    }
    if (*data_offset > len)
        return -1;
    *header_len = (Py_ssize_t)hlen;
    return 0;
}

/* Verify the header's fortran_order is False, that its descr matches
 * `descr` (e.g. "<f4"), and that its literal shape entry matches
 * `shape_str` (e.g. "'shape': (2, 3)" — numpy's canonical header repr).
 * A stored cell whose true shape differs from the declared per-row shape
 * but has an equal byte count must NOT be memcpy'd into the declared
 * shape (silent data reinterpretation); shape mismatch routes the cell to
 * the Python decode path, which preserves the true shape and lets
 * collation surface the error. */
static int
header_compatible(const char *header, Py_ssize_t header_len,
                  const char *descr, const char *shape_str)
{
    /* fortran_order must be False: C-contiguous copy only */
    const char *fo = NULL;
    char needle[64];
    size_t descr_len = strlen(descr);
    if (header_len <= 0 || header_len > 65536)
        return 0;
    {
        /* bounded search: header is not NUL-terminated */
        char *tmp = (char *)PyMem_Malloc((size_t)header_len + 1);
        int ok;
        if (tmp == NULL)
            return 0;
        memcpy(tmp, header, (size_t)header_len);
        tmp[header_len] = '\0';
        fo = strstr(tmp, "'fortran_order': False");
        if (fo == NULL)
            fo = strstr(tmp, "\"fortran_order\": False");
        ok = (fo != NULL);
        if (ok && descr_len + 2 < sizeof(needle)) {
            snprintf(needle, sizeof(needle), "'%s'", descr);
            if (strstr(tmp, needle) == NULL) {
                snprintf(needle, sizeof(needle), "\"%s\"", descr);
                ok = (strstr(tmp, needle) != NULL);
            }
        }
        if (ok)
            ok = (strstr(tmp, shape_str) != NULL);
        PyMem_Free(tmp);
        return ok;
    }
}

/* One contiguous range of validated (src, dst) payload copies performed
 * by one pool thread; parallel memcpy engages multiple memory channels,
 * so wide rows batch-copy faster than one core's streaming bandwidth. */
struct pt_npy_task {
    const char *const *srcs;
    char *out_data;
    Py_ssize_t row_bytes;
    Py_ssize_t lo, hi;
};

static void *
pt_npy_worker(void *arg)
{
    struct pt_npy_task *t = (struct pt_npy_task *)arg;
    Py_ssize_t i;

    for (i = t->lo; i < t->hi; i++)
        memcpy(t->out_data + i * t->row_bytes, t->srcs[i],
               (size_t)t->row_bytes);
    return NULL;
}

/* decode_npy_batch(cells: sequence of bytes-like or None,
 *                  out: ndarray (n, ...) C-contiguous, writable,
 *                  descr: str like '<f4',
 *                  shape_str: str like "'shape': (2, 3)",
 *                  threads: int = 0)
 * Returns: number of successfully decoded leading cells. A cell that is
 * None or incompatible stops fast-path decoding at its index (caller
 * finishes those via the Python path).
 *
 * Two-phase row-group-batch shape: headers parse and validate under the
 * GIL (cheap, Python buffer API), then every validated payload memcpys
 * with the GIL RELEASED — fanned across an internal pthread pool when
 * `threads > 1` (sized by the caller from
 * PETASTORM_TPU_IMAGE_DECODER_THREADS). */
static PyObject *
decode_npy_batch(PyObject *self, PyObject *args)
{
    PyObject *cells;
    PyArrayObject *out;
    const char *descr;
    const char *shape_str;
    Py_ssize_t n, i, n_ok;
    Py_ssize_t row_bytes;
    char *out_data;
    int threads_arg = 0;
    Py_buffer *views = NULL;
    const char **srcs = NULL;

    if (!PyArg_ParseTuple(args, "OO!ss|i", &cells, &PyArray_Type, &out,
                          &descr, &shape_str, &threads_arg))
        return NULL;
    if (!PyArray_IS_C_CONTIGUOUS(out) || !PyArray_ISWRITEABLE(out)) {
        PyErr_SetString(PyExc_ValueError,
                        "out must be C-contiguous and writable");
        return NULL;
    }
    n = PySequence_Length(cells);
    if (n < 0)
        return NULL;
    if (PyArray_DIM(out, 0) < n) {
        PyErr_SetString(PyExc_ValueError, "out batch dimension too small");
        return NULL;
    }
    row_bytes = (Py_ssize_t)(PyArray_NBYTES(out) / (PyArray_DIM(out, 0) > 0
                             ? PyArray_DIM(out, 0) : 1));
    out_data = (char *)PyArray_DATA(out);

    views = PyMem_Calloc((size_t)(n ? n : 1), sizeof(Py_buffer));
    srcs = PyMem_Malloc(sizeof(const char *) * (size_t)(n ? n : 1));
    if (views == NULL || srcs == NULL) {
        PyMem_Free(views);
        PyMem_Free(srcs);
        return PyErr_NoMemory();
    }

    /* phase 1 (GIL held): acquire buffers + validate headers; the
     * decoded prefix ends at the first None/incompatible cell */
    for (i = 0; i < n; i++) {
        PyObject *cell = PySequence_GetItem(cells, i);
        Py_ssize_t data_offset, header_len;
        const char *header;
        int ok;

        if (cell == NULL) {
            PyErr_Clear();
            break;
        }
        if (cell == Py_None) {
            Py_DECREF(cell);
            break;
        }
        if (PyObject_GetBuffer(cell, &views[i], PyBUF_SIMPLE) != 0) {
            PyErr_Clear();
            Py_DECREF(cell);
            break;
        }
        Py_DECREF(cell);
        ok = (parse_npy_header((const unsigned char *)views[i].buf,
                               views[i].len, &data_offset, &header,
                               &header_len) == 0)
             && header_compatible(header, header_len, descr, shape_str)
             && (views[i].len - data_offset == row_bytes);
        if (!ok) {
            PyBuffer_Release(&views[i]);
            break;
        }
        srcs[i] = (const char *)views[i].buf + data_offset;
    }
    n_ok = i;

    /* phase 2 (GIL released): copy every validated payload */
    if (n_ok > 0 && row_bytes > 0) {
        struct pt_npy_task tasks[PT_MAX_THREADS];
        Py_ssize_t n_tasks, t, chunk;

        n_tasks = threads_arg;
        if (n_tasks > PT_MAX_THREADS)
            n_tasks = PT_MAX_THREADS;
        if (n_tasks > n_ok)
            n_tasks = n_ok;
        if (n_tasks < 1)
            n_tasks = 1;
        chunk = (n_ok + n_tasks - 1) / n_tasks;
        for (t = 0; t < n_tasks; t++) {
            tasks[t].srcs = srcs;
            tasks[t].out_data = out_data;
            tasks[t].row_bytes = row_bytes;
            tasks[t].lo = t * chunk;
            tasks[t].hi = (t + 1) * chunk < n_ok ? (t + 1) * chunk : n_ok;
        }
        Py_BEGIN_ALLOW_THREADS
        {
            pthread_t tids[PT_MAX_THREADS];
            int created[PT_MAX_THREADS] = {0};
            for (t = 1; t < n_tasks; t++)
                created[t] = pthread_create(&tids[t], NULL, pt_npy_worker,
                                            &tasks[t]) == 0;
            pt_npy_worker(&tasks[0]);
            for (t = 1; t < n_tasks; t++) {
                if (created[t])
                    pthread_join(tids[t], NULL);
                else
                    pt_npy_worker(&tasks[t]);  /* spawn failed: copy inline */
            }
        }
        Py_END_ALLOW_THREADS
    }

    for (i = 0; i < n_ok; i++)
        PyBuffer_Release(&views[i]);
    PyMem_Free(views);
    PyMem_Free(srcs);
    return PyLong_FromSsize_t(n_ok);
}

static PyMethodDef NpyBatchMethods[] = {
    {"decode_npy_batch", decode_npy_batch, METH_VARARGS,
     "decode_npy_batch(cells, out, descr, shape_str, threads=0): batched "
     ".npy decode into a preallocated array; returns the decoded prefix "
     "count. Payload memcpys run with the GIL released, fanned across an "
     "internal pthread pool when threads > 1"},
    {NULL, NULL, 0, NULL}
};

static struct PyModuleDef npy_batch_module = {
    PyModuleDef_HEAD_INIT, "_npy_batch",
    "Native batched NPY decoder", -1, NpyBatchMethods
};

PyMODINIT_FUNC
PyInit__npy_batch(void)
{
    PyObject *m = PyModule_Create(&npy_batch_module);
    if (m == NULL)
        return NULL;
    import_array();
    return m;
}
