/* Native batched NPY decode: the hot inner loop of NdarrayCodec.
 *
 * decode_npy_batch(cells, out): parse each .npy payload's header (magic,
 * version, dict literal) in C and memcpy the raw data into row i of a
 * preallocated output batch — no per-cell Python object churn, no BytesIO,
 * no np.load. Falls back (returns 0 at the failing index) when a cell's
 * dtype/shape disagrees with the output, so the caller can route that cell
 * through the generic Python path.
 *
 * The framework-level rationale (SURVEY.md section 7.1): the reference's
 * native surface lived in its dependencies (pyarrow C++, OpenCV); this
 * framework owns the decode loop, so the batched inner loop is first-party
 * native code.
 */

#define PY_SSIZE_T_CLEAN
#define NPY_NO_DEPRECATED_API NPY_1_7_API_VERSION
#include <Python.h>
#include <numpy/arrayobject.h>
#include <string.h>
#include <stdint.h>

static const char NPY_MAGIC[6] = {'\x93', 'N', 'U', 'M', 'P', 'Y'};

/* Parse an npy header; on success sets *data_offset to the payload start
 * and returns the header dict substring (borrowed pointers into buf). */
static int
parse_npy_header(const unsigned char *buf, Py_ssize_t len,
                 Py_ssize_t *data_offset, const char **header,
                 Py_ssize_t *header_len)
{
    uint32_t hlen;
    if (len < 10 || memcmp(buf, NPY_MAGIC, 6) != 0)
        return -1;
    if (buf[6] == 1) {
        hlen = (uint32_t)buf[8] | ((uint32_t)buf[9] << 8);
        *data_offset = 10 + (Py_ssize_t)hlen;
        *header = (const char *)buf + 10;
    } else if (buf[6] == 2 || buf[6] == 3) {
        if (len < 12)
            return -1;
        hlen = (uint32_t)buf[8] | ((uint32_t)buf[9] << 8)
             | ((uint32_t)buf[10] << 16) | ((uint32_t)buf[11] << 24);
        *data_offset = 12 + (Py_ssize_t)hlen;
        *header = (const char *)buf + 12;
    } else {
        return -1;
    }
    if (*data_offset > len)
        return -1;
    *header_len = (Py_ssize_t)hlen;
    return 0;
}

/* Verify the header's fortran_order is False, that its descr matches
 * `descr` (e.g. "<f4"), and that its literal shape entry matches
 * `shape_str` (e.g. "'shape': (2, 3)" — numpy's canonical header repr).
 * A stored cell whose true shape differs from the declared per-row shape
 * but has an equal byte count must NOT be memcpy'd into the declared
 * shape (silent data reinterpretation); shape mismatch routes the cell to
 * the Python decode path, which preserves the true shape and lets
 * collation surface the error. */
static int
header_compatible(const char *header, Py_ssize_t header_len,
                  const char *descr, const char *shape_str)
{
    /* fortran_order must be False: C-contiguous copy only */
    const char *fo = NULL;
    char needle[64];
    size_t descr_len = strlen(descr);
    if (header_len <= 0 || header_len > 65536)
        return 0;
    {
        /* bounded search: header is not NUL-terminated */
        char *tmp = (char *)PyMem_Malloc((size_t)header_len + 1);
        int ok;
        if (tmp == NULL)
            return 0;
        memcpy(tmp, header, (size_t)header_len);
        tmp[header_len] = '\0';
        fo = strstr(tmp, "'fortran_order': False");
        if (fo == NULL)
            fo = strstr(tmp, "\"fortran_order\": False");
        ok = (fo != NULL);
        if (ok && descr_len + 2 < sizeof(needle)) {
            snprintf(needle, sizeof(needle), "'%s'", descr);
            if (strstr(tmp, needle) == NULL) {
                snprintf(needle, sizeof(needle), "\"%s\"", descr);
                ok = (strstr(tmp, needle) != NULL);
            }
        }
        if (ok)
            ok = (strstr(tmp, shape_str) != NULL);
        PyMem_Free(tmp);
        return ok;
    }
}

/* decode_npy_batch(cells: sequence of bytes-like or None,
 *                  out: ndarray (n, ...) C-contiguous, writable,
 *                  descr: str like '<f4',
 *                  shape_str: str like "'shape': (2, 3)")
 * Returns: number of successfully decoded leading cells. A cell that is
 * None or incompatible stops fast-path decoding at its index (caller
 * finishes those via the Python path). */
static PyObject *
decode_npy_batch(PyObject *self, PyObject *args)
{
    PyObject *cells;
    PyArrayObject *out;
    const char *descr;
    const char *shape_str;
    Py_ssize_t n, i;
    Py_ssize_t row_bytes;
    char *out_data;

    if (!PyArg_ParseTuple(args, "OO!ss", &cells, &PyArray_Type, &out, &descr,
                          &shape_str))
        return NULL;
    if (!PyArray_IS_C_CONTIGUOUS(out) || !PyArray_ISWRITEABLE(out)) {
        PyErr_SetString(PyExc_ValueError,
                        "out must be C-contiguous and writable");
        return NULL;
    }
    n = PySequence_Length(cells);
    if (n < 0)
        return NULL;
    if (PyArray_DIM(out, 0) < n) {
        PyErr_SetString(PyExc_ValueError, "out batch dimension too small");
        return NULL;
    }
    row_bytes = (Py_ssize_t)(PyArray_NBYTES(out) / (PyArray_DIM(out, 0) > 0
                             ? PyArray_DIM(out, 0) : 1));
    out_data = (char *)PyArray_DATA(out);

    for (i = 0; i < n; i++) {
        PyObject *cell = PySequence_GetItem(cells, i);
        Py_buffer view;
        Py_ssize_t data_offset, header_len;
        const char *header;
        int ok;

        if (cell == NULL)
            return NULL;
        if (cell == Py_None) {
            Py_DECREF(cell);
            break;
        }
        if (PyObject_GetBuffer(cell, &view, PyBUF_SIMPLE) != 0) {
            PyErr_Clear();
            Py_DECREF(cell);
            break;
        }
        ok = (parse_npy_header((const unsigned char *)view.buf, view.len,
                               &data_offset, &header, &header_len) == 0)
             && header_compatible(header, header_len, descr, shape_str)
             && (view.len - data_offset == row_bytes);
        if (ok) {
            memcpy(out_data + i * row_bytes,
                   (const char *)view.buf + data_offset, (size_t)row_bytes);
        }
        PyBuffer_Release(&view);
        Py_DECREF(cell);
        if (!ok)
            break;
    }
    return PyLong_FromSsize_t(i);
}

static PyMethodDef NpyBatchMethods[] = {
    {"decode_npy_batch", decode_npy_batch, METH_VARARGS,
     "Batched .npy decode into a preallocated array; returns decoded count"},
    {NULL, NULL, 0, NULL}
};

static struct PyModuleDef npy_batch_module = {
    PyModuleDef_HEAD_INIT, "_npy_batch",
    "Native batched NPY decoder", -1, NpyBatchMethods
};

PyMODINIT_FUNC
PyInit__npy_batch(void)
{
    PyObject *m = PyModule_Create(&npy_batch_module);
    if (m == NULL)
        return NULL;
    import_array();
    return m;
}
