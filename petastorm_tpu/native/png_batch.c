/* Native batched PNG decode: the 8-bit RGB fast path of
 * CompressedImageCodec, sibling of jpeg_batch.c.
 *
 * decode_png_batch(cells, out): decode each PNG cell straight into row i
 * of a preallocated (N, H, W, 3) uint8 batch with libpng — PNG stores RGB
 * natively, so rows land in the output with no channel conversion at all,
 * bit-identical to the cv2 path. The whole loop runs with the GIL
 * RELEASED in one native call; per cell, libpng's own decode cost equals
 * cv2's (~10us for a 32x32 cell, measured), so the win is the removed
 * per-cell Python dispatch/alloc (~5us/cell, ~40% of the small-image
 * path).
 *
 * Returns the count of successfully decoded leading cells; a cell that is
 * not a non-interlaced 8-bit RGB PNG of exactly the declared (H, W) stops
 * the loop, and the caller routes the remainder through the generic cv2
 * path (same prefix-count contract as jpeg_batch.c / npy_batch.c).
 *
 * Framework rationale (SURVEY.md section 7.3): the hello-world headline
 * rate is png-decode-bound; the reference left this loop to per-cell
 * OpenCV calls (petastorm/codecs.py:102-130) — here it is first-party
 * native code.
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <setjmp.h>
#include <stddef.h>
#include <string.h>
#include <png.h>

struct pt_mem_reader {
    const unsigned char *data;
    size_t len;
    size_t pos;
};

static void
pt_read_fn(png_structp png, png_bytep out, png_size_t n)
{
    struct pt_mem_reader *r = (struct pt_mem_reader *)png_get_io_ptr(png);
    if (r->pos + n > r->len)
        png_error(png, "premature end of PNG data");
    memcpy(out, r->data + r->pos, n);
    r->pos += n;
}

static void
pt_png_warn(png_structp png, png_const_charp msg)
{
    /* no stderr chatter from a data-loader hot loop */
    (void)png;
    (void)msg;
}

static void
pt_png_error(png_structp png, png_const_charp msg)
{
    /* libpng's DEFAULT error handler prints to stderr before jumping;
     * corrupt cells are an expected input here (they fall back to the
     * Python path), so jump silently */
    (void)msg;
    png_longjmp(png, 1);
}

/* Decode one cell; returns 0 on success, -1 on mismatch/corruption. */
static int
decode_one(const unsigned char *buf, size_t len, unsigned char *dst,
           int height, int width)
{
    struct pt_mem_reader rd = { buf, len, 0 };
    png_structp png;
    png_infop info;
    int r;

    png = png_create_read_struct(PNG_LIBPNG_VER_STRING, NULL, pt_png_error,
                                 pt_png_warn);
    if (png == NULL)
        return -1;
    info = png_create_info_struct(png);
    if (info == NULL) {
        png_destroy_read_struct(&png, NULL, NULL);
        return -1;
    }
    if (setjmp(png_jmpbuf(png))) {
        png_destroy_read_struct(&png, &info, NULL);
        return -1;
    }
    png_set_read_fn(png, &rd, pt_read_fn);
    png_read_info(png, info);
    if (png_get_color_type(png, info) != PNG_COLOR_TYPE_RGB
        || png_get_bit_depth(png, info) != 8
        || png_get_interlace_type(png, info) != PNG_INTERLACE_NONE
        || (int)png_get_image_height(png, info) != height
        || (int)png_get_image_width(png, info) != width) {
        /* grayscale / palette / RGBA / 16-bit / interlaced / wrong size:
         * the Python path owns these */
        png_destroy_read_struct(&png, &info, NULL);
        return -1;
    }
    for (r = 0; r < height; r++)
        png_read_row(png, dst + (size_t)r * (size_t)width * 3, NULL);
    png_destroy_read_struct(&png, &info, NULL);
    return 0;
}

static PyObject *
decode_png_batch(PyObject *self, PyObject *args)
{
    PyObject *cells;
    PyObject *out_obj;
    Py_buffer out_view;
    Py_ssize_t n, i, decoded;
    Py_buffer *views = NULL;
    int height, width;

    (void)self;
    if (!PyArg_ParseTuple(args, "OO", &cells, &out_obj))
        return NULL;
    if (PyObject_GetBuffer(out_obj, &out_view,
                           PyBUF_WRITABLE | PyBUF_ND
                           | PyBUF_C_CONTIGUOUS) != 0)
        return NULL;

    if (out_view.ndim != 4 || out_view.itemsize != 1
        || out_view.shape[3] != 3) {
        PyBuffer_Release(&out_view);
        PyErr_SetString(PyExc_ValueError,
                        "out must be a C-contiguous (N, H, W, 3) uint8 array");
        return NULL;
    }
    n = out_view.shape[0];
    height = (int)out_view.shape[1];
    width = (int)out_view.shape[2];

    if (!PySequence_Check(cells) || PySequence_Size(cells) != n) {
        PyBuffer_Release(&out_view);
        PyErr_SetString(PyExc_ValueError,
                        "cells must be a sequence matching out's batch dim");
        return NULL;
    }

    views = PyMem_Calloc((size_t)(n ? n : 1), sizeof(Py_buffer));
    if (views == NULL) {
        PyBuffer_Release(&out_view);
        return PyErr_NoMemory();
    }
    for (i = 0; i < n; i++) {
        PyObject *cell = PySequence_GetItem(cells, i);
        int rc;
        if (cell == NULL) {
            PyErr_Clear();  /* decode the prefix; Python path owns the rest */
            break;
        }
        rc = PyObject_GetBuffer(cell, &views[i], PyBUF_SIMPLE);
        Py_DECREF(cell);
        if (rc != 0) {
            PyErr_Clear();
            break;
        }
    }
    {
        Py_ssize_t n_views = i;
        size_t row_bytes = (size_t)height * (size_t)width * 3;
        unsigned char *out_base = (unsigned char *)out_view.buf;

        decoded = 0;
        Py_BEGIN_ALLOW_THREADS
        for (i = 0; i < n_views; i++) {
            if (decode_one((const unsigned char *)views[i].buf,
                           (size_t)views[i].len,
                           out_base + (size_t)i * row_bytes,
                           height, width) != 0)
                break;
            decoded++;
        }
        Py_END_ALLOW_THREADS

        for (i = 0; i < n_views; i++)
            PyBuffer_Release(&views[i]);
    }
    PyMem_Free(views);
    PyBuffer_Release(&out_view);
    return PyLong_FromSsize_t(decoded);
}

static PyMethodDef png_batch_methods[] = {
    {"decode_png_batch", decode_png_batch, METH_VARARGS,
     "Batched RGB PNG decode into a preallocated (N,H,W,3) uint8 array; "
     "returns the decoded prefix count"},
    {NULL, NULL, 0, NULL}
};

static struct PyModuleDef png_batch_module = {
    PyModuleDef_HEAD_INIT, "_png_batch",
    "Native batched PNG decoder (libpng)", -1, png_batch_methods,
    NULL, NULL, NULL, NULL
};

PyMODINIT_FUNC
PyInit__png_batch(void)
{
    return PyModule_Create(&png_batch_module);
}
