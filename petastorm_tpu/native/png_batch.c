/* Native batched PNG decode: the 8-bit RGB fast path of
 * CompressedImageCodec, sibling of jpeg_batch.c.
 *
 * decode_png_batch(cells, out, threads=0): decode each PNG cell straight
 * into row i of a preallocated (N, H, W, 3) uint8 batch with libpng —
 * PNG stores RGB natively, so rows land in the output with no channel
 * conversion at all, bit-identical to the cv2 path. The whole loop runs
 * with the GIL RELEASED in one native call; per cell, libpng's own
 * decode cost equals cv2's (~10us for a 32x32 cell, measured), so the
 * win is the removed per-cell Python dispatch/alloc (~5us/cell, ~40% of
 * the small-image path). `threads > 1` fans the cells across an internal
 * pthread pool (sized by the caller from
 * PETASTORM_TPU_IMAGE_DECODER_THREADS), decoding disjoint output rows in
 * parallel with zero Python-side task churn.
 *
 * Returns the count of successfully decoded leading cells; a cell that is
 * not a non-interlaced 8-bit RGB PNG of exactly the declared (H, W) stops
 * the loop, and the caller routes the remainder through the generic cv2
 * path (same prefix-count contract as jpeg_batch.c / npy_batch.c).
 *
 * Framework rationale (SURVEY.md section 7.3): the hello-world headline
 * rate is png-decode-bound; the reference left this loop to per-cell
 * OpenCV calls (petastorm/codecs.py:102-130) — here it is first-party
 * native code.
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <pthread.h>
#include <setjmp.h>
#include <stddef.h>
#include <string.h>
#include <png.h>

/* same internal-pool clamp as jpeg_batch.c */
#define PT_MAX_THREADS 32

struct pt_mem_reader {
    const unsigned char *data;
    size_t len;
    size_t pos;
};

static void
pt_read_fn(png_structp png, png_bytep out, png_size_t n)
{
    struct pt_mem_reader *r = (struct pt_mem_reader *)png_get_io_ptr(png);
    if (r->pos + n > r->len)
        png_error(png, "premature end of PNG data");
    memcpy(out, r->data + r->pos, n);
    r->pos += n;
}

static void
pt_png_warn(png_structp png, png_const_charp msg)
{
    /* no stderr chatter from a data-loader hot loop */
    (void)png;
    (void)msg;
}

static void
pt_png_error(png_structp png, png_const_charp msg)
{
    /* libpng's DEFAULT error handler prints to stderr before jumping;
     * corrupt cells are an expected input here (they fall back to the
     * Python path), so jump silently */
    (void)msg;
    png_longjmp(png, 1);
}

/* Decode one cell; returns 0 on success, -1 on mismatch/corruption. */
static int
decode_one(const unsigned char *buf, size_t len, unsigned char *dst,
           int height, int width)
{
    struct pt_mem_reader rd = { buf, len, 0 };
    png_structp png;
    png_infop info;
    int r;

    png = png_create_read_struct(PNG_LIBPNG_VER_STRING, NULL, pt_png_error,
                                 pt_png_warn);
    if (png == NULL)
        return -1;
    info = png_create_info_struct(png);
    if (info == NULL) {
        png_destroy_read_struct(&png, NULL, NULL);
        return -1;
    }
    if (setjmp(png_jmpbuf(png))) {
        png_destroy_read_struct(&png, &info, NULL);
        return -1;
    }
    png_set_read_fn(png, &rd, pt_read_fn);
    png_read_info(png, info);
    if (png_get_color_type(png, info) != PNG_COLOR_TYPE_RGB
        || png_get_bit_depth(png, info) != 8
        || png_get_interlace_type(png, info) != PNG_INTERLACE_NONE
        || (int)png_get_image_height(png, info) != height
        || (int)png_get_image_width(png, info) != width) {
        /* grayscale / palette / RGBA / 16-bit / interlaced / wrong size:
         * the Python path owns these */
        png_destroy_read_struct(&png, &info, NULL);
        return -1;
    }
    for (r = 0; r < height; r++)
        png_read_row(png, dst + (size_t)r * (size_t)width * 3, NULL);
    png_destroy_read_struct(&png, &info, NULL);
    return 0;
}

/* One contiguous cell range decoded by one pool thread; decode_one is
 * fully self-contained (per-cell libpng read struct, local jmp buffer),
 * so the only shared state is the disjoint output rows. `fail` is the
 * first rejected index in [lo, hi) (== hi when the range decoded). */
struct pt_png_task {
    const Py_buffer *views;
    unsigned char *out_base;
    size_t row_bytes;
    Py_ssize_t lo, hi;
    Py_ssize_t fail;
    int height, width;
};

static void *
pt_png_worker(void *arg)
{
    struct pt_png_task *t = (struct pt_png_task *)arg;
    Py_ssize_t i;

    for (i = t->lo; i < t->hi; i++) {
        const Py_buffer *v = &t->views[i];
        if (decode_one((const unsigned char *)v->buf, (size_t)v->len,
                       t->out_base + (size_t)i * t->row_bytes,
                       t->height, t->width) != 0)
            break;
    }
    t->fail = i;
    return NULL;
}

/* Fan the ranges across pool threads (calling thread = worker 0) and
 * fold per-range failures into the batch-wide decoded prefix — the
 * first rejected index overall (same contract as jpeg_batch.c). */
static Py_ssize_t
pt_png_run(struct pt_png_task *tasks, Py_ssize_t n_tasks,
           Py_ssize_t n_views)
{
    pthread_t tids[PT_MAX_THREADS];
    int created[PT_MAX_THREADS] = {0};
    Py_ssize_t t, decoded;

    for (t = 1; t < n_tasks; t++) {
        if (pthread_create(&tids[t], NULL, pt_png_worker, &tasks[t]) != 0) {
            tasks[t].fail = tasks[t].lo;
            continue;
        }
        created[t] = 1;
    }
    pt_png_worker(&tasks[0]);
    for (t = 1; t < n_tasks; t++) {
        if (created[t])
            pthread_join(tids[t], NULL);
    }
    decoded = n_views;
    for (t = 0; t < n_tasks; t++) {
        if (tasks[t].fail < tasks[t].hi && tasks[t].fail < decoded)
            decoded = tasks[t].fail;
    }
    return decoded;
}

static PyObject *
decode_png_batch(PyObject *self, PyObject *args)
{
    PyObject *cells;
    PyObject *out_obj;
    Py_buffer out_view;
    Py_ssize_t n, i, decoded;
    Py_buffer *views = NULL;
    int height, width;
    int threads_arg = 0;

    (void)self;
    if (!PyArg_ParseTuple(args, "OO|i", &cells, &out_obj, &threads_arg))
        return NULL;
    if (PyObject_GetBuffer(out_obj, &out_view,
                           PyBUF_WRITABLE | PyBUF_ND
                           | PyBUF_C_CONTIGUOUS) != 0)
        return NULL;

    if (out_view.ndim != 4 || out_view.itemsize != 1
        || out_view.shape[3] != 3) {
        PyBuffer_Release(&out_view);
        PyErr_SetString(PyExc_ValueError,
                        "out must be a C-contiguous (N, H, W, 3) uint8 array");
        return NULL;
    }
    n = out_view.shape[0];
    height = (int)out_view.shape[1];
    width = (int)out_view.shape[2];

    if (!PySequence_Check(cells) || PySequence_Size(cells) != n) {
        PyBuffer_Release(&out_view);
        PyErr_SetString(PyExc_ValueError,
                        "cells must be a sequence matching out's batch dim");
        return NULL;
    }

    views = PyMem_Calloc((size_t)(n ? n : 1), sizeof(Py_buffer));
    if (views == NULL) {
        PyBuffer_Release(&out_view);
        return PyErr_NoMemory();
    }
    for (i = 0; i < n; i++) {
        PyObject *cell = PySequence_GetItem(cells, i);
        int rc;
        if (cell == NULL) {
            PyErr_Clear();  /* decode the prefix; Python path owns the rest */
            break;
        }
        rc = PyObject_GetBuffer(cell, &views[i], PyBUF_SIMPLE);
        Py_DECREF(cell);
        if (rc != 0) {
            PyErr_Clear();
            break;
        }
    }
    {
        Py_ssize_t n_views = i;
        size_t row_bytes = (size_t)height * (size_t)width * 3;
        unsigned char *out_base = (unsigned char *)out_view.buf;
        struct pt_png_task tasks[PT_MAX_THREADS];
        Py_ssize_t n_tasks, t, chunk;

        n_tasks = threads_arg;
        if (n_tasks > PT_MAX_THREADS)
            n_tasks = PT_MAX_THREADS;
        if (n_tasks > n_views)
            n_tasks = n_views;
        if (n_tasks < 1)
            n_tasks = 1;
        chunk = (n_views + n_tasks - 1) / (n_tasks ? n_tasks : 1);
        for (t = 0; t < n_tasks; t++) {
            tasks[t].views = views;
            tasks[t].out_base = out_base;
            tasks[t].row_bytes = row_bytes;
            tasks[t].lo = t * chunk;
            tasks[t].hi = (t + 1) * chunk < n_views
                              ? (t + 1) * chunk : n_views;
            tasks[t].fail = tasks[t].lo;
            tasks[t].height = height;
            tasks[t].width = width;
        }
        Py_BEGIN_ALLOW_THREADS
        decoded = pt_png_run(tasks, n_tasks, n_views);
        Py_END_ALLOW_THREADS

        for (i = 0; i < n_views; i++)
            PyBuffer_Release(&views[i]);
    }
    PyMem_Free(views);
    PyBuffer_Release(&out_view);
    return PyLong_FromSsize_t(decoded);
}

static PyMethodDef png_batch_methods[] = {
    {"decode_png_batch", decode_png_batch, METH_VARARGS,
     "decode_png_batch(cells, out, threads=0): batched RGB PNG decode "
     "into a preallocated (N,H,W,3) uint8 array; returns the decoded "
     "prefix count. threads > 1 fans the cells across an internal "
     "pthread pool (GIL released)"},
    {NULL, NULL, 0, NULL}
};

static struct PyModuleDef png_batch_module = {
    PyModuleDef_HEAD_INIT, "_png_batch",
    "Native batched PNG decoder (libpng)", -1, png_batch_methods,
    NULL, NULL, NULL, NULL
};

PyMODINIT_FUNC
PyInit__png_batch(void)
{
    return PyModule_Create(&png_batch_module);
}
