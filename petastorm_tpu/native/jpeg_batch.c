/* Native batched JPEG decode: the hot inner loop of CompressedImageCodec.
 *
 * decode_jpeg_batch(cells, out, fancy=-1, threads=0): decode each JPEG
 * cell straight into row i of a preallocated (N, H, W, 3) uint8 batch
 * with libjpeg(-turbo), RGB output, ISLOW DCT (turbo's SIMD path). The
 * whole loop runs with the GIL RELEASED in one native call: no per-cell
 * Python dispatch, no thread-pool task churn, no intermediate
 * Mat/ndarray per cell. `threads > 1` fans the cells across an internal
 * pthread pool (one decompress object + row scratch per thread, disjoint
 * output rows) — true row-group-batch decode without Python-side task
 * churn or GIL round trips between chunks; the pool is sized by the
 * caller from PETASTORM_TPU_IMAGE_DECODER_THREADS so the C pool and the
 * Python-side executor never multiply.
 *
 * Upsampling policy: WHICH of libjpeg's two 4:2:0/4:2:2 chroma paths is
 * faster depends on the host's libjpeg build — merged upsampling skips a
 * pass, but libjpeg-turbo SIMD-vectorizes the fancy (triangle-filter)
 * upsampler while its merged RGB path is scalar on some configurations.
 * (Single-run timings on the shared dev boxes running this file have
 * shown BOTH orderings by large factors, which interleaved re-measurement
 * exposed as machine noise — hence measure-don't-assume, and measure
 * robustly.) The optional third argument `fancy` selects the mode
 * explicitly: 1 = fancy (bit-identical to OpenCV's imdecode of the same
 * bytes — both ride libjpeg; the mode the bit-exactness tests pin),
 * 0 = merged, and -1 (default) defers to the PETASTORM_TPU_JPEG_FANCY
 * env var (unset or 0 = merged). The Python caller
 * (codecs._native_image_batch) times both modes interleaved once per
 * process on the first real batch and passes the winner; direct C
 * callers keep the env-driven contract. 4:4:4 jpegs have no upsampling
 * step and decode identically either way.
 *
 * Returns the count of successfully decoded leading cells; a cell that is
 * not an 8-bit 3-component JPEG of exactly the declared (H, W) stops the
 * loop, and the caller routes the remainder through the generic cv2 path
 * (same prefix-count contract as npy_batch.c).
 *
 * Framework rationale (SURVEY.md section 7.3): jpeg decode throughput is
 * where the imagenet-style input rate is won or lost; the reference left
 * this loop to per-cell OpenCV calls (petastorm/codecs.py:102-130) — here
 * it is first-party native code.
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <pthread.h>
#include <setjmp.h>
#include <stddef.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <strings.h>  /* strcasecmp: POSIX, not ISO string.h */
#include <jpeglib.h>

/* clamp on the internal decode pool: beyond this, thread-spawn cost and
 * memory-bandwidth contention dominate any decode parallelism win */
#define PT_MAX_THREADS 32

struct pt_jpeg_error_mgr {
    struct jpeg_error_mgr pub;
    jmp_buf setjmp_buffer;
};

static void
pt_error_exit(j_common_ptr cinfo)
{
    struct pt_jpeg_error_mgr *err = (struct pt_jpeg_error_mgr *)cinfo->err;
    longjmp(err->setjmp_buffer, 1);
}

static void
pt_emit_message(j_common_ptr cinfo, int msg_level)
{
    /* decode warnings (e.g. premature EOF) must not write to stderr from
     * a data-loader hot loop; corrupt-data errors still longjmp out */
    (void)cinfo;
    (void)msg_level;
}

/* Decode one cell with a REUSED decompress object (created once per
 * batch: jpeg_create_decompress allocates its memory pools, and paying
 * that per cell is pure overhead in a batch loop). On success returns 0
 * and the object is ready for the next cell; on mismatch/corruption
 * returns -1 after jpeg_abort_decompress (which resets the object's
 * state while keeping its pools). The caller owns create/destroy and the
 * setjmp target: a longjmp from inside libjpeg lands in the caller so
 * the object can be destroyed exactly once.
 *
 * rows[] is a caller-provided scratch of >= height JSAMPROWs: handing
 * jpeg_read_scanlines the full remaining window per call lets turbo
 * process rec_outbuf_height rows per call instead of one. */
static int
decode_one(struct jpeg_decompress_struct *cinfo, const unsigned char *buf,
           size_t len, unsigned char *dst, int height, int width,
           JSAMPROW *rows, boolean fancy_upsampling, J_DCT_METHOD dct)
{
    size_t stride = (size_t)width * 3;
    int r;

    jpeg_mem_src(cinfo, buf, (unsigned long)len);
    if (jpeg_read_header(cinfo, TRUE) != JPEG_HEADER_OK) {
        jpeg_abort_decompress(cinfo);
        return -1;
    }
    if (cinfo->data_precision != 8 || cinfo->num_components != 3) {
        /* grayscale / CMYK / 12-bit: the Python path owns these */
        jpeg_abort_decompress(cinfo);
        return -1;
    }
    cinfo->out_color_space = JCS_RGB;
    /* FALSE selects merged chroma upsampling (the fast path); see the
     * module comment for the policy and the env escape hatch */
    cinfo->do_fancy_upsampling = fancy_upsampling;
    cinfo->dct_method = dct;
    jpeg_start_decompress(cinfo);
    if ((int)cinfo->output_height != height
        || (int)cinfo->output_width != width
        || cinfo->output_components != 3) {
        jpeg_abort_decompress(cinfo);
        return -1;
    }
    for (r = 0; r < height; r++)
        rows[r] = dst + (size_t)r * stride;
    while (cinfo->output_scanline < cinfo->output_height) {
        JDIMENSION done = cinfo->output_scanline;
        jpeg_read_scanlines(cinfo, rows + done,
                            cinfo->output_height - done);
    }
    jpeg_finish_decompress(cinfo);
    return 0;
}

/* One contiguous cell range decoded by one pool thread: each worker owns
 * its decompress object, error jmp target and row-pointer scratch, so the
 * only shared state is the disjoint output rows. `fail` is the first
 * index in [lo, hi) whose cell was rejected (== hi when the whole range
 * decoded); the dispatcher folds the per-range failures back into the
 * batch-wide decoded-prefix contract. */
struct pt_jpeg_task {
    const Py_buffer *views;
    unsigned char *out_base;
    size_t row_bytes;
    Py_ssize_t lo, hi;
    Py_ssize_t fail;
    int height, width;
    boolean fancy;
    J_DCT_METHOD dct;
};

static void *
pt_jpeg_worker(void *arg)
{
    struct pt_jpeg_task *t = (struct pt_jpeg_task *)arg;
    struct jpeg_decompress_struct cinfo;
    struct pt_jpeg_error_mgr jerr;
    JSAMPROW *rows;
    /* mutated between setjmp and a possible longjmp: must be volatile or
     * its post-longjmp value is indeterminate */
    volatile Py_ssize_t i_v = t->lo;

    t->fail = t->lo;
    rows = (JSAMPROW *)malloc(sizeof(JSAMPROW)
                              * (size_t)(t->height ? t->height : 1));
    if (rows == NULL)
        return NULL;
    cinfo.err = jpeg_std_error(&jerr.pub);
    jerr.pub.error_exit = pt_error_exit;
    jerr.pub.emit_message = pt_emit_message;
    if (setjmp(jerr.setjmp_buffer) == 0) {
        jpeg_create_decompress(&cinfo);
        for (; i_v < t->hi; i_v = i_v + 1) {
            const Py_buffer *v = &t->views[i_v];
            if (decode_one(&cinfo, (const unsigned char *)v->buf,
                           (size_t)v->len,
                           t->out_base + (size_t)i_v * t->row_bytes,
                           t->height, t->width, rows, t->fancy,
                           t->dct) != 0)
                break;
        }
        t->fail = i_v;
    } else {
        /* corrupt-data longjmp mid-cell: that cell is the failure */
        t->fail = i_v;
    }
    jpeg_destroy_decompress(&cinfo);
    free(rows);
    return NULL;
}

/* Fan n_views cells across `threads` pool threads (the calling thread is
 * worker 0) and fold the per-range failures into the batch-wide decoded
 * prefix: the first rejected index overall. Cells after that index in
 * OTHER ranges were decoded too — harmless, the caller may re-dispatch
 * them — but the prefix contract only promises the leading run. Runs
 * with the GIL released; workers never touch the Python API. */
static Py_ssize_t
pt_jpeg_run(struct pt_jpeg_task *tasks, Py_ssize_t n_tasks,
            Py_ssize_t n_views)
{
    pthread_t tids[PT_MAX_THREADS];
    int created[PT_MAX_THREADS] = {0};
    Py_ssize_t t, decoded;

    for (t = 1; t < n_tasks; t++) {
        if (pthread_create(&tids[t], NULL, pt_jpeg_worker,
                           &tasks[t]) != 0) {
            /* spawn failure: this range decodes 0 cells (fail == lo,
             * pre-set by the dispatcher) and the prefix fold below
             * reports it honestly */
            tasks[t].fail = tasks[t].lo;
            continue;
        }
        created[t] = 1;
    }
    pt_jpeg_worker(&tasks[0]);
    for (t = 1; t < n_tasks; t++) {
        if (created[t])
            pthread_join(tids[t], NULL);
    }
    decoded = n_views;
    for (t = 0; t < n_tasks; t++) {
        if (tasks[t].fail < tasks[t].hi && tasks[t].fail < decoded)
            decoded = tasks[t].fail;
    }
    return decoded;
}

static PyObject *
decode_jpeg_batch(PyObject *self, PyObject *args)
{
    PyObject *cells;
    PyObject *out_obj;
    Py_buffer out_view;
    Py_ssize_t n, i, decoded;
    Py_buffer *views = NULL;
    int height, width;
    int fancy_arg = -1;
    int threads_arg = 0;

    (void)self;
    if (!PyArg_ParseTuple(args, "OO|ii", &cells, &out_obj, &fancy_arg,
                          &threads_arg))
        return NULL;
    /* C-contiguous + ND so shape[] is populated (a plain "w*" request
     * yields a 1-D view with no shape information) */
    if (PyObject_GetBuffer(out_obj, &out_view,
                           PyBUF_WRITABLE | PyBUF_ND
                           | PyBUF_C_CONTIGUOUS) != 0)
        return NULL;

    /* out must be a C-contiguous writable (N, H, W, 3) uint8 buffer */
    if (out_view.ndim != 4 || out_view.itemsize != 1
        || out_view.shape[3] != 3) {
        PyBuffer_Release(&out_view);
        PyErr_SetString(PyExc_ValueError,
                        "out must be a C-contiguous (N, H, W, 3) uint8 array");
        return NULL;
    }
    n = out_view.shape[0];
    height = (int)out_view.shape[1];
    width = (int)out_view.shape[2];

    if (!PySequence_Check(cells) || PySequence_Size(cells) != n) {
        PyBuffer_Release(&out_view);
        PyErr_SetString(PyExc_ValueError,
                        "cells must be a sequence matching out's batch dim");
        return NULL;
    }

    /* acquire every cell's buffer up front (needs the GIL), then run the
     * whole decode loop without it */
    views = PyMem_Calloc((size_t)(n ? n : 1), sizeof(Py_buffer));
    if (views == NULL) {
        PyBuffer_Release(&out_view);
        return PyErr_NoMemory();
    }
    for (i = 0; i < n; i++) {
        PyObject *cell = PySequence_GetItem(cells, i);
        int rc;
        if (cell == NULL) {
            PyErr_Clear();  /* decode the prefix; Python path owns the rest */
            break;
        }
        rc = PyObject_GetBuffer(cell, &views[i], PyBUF_SIMPLE);
        Py_DECREF(cell);
        if (rc != 0) {
            PyErr_Clear();  /* non-buffer cell: decode the prefix only */
            break;
        }
    }
    {
        Py_ssize_t n_views = i;
        size_t row_bytes = (size_t)height * (size_t)width * 3;
        unsigned char *out_base = (unsigned char *)out_view.buf;
        struct pt_jpeg_task tasks[PT_MAX_THREADS];
        Py_ssize_t n_tasks, t, chunk;
        boolean fancy;
        J_DCT_METHOD dct;

        if (fancy_arg >= 0) {
            /* caller-selected mode (the Python calibration path) */
            fancy = fancy_arg ? TRUE : FALSE;
        } else {
            /* value-parsed, not presence-tested: FANCY=0 / FANCY=
             * must keep the merged default (docs say "set ...=1") */
            const char *fancy_env = getenv("PETASTORM_TPU_JPEG_FANCY");
            fancy = (fancy_env != NULL && fancy_env[0] != '\0'
                     && strcmp(fancy_env, "0") != 0) ? TRUE : FALSE;
        }
        /* DCT selector: "ifast" opts into turbo's fast integer DCT
         * (a further ~few-%% rate win at a small accuracy cost some
         * tf.data imagenet pipelines also take via INTEGER_FAST);
         * default ISLOW — turbo's SIMD path, and the method cv2 /
         * tf.data use by default, keeping the bit-exactness contract
         * under PETASTORM_TPU_JPEG_FANCY=1 intact. */
        {
            const char *dct_env = getenv("PETASTORM_TPU_JPEG_DCT");
            dct = (dct_env != NULL && strcasecmp(dct_env, "ifast") == 0)
                      ? JDCT_IFAST : JDCT_ISLOW;
        }
        n_tasks = threads_arg;
        if (n_tasks > PT_MAX_THREADS)
            n_tasks = PT_MAX_THREADS;
        if (n_tasks > n_views)
            n_tasks = n_views;
        if (n_tasks < 1)
            n_tasks = 1;
        chunk = (n_views + n_tasks - 1) / (n_tasks ? n_tasks : 1);
        for (t = 0; t < n_tasks; t++) {
            tasks[t].views = views;
            tasks[t].out_base = out_base;
            tasks[t].row_bytes = row_bytes;
            tasks[t].lo = t * chunk;
            tasks[t].hi = (t + 1) * chunk < n_views
                              ? (t + 1) * chunk : n_views;
            tasks[t].fail = tasks[t].lo;
            tasks[t].height = height;
            tasks[t].width = width;
            tasks[t].fancy = fancy;
            tasks[t].dct = dct;
        }
        Py_BEGIN_ALLOW_THREADS
        decoded = pt_jpeg_run(tasks, n_tasks, n_views);
        Py_END_ALLOW_THREADS

        for (i = 0; i < n_views; i++)
            PyBuffer_Release(&views[i]);
    }
    PyMem_Free(views);
    PyBuffer_Release(&out_view);
    return PyLong_FromSsize_t(decoded);
}

static PyMethodDef jpeg_batch_methods[] = {
    {"decode_jpeg_batch", decode_jpeg_batch, METH_VARARGS,
     "decode_jpeg_batch(cells, out, fancy=-1, threads=0): batched RGB "
     "JPEG decode into a preallocated (N,H,W,3) uint8 array; returns the "
     "decoded prefix count. fancy: 1 = fancy upsampling "
     "(cv2-bit-identical), 0 = merged, -1 = PETASTORM_TPU_JPEG_FANCY env "
     "default. threads > 1 fans the cells across an internal pthread "
     "pool (GIL released; one decompress object per thread)"},
    {NULL, NULL, 0, NULL}
};

static struct PyModuleDef jpeg_batch_module = {
    PyModuleDef_HEAD_INIT, "_jpeg_batch",
    "Native batched JPEG decoder (libjpeg-turbo)", -1, jpeg_batch_methods,
    NULL, NULL, NULL, NULL
};

PyMODINIT_FUNC
PyInit__jpeg_batch(void)
{
    return PyModule_Create(&jpeg_batch_module);
}
