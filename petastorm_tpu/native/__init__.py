"""First-party native (C) components for the decode hot path.

Each extension is built lazily on first use (cc via setuptools) and
cached next to the source; any build or import failure degrades silently
to the pure-Python decode path — the native layer is an accelerator,
never a dependency:

* ``_npy_batch.decode_npy_batch`` — batched ``.npy`` cell decode
  (:class:`~petastorm_tpu.codecs.NdarrayCodec`).
* ``_jpeg_batch.decode_jpeg_batch`` — batched RGB JPEG decode via
  libjpeg(-turbo) (:class:`~petastorm_tpu.codecs.CompressedImageCodec`);
  needs ``jpeglib.h`` + ``-ljpeg`` at build time.
* ``_png_batch.decode_png_batch`` — batched RGB PNG decode via libpng
  (same codec); needs ``png.h`` + ``-lpng`` at build time.
"""

import logging
import os
import sysconfig

logger = logging.getLogger(__name__)

_HERE = os.path.dirname(os.path.abspath(__file__))

#: extension name -> (source file, Extension kwargs beyond sources)
_EXTENSIONS = {
    '_npy_batch': ('npy_batch.c', {'numpy_include': True}),
    '_jpeg_batch': ('jpeg_batch.c', {'libraries': ['jpeg']}),
    '_png_batch': ('png_batch.c', {'libraries': ['png']}),
}

_loaded = {}            # name -> module
_attempted = set()      # names whose build/load already failed this process


def _build_script(name):
    """The exact setup script that builds extension ``name`` — also the
    build's IDENTITY: the script text embeds every compile/link flag, so
    hashing it (:func:`_build_identity`) captures a flag change (e.g.
    adding ``-pthread``) the .c-mtime staleness check cannot see."""
    source, opts = _EXTENSIONS[name]
    include_lines = ''
    # -pthread on both sides: the batch decoders fan cells across an
    # internal pthread pool (jpeg_batch.c / png_batch.c / npy_batch.c)
    ext_kwargs = "extra_compile_args=['-O3', '-pthread'], " \
                 "extra_link_args=['-pthread']"
    if opts.get('numpy_include'):
        include_lines = 'import numpy as np\n'
        ext_kwargs += ', include_dirs=[np.get_include()]'
    if opts.get('libraries'):
        ext_kwargs += ', libraries=%r' % (opts['libraries'],)
    return (
        'import os\n'
        'from setuptools import setup, Extension\n'
        + include_lines +
        'os.chdir(%r)\n'
        "setup(name=%r, script_args=['build_ext', '--inplace'],\n"
        '      ext_modules=[Extension(%r, [%r], %s)])\n'
    ) % (_HERE, name, name, source, ext_kwargs)


def _build_identity(name):
    """Stable fingerprint of everything that determines the built .so
    besides the C source bytes: the generated build script (flags,
    libraries, include dirs) and the interpreter's ABI tag."""
    import hashlib
    abi = sysconfig.get_config_var('EXT_SUFFIX') or '.so'
    return hashlib.md5(
        (_build_script(name) + abi).encode('utf-8')).hexdigest()


def _identity_path(name):
    return os.path.join(_HERE, name + '.buildid')


def _find_built_extension(name):
    """Path of a current compiled extension, or None.

    Two staleness probes, either of which forces a rebuild:

    * the .so is older than its C source (the exported signature may have
      changed);
    * the recorded build identity (``<name>.buildid``, written by
      :func:`_build_extension`) differs from the CURRENT build script's —
      a compiler/linker-flag change (e.g. adding ``-pthread``) must not
      load a stale extension whose binary never saw the flag. A missing
      sidecar counts as stale for the same reason: the .so predates
      identity tracking, so nothing vouches for its flags.
    """
    suffix = sysconfig.get_config_var('EXT_SUFFIX') or '.so'
    path = os.path.join(_HERE, name + suffix)
    if not os.path.exists(path):
        return None
    source = os.path.join(_HERE, _EXTENSIONS[name][0])
    try:
        if os.path.getmtime(path) < os.path.getmtime(source):
            return None
    except OSError:
        # Source missing (pruned install): a .so with no source to compare
        # against cannot be stale — use it (identity is moot too: without
        # the source a rebuild is impossible anyway).
        return path
    try:
        with open(_identity_path(name)) as f:
            recorded = f.read().strip()
    except OSError:
        return None
    if recorded != _build_identity(name):
        return None
    return path


def _build_extension(name):
    """One-shot in-tree build of one C extension.

    Serialized via an exclusive flock so concurrently-spawned pool workers
    hitting first decode don't race `build_ext --inplace` in the same
    directory (a racing build can expose a partially-written .so).
    """
    import subprocess
    import sys
    script = _build_script(name)
    lock_path = os.path.join(_HERE, '.build.lock')
    with open(lock_path, 'w') as lock_file:
        try:
            import fcntl
            fcntl.flock(lock_file.fileno(), fcntl.LOCK_EX)
        except (ImportError, OSError):
            # non-POSIX, or a filesystem without lock support (NFS ENOLCK):
            # accept the (unlikely) build race rather than disable native
            pass
        # The winner of the lock builds; losers find a fresh .so here.
        # Blocking INSIDE the flock is this function's entire point —
        # the build must finish before any waiter proceeds — and the
        # subprocess is bounded by its own timeout, so the analyzer's
        # under-lock rule is intentionally waived for this one call.
        if _find_built_extension(name) is None:
            subprocess.run(  # pipecheck: disable=blocking-under-lock
                [sys.executable, '-c', script], check=True,
                capture_output=True, timeout=120)
            # record the build identity AFTER a successful build (still
            # under the flock): the sidecar only ever describes a .so
            # that really was produced by this script
            with open(_identity_path(name), 'w') as f:
                f.write(_build_identity(name))


def native_disabled():
    """True when the ``PETASTORM_TPU_NATIVE`` kill-switch is off — the ONE
    owner of the token parse (callers that need to know why native is
    inactive, e.g. the benchmark's on/off comparison, must use this rather
    than re-parsing the env var and drifting)."""
    from petastorm_tpu.telemetry import knobs
    return knobs.is_disabled('PETASTORM_TPU_NATIVE')


def _get_extension(name):
    # Live kill-switch, checked per call (not cached): lets a benchmark or
    # an operator A/B the Python fallback against the native path in one
    # process, and disables a misbehaving native build without a rebuild.
    if native_disabled():
        return None
    if name in _loaded:
        return _loaded[name]
    if name in _attempted:
        return None
    _attempted.add(name)
    try:
        if _find_built_extension(name) is None:
            _build_extension(name)
        import importlib.util
        path = _find_built_extension(name)
        if path is None:
            return None
        spec = importlib.util.spec_from_file_location(name, path)
        module = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(module)
        _loaded[name] = module
        logger.debug('Native extension %s loaded from %s', name, path)
        return module
    except Exception:  # noqa: BLE001 - native layer is best-effort
        logger.info('Native extension %s unavailable; using the Python '
                    'decode path', name, exc_info=True)
        return None


def get_native_module():
    """The compiled ``_npy_batch`` module, or None when unavailable."""
    return _get_extension('_npy_batch')


def get_jpeg_module():
    """The compiled ``_jpeg_batch`` module, or None when unavailable."""
    return _get_extension('_jpeg_batch')


def get_png_module():
    """The compiled ``_png_batch`` module, or None when unavailable."""
    return _get_extension('_png_batch')
