"""First-party native (C) components for the decode hot path.

``decode_npy_batch`` is built lazily on first import (g++/cc via
setuptools) and cached next to the source; any build or import failure
degrades silently to the pure-Python decode path — the native layer is an
accelerator, never a dependency.
"""

import logging
import os
import sysconfig

logger = logging.getLogger(__name__)

_HERE = os.path.dirname(os.path.abspath(__file__))
_native = None
_build_attempted = False


def _find_built_extension():
    """Path of a current compiled extension, or None.

    A .so older than its C source is stale (the exported signature may have
    changed) and is treated as absent so it gets rebuilt.
    """
    suffix = sysconfig.get_config_var('EXT_SUFFIX') or '.so'
    path = os.path.join(_HERE, '_npy_batch' + suffix)
    if not os.path.exists(path):
        return None
    source = os.path.join(_HERE, 'npy_batch.c')
    try:
        if os.path.getmtime(path) < os.path.getmtime(source):
            return None
    except OSError:
        # Source missing (pruned install): a .so with no source to compare
        # against cannot be stale — use it.
        pass
    return path


def _build_extension():
    """One-shot in-tree build of the C extension.

    Serialized via an exclusive flock so concurrently-spawned pool workers
    hitting first decode don't race `build_ext --inplace` in the same
    directory (a racing build can expose a partially-written .so).
    """
    import subprocess
    import sys
    script = (
        "import os\n"
        "from setuptools import setup, Extension\n"
        "import numpy as np\n"
        "os.chdir(%r)\n"
        "setup(name='_npy_batch', script_args=['build_ext', '--inplace'],\n"
        "      ext_modules=[Extension('_npy_batch', ['npy_batch.c'],\n"
        "                             include_dirs=[np.get_include()],\n"
        "                             extra_compile_args=['-O3'])])\n"
    ) % _HERE
    lock_path = os.path.join(_HERE, '.build.lock')
    with open(lock_path, 'w') as lock_file:
        try:
            import fcntl
            fcntl.flock(lock_file.fileno(), fcntl.LOCK_EX)
        except (ImportError, OSError):
            # non-POSIX, or a filesystem without lock support (NFS ENOLCK):
            # accept the (unlikely) build race rather than disable native
            pass
        # The winner of the lock builds; losers find a fresh .so here.
        if _find_built_extension() is None:
            subprocess.run([sys.executable, '-c', script], check=True,
                           capture_output=True, timeout=120)


def get_native_module():
    """The compiled ``_npy_batch`` module, or None when unavailable."""
    global _native, _build_attempted
    if _native is not None:
        return _native
    if _build_attempted:
        return None
    _build_attempted = True
    try:
        if _find_built_extension() is None:
            _build_extension()
        import importlib.util
        path = _find_built_extension()
        if path is None:
            return None
        spec = importlib.util.spec_from_file_location('_npy_batch', path)
        module = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(module)
        _native = module
        logger.debug('Native NPY batch decoder loaded from %s', path)
    except Exception:  # noqa: BLE001 - native layer is best-effort
        logger.info('Native NPY decoder unavailable; using the Python '
                    'decode path', exc_info=True)
        return None
    return _native
