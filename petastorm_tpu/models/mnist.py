"""MNIST CNN: the framework's hello-world training consumer.

Mirror of the reference's ``examples/mnist`` (schema at
``examples/mnist/schema.py:21``, torch/tf train loops) re-done TPU-first:
flax CNN in bfloat16 compute, optax SGD, jit-compiled train step consuming
``{'image': (B,28,28,1), 'digit': (B,)}`` batches from a
:class:`~petastorm_tpu.jax.JaxLoader`.
"""

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax


class MnistCNN(nn.Module):
    """Small conv net; bfloat16 activations keep the MXU fed."""

    num_classes: int = 10
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        x = x.astype(self.dtype)
        x = nn.Conv(32, (3, 3), dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.avg_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(64, (3, 3), dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.avg_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(256, dtype=self.dtype)(x)
        x = nn.relu(x)
        # logits in f32 for a numerically stable softmax
        return nn.Dense(self.num_classes, dtype=jnp.float32)(x)


def mnist_loss(params, model, images, labels):
    logits = model.apply(params, images)
    return optax.softmax_cross_entropy_with_integer_labels(
        logits, labels).mean()


def mnist_train_step(model, optimizer):
    """Returns a jittable ``(params, opt_state, batch) -> (params, opt_state,
    loss)`` step."""

    def step(params, opt_state, images, labels):
        loss, grads = jax.value_and_grad(mnist_loss)(params, model, images,
                                                     labels)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    return step


def init_mnist(rng, batch_size=8):
    model = MnistCNN()
    images = jnp.zeros((batch_size, 28, 28, 1), jnp.float32)
    params = model.init(rng, images)
    return model, params, images
