"""Reference model families consuming petastorm_tpu loaders.

The reference ships example models (``examples/mnist/``, ``examples/imagenet``)
as consumers of its readers; here they are first-class, TPU-first: bfloat16
compute, mesh-sharded parameters, jit-compiled train steps.
"""

from petastorm_tpu.models.generate import (  # noqa: F401
    greedy_generate, sample_generate,
)
from petastorm_tpu.models.mnist import MnistCNN, mnist_train_step  # noqa: F401
from petastorm_tpu.models.transformer import (  # noqa: F401
    TransformerConfig, init_transformer_params, transformer_forward,
    transformer_masked_train_step, transformer_train_step,
)
from petastorm_tpu.models.vit import (  # noqa: F401
    ViTConfig, init_vit_params, vit_forward, vit_train_step,
)
