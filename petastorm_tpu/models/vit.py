"""Vision Transformer: the image-classification flagship consumer.

The reference ships no models at all (SURVEY.md §0 — it is an ingest
library); its image story stops at the ``examples/imagenet`` reader loop.
This ViT closes the loop TPU-first: uint8 image batches from the
:class:`~petastorm_tpu.jax.JaxLoader` (``CompressedImageCodec`` columns,
natively decoded) → on-device normalization → patch embedding → the SAME
pre-norm transformer blocks as the LM flagship
(:mod:`petastorm_tpu.models.transformer` — one block implementation serves
both model families, so the dp×tp Megatron sharding, sequence
parallelism, and pipelining machinery apply unchanged) → mean-pool →
linear classifier.

TPU notes: patchify is a reshape/transpose (no gather); all matmuls are
bf16 with f32 accumulation via the shared block code; mean-pool instead
of a CLS token keeps the sequence axis uniform (no ragged concat, XLA
fuses the reduction into the head matmul's producer).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from petastorm_tpu.models.transformer import (
    TransformerConfig, _block_forward, _constrain, _param_specs,
    _restrict_spec_to_mesh, _rmsnorm, init_transformer_params,
)


@dataclasses.dataclass(frozen=True)
class ViTConfig:
    image_size: int = 224
    patch_size: int = 16
    channels: int = 3
    n_classes: int = 1000
    d_model: int = 256
    n_heads: int = 8
    n_layers: int = 6
    d_ff: int = 1024
    dtype: object = jnp.bfloat16
    # attention implementation for the shared blocks: 'dense' or 'flash'
    # (the fused kernel runs bidirectional too; it engages only when
    # n_patches is a multiple of its 128 block — e.g. 32x32 patch grids —
    # and falls back to exact dense otherwise)
    attn_impl: str = 'dense'

    def __post_init__(self):
        if self.image_size % self.patch_size:
            raise ValueError('image_size=%d not divisible by patch_size=%d'
                             % (self.image_size, self.patch_size))
        if self.attn_impl not in ('dense', 'flash'):
            # fail where the typo is made, not later inside block_config
            raise ValueError("attn_impl must be 'dense' or 'flash'; got %r"
                             % (self.attn_impl,))

    @property
    def n_patches(self):
        return (self.image_size // self.patch_size) ** 2

    @property
    def patch_dim(self):
        return self.patch_size * self.patch_size * self.channels

    def block_config(self):
        """The shared-transformer-block view of this config."""
        return TransformerConfig(
            vocab_size=2,  # unused: ViT has no token embedding
            d_model=self.d_model, n_heads=self.n_heads,
            n_layers=self.n_layers, d_ff=self.d_ff,
            max_seq_len=self.n_patches, dtype=self.dtype,
            attn_impl=self.attn_impl)


def init_vit_params(rng, config, mesh=None):
    """Parameters; with a mesh, placed with their dp×tp shardings (the
    blocks reuse the LM transformer's Megatron specs)."""
    c = config
    k_patch, k_cls, k_blocks = jax.random.split(rng, 3)
    block_params = init_transformer_params(k_blocks, c.block_config())
    params = {
        'patch_embed': (jax.random.normal(k_patch,
                                          (c.patch_dim, c.d_model),
                                          jnp.float32)
                        * c.patch_dim ** -0.5),
        'pos_embed': (jax.random.normal(k_cls, (c.n_patches, c.d_model),
                                        jnp.float32) * 0.02),
        'blocks': block_params['blocks'],
        'ln_f': jnp.ones((c.d_model,), jnp.float32),
        'head': jnp.zeros((c.d_model, c.n_classes), jnp.float32),
    }
    if mesh is not None:
        block_specs = _param_specs(c.block_config())['blocks']
        specs = {
            'patch_embed': P(None, None),
            'pos_embed': P(None, None),
            'blocks': block_specs,
            'ln_f': P(None),
            'head': P(None, None),
        }
        params = jax.tree_util.tree_map(
            lambda x, s: jax.device_put(
                x, NamedSharding(mesh, _restrict_spec_to_mesh(s, mesh))),
            params, specs,
            is_leaf=lambda x: isinstance(x, (jnp.ndarray, np.ndarray)))
    return params


def _patchify(images, config):
    """(B, H, W, C) → (B, n_patches, patch_dim) by reshape/transpose —
    no gathers, XLA lowers this to a relayout."""
    c = config
    b = images.shape[0]
    g = c.image_size // c.patch_size
    x = images.reshape(b, g, c.patch_size, g, c.patch_size, c.channels)
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(b, g * g, c.patch_dim)


def vit_forward(params, images, config):
    """images (B, H, W, C) float in [0, 1] or normalized → logits
    (B, n_classes) f32."""
    c = config
    dtype = c.dtype
    bc = c.block_config()
    x = _patchify(images.astype(dtype), c)
    x = jnp.einsum('bpd,de->bpe', x, params['patch_embed'].astype(dtype),
                   preferred_element_type=jnp.float32).astype(dtype)
    x = x + params['pos_embed'].astype(dtype)
    x = _constrain(x)
    for block in params['blocks']:
        # bidirectional: every patch attends to every patch (a causal
        # raster-order mask would hide bottom-right content from earlier
        # positions)
        x = _block_forward(block, x, bc, causal=False)
    x = _rmsnorm(x, params['ln_f'])
    pooled = x.mean(axis=1)
    return jnp.einsum('bd,dc->bc', pooled, params['head'].astype(dtype),
                      preferred_element_type=jnp.float32)


def vit_loss(params, images, labels, config):
    import optax
    logits = vit_forward(params, images, config)
    return optax.softmax_cross_entropy_with_integer_labels(
        logits, labels).mean()


def vit_train_step(config, optimizer):
    """Jittable ``(params, opt_state, images, labels) -> (params,
    opt_state, loss)``; under a mesh the loss/grads inherit the params'
    dp×tp layout (same contract as the LM train step)."""
    import optax

    @jax.jit
    def step(params, opt_state, images, labels):
        loss, grads = jax.value_and_grad(vit_loss)(params, images, labels,
                                                   config)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    return step
