"""Decoder-only transformer LM with explicit dp×tp mesh sharding.

The flagship multi-chip consumer of the framework's sequence readout
(NGram / sequence columns → token batches). Written in pure JAX so every
parameter's mesh layout is explicit:

* attention QKV and MLP-in are column-sharded over ``'model'`` (heads /
  hidden split, no communication on the way in);
* attention-out and MLP-out are row-sharded over ``'model'`` (XLA inserts
  the psum on the way out — the standard Megatron layout);
* activations carry ``P('data', None, None)`` constraints so the batch axis
  stays data-parallel and collectives ride the ICI mesh axes.

bfloat16 matmuls with f32 accumulation (``preferred_element_type``), f32
softmax/norm statistics.
"""

import dataclasses
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from petastorm_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 256
    d_model: int = 64
    n_heads: int = 4
    n_layers: int = 2
    d_ff: int = 256
    max_seq_len: int = 128
    dtype: object = jnp.bfloat16
    # expert parallelism: n_experts > 0 swaps every block's dense FFN for a
    # Switch-MoE layer (petastorm_tpu.models.moe) with experts sharded over
    # ``expert_axis``; the Switch aux loss joins the train loss weighted by
    # ``moe_aux_weight``.
    n_experts: int = 0
    capacity_factor: float = 1.25
    moe_aux_weight: float = 0.01
    expert_axis: str = 'expert'
    # sequence/context parallelism: a mesh axis name (e.g. 'seq') shards
    # the sequence dimension of every activation; attention then runs
    # through the chosen exact strategy (global causal mask) so no single
    # chip ever holds the full sequence. Requires passing the mesh to
    # transformer_train_step/forward. seq_impl: 'ring' (ppermute KV
    # rotation — works for any head count, O(S/N) score memory) or
    # 'ulysses' (all-to-all head split — fewer collectives, needs
    # n_heads % n_seq_shards == 0).
    seq_axis: Optional[str] = None
    seq_impl: str = 'ring'
    # single-chip attention implementation: 'dense' materializes the
    # (B,H,S,S) scores (exact, runs anywhere); 'flash' uses the fused
    # Pallas kernel on TPU (ops/flash_attention.py; falls back to dense
    # off-TPU so the same config tests on the CPU mesh). Ignored when
    # seq_axis is set — ring/Ulysses own the sharded-sequence case.
    attn_impl: str = 'dense'
    # grouped-query attention: n_kv_heads < n_heads shares each K/V head
    # across a group of n_heads // n_kv_heads query heads (GQA; = 1 is
    # MQA). None means full multi-head (n_kv_heads == n_heads). Training
    # math is exactly MHA with the shared K/V repeated per group; the win
    # is the KV cache — models/generate.py stores and reads only
    # n_kv_heads, shrinking decode cache HBM (and its per-token reads) by
    # the group factor.
    n_kv_heads: Optional[int] = None
    # position encoding: 'learned' adds a trained (max_seq_len, d_model)
    # table at the embedding (the classic GPT-2 layout); 'rope' rotates
    # q/k per head-dim pair by position-dependent angles (no table — the
    # 'pos_embed' parameter disappears; positions are global even under
    # sequence parallelism, where each shard rotates by its global
    # offsets). rope_theta is the standard wavelength base.
    pos_encoding: str = 'learned'
    rope_theta: float = 10000.0
    # dense-FFN activation: 'gelu' is the classic 2-matrix MLP; 'swiglu'
    # gates it — silu(x @ W_gate) * (x @ W_in) @ W_out, adding a third
    # (d_model, d_ff) matrix. Note d_ff keeps its literal meaning (the
    # hidden width), so a swiglu block holds 1.5x the FFN params of a
    # gelu block at the same d_ff — scale d_ff by ~2/3 for param parity
    # (the standard LLaMA sizing). Dense blocks only; MoE experts own
    # their FFN (n_experts > 0 rejects this knob).
    ffn: str = 'gelu'
    # rematerialization: True wraps every block's forward in
    # jax.checkpoint, so the backward recomputes block activations
    # instead of keeping them in HBM — peak activation memory drops from
    # O(n_layers) to O(1) blocks (+ sqrt-ish recompute cost), the
    # standard lever for deeper models / longer sequences. Numerically
    # identical (the recompute replays the same ops). Applies to the
    # layered AND pipelined forwards; composes with loss_chunk (which
    # already remats the head).
    remat: bool = False
    # loss memory: 0 materializes the full (B, S, V) logits in the loss
    # (exact, simple); N > 0 computes head matmul + cross-entropy in
    # position chunks of N under jax.checkpoint, so peak HBM for the loss
    # drops from O(B*S*V) to O(B*N*V) (the backward recomputes each
    # chunk's logits). Numerically identical up to float reassociation.
    loss_chunk: int = 0

    def __post_init__(self):
        # validate at construction, not mid-trace inside layer 0's
        # attention (and even when seq_axis is unset, where the typo would
        # otherwise silently train dense)
        if self.seq_impl not in ('ring', 'ulysses'):
            raise ValueError("seq_impl must be 'ring' or 'ulysses'; got %r"
                             % (self.seq_impl,))
        if self.attn_impl not in ('dense', 'flash'):
            raise ValueError("attn_impl must be 'dense' or 'flash'; got %r"
                             % (self.attn_impl,))
        if self.n_kv_heads is not None:
            if not 1 <= self.n_kv_heads <= self.n_heads:
                raise ValueError('n_kv_heads must be in [1, n_heads=%d]; '
                                 'got %r' % (self.n_heads, self.n_kv_heads))
            if self.n_heads % self.n_kv_heads != 0:
                raise ValueError('n_heads (%d) must be a multiple of '
                                 'n_kv_heads (%d)' % (self.n_heads,
                                                      self.n_kv_heads))
        if self.pos_encoding not in ('learned', 'rope'):
            raise ValueError("pos_encoding must be 'learned' or 'rope'; "
                             'got %r' % (self.pos_encoding,))
        if (self.pos_encoding == 'rope'
                and (self.d_model // self.n_heads) % 2 != 0):
            raise ValueError('rope needs an even head_dim; got %d'
                             % (self.d_model // self.n_heads))
        if self.ffn not in ('gelu', 'swiglu'):
            raise ValueError("ffn must be 'gelu' or 'swiglu'; got %r"
                             % (self.ffn,))
        if self.ffn != 'gelu' and self.n_experts > 0:
            raise ValueError('ffn=%r applies to dense blocks only; MoE '
                             'configs (n_experts > 0) own their expert '
                             'FFN' % (self.ffn,))

    @property
    def kv_heads(self):
        """Effective K/V head count (n_kv_heads, defaulting to n_heads)."""
        return self.n_kv_heads if self.n_kv_heads is not None else self.n_heads

    def moe_config(self):
        from petastorm_tpu.models.moe import MoEConfig
        return MoEConfig(d_model=self.d_model, d_ff=self.d_ff,
                         n_experts=self.n_experts,
                         capacity_factor=self.capacity_factor,
                         dtype=self.dtype)


def _param_specs(config):
    """PartitionSpec per parameter (Megatron column/row split; MoE blocks
    shard experts over the config's expert axis instead of splitting the
    FFN over 'model')."""
    block = {
        'qkv': P(None, MODEL_AXIS),
        'attn_out': P(MODEL_AXIS, None),
        'ln1': P(None),
        'ln2': P(None),
    }
    if config.n_experts > 0:
        from petastorm_tpu.models.moe import moe_param_specs
        block['moe'] = moe_param_specs(config.moe_config(),
                                       axis=config.expert_axis)
    else:
        block['mlp_in'] = P(None, MODEL_AXIS)
        block['mlp_out'] = P(MODEL_AXIS, None)
        if config.ffn == 'swiglu':
            # a separate gate matrix (not fused into mlp_in) keeps the
            # Megatron column split even: both shard (d, d_ff/tp) and the
            # silu(gate)*up product stays shard-local
            block['mlp_gate'] = P(None, MODEL_AXIS)
    specs = {
        'embed': P(None, None),
        'blocks': [dict(block) for _ in range(config.n_layers)],
        'ln_f': P(None),
        'lm_head': P(None, MODEL_AXIS),
    }
    if config.pos_encoding == 'learned':
        specs['pos_embed'] = P(None, None)
    return specs


def init_transformer_params(rng, config, mesh=None):
    """Initialize parameters; with a mesh, each leaf is placed with its
    tensor-parallel sharding so no later reshard is needed."""
    c = config
    keys_per_layer = (3 if c.n_experts > 0
                      else 5 if c.ffn == 'swiglu' else 4)
    keys = jax.random.split(rng, 3 + keys_per_layer * c.n_layers)
    k = iter(range(len(keys)))

    def dense(key, shape, scale):
        return (jax.random.normal(keys[key], shape, jnp.float32)
                * scale).astype(jnp.float32)

    params = {
        'embed': dense(next(k), (c.vocab_size, c.d_model), 0.02),
        'blocks': [],
        'ln_f': jnp.ones((c.d_model,), jnp.float32),
    }
    if c.pos_encoding == 'learned':
        params['pos_embed'] = dense(next(k), (c.max_seq_len, c.d_model),
                                    0.02)
    else:
        next(k)  # rope has no table; keep downstream key assignment stable
    params['lm_head'] = dense(next(k), (c.d_model, c.vocab_size), 0.02)
    head_dim = c.d_model // c.n_heads
    qkv_width = (c.n_heads + 2 * c.kv_heads) * head_dim
    for _ in range(c.n_layers):
        block = {
            'qkv': dense(next(k), (c.d_model, qkv_width),
                         c.d_model ** -0.5),
            'attn_out': dense(next(k), (c.d_model, c.d_model),
                              c.d_model ** -0.5),
            'ln1': jnp.ones((c.d_model,), jnp.float32),
            'ln2': jnp.ones((c.d_model,), jnp.float32),
        }
        if c.n_experts > 0:
            from petastorm_tpu.models.moe import init_moe_params
            block['moe'] = init_moe_params(keys[next(k)], c.moe_config())
        else:
            block['mlp_in'] = dense(next(k), (c.d_model, c.d_ff),
                                    c.d_model ** -0.5)
            if c.ffn == 'swiglu':
                block['mlp_gate'] = dense(next(k), (c.d_model, c.d_ff),
                                          c.d_model ** -0.5)
            block['mlp_out'] = dense(next(k), (c.d_ff, c.d_model),
                                     c.d_ff ** -0.5)
        params['blocks'].append(block)
    if mesh is not None:
        specs = _param_specs(c)
        params = jax.tree_util.tree_map(
            lambda x, s: jax.device_put(
                x, NamedSharding(mesh, _restrict_spec_to_mesh(s, mesh))),
            params, specs,
            is_leaf=lambda x: isinstance(x, (jnp.ndarray, np.ndarray)))
    return params


def _restrict_spec_to_mesh(spec, mesh):
    """Replicate over any spec axis the mesh does not have: the same model
    runs dp×tp, dp×ep, or dp-only depending on which axes the mesh names
    (e.g. on a ('data','expert') mesh the Megatron 'model' splits become
    replication, and experts still shard)."""
    from jax.sharding import PartitionSpec
    return PartitionSpec(
        *(axis if axis in mesh.axis_names else None for axis in spec))


def _rmsnorm(x, gain):
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    return (x.astype(jnp.float32) * jax.lax.rsqrt(var + 1e-6) * gain).astype(x.dtype)


def _split_qkv(qkv, n_heads, kv_heads, head_dim):
    """Split the fused projection (…, (H + 2·KV)·Dh) → q (…, H·Dh),
    k/v (…, KV·Dh). With kv_heads == n_heads this is the classic
    third-split."""
    q_w = n_heads * head_dim
    kv_w = kv_heads * head_dim
    return (qkv[..., :q_w], qkv[..., q_w:q_w + kv_w],
            qkv[..., q_w + kv_w:])


def _rope_rotate(t_bshd, positions, theta):
    """Rotary position embedding (GPT-NeoX split-half convention):
    rotate each head-dim pair ``(i, i + Dh/2)`` of ``t`` (B, S, H, Dh) by
    ``positions``-dependent angles. ``positions`` is (S,) int — GLOBAL
    token positions, which is what makes the same function correct under
    sequence parallelism (the caller supplies the shard's global offsets).
    Rotation is per-(position, head-dim) and head-independent, so it
    commutes with GQA's K/V head expansion."""
    dh = t_bshd.shape[-1]
    half = dh // 2
    freqs = theta ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    angles = positions.astype(jnp.float32)[:, None] * freqs  # (S, half)
    cos = jnp.cos(angles)[None, :, None, :]
    sin = jnp.sin(angles)[None, :, None, :]
    t1 = t_bshd[..., :half].astype(jnp.float32)
    t2 = t_bshd[..., half:].astype(jnp.float32)
    return jnp.concatenate([t1 * cos - t2 * sin,
                            t1 * sin + t2 * cos],
                           axis=-1).astype(t_bshd.dtype)


def _expand_kv_heads(t_bshd, n_heads):
    """(B, S, KV, Dh) → (B, S, H, Dh): repeat each shared K/V head across
    its query-head group. GQA's training math IS this expansion — done
    once here, every full-head attention impl (dense/flash/ring/Ulysses)
    runs unchanged; only the decode cache path avoids it (grouped einsum
    over the un-expanded cache, models/generate.py)."""
    kv = t_bshd.shape[2]
    if kv == n_heads:
        return t_bshd
    return jnp.repeat(t_bshd, n_heads // kv, axis=2)


def _attention(x, qkv_w, out_w, n_heads, dtype, seq_axis=None, mesh=None,
               seq_impl='ring', attn_impl='dense', seq_manual=False,
               causal=True, kv_heads=None, rope_theta=None):
    b, s, d = x.shape
    head_dim = d // n_heads
    kv_heads = n_heads if kv_heads is None else kv_heads
    qkv = jnp.einsum('bsd,de->bse', x, qkv_w.astype(dtype),
                     preferred_element_type=jnp.float32).astype(dtype)
    q, k_, v = _split_qkv(qkv, n_heads, kv_heads, head_dim)
    if rope_theta is not None:
        # rotate q/k by GLOBAL positions before any attention impl or
        # K/V expansion (rotation is head-independent, so it commutes
        # with both). Inside a seq-manual shard_map region `s` is the
        # LOCAL length — each shard's offset comes from its axis index.
        positions = jnp.arange(s, dtype=jnp.int32)
        if seq_manual and seq_axis is not None:
            positions = positions + s * jax.lax.axis_index(seq_axis)
        q = _rope_rotate(q.reshape(b, s, n_heads, head_dim), positions,
                         rope_theta).reshape(b, s, -1)
        k_ = _rope_rotate(k_.reshape(b, s, kv_heads, head_dim), positions,
                          rope_theta).reshape(b, s, -1)
    if kv_heads != n_heads:
        # GQA: expand shared K/V per query-head group once, then every
        # full-head impl below runs unchanged (the decode path keeps the
        # un-expanded cache instead — models/generate.py)
        k_ = _expand_kv_heads(k_.reshape(b, s, kv_heads, head_dim),
                              n_heads).reshape(b, s, d)
        v = _expand_kv_heads(v.reshape(b, s, kv_heads, head_dim),
                             n_heads).reshape(b, s, d)

    if seq_axis is not None and seq_manual:
        # already INSIDE a shard_map manual over seq_axis (the pipelined
        # forward's pipe x seq region): call the strategies' per-device
        # bodies directly — their own shard_map wrappers cannot nest
        bshd = (b, s, n_heads, head_dim)
        if seq_impl == 'ring':
            from petastorm_tpu.ops.ring_attention import \
                _ring_attention_local
            ctx = _ring_attention_local(
                q.reshape(bshd), k_.reshape(bshd), v.reshape(bshd),
                axis_name=seq_axis, causal=causal, scale=head_dim ** -0.5)
        else:
            from petastorm_tpu.ops.ulysses_attention import _ulysses_local
            ctx = _ulysses_local(
                q.reshape(bshd), k_.reshape(bshd), v.reshape(bshd),
                axis_name=seq_axis, causal=causal, scale=head_dim ** -0.5)
        ctx = ctx.reshape(b, s, d)
    elif seq_axis is not None and mesh is not None:
        # sequence parallel: attention is the ONLY cross-token op, so it is
        # the only place the seq sharding needs special handling — both
        # strategies apply the causal mask over GLOBAL positions while the
        # S axis stays sharded over `seq_axis`
        if seq_impl == 'ring':
            from petastorm_tpu.ops.ring_attention import \
                ring_attention as seq_attention
        elif seq_impl == 'ulysses':
            from petastorm_tpu.ops.ulysses_attention import \
                ulysses_attention as seq_attention
        else:
            raise ValueError("seq_impl must be 'ring' or 'ulysses'; got %r"
                             % (seq_impl,))
        batch_axis = DATA_AXIS if DATA_AXIS in mesh.axis_names else None
        bshd = (b, s, n_heads, head_dim)
        ctx = seq_attention(q.reshape(bshd), k_.reshape(bshd),
                            v.reshape(bshd), mesh, axis_name=seq_axis,
                            causal=causal, batch_axis=batch_axis)
        ctx = ctx.reshape(b, s, d)
    elif attn_impl == 'flash':
        from petastorm_tpu.ops.flash_attention import flash_attention_fused
        bshd = (b, s, n_heads, head_dim)
        ctx = flash_attention_fused(q.reshape(bshd), k_.reshape(bshd),
                                    v.reshape(bshd), causal=causal)
        ctx = ctx.reshape(b, s, d)
    else:
        def heads(t):
            return t.reshape(b, s, n_heads, head_dim).transpose(0, 2, 1, 3)

        q, k_, v = heads(q), heads(k_), heads(v)
        scores = jnp.einsum('bhqd,bhkd->bhqk', q, k_,
                            preferred_element_type=jnp.float32)
        scores = scores / np.sqrt(head_dim)
        if causal:
            mask = jnp.tril(jnp.ones((s, s), bool))
            scores = jnp.where(mask, scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1).astype(dtype)
        ctx = jnp.einsum('bhqk,bhkd->bhqd', probs, v,
                         preferred_element_type=jnp.float32).astype(dtype)
        ctx = ctx.transpose(0, 2, 1, 3).reshape(b, s, d)
    return jnp.einsum('bsd,de->bse', ctx, out_w.astype(dtype),
                      preferred_element_type=jnp.float32).astype(dtype)


def _block_attention_half(block, x, config, mesh=None, seq_manual=False,
                          causal=True):
    """Pre-norm attention sublayer with residual + sharding constraint.

    ``seq_manual``: running inside a shard_map already manual over
    ``config.seq_axis`` (the pp×sp pipeline) — attention calls the
    strategy's per-device body, and the seq constraint (now a manual
    axis, unreachable by with_sharding_constraint) is skipped.
    ``causal=False`` gives bidirectional attention (ViT-style encoders);
    the LM paths keep the causal default."""
    h = _rmsnorm(x, block['ln1'])
    x = x + _attention(h, block['qkv'], block['attn_out'], config.n_heads,
                       config.dtype, seq_axis=config.seq_axis, mesh=mesh,
                       seq_impl=config.seq_impl, attn_impl=config.attn_impl,
                       seq_manual=seq_manual, causal=causal,
                       kv_heads=config.kv_heads,
                       rope_theta=(config.rope_theta
                                   if config.pos_encoding == 'rope'
                                   else None))
    return _constrain(x, None if seq_manual else config.seq_axis)


def _block_dense_ffn_half(block, x, config, seq_manual=False):
    """Pre-norm dense-FFN sublayer with residual + sharding constraint:
    gelu MLP, or the gated silu variant when ``config.ffn == 'swiglu'``."""
    dtype = config.dtype
    h = _rmsnorm(x, block['ln2'])
    up = jnp.einsum('bsd,df->bsf', h, block['mlp_in'].astype(dtype),
                    preferred_element_type=jnp.float32)
    if config.ffn == 'swiglu':
        gate = jnp.einsum('bsd,df->bsf', h, block['mlp_gate'].astype(dtype),
                          preferred_element_type=jnp.float32)
        h = (jax.nn.silu(gate) * up).astype(dtype)
    else:
        h = jax.nn.gelu(up.astype(jnp.float32)).astype(dtype)
    x = x + jnp.einsum('bsf,fd->bsd', h, block['mlp_out'].astype(dtype),
                       preferred_element_type=jnp.float32).astype(dtype)
    return _constrain(x, None if seq_manual else config.seq_axis)


def _block_forward(block, x, config, mesh=None, seq_manual=False,
                   causal=True):
    """One dense transformer block — shared by the layered forward, the
    pipeline stage executor, and (with ``causal=False``) the ViT."""
    x = _block_attention_half(block, x, config, mesh=mesh,
                              seq_manual=seq_manual, causal=causal)
    return _block_dense_ffn_half(block, x, config, seq_manual=seq_manual)


def _make_block_runner(config, mesh=None, seq_manual=False):
    """``(block, x) -> (x, aux_or_None)`` for one transformer block —
    the ONE place the MoE/dense branch and the ``config.remat`` wrap
    live, so the layered and pipelined forwards cannot diverge. With
    ``remat``, the whole block recomputes in the backward
    (``jax.checkpoint``): activation memory O(1) blocks."""
    c = config
    if c.n_experts > 0:
        def run_block(block, x):
            x = _block_attention_half(block, x, c, mesh=mesh,
                                      seq_manual=seq_manual)
            return _block_moe_half(block, x, c, seq=c.seq_axis,
                                   seq_manual=seq_manual)
    else:
        def run_block(block, x):
            return _block_forward(block, x, c, mesh=mesh,
                                  seq_manual=seq_manual), None
    if c.remat:
        run_block = jax.checkpoint(run_block)
    return run_block


def _block_moe_half(block, x, config, seq=None, seq_manual=False):
    """MoE FFN sublayer (RMSNorm → Switch MoE → constrained residual) —
    shared by the layered forward and the pipeline stage executor.
    ``seq_manual``: inside a shard_map manual over ``config.seq_axis``
    (pp×sp×ep) — routing goes local-per-shard with psum'd aux statistics
    (see :func:`petastorm_tpu.models.moe.moe_forward`), and the sharding
    constraint (a manual axis) is skipped. Returns ``(x, aux)``."""
    from petastorm_tpu.models.moe import moe_forward
    h = _rmsnorm(x, block['ln2'])
    ffn_out, aux = moe_forward(block['moe'], h, config.moe_config(),
                               seq_axis=config.seq_axis if seq_manual
                               else None)
    return _constrain(x + ffn_out.astype(config.dtype),
                      None if seq_manual else seq), aux


def transformer_forward_with_aux(params, tokens, config, mesh=None):
    """tokens (B, S) int32 → (logits (B, S, V) f32, scalar aux loss).

    The aux term is the summed Switch load-balancing loss over MoE blocks
    (0.0 for a dense model). ``mesh`` is required for sequence-parallel
    configs (``config.seq_axis``) so attention can run the ring collective;
    other parallelism axes need no mesh argument (constraints find the
    ambient mesh)."""
    x, aux_total = _features_with_aux(params, tokens, config, mesh=mesh)
    logits = jnp.einsum('bsd,dv->bsv', x,
                        params['lm_head'].astype(config.dtype),
                        preferred_element_type=jnp.float32)
    return logits, aux_total


def transformer_forward(params, tokens, config, mesh=None):
    """tokens (B, S) int32 → logits (B, S, V) f32."""
    return transformer_forward_with_aux(params, tokens, config, mesh=mesh)[0]


# Mesh detection uses a private jax module; resolve it ONCE at import so an
# API move degrades visibly here (module keeps working, constraint falls
# back to try/except) instead of erroring on the forward-pass hot path.
try:
    from jax._src.mesh import thread_resources as _thread_resources
except Exception:  # noqa: BLE001 - private API moved
    _thread_resources = None


def _constrain(x, seq_axis=None):
    """Keep activations data-parallel on the batch axis — and, for
    sequence-parallel configs, sequence-sharded on dim 1 — when running
    under a mesh; outside a mesh context this is a no-op. The no-mesh case
    is detected explicitly where possible — a real constraint failure must
    surface, not silently drop the sharding."""

    def build_spec(available_axes):
        dims = [DATA_AXIS if DATA_AXIS in available_axes else None]
        if x.ndim > 1:
            dims.append(seq_axis if seq_axis in available_axes else None)
        dims.extend([None] * (x.ndim - len(dims)))
        if all(d is None for d in dims):
            return None
        return P(*dims)

    if _thread_resources is not None:
        physical = _thread_resources.env.physical_mesh
        if physical.empty:
            return x
        spec = build_spec(physical.axis_names)
        if spec is None:
            return x
        return jax.lax.with_sharding_constraint(x, spec)
    spec = build_spec((DATA_AXIS, seq_axis))
    try:
        return jax.lax.with_sharding_constraint(x, spec)
    except ValueError:  # no ambient mesh
        return x


def _features_with_aux(params, tokens, config, mesh=None):
    """The forward WITHOUT the lm_head: post-``ln_f`` hidden states
    (B, S, D) + aux — the seam that lets the loss choose how to
    materialize logits."""
    c = config
    seq = c.seq_axis
    if seq is not None and mesh is None:
        raise ValueError('config.seq_axis=%r needs the mesh passed to the '
                         'forward/train step (ring attention runs a '
                         'collective over that axis)' % (seq,))
    aux_total = jnp.zeros((), jnp.float32)
    x = params['embed'][tokens].astype(c.dtype)
    if c.pos_encoding == 'learned':
        x = x + params['pos_embed'][:tokens.shape[1]].astype(c.dtype)
    x = _constrain(x, seq)

    run_block = _make_block_runner(c, mesh=mesh)
    for block in params['blocks']:
        x, aux = run_block(block, x)
        if aux is not None:
            aux_total = aux_total + aux
    return _rmsnorm(x, params['ln_f']), aux_total


def _chunked_next_token_nll(x, lm_head, targets, mask, chunk, dtype):
    """``(sum_nll, count)`` over position chunks of ``chunk``: each chunk
    runs head-matmul → log_softmax → gather under ``jax.checkpoint``, so
    the full (B, S, V) logits never exist and the backward recomputes one
    chunk's logits at a time. ``mask`` weights positions (float/bool)."""
    from jax import lax

    b, s, d = x.shape
    pad = (-s) % chunk
    if pad:
        x = jnp.pad(x, ((0, 0), (0, pad), (0, 0)))
        targets = jnp.pad(targets, ((0, 0), (0, pad)))
        mask = jnp.pad(mask, ((0, 0), (0, pad)))
    n = (s + pad) // chunk
    xs = x.reshape(b, n, chunk, d).transpose(1, 0, 2, 3)
    ts = targets.reshape(b, n, chunk).transpose(1, 0, 2)
    ms = mask.astype(jnp.float32).reshape(b, n, chunk).transpose(1, 0, 2)

    @jax.checkpoint
    def chunk_nll(xc, tc, mc):
        logits = jnp.einsum('bcd,dv->bcv', xc, lm_head.astype(dtype),
                            preferred_element_type=jnp.float32)
        logp = jax.nn.log_softmax(logits, axis=-1)
        ll = jnp.take_along_axis(logp, tc[..., None], axis=-1)[..., 0]
        return -(ll * mc).sum(), mc.sum()

    def body(carry, inp):
        nll, cnt = carry
        nll_c, cnt_c = chunk_nll(*inp)
        return (nll + nll_c, cnt + cnt_c), None

    (nll, cnt), _ = lax.scan(body, (jnp.zeros((), jnp.float32),
                                    jnp.zeros((), jnp.float32)),
                             (xs, ts, ms))
    return nll, cnt


def transformer_loss(params, tokens, config, mesh=None):
    """Next-token cross-entropy over (B, S) int token batches (+ weighted
    Switch aux loss for MoE configs). ``config.loss_chunk > 0`` computes
    it chunked (see :class:`TransformerConfig`)."""
    if config.loss_chunk > 0:
        x, aux = _features_with_aux(params, tokens[:, :-1], config,
                                    mesh=mesh)
        targets = tokens[:, 1:]
        mask = jnp.ones(targets.shape, jnp.float32)
        nll, cnt = _chunked_next_token_nll(x, params['lm_head'], targets,
                                           mask, config.loss_chunk,
                                           config.dtype)
        return nll / cnt + config.moe_aux_weight * aux
    logits, aux = transformer_forward_with_aux(params, tokens[:, :-1], config,
                                               mesh=mesh)
    targets = tokens[:, 1:]
    logp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return -ll.mean() + config.moe_aux_weight * aux


def transformer_masked_loss(params, tokens, lengths, config, mesh=None):
    """Next-token cross-entropy over PADDED (B, S) batches: position ``i``
    predicts ``i+1`` and contributes only when ``i+1 < length`` — the loss
    shape for the loader's ``pad_ragged``/``bucket_boundaries`` batches
    (``tokens`` padded to a static S, ``lengths`` the ``<field>_len``
    column). Zero-length (padding) rows contribute nothing; the mean
    normalizes by the REAL target count, so batches of different
    valid-token totals train at consistent per-token scale.

    DENSE configs only: the Switch router's load-balancing statistics are
    computed over every position, and masking them per-row is a router
    change, not a loss change — an unmasked aux would silently train the
    router to balance pad tokens and break this loss's pad-invariance."""
    if config.n_experts > 0:
        raise NotImplementedError(
            'transformer_masked_loss supports dense configs only: the '
            'Switch aux statistics would include padding positions. Use '
            'packed batches (examples.lm.pretrain_example) for MoE.')
    # target position i (0-based over the shifted S-1 axis) is real when
    # i + 1 < length; lengths can exceed S for truncated rows — the
    # comparison saturates, exactly the pad_ragged <field>_len contract
    targets = tokens[:, 1:]
    positions = jnp.arange(targets.shape[1])[None, :]
    mask = (positions + 1
            < jnp.minimum(lengths, tokens.shape[1])[:, None])
    if config.loss_chunk > 0:
        x, aux = _features_with_aux(params, tokens[:, :-1], config,
                                    mesh=mesh)
        nll, cnt = _chunked_next_token_nll(
            x, params['lm_head'], targets, mask, config.loss_chunk,
            config.dtype)
        return nll / jnp.maximum(cnt, 1) + config.moe_aux_weight * aux
    logits, aux = transformer_forward_with_aux(params, tokens[:, :-1], config,
                                               mesh=mesh)
    logp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    count = jnp.maximum(mask.sum(), 1)
    return (-(ll * mask).sum() / count
            + config.moe_aux_weight * aux)


def transformer_masked_train_step(config, optimizer, mesh=None):
    """Jittable ``(params, opt_state, tokens, lengths) -> (params,
    opt_state, loss)`` over padded/bucketed batches (see
    :func:`transformer_masked_loss`). One instance compiles per static
    ``tokens.shape`` — with ``bucket_boundaries`` that is one compile per
    bucket."""

    import optax

    @jax.jit
    def step(params, opt_state, tokens, lengths):
        loss, grads = jax.value_and_grad(transformer_masked_loss)(
            params, tokens, lengths, config, mesh)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    return step


def init_pipelined_transformer_params(rng, config, mesh, pipe_axis=None):
    """Parameters for the PIPELINE-PARALLEL transformer: blocks stacked on
    a leading ``(n_stages, layers_per_stage)`` axis pair sharded over
    ``pipe_axis``, composing with tensor-parallel splits over ``'model'``,
    expert parallelism over the config's ``expert_axis`` (MoE configs),
    and data parallelism over ``'data'`` on the same mesh — dp×pp×tp,
    pp×ep, or the full dp×pp×ep in one jitted step. (dp×pp×ep used to
    CHECK-crash XLA's SPMD partitioner on the router's take_along_axis
    gather; routing is gather-free now — ``models/moe.py`` — and the
    composition is validated against the layered oracle in
    ``dryrun_multichip``.)

    Requires ``config.n_layers % mesh.shape[pipe_axis] == 0``.
    Seq-parallel composition (pp×sp, and pp×sp×ep for MoE configs):
    configs with ``seq_axis`` set pipeline with the sequence sharded over
    that axis — the pipeline shard_map goes manual over both axes,
    attention runs the ring/Ulysses per-device body
    (``ops/ring_attention.py:48``, ``ops/ulysses_attention.py:33``)
    inside each stage, and Switch routing goes local-per-shard with
    exact psum'd aux statistics (``moe_forward(seq_axis=...)``).
    """
    from petastorm_tpu.parallel.mesh import PIPE_AXIS
    if pipe_axis is None:
        pipe_axis = PIPE_AXIS
    c = config
    n_stages = mesh.shape[pipe_axis]
    if c.n_layers % n_stages:
        raise ValueError('n_layers=%d not divisible into %d pipeline stages'
                         % (c.n_layers, n_stages))
    from petastorm_tpu.parallel.pipeline import shard_stage_params

    params = init_transformer_params(rng, c)  # unsharded, layered
    blocks = params.pop('blocks')
    per_stage = c.n_layers // n_stages

    def stack(*leaves):
        # n_layers leaves → (n_stages, layers_per_stage, *param dims);
        # tree_map over the block pytrees handles nested MoE params too
        stacked = jnp.stack(leaves)
        return stacked.reshape((n_stages, per_stage) + stacked.shape[1:])

    stages = jax.tree_util.tree_map(stack, *blocks)
    top_specs = _param_specs(c)
    block_specs = top_specs['blocks'][0]
    # dims after the stage axis: (layers_per_stage, *param dims) — the
    # layer dim replicates, the param dims keep their Megatron/expert
    # splits (PartitionSpec is a pytree leaf, so tree_map walks specs)
    inner_specs = jax.tree_util.tree_map(
        lambda spec: P(None, *_restrict_spec_to_mesh(spec, mesh)),
        block_specs)
    stages = shard_stage_params(stages, mesh, axis_name=pipe_axis,
                                inner_specs=inner_specs)

    placed = {
        name: jax.device_put(
            params[name],
            NamedSharding(mesh, _restrict_spec_to_mesh(top_specs[name],
                                                       mesh)))
        for name in ('embed', 'pos_embed', 'ln_f', 'lm_head')
        if name in params
    }
    placed['stages'] = stages
    return placed


def _pipelined_features_with_aux(params, tokens, config, mesh,
                                 pipe_axis=None, n_microbatches=None):
    """tokens (B, S) int32 → (post-``ln_f`` hidden (B, S, D), aux scalar),
    with the block stack executed as a GPipe pipeline over
    ``mesh[pipe_axis]`` (embedding and head run outside the pipeline on
    every stage's devices). MoE configs route per microbatch inside each
    stage; the aux scalar is the Switch load-balancing loss summed over
    layers, averaged over microbatches (0.0 for dense configs). Dense
    configs with ``seq_axis`` set compose pp×sp: the sequence dim
    additionally shards over that axis through the pipeline (requires the
    post-shift sequence length divisible by the seq axis size). The
    head-free seam is what lets the pipelined loss honor
    ``config.loss_chunk`` exactly like the layered one."""
    from petastorm_tpu.parallel.mesh import PIPE_AXIS
    from petastorm_tpu.parallel.pipeline import pipeline_apply

    if pipe_axis is None:
        pipe_axis = PIPE_AXIS
    c = config
    dtype = c.dtype
    per_stage = jax.tree_util.tree_leaves(params['stages'])[0].shape[1]
    moe = c.n_experts > 0
    seq = c.seq_axis

    x = params['embed'][tokens].astype(dtype)
    if c.pos_encoding == 'learned':
        x = x + params['pos_embed'][:tokens.shape[1]].astype(dtype)
    x = _constrain(x, seq)

    run_block = _make_block_runner(c, seq_manual=seq is not None)

    def stage_fn(stage_params, x):
        aux_total = jnp.zeros((), jnp.float32)
        for layer in range(per_stage):
            block = jax.tree_util.tree_map(lambda leaf: leaf[layer],
                                           stage_params)
            x, aux = run_block(block, x)
            if aux is not None:
                aux_total = aux_total + aux
        return (x, aux_total) if moe else x

    if moe:
        x, aux = pipeline_apply(stage_fn, params['stages'], x, mesh,
                                axis_name=pipe_axis,
                                n_microbatches=n_microbatches,
                                with_aux=True, seq_axis=seq)
    else:
        x = pipeline_apply(stage_fn, params['stages'], x, mesh,
                           axis_name=pipe_axis,
                           n_microbatches=n_microbatches, seq_axis=seq)
        aux = jnp.zeros((), jnp.float32)
    x = _constrain(x, seq)
    return _rmsnorm(x, params['ln_f']), aux


def pipelined_transformer_forward_with_aux(params, tokens, config, mesh,
                                           pipe_axis=None,
                                           n_microbatches=None):
    """tokens (B, S) int32 → (logits (B, S, V) f32, aux scalar) through
    the pipeline (see :func:`_pipelined_features_with_aux`)."""
    x, aux = _pipelined_features_with_aux(params, tokens, config, mesh,
                                          pipe_axis=pipe_axis,
                                          n_microbatches=n_microbatches)
    logits = jnp.einsum('bsd,dv->bsv', x,
                        params['lm_head'].astype(config.dtype),
                        preferred_element_type=jnp.float32)
    return logits, aux


def pipelined_transformer_forward(params, tokens, config, mesh,
                                  pipe_axis=None, n_microbatches=None):
    """tokens (B, S) int32 → logits (B, S, V) f32 (aux discarded)."""
    logits, _ = pipelined_transformer_forward_with_aux(
        params, tokens, config, mesh, pipe_axis=pipe_axis,
        n_microbatches=n_microbatches)
    return logits


def pipelined_transformer_train_step(config, optimizer, mesh,
                                     pipe_axis=None, n_microbatches=None):
    """Jittable dp×pp×tp (or pp×ep for MoE configs — see the mesh caveat
    on :func:`init_pipelined_transformer_params`) train step over
    stacked-stage parameters; MoE aux joins the loss exactly as in the
    layered :func:`transformer_loss`."""

    import optax

    def loss_fn(params, tokens):
        targets = tokens[:, 1:]
        if config.loss_chunk > 0:
            x, aux = _pipelined_features_with_aux(
                params, tokens[:, :-1], config, mesh, pipe_axis=pipe_axis,
                n_microbatches=n_microbatches)
            mask = jnp.ones(targets.shape, jnp.float32)
            nll, cnt = _chunked_next_token_nll(
                x, params['lm_head'], targets, mask, config.loss_chunk,
                config.dtype)
            return nll / cnt + config.moe_aux_weight * aux
        logits, aux = pipelined_transformer_forward_with_aux(
            params, tokens[:, :-1], config, mesh, pipe_axis=pipe_axis,
            n_microbatches=n_microbatches)
        logp = jax.nn.log_softmax(logits, axis=-1)
        ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
        return -ll.mean() + config.moe_aux_weight * aux

    @jax.jit
    def step(params, opt_state, tokens):
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    return step


def transformer_train_step(config, optimizer, mesh=None, donate=False,
                           accum_steps=1):
    """Jittable ``(params, opt_state, tokens) -> (params, opt_state, loss)``.

    ``mesh`` is required for sequence-parallel configs (``seq_axis``).

    ``donate=True`` donates the params/opt_state buffers to the step
    (``jax.jit(donate_argnums=(0, 1))``): XLA updates the train state in
    place, cutting peak HBM by roughly a params+opt_state copy — measured
    on a v5e-16GB, it admits a model two layers deeper at the same batch.
    The caller must then never touch the PASSED-IN state after the call
    (the standard ``state = step(state, ...)`` training-loop pattern);
    off by default because oracle tests and examples legitimately reuse
    the old params for comparisons.

    ``accum_steps=k`` gradient-accumulates: the (B, S) batch is split
    into k microbatches of B/k rows (B divisible by k), gradients are
    averaged over a ``lax.scan`` of per-microbatch backwards, and ONE
    optimizer update applies — the arithmetic of a B-row step at the
    activation memory of a B/k-row step. EXACT for dense configs (every
    position carries a target, so the microbatch mean equals the
    full-batch mean — pinned by test); for MoE configs the Switch aux
    loss becomes the mean of per-microbatch statistics, an estimator of
    (not identical to) the full-batch aux — the same semantics the
    pipelined step's microbatching has. Composes with ``config.remat``
    (which shrinks the per-microbatch activations further) and
    ``donate``."""

    import optax

    if accum_steps < 1:
        raise ValueError('accum_steps must be >= 1; got %r' % (accum_steps,))

    @partial(jax.jit, donate_argnums=(0, 1) if donate else ())
    def step(params, opt_state, tokens):
        if accum_steps == 1:
            loss, grads = jax.value_and_grad(transformer_loss)(
                params, tokens, config, mesh)
        else:
            b = tokens.shape[0]
            if b % accum_steps:
                raise ValueError('batch size %d not divisible by '
                                 'accum_steps %d' % (b, accum_steps))
            chunks = tokens.reshape(accum_steps, b // accum_steps,
                                    tokens.shape[1])

            def body(carry, chunk):
                loss_sum, grad_sum = carry
                loss, grads = jax.value_and_grad(transformer_loss)(
                    params, chunk, config, mesh)
                return (loss_sum + loss,
                        jax.tree_util.tree_map(jnp.add, grad_sum, grads)),\
                    None

            zeros = jax.tree_util.tree_map(
                lambda p: jnp.zeros(p.shape, jnp.float32), params)
            (loss_sum, grad_sum), _ = jax.lax.scan(
                body, (jnp.zeros((), jnp.float32), zeros), chunks)
            loss = loss_sum / accum_steps
            grads = jax.tree_util.tree_map(lambda g: g / accum_steps,
                                           grad_sum)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    return step
