"""Switch-style mixture-of-experts layer with expert parallelism.

Expert parallelism (EP) the TPU-native way: expert weights carry a leading
``(n_experts, ...)`` axis sharded over the mesh's ``'expert'`` axis, and
token routing is expressed as dense one-hot dispatch/combine einsums (the
GShard formulation). XLA then lowers the dispatch to the expert all-to-all
on its own — no hand-written collective, static shapes throughout (the
capacity bound makes routing jit-compatible: every expert processes exactly
``capacity`` token slots, overflow tokens are dropped and pass through on
the residual).

Top-1 (Switch) routing with the standard auxiliary load-balancing loss
``E * Σ_e f_e · p_e`` (fraction of tokens routed to e × mean router prob
of e), which is minimized at uniform routing.

The reference framework has no model layer at all (SURVEY.md §0: it is an
input pipeline); this module is part of the consumer layer that turns the
framework's batches into sharded training steps, alongside
:mod:`petastorm_tpu.models.transformer`.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from petastorm_tpu.parallel.mesh import EXPERT_AXIS


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    d_model: int = 64
    d_ff: int = 256
    n_experts: int = 4
    capacity_factor: float = 1.25
    dtype: object = jnp.bfloat16


def moe_param_specs(config, axis=EXPERT_AXIS):
    """PartitionSpec per parameter: experts shard over ``axis``, the router
    is replicated (every token scores every expert)."""
    return {
        'router': P(None, None),
        'w_in': P(axis, None, None),
        'w_out': P(axis, None, None),
    }


def init_moe_params(rng, config, mesh=None, axis=EXPERT_AXIS):
    c = config
    k_r, k_i, k_o = jax.random.split(rng, 3)
    params = {
        'router': (jax.random.normal(k_r, (c.d_model, c.n_experts),
                                     jnp.float32) * c.d_model ** -0.5),
        'w_in': (jax.random.normal(k_i, (c.n_experts, c.d_model, c.d_ff),
                                   jnp.float32) * c.d_model ** -0.5),
        'w_out': (jax.random.normal(k_o, (c.n_experts, c.d_ff, c.d_model),
                                    jnp.float32) * c.d_ff ** -0.5),
    }
    if mesh is not None:
        specs = moe_param_specs(c, axis=axis)
        params = {name: jax.device_put(value,
                                       NamedSharding(mesh, specs[name]))
                  for name, value in params.items()}
    return params


def expert_capacity(n_tokens, n_experts, capacity_factor):
    """Static per-expert token budget (ceil of the uniform share x factor)."""
    return max(1, int(np.ceil(n_tokens / n_experts * capacity_factor)))


def moe_forward(params, x, config, capacity=None, seq_axis=None):
    """Apply the MoE layer.

    :param x: (..., d_model) activations; leading axes are flattened into a
        token axis for routing and restored on return.
    :param capacity: per-expert token slots (default from
        :func:`expert_capacity`). Must be static under jit.
    :param seq_axis: name of a mesh axis this call is ALREADY MANUAL over
        (shard_map) with the token/sequence dim sharded across it — the
        pp×sp×ep pipeline. Routing and the capacity budget are then LOCAL
        to each shard's tokens (the standard sharded-MoE estimator: under
        ample capacity identical to global routing, and drops partition
        per-shard otherwise), while the aux statistics are psum-averaged
        over the axis so the load-balancing loss equals the full-sequence
        statistic exactly (equal-size shards). Leave None under auto
        sharding — XLA already computes global semantics there.
    :return: (y, aux_loss) — y shaped like ``x``; aux_loss the scalar f32
        Switch load-balancing loss.
    """
    c = config
    lead_shape = x.shape[:-1]
    tokens = x.reshape(-1, c.d_model)
    n_tokens = tokens.shape[0]
    if capacity is None:
        capacity = expert_capacity(n_tokens, c.n_experts, c.capacity_factor)

    # --- routing (f32 throughout: router decisions must not flip in bf16)
    logits = jnp.einsum('td,de->te', tokens.astype(jnp.float32),
                        params['router'].astype(jnp.float32))
    probs = jax.nn.softmax(logits, axis=-1)                     # (T, E)
    expert_idx = jnp.argmax(probs, axis=-1)                     # (T,)
    onehot = jax.nn.one_hot(expert_idx, c.n_experts, dtype=jnp.float32)
    # gate = argmax prob, via the one-hot contraction rather than a
    # take_along_axis GATHER: under a dp×pp×ep mesh (pipe manual via
    # shard_map, data+expert auto) XLA's SPMD partitioner CHECK-crashes
    # partitioning that gather (spmd_partitioner_util.cc:495, observed on
    # XLA:CPU); the contraction is also the MXU-friendly form — no
    # data-dependent addressing anywhere in the routing path.
    gate = jnp.sum(probs * onehot, axis=-1)                     # (T,)
    # position of each token within its expert's queue (0-based). Integer
    # cumsum: an f32 running count loses exactness past 2^24 tokens per
    # expert (pod-scale batches), silently merging capacity slots.
    ionehot = onehot.astype(jnp.int32)
    position = jnp.cumsum(ionehot, axis=0) * ionehot - ionehot  # (T, E)
    position = position.sum(axis=-1)                            # (T,)
    keep = position < capacity
    gate = gate * keep

    # Switch aux loss: E * sum_e (fraction routed to e) * (mean prob of e).
    # Computed BEFORE the capacity drop — it penalizes the router's intent.
    fraction = onehot.mean(axis=0)
    mean_prob = probs.mean(axis=0)
    if seq_axis is not None:
        # manual seq sharding: average the per-shard statistics BEFORE the
        # nonlinear product, so the loss is the exact full-sequence value,
        # not a mean of per-shard losses
        n_shards = jax.lax.psum(1, seq_axis)
        fraction = jax.lax.psum(fraction, seq_axis) / n_shards
        mean_prob = jax.lax.psum(mean_prob, seq_axis) / n_shards
    aux_loss = c.n_experts * jnp.sum(fraction * mean_prob)

    # --- dense dispatch/combine (GShard): (T, E, C) one-hots
    slot = jax.nn.one_hot(jnp.where(keep, position, capacity),
                          capacity, dtype=jnp.float32)          # (T, C)
    dispatch = onehot[:, :, None] * slot[:, None, :] * keep[:, None, None]
    combine = dispatch * gate[:, None, None]

    # --- expert compute: everything below carries the leading E axis, so
    # sharding 'expert' on the params makes XLA place each expert's matmul
    # on its own mesh slice and insert the dispatch all-to-all
    dtype = c.dtype
    expert_in = jnp.einsum('tec,td->ecd', dispatch.astype(dtype),
                           tokens.astype(dtype),
                           preferred_element_type=jnp.float32).astype(dtype)
    h = jnp.einsum('ecd,edf->ecf', expert_in, params['w_in'].astype(dtype),
                   preferred_element_type=jnp.float32)
    h = jax.nn.gelu(h).astype(dtype)
    expert_out = jnp.einsum('ecf,efd->ecd', h, params['w_out'].astype(dtype),
                            preferred_element_type=jnp.float32)
    y = jnp.einsum('tec,ecd->td', combine.astype(jnp.float32), expert_out,
                   preferred_element_type=jnp.float32)
    return y.reshape(lead_shape + (c.d_model,)).astype(x.dtype), aux_loss


def dense_oracle(params, x, config):
    """Unsharded, loop-based semantics oracle for tests: every token goes to
    its argmax expert with NO capacity bound; gate-weighted expert MLP."""
    c = config
    lead_shape = x.shape[:-1]
    tokens = np.asarray(x, np.float32).reshape(-1, c.d_model)
    router = np.asarray(params['router'], np.float32)
    w_in = np.asarray(params['w_in'], np.float32)
    w_out = np.asarray(params['w_out'], np.float32)

    logits = tokens @ router
    e_x = np.exp(logits - logits.max(axis=-1, keepdims=True))
    probs = e_x / e_x.sum(axis=-1, keepdims=True)
    out = np.zeros_like(tokens)
    for t in range(tokens.shape[0]):
        e = int(np.argmax(probs[t]))
        h = tokens[t] @ w_in[e]
        h = np.asarray(jax.nn.gelu(jnp.asarray(h, jnp.float32)))
        out[t] = probs[t, e] * (h @ w_out[e])
    return out.reshape(lead_shape + (c.d_model,))
