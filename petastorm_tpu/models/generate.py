"""Autoregressive decoding for the transformer LM: static KV cache + scan.

The training side (:mod:`petastorm_tpu.models.transformer`) recomputes
attention over the full sequence each step; generation would be O(S²) per
token that way. This module adds the inference half TPU-first:

* a **static-shape KV cache** ``(B, max_seq_len, KV, Dh)`` per layer
  (``KV = config.kv_heads`` — with grouped-query attention the cache and
  its per-token reads shrink by the query-group factor) —
  XLA-friendly: the cache is updated in place with
  ``lax.dynamic_update_slice`` at a traced position, no growing arrays;
* **prefill** runs the prompt through the blocks once, recording K/V;
* the **decode loop is one ``lax.scan``** over new positions (single
  trace, no per-token re-jit), each step attending to cache positions
  ``<= pos`` via an explicit mask over the static length.

Correctness is pinned by an oracle test: greedy generation must equal the
naive recompute-the-full-forward-per-token loop exactly.

Dense configs only (no MoE routing cache, no sequence sharding — decode
states are tiny; sharding them buys nothing).
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from petastorm_tpu.models.transformer import (
    _block_dense_ffn_half, _rmsnorm, _rope_rotate, _split_qkv,
)


def _split_heads(t, n_heads):
    b, s, d = t.shape
    return t.reshape(b, s, n_heads, d // n_heads)


def _block_kv(block, x, config, positions=None):
    """One block's normalized-input QKV projection → q (B, S, H, Dh),
    k/v (B, S, KV, Dh) — the same math as the training ``_attention``
    entry; with GQA (``kv_heads < n_heads``) K/V stay at their shared
    head count, which is exactly what the cache stores. With rope,
    ``positions`` (S,) rotates q/k here, so the cache stores ROTATED
    keys (the standard layout: each key's rotation is fixed at its own
    position, queries rotate at theirs as they arrive)."""
    h = _rmsnorm(x, block['ln1'])
    qkv = jnp.einsum('bsd,de->bse', h, block['qkv'].astype(config.dtype),
                     preferred_element_type=jnp.float32).astype(config.dtype)
    n, kv = config.n_heads, config.kv_heads
    q, k, v = _split_qkv(qkv, n, kv, config.d_model // n)
    q, k, v = _split_heads(q, n), _split_heads(k, kv), _split_heads(v, kv)
    if config.pos_encoding == 'rope':
        q = _rope_rotate(q, positions, config.rope_theta)
        k = _rope_rotate(k, positions, config.rope_theta)
    return q, k, v


def _attend(q, keys, values, valid_mask, out_w, config):
    """q (B, S_q, H, Dh) over ``keys``/``values`` (B, S_k, KV, Dh), masked
    by ``valid_mask`` (B, S_q, S_k). With GQA the query heads are grouped
    over their shared K/V head in the einsum itself — the cache is read
    at KV width, never materialized at H width (that per-token expansion
    would cost the exact HBM reads the smaller cache saves). Per output
    element the contraction is identical to the training path's
    expanded-heads dense attention, so oracle exactness holds. The score
    scaling is the IDENTICAL op to the training path's
    (``scores / np.sqrt(head_dim)``) — a mathematically-equal
    ``* dh**-.5`` differs in the last ulp and would make the exact-parity
    contract with the oracle seed-dependent."""
    dtype = config.dtype
    b, s_q, n, dh = q.shape
    kv = keys.shape[2]
    group = n // kv
    qg = q.reshape(b, s_q, kv, group, dh)
    scores = jnp.einsum('bqkgd,bskd->bkgqs', qg, keys,
                        preferred_element_type=jnp.float32)
    scores = scores / np.sqrt(dh)
    scores = jnp.where(valid_mask[:, None, None], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(dtype)
    ctx = jnp.einsum('bkgqs,bskd->bqkgd', probs, values,
                     preferred_element_type=jnp.float32).astype(dtype)
    ctx = ctx.reshape(b, s_q, -1)
    return jnp.einsum('bsd,de->bse', ctx, out_w.astype(dtype),
                      preferred_element_type=jnp.float32).astype(dtype)


def _head_logits(params, x_last, config):
    x = _rmsnorm(x_last, params['ln_f'])
    return jnp.einsum('bd,dv->bv', x, params['lm_head'].astype(config.dtype),
                      preferred_element_type=jnp.float32)


def greedy_generate(params, prompt, config, max_new_tokens, eos_token=None):
    """Greedy decode: prompt (B, P) int32 → (B, P + max_new_tokens).

    Requires ``P + max_new_tokens <= config.max_seq_len`` and a dense
    config. The whole decode is ONE jittable function: prefill + a
    ``lax.scan`` of single-token steps over the static KV cache.
    ``eos_token``: rows that emit it keep emitting it (finished rows
    freeze — the scan's shape stays static, the standard XLA pattern for
    early stop)."""
    return _generate(params, prompt, config, max_new_tokens, rng=None,
                     eos_token=eos_token)


def sample_generate(params, prompt, config, max_new_tokens, rng,
                    temperature=1.0, top_k=0, top_p=0.0, eos_token=None):
    """Stochastic decode: categorical sampling at ``temperature``,
    optionally restricted to the ``top_k`` highest logits (0 = full
    vocab) and/or the nucleus of cumulative probability ``top_p``
    (0 = off; both set = intersect, the common pairing). Same
    static-cache scan as :func:`greedy_generate`; ``temperature`` → 0
    recovers greedy (use :func:`greedy_generate` directly for that — it
    skips the RNG plumbing)."""
    if temperature <= 0:
        raise ValueError('temperature must be > 0; for deterministic '
                         'decoding use greedy_generate')
    if not 0.0 <= top_p <= 1.0:
        raise ValueError('top_p must be in [0, 1]; got %r' % (top_p,))
    return _generate(params, prompt, config, max_new_tokens, rng=rng,
                     temperature=temperature, top_k=top_k, top_p=top_p,
                     eos_token=eos_token)


def _select(logits, rng, temperature, top_k, top_p=0.0):
    """One next-token choice from (B, V) logits."""
    if rng is None:
        return jnp.argmax(logits, axis=-1)
    if top_k > 0:
        k = min(top_k, logits.shape[-1])  # top_k >= V = full-vocab
        if k < logits.shape[-1]:
            # O(V log k) threshold, not a full sort of the logits on the
            # per-token hot path
            kth = lax.top_k(logits, k)[0][:, -1][:, None]
            logits = jnp.where(logits >= kth, logits, -jnp.inf)
    if top_p > 0.0 and top_p < 1.0:
        # nucleus: keep the smallest prefix of descending-probability
        # tokens whose mass reaches top_p (the top token always survives)
        sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_logits / temperature, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # a token is inside while the mass BEFORE it is < top_p; the
        # threshold is the smallest logit still inside
        inside = cum - probs < top_p
        kth = jnp.min(jnp.where(inside, sorted_logits, jnp.inf), axis=-1)
        logits = jnp.where(logits >= kth[:, None], logits, -jnp.inf)
    return jax.random.categorical(rng, logits / temperature, axis=-1)


def _generate(params, prompt, config, max_new_tokens, rng,
              temperature=1.0, top_k=0, top_p=0.0, eos_token=None):
    c = config
    if c.n_experts > 0 or c.seq_axis is not None:
        raise NotImplementedError('greedy_generate/sample_generate support '
                                  'dense, unsharded-sequence configs')
    if max_new_tokens < 1:
        raise ValueError('max_new_tokens must be >= 1; got %d'
                         % max_new_tokens)
    b, p = prompt.shape
    total = p + max_new_tokens
    if total > c.max_seq_len:
        raise ValueError('prompt (%d) + max_new_tokens (%d) exceeds '
                         'max_seq_len (%d)' % (p, max_new_tokens,
                                               c.max_seq_len))
    n_layers = len(params['blocks'])
    dh = c.d_model // c.n_heads
    length = c.max_seq_len

    # -- prefill: run the prompt, record each layer's K/V ------------------
    # attention here is over the p-length prompt K/V with a plain (p, p)
    # causal mask — not over the full static cache (O(p²), not O(p·L),
    # which matters when max_seq_len >> prompt)
    x = params['embed'][prompt].astype(c.dtype)
    if c.pos_encoding == 'learned':
        x = x + params['pos_embed'][:p].astype(c.dtype)
    # GQA: the cache is (…, kv_heads, Dh) — the group factor is the whole
    # point (smaller cache HBM and per-token reads); _attend groups the
    # query heads over it without expansion
    k_cache = jnp.zeros((n_layers, b, length, c.kv_heads, dh), c.dtype)
    v_cache = jnp.zeros_like(k_cache)
    causal = jnp.broadcast_to(jnp.tril(jnp.ones((p, p), bool))[None],
                              (b, p, p))
    prefill_positions = jnp.arange(p, dtype=jnp.int32)
    for i, block in enumerate(params['blocks']):
        q, k, v = _block_kv(block, x, c, positions=prefill_positions)
        k_cache = k_cache.at[i, :, :p].set(k)
        v_cache = v_cache.at[i, :, :p].set(v)
        x = x + _attend(q, k, v, causal, block['attn_out'], c)
        x = _block_dense_ffn_half(block, x, c)
    if rng is not None:
        rng, first_rng = jax.random.split(rng)
    else:
        first_rng = None
    next_token = _select(_head_logits(params, x[:, -1], c), first_rng,
                         temperature, top_k, top_p).astype(prompt.dtype)

    # -- decode: one scan step per new token (max_new_tokens - 1 steps:
    # the prefill already decided token 1, and emitting the FRESH token
    # each step avoids a final forward whose output would be discarded)
    done0 = (jnp.zeros((b,), bool) if eos_token is None
             else next_token == eos_token)

    def step(carry, step_rng):
        k_cache, v_cache, token, pos, done = carry
        x = params['embed'][token].astype(c.dtype)
        if c.pos_encoding == 'learned':
            x = x + lax.dynamic_index_in_dim(
                params['pos_embed'], pos, keepdims=False).astype(c.dtype)
        x = x[:, None, :]  # (B, 1, D)
        valid = (jnp.arange(length) <= pos)[None, None, :]  # (1, 1, L)
        valid = jnp.broadcast_to(valid, (b, 1, length))
        for i, block in enumerate(params['blocks']):
            q, k, v = _block_kv(block, x, c, positions=pos[None])
            k_cache = lax.dynamic_update_slice(
                k_cache, k[None], (i, 0, pos, 0, 0))
            v_cache = lax.dynamic_update_slice(
                v_cache, v[None], (i, 0, pos, 0, 0))
            x = x + _attend(q, k_cache[i], v_cache[i], valid,
                            block['attn_out'], c)
            x = _block_dense_ffn_half(block, x, c)
        logits = _head_logits(params, x[:, 0], c)
        new_token = _select(logits, step_rng, temperature,
                            top_k, top_p).astype(token.dtype)
        if eos_token is not None:
            # finished rows keep emitting EOS; static shapes throughout
            new_token = jnp.where(done, jnp.asarray(eos_token,
                                                    token.dtype),
                                  new_token)
            done = done | (new_token == eos_token)
        return (k_cache, v_cache, new_token, pos + 1, done), new_token

    step_rngs = (None if rng is None
                 else jax.random.split(rng, max(max_new_tokens - 1, 1))
                 [:max_new_tokens - 1])
    _, later = lax.scan(
        step, (k_cache, v_cache, next_token, jnp.int32(p), done0),
        step_rngs, length=max_new_tokens - 1)
    generated = jnp.concatenate(
        [next_token[:, None], jnp.moveaxis(later, 0, 1)], axis=1)
    return jnp.concatenate([prompt, generated], axis=1)


def reference_greedy_generate(params, prompt, config, max_new_tokens):
    """Oracle: recompute the FULL forward for every new token (O(S²) per
    token); greedy_generate must match this exactly."""
    from petastorm_tpu.models.transformer import transformer_forward
    tokens = prompt
    for _ in range(max_new_tokens):
        logits = transformer_forward(params, tokens, config)
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(tokens.dtype)
        tokens = jnp.concatenate([tokens, nxt[:, None]], axis=1)
    return tokens
