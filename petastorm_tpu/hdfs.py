"""HDFS high-availability namenode resolution and runtime failover.

Re-design of ``petastorm/hdfs/namenode.py`` on top of fsspec/pyarrow's HDFS
driver: the reference hand-wrapped libhdfs/libhdfs3 clients and decorated
every filesystem method with failover (``namenode.py:146-239``); here HA
works in two stages:

* **connect time** — a logical nameservice from ``hdfs-site.xml`` is
  expanded to its namenode list and connection attempts round-robin
  through them (:class:`HdfsConnector`);
* **runtime** — for HA nameservices the returned filesystem is an
  :class:`HAHdfsFilesystem` proxy: any filesystem method that raises an
  I/O error mid-use reconnects to the next namenode (max
  ``MAX_FAILOVER_ATTEMPTS`` failovers per call, mirroring the reference's
  ``namenode.py:146-239`` retry decorator) and retries. The proxy is
  picklable (``namenode.py:231`` ``__reduce__`` parity), reconnecting on
  unpickle, so it rides the process-pool spawn boundary.

Configuration source: an explicit dict (e.g. from a Spark
``HadoopConfiguration``) or the site XMLs under ``$HADOOP_HOME`` /
``$HADOOP_PREFIX`` / ``$HADOOP_INSTALL`` ``etc/hadoop/``
(``namenode.py:44-57``).
"""

import functools
import logging
import os
import xml.etree.ElementTree as ET

logger = logging.getLogger(__name__)

_HADOOP_ENV_VARS = ('HADOOP_HOME', 'HADOOP_PREFIX', 'HADOOP_INSTALL')
MAX_NAMENODE_ATTEMPTS = 2
#: reconnect-to-next-namenode retries per filesystem call (reference:
#: ``petastorm/hdfs/namenode.py:40`` MAX_FAILOVER_ATTEMPTS)
MAX_FAILOVER_ATTEMPTS = 2


class HdfsConnectError(RuntimeError):
    pass


class HdfsNamenodeResolver:
    """Resolve HDFS nameservices to concrete namenode ``host:port`` lists."""

    def __init__(self, hadoop_configuration=None):
        self._hadoop_env = None
        self._hadoop_path = None
        if hadoop_configuration is None:
            hadoop_configuration = self._load_from_environment()
        self._config = hadoop_configuration or {}

    def _load_from_environment(self):
        for env in _HADOOP_ENV_VARS:
            if env in os.environ:
                self._hadoop_env = env
                self._hadoop_path = os.environ[env]
                config = {}
                for site in ('hdfs-site.xml', 'core-site.xml'):
                    self._parse_site_xml(
                        os.path.join(self._hadoop_path, 'etc', 'hadoop', site),
                        config)
                return config
        logger.warning(
            'No Hadoop configuration found (none of %s set); HDFS '
            'nameservice resolution is unavailable', ', '.join(_HADOOP_ENV_VARS))
        return {}

    @staticmethod
    def _parse_site_xml(xml_path, into):
        try:
            root = ET.parse(xml_path).getroot()
        except (OSError, ET.ParseError) as e:
            logger.debug('Could not parse %s: %s', xml_path, e)
            return
        for prop in root.iter('property'):
            name = prop.find('name')
            value = prop.find('value')
            if name is not None and value is not None:
                into[name.text] = value.text

    def resolve_hdfs_name_service(self, nameservice):
        """Namenode ``host:port`` list for a nameservice, or None when the
        name is not a configured nameservice (it may be a plain hostname)."""
        namenode_ids = self._config.get('dfs.ha.namenodes.%s' % nameservice)
        if not namenode_ids:
            return None
        addresses = []
        for nn in namenode_ids.split(','):
            key = 'dfs.namenode.rpc-address.%s.%s' % (nameservice, nn.strip())
            address = self._config.get(key)
            if not address:
                raise HdfsConnectError(
                    'Hadoop configuration declares namenode %r of '
                    'nameservice %r but provides no %r' % (nn, nameservice, key))
            addresses.append(address)
        return addresses

    def resolve_default_hdfs_service(self):
        """(nameservice, [namenode addresses]) from ``fs.defaultFS``."""
        default_fs = self._config.get('fs.defaultFS')
        if not default_fs or not default_fs.startswith('hdfs://'):
            raise HdfsConnectError(
                'fs.defaultFS is missing or not an hdfs:// URL: %r' % default_fs)
        nameservice = default_fs[len('hdfs://'):].split('/')[0]
        namenodes = self.resolve_hdfs_name_service(nameservice)
        if not namenodes:
            raise HdfsConnectError(
                'Unable to get namenodes for the default nameservice %r'
                % nameservice)
        return nameservice, namenodes


def _connect_first_alive(candidates, storage_options, connect_fn):
    """Try ``(index, address)`` candidates in order; return
    ``(filesystem, index)`` for the first that connects, else raise
    :class:`HdfsConnectError` listing every attempt."""
    errors = []
    for index, address in candidates:
        host, _, port = address.partition(':')
        try:
            fs = connect_fn(host, int(port) if port else 8020,
                            storage_options)
            return fs, index
        except Exception as e:  # noqa: BLE001 - try the next namenode
            logger.warning('Failed to connect to namenode %s: %s', address, e)
            errors.append('%s: %s' % (address, e))
    raise HdfsConnectError(
        'Could not connect to any namenode of %s; attempts: %s'
        % ([address for _, address in candidates], errors))


class HdfsConnector:
    """Round-robin connection attempts over resolved namenodes
    (reference: ``namenode.py:241-319``)."""

    @staticmethod
    def _connect_one(host, port, storage_options):
        import fsspec
        return fsspec.filesystem('hdfs', host=host, port=port,
                                 **(storage_options or {}))

    @classmethod
    def connect(cls, namenodes, storage_options=None,
                max_attempts=MAX_NAMENODE_ATTEMPTS, connect_fn=None):
        """First namenode that accepts a connection wins; each candidate is
        tried at most once, up to ``max_attempts`` candidates."""
        fs, _ = _connect_first_alive(
            list(enumerate(namenodes))[:max_attempts], storage_options,
            connect_fn or cls._connect_one)
        return fs


class HAHdfsFilesystem:
    """Failover proxy over an fsspec filesystem: reconnect + retry on I/O
    errors, rotating through the nameservice's namenodes.

    Every attribute access delegates to the live filesystem; calling a
    proxied method that raises an :class:`OSError` (other than
    :class:`FileNotFoundError` — a missing path is an answer, not an
    outage) reconnects to the NEXT namenode and retries the call, up to
    ``max_failovers`` reconnects per call. This is the fsspec-shaped
    equivalent of the reference's per-method failover decoration of its
    hand-rolled HDFS client (``petastorm/hdfs/namenode.py:146-239``).

    Picklable like the reference's ``HAHdfsClient`` (``namenode.py:231``):
    unpickling reconnects from the namenode list, so the proxy crosses the
    process-pool spawn boundary inside :class:`ParquetDatasetInfo`. A
    custom ``connect_fn`` is not pickled — reconstruction uses the default
    fsspec connector.

    File handles returned by ``open()`` bind the connection that created
    them: a handle that starts failing is not retried (re-``open`` from
    the caller, as the readers do per row-group), but the next ``open``
    fails over.
    """

    def __init__(self, namenodes, storage_options=None,
                 max_failovers=MAX_FAILOVER_ATTEMPTS, connect_fn=None):
        if not namenodes:
            raise ValueError('HAHdfsFilesystem needs at least one namenode')
        self._namenodes = list(namenodes)
        self._storage_options = storage_options
        self._max_failovers = max_failovers
        self._connect_fn = connect_fn or HdfsConnector._connect_one
        self._active = 0
        self._fs = None
        self._connect(initial=True)

    # -- connection management ----------------------------------------------

    def _connect(self, initial=False):
        """Connect to the next live namenode, starting at ``self._active``;
        every namenode is tried once before giving up."""
        n = len(self._namenodes)
        rotation = [((self._active + offset) % n,
                     self._namenodes[(self._active + offset) % n])
                    for offset in range(n)]
        self._fs, self._active = _connect_first_alive(
            rotation, self._storage_options, self._connect_fn)
        if not initial:
            logger.warning('HDFS failover: now connected to namenode %s',
                           self._namenodes[self._active])

    def _failover(self):
        self._active = (self._active + 1) % len(self._namenodes)
        self._connect()

    # -- proxying ------------------------------------------------------------

    def _call_with_failover(self, name, *args, **kwargs):
        failovers = 0
        while True:
            try:
                return getattr(self._fs, name)(*args, **kwargs)
            except FileNotFoundError:
                raise
            except OSError as e:
                if failovers >= self._max_failovers:
                    raise
                failovers += 1
                logger.warning(
                    'HDFS %s() failed on namenode %s (%s); failing over '
                    '(%d/%d)', name, self._namenodes[self._active], e,
                    failovers, self._max_failovers)
                self._failover()

    def __getattr__(self, name):
        if name.startswith('_'):
            raise AttributeError(name)
        value = getattr(self._fs, name)
        if callable(value):
            return functools.partial(self._call_with_failover, name)
        return value

    def __reduce__(self):
        return (type(self), (self._namenodes, self._storage_options,
                             self._max_failovers))

    def __repr__(self):
        return ('HAHdfsFilesystem(namenodes=%r, active=%r)'
                % (self._namenodes, self._namenodes[self._active]))


def connect_hdfs_url(url, hadoop_configuration=None, storage_options=None,
                     connect_fn=None, max_failovers=MAX_FAILOVER_ATTEMPTS):
    """(fs, path) for an ``hdfs://`` URL, expanding HA nameservices.

    * ``hdfs:///path`` → ``fs.defaultFS`` nameservice.
    * ``hdfs://nameservice/path`` (no port) → nameservice lookup, falling
      back to treating the netloc as a plain ``host``.
    * ``hdfs://host:port/path`` → direct connection.

    Multi-namenode resolutions (a real HA nameservice) return an
    :class:`HAHdfsFilesystem` with runtime failover; single-address URLs
    return the plain filesystem, matching the reference's
    HA-clients-only failover scope.
    """
    from urllib.parse import urlparse
    parsed = urlparse(url)
    resolver = HdfsNamenodeResolver(hadoop_configuration)
    if not parsed.netloc:
        _, namenodes = resolver.resolve_default_hdfs_service()
    elif ':' in parsed.netloc:
        namenodes = [parsed.netloc]
    else:
        namenodes = (resolver.resolve_hdfs_name_service(parsed.netloc)
                     or [parsed.netloc + ':8020'])
    if len(namenodes) > 1:
        fs = HAHdfsFilesystem(namenodes, storage_options,
                              max_failovers=max_failovers,
                              connect_fn=connect_fn)
    else:
        fs = HdfsConnector.connect(namenodes, storage_options,
                                   connect_fn=connect_fn)
    return fs, parsed.path
