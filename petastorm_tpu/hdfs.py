"""HDFS high-availability namenode resolution and failover.

Re-design of ``petastorm/hdfs/namenode.py`` on top of fsspec/pyarrow's HDFS
driver: the reference hand-wrapped libhdfs/libhdfs3 clients and decorated
every filesystem method with failover (``namenode.py:146-239``); here HA is
resolved **up front** — a logical nameservice from ``hdfs-site.xml`` is
expanded to its namenode list and connection attempts round-robin through
them — and the returned filesystem is a plain fsspec filesystem. (Per-call
RPC failover after a connection is established is the Hadoop client
library's own job.)

Configuration source: an explicit dict (e.g. from a Spark
``HadoopConfiguration``) or the site XMLs under ``$HADOOP_HOME`` /
``$HADOOP_PREFIX`` / ``$HADOOP_INSTALL`` ``etc/hadoop/``
(``namenode.py:44-57``).
"""

import logging
import os
import xml.etree.ElementTree as ET

logger = logging.getLogger(__name__)

_HADOOP_ENV_VARS = ('HADOOP_HOME', 'HADOOP_PREFIX', 'HADOOP_INSTALL')
MAX_NAMENODE_ATTEMPTS = 2


class HdfsConnectError(RuntimeError):
    pass


class HdfsNamenodeResolver:
    """Resolve HDFS nameservices to concrete namenode ``host:port`` lists."""

    def __init__(self, hadoop_configuration=None):
        self._hadoop_env = None
        self._hadoop_path = None
        if hadoop_configuration is None:
            hadoop_configuration = self._load_from_environment()
        self._config = hadoop_configuration or {}

    def _load_from_environment(self):
        for env in _HADOOP_ENV_VARS:
            if env in os.environ:
                self._hadoop_env = env
                self._hadoop_path = os.environ[env]
                config = {}
                for site in ('hdfs-site.xml', 'core-site.xml'):
                    self._parse_site_xml(
                        os.path.join(self._hadoop_path, 'etc', 'hadoop', site),
                        config)
                return config
        logger.warning(
            'No Hadoop configuration found (none of %s set); HDFS '
            'nameservice resolution is unavailable', ', '.join(_HADOOP_ENV_VARS))
        return {}

    @staticmethod
    def _parse_site_xml(xml_path, into):
        try:
            root = ET.parse(xml_path).getroot()
        except (OSError, ET.ParseError) as e:
            logger.debug('Could not parse %s: %s', xml_path, e)
            return
        for prop in root.iter('property'):
            name = prop.find('name')
            value = prop.find('value')
            if name is not None and value is not None:
                into[name.text] = value.text

    def resolve_hdfs_name_service(self, nameservice):
        """Namenode ``host:port`` list for a nameservice, or None when the
        name is not a configured nameservice (it may be a plain hostname)."""
        namenode_ids = self._config.get('dfs.ha.namenodes.%s' % nameservice)
        if not namenode_ids:
            return None
        addresses = []
        for nn in namenode_ids.split(','):
            key = 'dfs.namenode.rpc-address.%s.%s' % (nameservice, nn.strip())
            address = self._config.get(key)
            if not address:
                raise HdfsConnectError(
                    'Hadoop configuration declares namenode %r of '
                    'nameservice %r but provides no %r' % (nn, nameservice, key))
            addresses.append(address)
        return addresses

    def resolve_default_hdfs_service(self):
        """(nameservice, [namenode addresses]) from ``fs.defaultFS``."""
        default_fs = self._config.get('fs.defaultFS')
        if not default_fs or not default_fs.startswith('hdfs://'):
            raise HdfsConnectError(
                'fs.defaultFS is missing or not an hdfs:// URL: %r' % default_fs)
        nameservice = default_fs[len('hdfs://'):].split('/')[0]
        namenodes = self.resolve_hdfs_name_service(nameservice)
        if not namenodes:
            raise HdfsConnectError(
                'Unable to get namenodes for the default nameservice %r'
                % nameservice)
        return nameservice, namenodes


class HdfsConnector:
    """Round-robin connection attempts over resolved namenodes
    (reference: ``namenode.py:241-319``)."""

    @staticmethod
    def _connect_one(host, port, storage_options):
        import fsspec
        return fsspec.filesystem('hdfs', host=host, port=port,
                                 **(storage_options or {}))

    @classmethod
    def connect(cls, namenodes, storage_options=None,
                max_attempts=MAX_NAMENODE_ATTEMPTS, connect_fn=None):
        """First namenode that accepts a connection wins; each candidate is
        tried at most once, up to ``max_attempts`` candidates."""
        connect_fn = connect_fn or cls._connect_one
        errors = []
        for address in namenodes[:max_attempts]:
            host, _, port = address.partition(':')
            try:
                return connect_fn(host, int(port) if port else 8020,
                                  storage_options)
            except Exception as e:  # noqa: BLE001 - try the next namenode
                logger.warning('Failed to connect to namenode %s: %s',
                               address, e)
                errors.append('%s: %s' % (address, e))
        raise HdfsConnectError(
            'Could not connect to any namenode of %s; attempts: %s'
            % (namenodes, errors))


def connect_hdfs_url(url, hadoop_configuration=None, storage_options=None,
                     connect_fn=None):
    """(fs, path) for an ``hdfs://`` URL, expanding HA nameservices.

    * ``hdfs:///path`` → ``fs.defaultFS`` nameservice.
    * ``hdfs://nameservice/path`` (no port) → nameservice lookup, falling
      back to treating the netloc as a plain ``host``.
    * ``hdfs://host:port/path`` → direct connection.
    """
    from urllib.parse import urlparse
    parsed = urlparse(url)
    resolver = HdfsNamenodeResolver(hadoop_configuration)
    if not parsed.netloc:
        _, namenodes = resolver.resolve_default_hdfs_service()
    elif ':' in parsed.netloc:
        namenodes = [parsed.netloc]
    else:
        namenodes = (resolver.resolve_hdfs_name_service(parsed.netloc)
                     or [parsed.netloc + ':8020'])
    fs = HdfsConnector.connect(namenodes, storage_options,
                               connect_fn=connect_fn)
    return fs, parsed.path
