"""Deterministic weighted interleave: the arithmetic heart of the mixture.

The mixture engine must answer one question for any stream position
``p``: *which source supplies document p?* — and the answer must be a
pure function of ``(seed, weights, p)``. An RNG draw (what
:class:`~petastorm_tpu.weighted_sampling_reader.WeightedSamplingReader`
does) cannot give that: the readahead mirror would have to replay the
generator, any consumer reshard would have to ship generator state, and
two ranks could never agree on position ``p`` without agreeing on every
position before it.

:class:`InterleaveSchedule` instead runs a *smooth weighted round-robin*
(the credit-counter schedule used by nginx/LVS, equivalent to walking
the Stern-Brocot mediant tree for two sources): every source carries an
integer credit; each step adds the source's weight numerator to its
credit, emits the source with the largest credit, and charges the
emitted source the common denominator. All arithmetic is exact integer
arithmetic over a common denominator (weights pass through
:class:`fractions.Fraction`), so there is no float drift, the state is
JSON-exact, and the realized mix obeys a hard deviation bound: after
``p`` emissions source ``i`` has been chosen ``p * f_i ± O(1)`` times
(``f_i`` the normalized weight) — not merely in expectation, always.

The ``seed`` perturbs the schedule without touching the guarantee: it
derives a tie-break priority permutation and the initial credit
offsets, so different seeds produce different (but individually
deterministic) interleavings of the same weights.

State is ``{'position', 'credits'}`` — O(sources) and O(1) to resume:
``from_state`` continues the emission sequence exactly where the
snapshot left it, which is what lets a mixture checkpoint re-shard at
interleave-position granularity.
"""

from fractions import Fraction

import numpy as np

_STATE_VERSION = 1


def _normalize_weights(weights):
    """Per-source integer weight numerators over a common denominator.

    Returns ``(numerators, total)`` with ``numerators[i] / total`` equal
    to the exact normalized weight of source ``i``.
    """
    if not weights:
        raise ValueError('Interleave needs at least one source weight')
    fracs = []
    for w in weights:
        f = Fraction(str(w)) if isinstance(w, float) else Fraction(w)
        if f <= 0:
            raise ValueError('Source weights must be positive, got %r' % (w,))
        # Bound the integers: float weights like 0.30000000000000004 would
        # otherwise blow the common denominator into hundreds of digits.
        fracs.append(f.limit_denominator(1 << 20))
    total = sum(fracs)
    fracs = [f / total for f in fracs]
    denom = 1
    for f in fracs:
        denom = denom * f.denominator // _gcd(denom, f.denominator)
    nums = [int(f * denom) for f in fracs]
    return nums, sum(nums)


def _gcd(a, b):
    while b:
        a, b = b, a % b
    return a


class InterleaveSchedule:
    """Constant-memory deterministic source order for a weighted mixture.

    ``schedule.next()`` advances one position and returns the source
    index; ``schedule.peek(k)`` previews the next ``k`` source indices
    without advancing (what the readahead plan consumes);
    ``InterleaveSchedule.order(weights, seed, start, k)`` is the pure
    classmethod form — source order for positions ``start..start+k`` with
    no instance state at all.
    """

    def __init__(self, weights, seed=0):
        self._weights = list(weights)
        self._seed = int(seed)
        self._nums, self._total = _normalize_weights(self._weights)
        n = len(self._nums)
        rng = np.random.RandomState(self._seed % (2 ** 32))
        # Lower tie_rank wins credit ties; a seed-derived permutation so
        # equal-weight sources do not always break toward index order.
        self._tie_rank = [int(r) for r in np.argsort(rng.permutation(n))]
        # Initial credit offsets stagger the first emissions per seed.
        # Each offset is strictly below the source's own refill so no
        # source starts more than one emission ahead of its entitlement.
        self._init_credits = [
            int(rng.randint(0, max(1, num))) for num in self._nums]
        self._credits = list(self._init_credits)
        self._position = 0

    # -- core arithmetic ---------------------------------------------------

    def _step(self, credits):
        """Advance ``credits`` in place one emission; return the source."""
        for i, num in enumerate(self._nums):
            credits[i] += num
        best = 0
        for i in range(1, len(credits)):
            if (credits[i], -self._tie_rank[i]) > (
                    credits[best], -self._tie_rank[best]):
                best = i
        credits[best] -= self._total
        return best

    # -- public surface ----------------------------------------------------

    @property
    def num_sources(self):
        return len(self._nums)

    @property
    def position(self):
        """Number of emissions so far == the next stream position."""
        return self._position

    @property
    def fractions(self):
        """Exact normalized weights as floats (for telemetry/bench)."""
        return [num / self._total for num in self._nums]

    def next(self):
        """Source index for the current position; advances by one."""
        src = self._step(self._credits)
        self._position += 1
        return src

    def peek(self, k):
        """Source indices for the next ``k`` positions, without advancing."""
        credits = list(self._credits)
        return [self._step(credits) for _ in range(int(k))]

    def source_at(self, position):
        """Source index at absolute ``position`` — pure in (seed, weights,
        position). Replays from position 0, so it is O(position): use
        :meth:`peek`/:meth:`next` for streaming access and keep this for
        spot checks and oracle tests."""
        position = int(position)
        if position < 0:
            raise ValueError('position must be >= 0')
        credits = list(self._init_credits)
        src = None
        for _ in range(position + 1):
            src = self._step(credits)
        return src

    def reset(self):
        """Rewind to position 0 (same seed, same order)."""
        self._credits = list(self._init_credits)
        self._position = 0

    # -- state -------------------------------------------------------------

    def state_dict(self):
        return {
            'version': _STATE_VERSION,
            'position': self._position,
            'credits': list(self._credits),
        }

    def load_state_dict(self, state):
        if int(state.get('version', 0)) != _STATE_VERSION:
            raise ValueError(
                'Unsupported interleave state version %r' %
                (state.get('version'),))
        credits = [int(c) for c in state['credits']]
        if len(credits) != len(self._nums):
            raise ValueError(
                'Interleave state carries %d sources, schedule has %d' %
                (len(credits), len(self._nums)))
        self._credits = credits
        self._position = int(state['position'])

    @classmethod
    def from_state(cls, weights, seed, state):
        schedule = cls(weights, seed=seed)
        schedule.load_state_dict(state)
        return schedule

    @classmethod
    def order(cls, weights, seed, start, k):
        """Pure source order for positions ``start .. start + k - 1``."""
        schedule = cls(weights, seed=seed)
        credits = list(schedule._init_credits)
        for _ in range(int(start)):
            schedule._step(credits)
        return [schedule._step(credits) for _ in range(int(k))]


def realized_deviation(order, weights):
    """Max over prefixes and sources of ``|count_i(p) - p * f_i|`` — the
    smoothness figure the bench reports for interleave-vs-RNG divergence."""
    nums, total = _normalize_weights(weights)
    fractions = [num / total for num in nums]
    counts = [0] * len(nums)
    worst = 0.0
    for p, src in enumerate(order, start=1):
        counts[src] += 1
        for i, f in enumerate(fractions):
            worst = max(worst, abs(counts[i] - p * f))
    return worst
