"""Token-budget sequence packing: variable-length docs -> fixed rows.

LM pretraining consumes fixed ``(seq_len,)`` rows; Parquet delivers
variable-length token documents. :class:`SequencePacker` bridges them
with greedy first-fit over a *bounded* set of open bins:

* a document is placed whole into the first open bin with room
  (first-fit keeps placement deterministic and O(open_bins));
* when no open bin has room and the open set is below its bound, a new
  bin opens;
* when the open set is at its bound, the document is *split*: a prefix
  fills the oldest open bin exactly (emitting it with zero padding) and
  the tail carries over into a fresh bin — so a bounded open set never
  forces padding mid-stream, it only trades padding for splits;
* ``flush()`` pads and emits whatever is still open at end of stream.

Each emitted row carries three aligned ``(seq_len,)`` arrays:

``tokens``
    the packed token ids, padded with ``pad_id``;
``loss_mask``
    1 for real tokens, 0 for padding — the training loss multiplier;
``segment_ids``
    1-based per-row document segment numbering (0 for padding), the
    input to block-diagonal attention masking so packed documents do
    not attend across boundaries.

Emission order is deterministic: bins emit the moment they fill, in
fill order, and ``flush()`` emits the remaining open bins oldest-first.
Every row therefore has a well-defined global ordinal — the unit at
which the mixture re-shards across consumer counts.

Packing work is accounted under the canonical ``pack`` stage and the
``petastorm_tpu_pack_*`` counters; :attr:`SequencePacker.stats`
summarizes fill ratio, docs/row, and truncation counts for the
``mixture_stream`` bench section.

Packer state (open-bin contents plus counters) is JSON-safe and small —
it is the "packer carry" leg of the mixture checkpoint.
"""

import numpy as np

from petastorm_tpu.telemetry import get_registry, knobs, metrics_disabled, span

PACK_ROWS = 'petastorm_tpu_pack_rows_total'
PACK_TOKENS = 'petastorm_tpu_pack_tokens_total'
PACK_PADDING_TOKENS = 'petastorm_tpu_pack_padding_tokens_total'
PACK_SPLIT_DOCS = 'petastorm_tpu_pack_split_docs_total'

_STATE_VERSION = 1

#: Default bound on the open-bin set (overridable per instance and via
#: the PETASTORM_TPU_MIXTURE_OPEN_BINS knob).
DEFAULT_OPEN_BINS = 4


class _Bin:
    """One open row under construction: a list of document segments."""

    __slots__ = ('segments',)

    def __init__(self, segments=None):
        self.segments = segments if segments is not None else []

    def used(self):
        return sum(len(s) for s in self.segments)


class SequencePacker:
    """Pack variable-length token documents into fixed ``seq_len`` rows.

    Feed documents with :meth:`feed` (returns zero or more completed
    rows), then :meth:`flush` at end of stream. ``state_dict`` /
    ``load_state_dict`` round-trip the open-bin carry exactly.
    """

    def __init__(self, seq_len, open_bins=None, pad_id=0, dtype=np.int32):
        if int(seq_len) <= 0:
            raise ValueError('seq_len must be positive, got %r' % (seq_len,))
        if open_bins is None:
            open_bins = knobs.get_int(
                'PETASTORM_TPU_MIXTURE_OPEN_BINS', DEFAULT_OPEN_BINS, floor=1)
        if int(open_bins) < 1:
            raise ValueError('open_bins must be >= 1, got %r' % (open_bins,))
        self._seq_len = int(seq_len)
        self._open_bins = int(open_bins)
        self._pad_id = int(pad_id)
        self._dtype = np.dtype(dtype)
        self._bins = []
        # Counters (lifetime of the packer; round-tripped by state_dict).
        self._docs = 0
        self._split_docs = 0
        self._rows = 0
        self._tokens = 0
        self._padding = 0

    # -- packing -----------------------------------------------------------

    @property
    def seq_len(self):
        return self._seq_len

    def feed(self, doc):
        """Pack one document; return the list of rows completed by it."""
        tokens = [int(t) for t in np.asarray(doc).ravel().tolist()]
        if not tokens:
            return []
        with span('pack'):
            return self._feed(tokens)

    def _feed(self, tokens):
        self._docs += 1
        self._tokens += len(tokens)
        emitted = []
        pieces = 0
        while tokens:
            placed = False
            for idx, b in enumerate(self._bins):
                free = self._seq_len - b.used()
                if free >= len(tokens):
                    b.segments.append(tokens)
                    tokens = []
                    if free == len(b.segments[-1]):
                        emitted.append(self._emit(idx))
                    placed = True
                    break
            if placed:
                break
            if len(self._bins) < self._open_bins:
                b = _Bin()
                self._bins.append(b)
                take = min(self._seq_len, len(tokens))
                b.segments.append(tokens[:take])
                tokens = tokens[take:]
                if take == self._seq_len:
                    emitted.append(self._emit(len(self._bins) - 1))
            else:
                # Open set at its bound: fill the oldest bin exactly and
                # carry the tail — padding-free, at the price of a split.
                b = self._bins[0]
                take = self._seq_len - b.used()
                b.segments.append(tokens[:take])
                tokens = tokens[take:]
                emitted.append(self._emit(0))
            pieces += 1
        if pieces > 1:
            self._split_docs += 1
            if not metrics_disabled():
                get_registry().counter(PACK_SPLIT_DOCS).inc()
        return emitted

    def flush(self):
        """Emit (padded) every remaining open bin, oldest first."""
        with span('pack'):
            rows = []
            while self._bins:
                rows.append(self._emit(0, pad=True))
            return rows

    def _emit(self, idx, pad=False):
        b = self._bins.pop(idx)
        tokens = np.full(self._seq_len, self._pad_id, dtype=self._dtype)
        loss_mask = np.zeros(self._seq_len, dtype=np.int32)
        segment_ids = np.zeros(self._seq_len, dtype=np.int32)
        cursor = 0
        for seg_no, seg in enumerate(b.segments, start=1):
            tokens[cursor:cursor + len(seg)] = seg
            loss_mask[cursor:cursor + len(seg)] = 1
            segment_ids[cursor:cursor + len(seg)] = seg_no
            cursor += len(seg)
        padding = self._seq_len - cursor
        if padding and not pad:
            raise AssertionError('non-flush emit of a partially full bin')
        self._rows += 1
        self._padding += padding
        if not metrics_disabled():
            registry = get_registry()
            registry.counter(PACK_ROWS).inc()
            registry.counter(PACK_TOKENS).inc(cursor)
            if padding:
                registry.counter(PACK_PADDING_TOKENS).inc(padding)
        return {
            'tokens': tokens,
            'loss_mask': loss_mask,
            'segment_ids': segment_ids,
        }

    # -- telemetry ---------------------------------------------------------

    @property
    def stats(self):
        """Pack telemetry: rows/docs/tokens, fill ratio, docs per row."""
        emitted_tokens = self._rows * self._seq_len
        real = emitted_tokens - self._padding
        return {
            'rows': self._rows,
            'docs': self._docs,
            'split_docs': self._split_docs,
            'tokens': self._tokens,
            'padding_tokens': self._padding,
            'fill_ratio': (real / emitted_tokens) if emitted_tokens else 0.0,
            'docs_per_row': (self._docs / self._rows) if self._rows else 0.0,
            'carried_tokens': sum(b.used() for b in self._bins),
            'open_bins': len(self._bins),
        }

    # -- state -------------------------------------------------------------

    def state_dict(self):
        return {
            'version': _STATE_VERSION,
            'seq_len': self._seq_len,
            'pad_id': self._pad_id,
            'bins': [[list(seg) for seg in b.segments] for b in self._bins],
            'counters': {
                'docs': self._docs,
                'split_docs': self._split_docs,
                'rows': self._rows,
                'tokens': self._tokens,
                'padding': self._padding,
            },
        }

    def load_state_dict(self, state):
        if int(state.get('version', 0)) != _STATE_VERSION:
            raise ValueError(
                'Unsupported packer state version %r' % (state.get('version'),))
        if int(state['seq_len']) != self._seq_len:
            raise ValueError(
                'Packer state seq_len %r != configured %r' %
                (state['seq_len'], self._seq_len))
        self._bins = [
            _Bin([[int(t) for t in seg] for seg in segments])
            for segments in state['bins']]
        counters = state.get('counters', {})
        self._docs = int(counters.get('docs', 0))
        self._split_docs = int(counters.get('split_docs', 0))
        self._rows = int(counters.get('rows', 0))
        self._tokens = int(counters.get('tokens', 0))
        self._padding = int(counters.get('padding', 0))
