"""MixtureSpec: the declarative description of a multi-source stream.

A mixture is a list of named, weighted sources plus the global knobs
that make the stream reproducible: the interleave ``seed``, the packed
row length ``seq_len`` (``None`` streams raw documents), and the column
``token_field`` holding each row's token array. The spec is pure data —
it builds no readers — so it can be pickled to workers, embedded in a
checkpoint fingerprint, and compared across ranks.

Each :class:`MixtureSource` names either a ``url`` (read with
``make_batch_reader``) or a ``reader_factory`` callable (anything that
returns a Reader-compatible object, e.g. a partial over
``make_batch_reader`` with a daemon-backed pool). The engine normalizes
weights exactly (see :mod:`petastorm_tpu.mixture.interleave`), so
weights may be any positive numbers — ``[3, 1]`` and ``[0.75, 0.25]``
describe the same mixture.

:meth:`MixtureSpec.fingerprint` digests the fields that define the
*stream identity* (names, weights, seed, seq_len, token_field). A
checkpoint records it and restore refuses a mismatch: loading tomato
state into a potato mixture is a silent-corruption bug the paper's
reproducibility contract (PAPERS.md, arxiv 2604.21275) exists to
prevent.
"""

import hashlib
import json


class MixtureSource:
    """One weighted leg of a mixture."""

    def __init__(self, name, weight, url=None, reader_factory=None,
                 reader_kwargs=None):
        if not name:
            raise ValueError('Every mixture source needs a name')
        if weight is None or float(weight) <= 0:
            raise ValueError(
                'Source %r weight must be positive, got %r' % (name, weight))
        if (url is None) == (reader_factory is None):
            raise ValueError(
                'Source %r needs exactly one of url= or reader_factory=' %
                (name,))
        self.name = str(name)
        self.weight = weight
        self.url = url
        self.reader_factory = reader_factory
        self.reader_kwargs = dict(reader_kwargs or {})

    def __repr__(self):
        return 'MixtureSource(name=%r, weight=%r, url=%r)' % (
            self.name, self.weight, self.url)


class MixtureSpec:
    """Sources + seed + packing geometry of a deterministic mixture."""

    def __init__(self, sources, seed=0, seq_len=None, token_field='tokens',
                 open_bins=None, pad_id=0):
        sources = list(sources)
        if not sources:
            raise ValueError('A mixture needs at least one source')
        for source in sources:
            if not isinstance(source, MixtureSource):
                raise TypeError(
                    'sources must be MixtureSource instances, got %r' %
                    (source,))
        names = [s.name for s in sources]
        if len(set(names)) != len(names):
            raise ValueError('Duplicate source names: %r' % (names,))
        if seq_len is not None and int(seq_len) <= 0:
            raise ValueError('seq_len must be positive, got %r' % (seq_len,))
        self.sources = sources
        self.seed = int(seed)
        self.seq_len = int(seq_len) if seq_len is not None else None
        self.token_field = str(token_field)
        self.open_bins = open_bins
        self.pad_id = int(pad_id)

    @property
    def weights(self):
        return [s.weight for s in self.sources]

    @property
    def names(self):
        return [s.name for s in self.sources]

    def fingerprint(self):
        """Stable digest of the stream-identity fields."""
        payload = json.dumps({
            'names': self.names,
            'weights': [str(w) for w in self.weights],
            'seed': self.seed,
            'seq_len': self.seq_len,
            'token_field': self.token_field,
        }, sort_keys=True)
        return hashlib.sha256(payload.encode('utf-8')).hexdigest()[:16]

    def __repr__(self):
        return 'MixtureSpec(%d sources, seed=%d, seq_len=%r)' % (
            len(self.sources), self.seed, self.seq_len)
