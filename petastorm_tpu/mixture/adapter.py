"""MixtureBatchReader: the Reader-shaped face of a MixtureStream.

``make_jax_loader(mixture=...)`` needs the mixture to look like a
batched reader — ``batched_output``, ``next_batch_info`` provenance,
the ``resume_state_from`` / ``consumption_record_for_resume`` resume
protocol, ``reset/stop/join/diagnostics/schema``. This adapter provides
exactly that surface over a :class:`~petastorm_tpu.mixture.engine.
MixtureStream` of packed rows.

Provenance here is *pull ordinals*, not row-group items: each
``next_batch_info`` call fixes ``rows_per_pull`` packed rows into one
batch and tags it with a monotonically increasing pull id (epoch is
always 0 — the mixture's own epoch arithmetic lives in its sources).
Before producing pull ``k`` the adapter snapshots the stream's
``state_dict``; ``resume_state_from(delivered)`` then answers the
JaxLoader's delivery-accurate checkpoint question — "rewind to the
earliest pull the consumer has NOT fully received" — by returning that
pull's pre-snapshot. Snapshots are bounded (``snapshot_window`` pulls):
the loader's buffering is bounded by prefetch + shuffle capacity, so a
window of a few hundred pulls covers it with a loud error past it.

Resume is exact when the loader delivers pulls in order (no row
shuffling); a shuffling loader interleaves rows of different pulls, so
its delivered-set can have gaps and resume degrades to the package-wide
at-least-once contract.
"""

import numpy as np

from petastorm_tpu.mixture.engine import MixtureStream

#: How many per-pull stream snapshots resume_state_from can rewind to.
DEFAULT_SNAPSHOT_WINDOW = 512


class _MixtureSchema:
    """Minimal schema surface: named fields, no codecs."""

    def __init__(self, names):
        self.fields = {name: None for name in names}

    def make_namedtuple(self, **kwargs):
        raise TypeError('Mixture batches are plain dicts, not namedtuples')


class MixtureBatchReader:
    """Batched-reader adapter over a :class:`MixtureStream`."""

    batched_output = True
    ngram = None

    def __init__(self, stream, rows_per_pull=64,
                 snapshot_window=DEFAULT_SNAPSHOT_WINDOW):
        if not isinstance(stream, MixtureStream):
            raise TypeError('stream must be a MixtureStream, got %r'
                            % (stream,))
        if stream.spec.seq_len is None:
            raise ValueError(
                'make_jax_loader(mixture=...) needs packed rows: give the '
                'MixtureSpec a seq_len (raw ragged documents cannot batch)')
        self._stream = stream
        self._rows = max(1, int(rows_per_pull))
        self._window = max(1, int(snapshot_window))
        self._schema = _MixtureSchema(('tokens', 'loss_mask', 'segment_ids'))
        self._snapshots = {}
        self._next_pull = 0
        self.last_row_consumed = False
        self._stopped = False

    # -- reader surface ----------------------------------------------------

    @property
    def schema(self):
        return self._schema

    @property
    def stream(self):
        return self._stream

    @property
    def cur_shard(self):
        return self._stream.cur_shard

    @property
    def shard_count(self):
        return self._stream.shard_count

    @property
    def num_epochs(self):
        return 1

    @property
    def diagnostics(self):
        return dict(self._stream.diagnostics)

    def __iter__(self):
        return self

    def __next__(self):
        columns, _, _ = self.next_batch_info()
        return columns

    def next_batch_info(self):
        if self._stopped:
            raise RuntimeError('Trying to read from a stopped mixture reader')
        snapshot = self._stream.state_dict()
        rows = []
        try:
            while len(rows) < self._rows:
                rows.append(next(self._stream))
        except StopIteration:
            if not rows:
                self.last_row_consumed = True
                raise StopIteration from None
        pull = self._next_pull
        self._next_pull += 1
        self._snapshots[pull] = snapshot
        while len(self._snapshots) > self._window:
            del self._snapshots[min(self._snapshots)]
        columns = {
            field: np.stack([row[field] for row in rows])
            for field in self._schema.fields}
        return columns, pull, 0

    # -- resume protocol ---------------------------------------------------

    def state_dict(self):
        state = dict(self._stream.state_dict())
        state['pull_base'] = self._next_pull
        return state

    def resume_state_from(self, delivered):
        """Stream state rewound to the earliest UNdelivered pull."""
        done = set()
        for items in delivered.values():
            done.update(int(i) for i in items)
        cursor = 0
        while cursor in done:
            cursor += 1
        if cursor >= self._next_pull:
            # everything produced so far was delivered: the current
            # stream position IS the resume point
            state = dict(self._stream.state_dict())
        else:
            snapshot = self._snapshots.get(cursor)
            if snapshot is None:
                raise RuntimeError(
                    'Mixture pull snapshot %d evicted (window=%d): the '
                    'consumer buffered more pulls than snapshot_window — '
                    'raise MixtureBatchReader(snapshot_window=...)'
                    % (cursor, self._window))
            state = dict(snapshot)
        state['pull_base'] = cursor
        return state

    def load_state_dict(self, state):
        state = dict(state)
        base = int(state.pop('pull_base', 0))
        self._stream.load_state_dict(state)
        self._snapshots = {}
        self._next_pull = base
        self.last_row_consumed = False

    def consumption_record_for_resume(self, state):
        return {0: set(range(int(state.get('pull_base', 0))))}

    # -- lifecycle ---------------------------------------------------------

    def reset(self):
        if not self.last_row_consumed:
            raise NotImplementedError(
                'Resetting a mixture reader mid-iteration is not supported; '
                'consume all rows first')
        self._stream.reset()
        self._snapshots = {}
        self._next_pull = 0
        self.last_row_consumed = False

    def stop(self):
        self._stopped = True
        self._stream.stop()

    def join(self):
        self._stream.join()
