"""Streaming mixture engine: deterministic multi-dataset mixing +
token-budget sequence packing (docs/mixture.md).

Public surface::

    from petastorm_tpu.mixture import (
        MixtureSpec, MixtureSource,      # declarative mixture description
        MixtureStream,                   # the packed-row iterator
        MixtureBatchReader,              # Reader-shaped adapter (JaxLoader)
        InterleaveSchedule,              # arithmetic source order
        SequencePacker,                  # doc -> fixed-row packing
        merge_mixture_states,            # N -> M elastic resume
    )
"""

from petastorm_tpu.mixture.adapter import MixtureBatchReader  # noqa: F401
from petastorm_tpu.mixture.engine import (  # noqa: F401
    MixtureStream, build_source_readers, merge_mixture_states,
)
from petastorm_tpu.mixture.interleave import (  # noqa: F401
    InterleaveSchedule, realized_deviation,
)
from petastorm_tpu.mixture.packing import SequencePacker  # noqa: F401
from petastorm_tpu.mixture.spec import (  # noqa: F401
    MixtureSource, MixtureSpec,
)
