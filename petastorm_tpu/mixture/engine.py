"""MixtureStream: deterministic multi-source document mixing.

The engine composes the subsystem's layers into one iterator:

1. each source's :class:`~petastorm_tpu.reader.Reader` (any pool flavor,
   including daemon-backed QoS jobs) is wrapped in a
   :class:`_OrderedDocSource` that *resequences* the pool's
   completion-order deliveries back into the ventilator's deterministic
   ventilation order and serves per-row token documents;
2. the :class:`~petastorm_tpu.mixture.interleave.InterleaveSchedule`
   picks the source of every document position arithmetically;
3. the :class:`~petastorm_tpu.mixture.packing.SequencePacker` folds the
   document stream into fixed ``(seq_len,)`` rows (optional — with
   ``seq_len=None`` raw documents stream through);
4. every emitted row gets a **global ordinal**, and a consumer shard
   ``(cur_shard, shard_count)`` delivers exactly the ordinals with
   ``ordinal % shard_count == cur_shard``.

Step 4 is what makes the mixture *elastic*: every rank computes the
same global stream (steps 1-3 are pure functions of the spec), so a
rank's checkpoint is a consistent snapshot of the whole mixture at its
cursor, and :func:`merge_mixture_states` can re-shard a saved run onto
any consumer count by replaying from the earliest snapshot and fast-
forwarding to the aligned resume ordinal. When every rank checkpointed
at the same per-rank delivery count (the training-step-boundary case),
the restored stream is bit-identical to the uninterrupted run; a
mid-step checkpoint degrades to the package-wide at-least-once
contract (rows re-delivered, never lost).

The per-position source order is exactly what the readahead plane needs
to keep mixture reads coalesced: each source Reader already ships its
own plan (its ventilator's order IS the source-local upcoming order),
and the engine annotates it with the source's exact mixture share so
per-worker readahead depth follows the mixing ratio
(:func:`petastorm_tpu.readahead.build_plan`'s ``interleave=``).
"""

import logging
import time
from collections import deque

import numpy as np

from petastorm_tpu.mixture.interleave import InterleaveSchedule
from petastorm_tpu.mixture.packing import SequencePacker
from petastorm_tpu.mixture.spec import MixtureSpec
from petastorm_tpu.telemetry import (
    get_registry, knobs, metrics_disabled, tracing,
)

logger = logging.getLogger(__name__)

MIXTURE_DOCS = 'petastorm_tpu_mixture_docs_total'

_STATE_VERSION = 1

#: Default bound on out-of-order batches a source resequencer may hold
#: (overridable via PETASTORM_TPU_MIXTURE_RESEQ_MAX).
DEFAULT_RESEQ_MAX = 256


def _doc_rows(column):
    """Split one batch's token column into per-row 1-D arrays."""
    arr = column
    if isinstance(arr, np.ndarray) and arr.dtype != object:
        if arr.ndim >= 2:
            return [arr[i].ravel() for i in range(arr.shape[0])]
        # scalar column: each row is a single token
        return [arr[i:i + 1] for i in range(len(arr))]
    return [np.asarray(row).ravel() for row in arr]


class _OrderedDocSource:
    """Deterministic per-row document stream over one batched Reader.

    Pools deliver row-group batches in COMPLETION order — whichever
    worker finishes first — which varies run to run. Determinism is
    restored here: batches are buffered by ``(epoch, item_index)``
    provenance (``next_batch_info``) and released strictly in the
    ventilator's arithmetic ventilation order
    (:meth:`~petastorm_tpu.reader.Reader.ventilation_order`), so the
    document sequence any consumer observes is a pure function of the
    reader's (seed, shard, epoch) — independent of pool flavor, worker
    count, and scheduling jitter. The reorder buffer is bounded
    (``PETASTORM_TPU_MIXTURE_RESEQ_MAX``): ventilation back-pressure
    keeps in-flight items near the pool size, so the bound trips only
    on a contract violation, and loudly.

    Checkpointing rides the reader's at-least-once machinery: an item is
    recorded consumed only when its LAST row was handed out, and a
    partially-consumed batch checkpoints as ``(item, row_offset)`` so
    resume re-delivers the batch and skips the first ``row_offset``
    rows — exact delivery-granular resume.
    """

    def __init__(self, reader, token_field, reseq_max=None, source=None):
        if not getattr(reader, 'batched_output', False):
            raise ValueError('Mixture sources need batched readers '
                             '(make_batch_reader)')
        if reseq_max is None:
            reseq_max = knobs.get_int('PETASTORM_TPU_MIXTURE_RESEQ_MAX',
                                      DEFAULT_RESEQ_MAX, floor=1)
        self._reader = reader
        self._token_field = token_field
        self._source = source
        self._reseq_max = int(reseq_max)
        self._epoch = 0
        self._order = deque(reader.ventilation_order(0))
        self._buffer = {}
        self._delivered = {}
        self._current = None
        self._current_key = None
        self._row = 0
        self._skip_item = None
        self._skip_rows = 0
        self._drained = False

    @property
    def reader(self):
        return self._reader

    def __iter__(self):
        return self

    def __next__(self):
        while True:
            if self._current is not None:
                if self._row < len(self._current):
                    doc = self._current[self._row]
                    self._row += 1
                    return doc
                epoch, item = self._current_key
                self._delivered.setdefault(epoch, set()).add(item)
                self._current = None
            if not self._order:
                if self._drained and not self._buffer:
                    raise StopIteration
                nxt = self._epoch + 1
                epochs = self._reader.num_epochs
                if epochs is not None and nxt >= epochs:
                    if self._drained:
                        raise StopIteration
                else:
                    self._epoch = nxt
                    self._order = deque(
                        self._reader.ventilation_order(nxt))
                    continue
            if self._order:
                key = (self._epoch, self._order[0])
                if key in self._buffer:
                    self._order.popleft()
                    self._current = self._buffer.pop(key)
                    self._current_key = key
                    self._row = 0
                    if self._skip_rows and key[1] == self._skip_item:
                        self._row = min(self._skip_rows, len(self._current))
                    self._skip_item, self._skip_rows = None, 0
                    continue
                if self._drained:
                    # The pool never produced this item (poison skip /
                    # zero-row group): completed-with-zero-rows.
                    self._delivered.setdefault(self._epoch, set()).add(
                        self._order.popleft())
                    continue
            self._pull()

    def _pull(self):
        t0 = time.time()
        try:
            columns, item, epoch = self._reader.next_batch_info()
        except StopIteration:
            self._drained = True
            return
        if self._source is not None:
            # join the row-group's lifeline from the mixture side: the
            # pull event shares the trace id the source reader's worker
            # stages minted, so per-source starvation is visible on the
            # same timeline as decode/io (shard carries the source index)
            ctx = tracing.ctx_for(item, epoch, shard=self._source)
            if ctx is not None:
                tracing.record_complete(
                    'mixture_pull', t0, time.time() - t0, ctx,
                    track='mixture-src-%d' % self._source)
        column = columns.get(self._token_field)
        if column is None:
            raise KeyError(
                'Mixture token_field %r missing from batch columns %s' %
                (self._token_field, sorted(columns)))
        self._buffer[(epoch, item)] = _doc_rows(column)
        if len(self._buffer) > self._reseq_max:
            raise RuntimeError(
                'Mixture resequencer overflow: %d out-of-order batches '
                'held (bound %d, PETASTORM_TPU_MIXTURE_RESEQ_MAX) waiting '
                'for item %r of epoch %d — the pool is delivering items '
                'the ventilator never ordered'
                % (len(self._buffer), self._reseq_max,
                   self._order[0] if self._order else None, self._epoch))

    # -- state -------------------------------------------------------------

    def state_dict(self):
        record = {e: set(items) for e, items in self._delivered.items()}
        partial = None
        if self._current is not None:
            epoch, item = self._current_key
            if self._row >= len(self._current):
                # every row handed out, batch just not advanced past yet:
                # that IS full consumption (the lazy delivered-mark would
                # otherwise re-deliver the whole batch on resume)
                record.setdefault(epoch, set()).add(item)
            else:
                partial = {'epoch': epoch, 'item': item, 'row': self._row}
        state = self._reader.resume_state_from(record)
        return {'reader': state, 'partial': partial}

    def load_state_dict(self, state):
        reader_state = state['reader']
        self._reader.load_state_dict(reader_state)
        record = self._reader.consumption_record_for_resume(reader_state)
        self._delivered = {e: set(items) for e, items in record.items()}
        self._epoch = int(reader_state['epoch'])
        self._order = deque(self._reader.ventilation_order(self._epoch))
        self._buffer = {}
        self._current = None
        self._current_key = None
        self._row = 0
        self._drained = False
        partial = state.get('partial')
        self._skip_item, self._skip_rows = None, 0
        if partial is not None and int(partial['epoch']) == self._epoch:
            # The partially-consumed batch is re-delivered first (it is
            # the earliest unconsumed item in ventilation order); skip
            # the rows that were already handed out.
            self._skip_item = partial['item']
            self._skip_rows = int(partial['row'])

    def reset(self):
        self._reader.reset()
        self._epoch = 0
        self._order = deque(self._reader.ventilation_order(0))
        self._buffer = {}
        self._delivered = {}
        self._current = None
        self._current_key = None
        self._row = 0
        self._skip_item, self._skip_rows = None, 0
        self._drained = False

    def stop(self):
        self._reader.stop()

    def join(self):
        self._reader.join()


def build_source_readers(spec, num_epochs=1, reader_pool_type='thread',
                         workers_count=None, shuffle_row_groups=True,
                         **common_kwargs):
    """One Reader per :class:`MixtureSource`, mixture-aware.

    Each reader gets a per-source seed derived from the spec seed (so
    two sources over the same files do not march in lock-step), the
    source's exact interleave share annotated into its readahead plan
    (``mixture_interleave=``), and — when ``reader_pool_type='service'``
    with a standing daemon configured — its OWN
    :class:`~petastorm_tpu.service.daemon.DaemonClientPool` registered
    under the source's name with the source's weight, so the daemon's
    QoS fair-share allocates the shared fleet in mixture proportion.
    """
    from petastorm_tpu.reader import make_batch_reader
    schedule = InterleaveSchedule(spec.weights, seed=spec.seed)
    shares = schedule.fractions
    daemon = None
    if reader_pool_type == 'service':
        daemon = knobs.get_str('PETASTORM_TPU_SERVICE_DAEMON') or None
    readers = []
    try:
        for idx, source in enumerate(spec.sources):
            kwargs = dict(common_kwargs)
            kwargs.update(source.reader_kwargs)
            kwargs.setdefault('seed', (spec.seed + idx) % (2 ** 32))
            kwargs.setdefault('shuffle_row_groups', shuffle_row_groups)
            kwargs.setdefault('num_epochs', num_epochs)
            kwargs.setdefault('workers_count', workers_count)
            kwargs.setdefault('mixture_interleave', {
                'source': idx,
                'sources': len(spec.sources),
                'share': shares[idx],
                'seed': spec.seed,
            })
            pool = reader_pool_type
            if daemon:
                from petastorm_tpu.service.daemon import DaemonClientPool
                pool = DaemonClientPool(daemon, name=source.name,
                                        weight=source.weight)
            kwargs.setdefault('reader_pool_type', pool)
            if source.reader_factory is not None:
                readers.append(source.reader_factory(**kwargs))
            else:
                readers.append(make_batch_reader(source.url, **kwargs))
    except Exception:
        for reader in readers:
            reader.stop()
            reader.join()
        raise
    return readers


class MixtureStream:
    """Iterator of packed rows (or raw documents) over a weighted mixture.

    With ``spec.seq_len`` set, every item is a dict of three aligned
    ``(seq_len,)`` arrays — ``tokens``, ``loss_mask``, ``segment_ids``
    (see :mod:`petastorm_tpu.mixture.packing`). With ``seq_len=None``,
    items are ``{'tokens': <1-D array>, 'source': <int>}`` raw
    documents. Either way the GLOBAL stream is a pure function of the
    spec, and this consumer delivers the ordinals of its shard.

    The stream ends when the first source exhausts (every remaining
    open bin flushes, padded) — the deterministic analogue of a mixture
    epoch. ``num_epochs=None`` sources never exhaust.
    """

    def __init__(self, spec, num_epochs=1, cur_shard=None, shard_count=None,
                 reader_pool_type='thread', workers_count=None,
                 shuffle_row_groups=True, readers=None, **reader_kwargs):
        if not isinstance(spec, MixtureSpec):
            raise TypeError('spec must be a MixtureSpec, got %r' % (spec,))
        if (cur_shard is None) != (shard_count is None):
            raise ValueError('cur_shard and shard_count must be given '
                             'together')
        self._spec = spec
        self._cur_shard = int(cur_shard) if cur_shard is not None else 0
        self._shard_count = int(shard_count) if shard_count is not None else 1
        if not 0 <= self._cur_shard < self._shard_count:
            raise ValueError('cur_shard %r out of range for shard_count %r'
                             % (cur_shard, shard_count))
        self._schedule = InterleaveSchedule(spec.weights, seed=spec.seed)
        if readers is None:
            readers = build_source_readers(
                spec, num_epochs=num_epochs,
                reader_pool_type=reader_pool_type,
                workers_count=workers_count,
                shuffle_row_groups=shuffle_row_groups, **reader_kwargs)
        elif len(readers) != len(spec.sources):
            raise ValueError('readers has %d entries for %d sources'
                             % (len(readers), len(spec.sources)))
        self._sources = [_OrderedDocSource(r, spec.token_field, source=idx)
                         for idx, r in enumerate(readers)]
        self._packer = None
        if spec.seq_len is not None:
            self._packer = SequencePacker(spec.seq_len,
                                          open_bins=spec.open_bins,
                                          pad_id=spec.pad_id)
        self._pending = deque()
        self._next_ordinal = 0
        self._delivered_local = 0
        self._skip_until = 0
        self._finished = False
        self._source_docs = [0] * len(self._sources)

    # -- iteration ---------------------------------------------------------

    @property
    def spec(self):
        return self._spec

    @property
    def shard_count(self):
        return self._shard_count

    @property
    def cur_shard(self):
        return self._cur_shard

    @property
    def pack_stats(self):
        return self._packer.stats if self._packer is not None else None

    @property
    def source_doc_counts(self):
        """Documents drawn per source so far (realized mix)."""
        return list(self._source_docs)

    def __iter__(self):
        return self

    def __next__(self):
        while True:
            if self._pending:
                ordinal, row = self._pending.popleft()
                if ordinal < self._skip_until:
                    continue  # delivered before an elastic re-shard
                if ordinal % self._shard_count != self._cur_shard:
                    continue  # another consumer's row
                self._delivered_local += 1
                return row
            if self._finished:
                raise StopIteration
            self._produce()

    def _produce(self):
        """Draw one document, pack it, queue any completed rows."""
        src = self._schedule.peek(1)[0]
        try:
            doc = next(self._sources[src])
        except StopIteration:
            # The peeked draw was never delivered: do NOT charge the
            # schedule (the WeightedSamplingReader _draws lesson), so a
            # checkpoint taken at mixture end replays exactly.
            if self._packer is not None:
                for row in self._packer.flush():
                    self._enqueue(row)
            self._finished = True
            return
        self._schedule.next()
        self._source_docs[src] += 1
        if not metrics_disabled():
            get_registry().counter(
                MIXTURE_DOCS, source=self._spec.sources[src].name).inc()
        if self._packer is None:
            self._enqueue({'tokens': np.asarray(doc), 'source': src})
        else:
            for row in self._packer.feed(doc):
                self._enqueue(row)

    def _enqueue(self, row):
        self._pending.append((self._next_ordinal, row))
        self._next_ordinal += 1

    # -- checkpoint / elastic resume ---------------------------------------

    def state_dict(self):
        """Consistent GLOBAL snapshot at this consumer's cursor.

        JSON-safe. Restorable onto any shard layout: the snapshot
        regenerates every global ordinal from its earliest pending row
        onward, so a different consumer count simply re-deals the
        ordinals (see :func:`merge_mixture_states`).
        """
        pending = []
        for ordinal, row in self._pending:
            pending.append({
                'ordinal': ordinal,
                'row': {key: np.asarray(value).ravel().tolist()
                        if isinstance(value, np.ndarray) else int(value)
                        for key, value in row.items()},
            })
        return {
            'version': _STATE_VERSION,
            'mixture': self._spec.fingerprint(),
            'interleave': self._schedule.state_dict(),
            'packer': (self._packer.state_dict()
                       if self._packer is not None else None),
            'pending': pending,
            'next_ordinal': self._next_ordinal,
            'delivered_local': self._delivered_local,
            'finished': self._finished,
            'source_docs': list(self._source_docs),
            'sources': [source.state_dict() for source in self._sources],
            'shard_count': self._shard_count,
            'cur_shard': self._cur_shard,
        }

    def load_state_dict(self, state):
        if int(state.get('version', 0)) != _STATE_VERSION:
            raise ValueError('Unsupported mixture state version %r'
                             % (state.get('version'),))
        if state.get('mixture') != self._spec.fingerprint():
            raise ValueError(
                'Mixture state fingerprint %r does not match this spec '
                '(%r): the checkpoint was taken under different sources, '
                'weights, seed or packing geometry'
                % (state.get('mixture'), self._spec.fingerprint()))
        self._schedule.load_state_dict(state['interleave'])
        if self._packer is not None:
            self._packer.load_state_dict(state['packer'])
        for source, src_state in zip(self._sources, state['sources']):
            source.load_state_dict(src_state)
        self._pending = deque()
        for entry in state['pending']:
            row = {}
            for key, value in entry['row'].items():
                if key == 'source':
                    row[key] = int(value)
                else:
                    dtype = np.int32 if key != 'tokens' else (
                        self._packer._dtype if self._packer is not None
                        else np.int64)
                    row[key] = np.asarray(value, dtype=dtype)
            self._pending.append((int(entry['ordinal']), row))
        self._next_ordinal = int(state['next_ordinal'])
        self._finished = bool(state['finished'])
        self._source_docs = [int(n) for n in state['source_docs']]
        # resume_ordinal is stamped by merge_mixture_states: everything
        # below it was already delivered (by some rank, under the old
        # layout). A per-shard state replays exactly, so default 0.
        self._skip_until = int(state.get('resume_ordinal', 0))
        if 'resume_ordinal' in state:
            # Seed the delivery count with the pre-resume ordinals that
            # BELONG to this shard under the new layout — a later merge
            # (a second reshard) then recomputes the same global cursor
            # instead of rewinding below it.
            skip, m, r = self._skip_until, self._shard_count, self._cur_shard
            self._delivered_local = skip // m + (1 if skip % m > r else 0)
        else:
            self._delivered_local = int(state.get('delivered_local', 0))

    def reset(self):
        """Restart the mixture sweep (valid once every source drained)."""
        for source in self._sources:
            source.reset()
        self._schedule.reset()
        if self._packer is not None:
            self._packer = SequencePacker(self._spec.seq_len,
                                          open_bins=self._spec.open_bins,
                                          pad_id=self._spec.pad_id)
        self._pending = deque()
        self._next_ordinal = 0
        self._delivered_local = 0
        self._skip_until = 0
        self._finished = False
        self._source_docs = [0] * len(self._sources)

    # -- lifecycle ---------------------------------------------------------

    def stop(self):
        for source in self._sources:
            source.stop()

    def join(self):
        for source in self._sources:
            source.join()

    @property
    def diagnostics(self):
        diag = {
            'mixture_sources': len(self._sources),
            'mixture_next_ordinal': self._next_ordinal,
            'mixture_delivered_local': self._delivered_local,
            'mixture_source_docs': list(self._source_docs),
        }
        if self._packer is not None:
            diag['pack_stats'] = self._packer.stats
        return diag


def merge_mixture_states(states):
    """Fold per-rank mixture states into one elastically-restorable state.

    Every rank's state is a full-stream snapshot, so the merge only has
    to pick the earliest one (its replay covers every later cursor) and
    compute the resume ordinal: rank ``r`` having delivered ``n_r``
    rows has delivered exactly the ordinals ``r, r+M, ...,
    r+(n_r-1)*M``, so the earliest ordinal NOT delivered by anyone is
    ``min_r(r + n_r * M)``. The restored stream fast-forwards to that
    ordinal and re-deals the rest under the new layout — bit-identical
    when the ``n_r`` are equal (checkpoints taken at a train-step
    boundary), at-least-once otherwise (the faster ranks' extra rows
    are re-delivered, never lost).
    """
    states = list(states)
    if not states:
        raise ValueError('No mixture states to merge')
    fingerprints = {s.get('mixture') for s in states}
    if len(fingerprints) != 1:
        raise ValueError('Cannot merge states of different mixtures: %s'
                         % sorted(fingerprints))
    shard_counts = {int(s['shard_count']) for s in states}
    if len(shard_counts) != 1:
        raise ValueError('Cannot merge states with mixed shard_count: %s'
                         % sorted(shard_counts))
    shard_count = shard_counts.pop()
    shards = sorted(int(s['cur_shard']) for s in states)
    if shards != list(range(shard_count)):
        raise ValueError('Need one state per shard 0..%d, got shards %s'
                         % (shard_count - 1, shards))
    delivered = {int(s['cur_shard']): int(s.get('delivered_local', 0))
                 for s in states}
    if len(set(delivered.values())) != 1:
        logger.warning(
            'Merging mixture states with unequal per-rank delivery counts '
            '%s: resume is at-least-once (rows beyond the minimum are '
            're-delivered)', [delivered[r] for r in sorted(delivered)])
    resume_ordinal = min(r + n * shard_count for r, n in delivered.items())
    # The earliest snapshot (smallest production cursor) can regenerate
    # every ordinal >= its first pending row, which is always <= the
    # aligned resume ordinal.
    def cursor(s):
        pending = s.get('pending') or []
        first = min((int(p['ordinal']) for p in pending),
                    default=int(s['next_ordinal']))
        return first
    base = min(states, key=cursor)
    if cursor(base) > resume_ordinal:
        raise ValueError(
            'No merged state can regenerate ordinal %d (earliest snapshot '
            'starts at %d) — states were not taken from one consistent run'
            % (resume_ordinal, cursor(base)))
    merged = dict(base)
    merged['resume_ordinal'] = resume_ordinal
    merged.pop('delivered_local', None)
    merged.pop('shard_count', None)
    merged.pop('cur_shard', None)
    return merged
