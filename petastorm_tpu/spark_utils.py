"""Spark RDD helper (reference: ``petastorm/spark_utils.py:23-52``).

Gated on pyspark: builds an RDD of decoded namedtuple rows from a
materialized dataset — the decode happens on Spark executors.
"""


def dataset_as_rdd(dataset_url, spark_session, schema_fields=None,
                   storage_options=None):
    """RDD over a petastorm_tpu dataset, one decoded namedtuple per row."""
    try:
        import pyspark  # noqa: F401
    except ImportError as e:
        raise ImportError('dataset_as_rdd requires pyspark') from e

    from petastorm_tpu.etl.dataset_metadata import (
        ParquetDatasetInfo, infer_or_load_unischema, load_row_groups,
    )

    info = ParquetDatasetInfo(dataset_url, storage_options)
    schema = infer_or_load_unischema(info)
    view = schema.create_schema_view(schema_fields) if schema_fields else schema
    pieces = list(range(len(load_row_groups(info))))

    def read_piece(piece_index):
        from petastorm_tpu.arrow_worker import RowGroupWorker
        rows = []

        class _Collect:
            def __call__(self, batch):
                for i in range(batch.length):
                    rows.append(view.make_namedtuple(**batch.row(i)))

        local_info = ParquetDatasetInfo(dataset_url, storage_options)
        worker = RowGroupWorker(0, _Collect(), {
            'dataset_info': local_info,
            'schema': view,
            'loaded_schema': view,
            'stored_schema': schema,
            'transform_spec': None,
            'cache': None,
            'ngram': None,
            'row_groups': load_row_groups(local_info),
        })
        worker.process(piece_index)
        worker.shutdown()
        return rows

    rdd = spark_session.sparkContext.parallelize(pieces, len(pieces))
    return rdd.flatMap(read_piece)
