"""Copy/transform a materialized dataset (reference:
``petastorm/tools/copy_dataset.py:34-153``): column subset by regex,
not-null row filter, re-partitioning into a different file/row-group layout
— Spark-free, streaming row-group at a time through the batched reader.

Usage: ``python -m petastorm_tpu.tools.copy_dataset <src_url> <dst_url>``
"""

import argparse
import logging
import sys

logger = logging.getLogger(__name__)


def copy_dataset(source_url, target_url, field_regex=None,
                 not_null_fields=None, rowgroup_size_rows=1000, num_files=4,
                 partition_by=(), storage_options=None):
    """Copy ``source_url`` → ``target_url``.

    :param field_regex: regex list; only matching fields are copied.
    :param not_null_fields: rows with a null in any of these fields are
        dropped.
    :param partition_by: hive-partition the copy by these fields.
    """
    from petastorm_tpu.etl.dataset_metadata import (
        DatasetWriter, ParquetDatasetInfo, infer_or_load_unischema,
        materialize_dataset,
    )
    from petastorm_tpu.predicates import in_lambda
    from petastorm_tpu.reader import make_batch_reader

    info = ParquetDatasetInfo(source_url, storage_options)
    schema = infer_or_load_unischema(info)
    if field_regex:
        schema = schema.create_schema_view(field_regex)

    predicate = None
    if not_null_fields:
        predicate = in_lambda(
            list(not_null_fields),
            lambda values: all(v is not None for v in values.values()))

    from petastorm_tpu.etl.dataset_metadata import load_row_groups
    n_source_rowgroups = len(load_row_groups(info))
    rowgroups_per_file = max(1, -(-n_source_rowgroups // max(1, num_files)))

    copied = 0
    with materialize_dataset(target_url, schema,
                             storage_options=storage_options):
        writer = DatasetWriter(target_url, schema,
                               rowgroup_size_rows=rowgroup_size_rows,
                               partition_by=partition_by,
                               storage_options=storage_options)
        with make_batch_reader(source_url, schema_fields=field_regex,
                               predicate=predicate, num_epochs=1,
                               shuffle_row_groups=False,
                               storage_options=storage_options) as reader:
            with writer:
                for rowgroup_idx, batch in enumerate(reader):
                    if rowgroup_idx and rowgroup_idx % rowgroups_per_file == 0:
                        writer.new_file()
                    columns = batch._asdict()
                    n = len(next(iter(columns.values())))
                    writer.write_row_dicts(
                        {name: columns[name][i] for name in schema.fields}
                        for i in range(n))
                    copied += n
    logger.info('Copied %d rows from %s to %s', copied, source_url, target_url)
    return copied


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('source_url')
    parser.add_argument('target_url')
    parser.add_argument('--field-regex', nargs='+', default=None)
    parser.add_argument('--not-null-fields', nargs='+', default=None)
    parser.add_argument('--rowgroup-size-rows', type=int, default=1000)
    parser.add_argument('--partition-by', nargs='+', default=())
    parser.add_argument('-v', '--verbose', action='store_true')
    args = parser.parse_args(argv)
    logging.basicConfig(
        level=logging.DEBUG if args.verbose else logging.INFO)
    copy_dataset(args.source_url, args.target_url,
                 field_regex=args.field_regex,
                 not_null_fields=args.not_null_fields,
                 rowgroup_size_rows=args.rowgroup_size_rows,
                 partition_by=tuple(args.partition_by))
    return 0


if __name__ == '__main__':
    sys.exit(main())
