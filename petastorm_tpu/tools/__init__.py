"""Dataset maintenance CLIs (reference: ``petastorm/tools/``)."""
