"""Uniform SparkSession argparse flags for CLI tools.

Parity module for the reference's ``tools/spark_session_cli.py:19-90``: any
command-line tool that optionally drives a Spark job adds ``--master`` and
``--spark-session-config`` through :func:`add_configure_spark_arguments` and
applies them with :func:`configure_spark`.

The builder is duck-typed (anything with ``.config(key, value)`` and
``.master(url)``), so the flag plumbing is testable without pyspark; only
the caller's ``SparkSession.builder...getOrCreate()`` needs it installed.
"""


def add_configure_spark_arguments(argparser):
    """Add the shared Spark-session flags to an ``argparse.ArgumentParser``."""
    argparser.add_argument(
        '--master', type=str, default=None,
        help='Spark master URL, e.g. "local[4]". Uses the environment '
             'default when omitted.')
    argparser.add_argument(
        '--spark-session-config', type=str, nargs='+', default=None,
        help='key=value pairs applied to the SparkSession builder, e.g. '
             '--spark-session-config spark.executor.cores=2 '
             'spark.executor.memory=10g')


def configure_spark(spark_session_builder, args):
    """Apply parsed :func:`add_configure_spark_arguments` flags to a
    ``SparkSession.Builder`` (returned, for chaining)."""
    if not hasattr(args, 'spark_session_config') or not hasattr(args, 'master'):
        raise RuntimeError(
            'args is missing --master/--spark-session-config; call '
            'add_configure_spark_arguments() on the parser first')

    for key, value in parse_session_config(args.spark_session_config).items():
        spark_session_builder = spark_session_builder.config(key, value)
    if args.master:
        spark_session_builder = spark_session_builder.master(args.master)
    return spark_session_builder


def parse_session_config(pairs):
    """``['k=v', ...]`` → dict, rejecting malformed entries."""
    config = {}
    for pair in pairs or []:
        key, sep, value = pair.partition('=')
        if not sep or not key or not value:
            raise ValueError(
                'Spark session config entries must be key=value, got %r'
                % pair)
        config[key] = value
    return config
