"""Row-group selectors: coarse selection using prebuilt footer indexes.

Parity with ``petastorm/selectors.py:19-100``.
"""

from abc import ABCMeta, abstractmethod


class RowGroupSelectorBase(metaclass=ABCMeta):
    @abstractmethod
    def get_index_names(self):
        """Names of the indexes this selector needs."""

    @abstractmethod
    def select_row_groups(self, index_dict):
        """Set of row-group ordinals to read, given ``{name: index}``."""


class SingleIndexSelector(RowGroupSelectorBase):
    """Row-groups containing any of the given values in one index."""

    def __init__(self, index_name, values_list):
        self._index_name = index_name
        self._values = list(values_list)

    def get_index_names(self):
        return [self._index_name]

    def select_row_groups(self, index_dict):
        indexer = index_dict[self._index_name]
        selected = set()
        for value in self._values:
            selected |= set(indexer.get_row_group_indexes(value))
        return selected


class IntersectIndexSelector(RowGroupSelectorBase):
    """Row-groups selected by ALL of the child selectors."""

    def __init__(self, single_index_selectors):
        self._selectors = list(single_index_selectors)

    def get_index_names(self):
        names = []
        for s in self._selectors:
            names.extend(s.get_index_names())
        return names

    def select_row_groups(self, index_dict):
        sets = [s.select_row_groups(index_dict) for s in self._selectors]
        return set.intersection(*sets) if sets else set()


class UnionIndexSelector(RowGroupSelectorBase):
    """Row-groups selected by ANY of the child selectors."""

    def __init__(self, single_index_selectors):
        self._selectors = list(single_index_selectors)

    def get_index_names(self):
        names = []
        for s in self._selectors:
            names.extend(s.get_index_names())
        return names

    def select_row_groups(self, index_dict):
        result = set()
        for s in self._selectors:
            result |= s.select_row_groups(index_dict)
        return result
