"""Wire-speed I/O plane: coalesced column-chunk readahead for decode workers.

Every row-group read used to be a blocking ``pf.read_row_group`` inside
the decode worker, so storage I/O and decode serialized per worker even
though the ventilator publishes the upcoming item order well in advance —
on remote/cold storage the whole fleet idles on the wire. tf.data
(PAPERS.md, arxiv 2101.12127) and the tabular-preprocessing study (arxiv
2409.14912) both locate the next order of magnitude for input pipelines in
exactly this overlap: prefetch storage bytes ahead of compute, and read
them in large coalesced ranges instead of one column-chunk syscall at a
time. This module is that plane, per decode *process*:

* a :class:`ReadaheadManager` (one per worker process, shared by every
  thread-pool worker in it) mirrors the ventilator's upcoming-item
  sequence arithmetically — same per-epoch permutation seed, same
  ``always_exclude`` filtering (statistics-pruned row-groups never fetch),
  same reset-stride seed advance — so it knows which row-groups the pool
  will ask for next without any extra channel;
* for each upcoming row-group it plans **exact byte ranges** per needed
  column chunk from the PR 12 memoized footer metadata
  (:class:`petastorm_tpu.pushdown.StatsIndex` — one footer read per file,
  process-wide memo) and **coalesces** adjacent ranges into large
  sequential reads (``PETASTORM_TPU_READAHEAD_GAP_KB`` /
  ``.._MAX_RANGE_MB``);
* a small pool of GIL-released fetch threads reads the ranges ``depth``
  row-groups ahead into a bounded shared buffer pool
  (``PETASTORM_TPU_READAHEAD_POOL_MB``), under the canonical
  ``readahead_fetch`` stage;
* the worker's ``_read_columns`` is then served **zero-copy**: the hit
  deserializes through :class:`pyarrow.BufferReader`-backed slices of the
  pooled fetch buffers (a file-like over the fetched ranges, handed to
  ``pq.ParquetFile(..., metadata=)`` so the footer is never re-read), and
  the pool accounting holds the buffer until the served table dies.

Failure never changes an answer: a fetch error (which rides the existing
``io.read`` faultpoint with a ``#readahead`` key suffix), a missing
footer, pool exhaustion, or a deserialization surprise all degrade to the
worker's blocking read, counted in
``petastorm_tpu_readahead_degraded_total{reason=…}``.
``PETASTORM_TPU_READAHEAD=0`` keeps the blocking read as the exact-parity
oracle (``tests/test_readahead.py`` holds row multisets identical across
thread/process/service pools). The late-materialization two-phase split is
respected: under a predicate only the predicate columns prefetch;
survivors' heavy columns stay on-demand.

Ownership (pipesan): fetch buffers are owned by the pool; the views a
served table holds are pinned by the entry's reference count (the
``weakref.finalize`` census on every served table), and under
``PETASTORM_TPU_SANITIZE=1`` the buffers carry red-zone canaries checked
when the pool reclaims them. ``fetch.ranges`` is a registered borrow
source in ``analysis/contracts.py``.
"""

import logging
import math
import os
import threading
import weakref

from petastorm_tpu import faults
from petastorm_tpu.telemetry import (
    get_registry, knobs, metrics_disabled, register_refresh, span,
)

logger = logging.getLogger(__name__)

#: registry series (docs/telemetry.md metric reference). Worker-side
#: increments ride the pool delta channels like every metric, so the
#: consumer's report sees the whole fleet's readahead activity.
READAHEAD_HITS = 'petastorm_tpu_readahead_hits_total'
READAHEAD_MISSES = 'petastorm_tpu_readahead_misses_total'
READAHEAD_BYTES = 'petastorm_tpu_readahead_bytes_total'
READAHEAD_COALESCED_READS = 'petastorm_tpu_readahead_coalesced_reads_total'
READAHEAD_DEGRADED = 'petastorm_tpu_readahead_degraded_total'
READAHEAD_POOL_BYTES = 'petastorm_tpu_readahead_pool_bytes'

#: how long a serve may wait on an in-flight fetch before degrading to
#: the blocking read (a dead fetch thread must wedge nothing)
_SERVE_WAIT_S = 30.0

#: bound on the per-manager order cache (current epoch ± lookahead)
_ORDER_CACHE_MAX = 4
#: bound on the sweep-detection seen-sets (epochs per sweep retained)
_SEEN_EPOCHS_MAX = 4

#: the worker-args key the per-process manager parks under (set by
#: :func:`attach` AFTER unpickling on the worker side, so it never
#: travels a job-spec/process-pool wire)
_ARGS_KEY = '_readahead_manager'

# cached enablement knob (refresh_readahead/telemetry.refresh re-reads)
_enabled = None

#: autotuner depth override (single slot per process, like the decoder-
#: thread override in codecs): None = the knob rules
_depth_override = None

#: live managers in this process (report/health occupancy)
_live_managers = weakref.WeakSet()


def readahead_enabled():
    """True unless ``PETASTORM_TPU_READAHEAD=0`` pins the blocking-read
    oracle (on by default: a miss is exactly the blocking read, so
    enabling it is parity-safe). Resolved in the WORKER's own process —
    service fleets set it fleet-wide like the pushdown knobs."""
    global _enabled
    if _enabled is None:
        _enabled = not knobs.is_disabled('PETASTORM_TPU_READAHEAD')
    return _enabled


def readahead_depth():
    """Row-groups fetched ahead of the sequence position (the knob half;
    :func:`current_depth` folds in the autotuner override)."""
    return knobs.get_int('PETASTORM_TPU_READAHEAD_DEPTH', 4, floor=1)


def readahead_max_depth():
    """Autotuner deepen ceiling."""
    return knobs.get_int('PETASTORM_TPU_READAHEAD_MAX_DEPTH', 16, floor=1)


def readahead_threads():
    return knobs.get_int('PETASTORM_TPU_READAHEAD_THREADS', 2, floor=1)


def pool_budget_bytes():
    return knobs.get_int('PETASTORM_TPU_READAHEAD_POOL_MB', 256,
                         floor=1) * 2 ** 20


def gap_bytes():
    """Coalescing gap: adjacent column-chunk ranges closer than this are
    merged into one sequential read (the gap bytes are fetched and
    discarded — cheaper than a second request on real storage)."""
    return knobs.get_int('PETASTORM_TPU_READAHEAD_GAP_KB', 64,
                         floor=0) * 1024


def max_range_bytes():
    """Upper bound on one coalesced read (a single larger chunk still
    gets its own read — never split mid-chunk)."""
    return knobs.get_int('PETASTORM_TPU_READAHEAD_MAX_RANGE_MB', 16,
                         floor=1) * 2 ** 20


def refresh_readahead():
    """Re-read the cached enablement knob (part of
    ``petastorm_tpu.telemetry.refresh()``); the sizing knobs are read at
    manager construction / per scheduling pass."""
    global _enabled
    _enabled = None


register_refresh(refresh_readahead)


def current_depth():
    """The live readahead depth: the autotuner's override when one is
    set, else the knob."""
    override = _depth_override
    return override if override is not None else readahead_depth()


def set_depth_override(depth):
    """In-process override of the depth knob (the staging autotuner's
    seam; never an ``os.environ`` mutation). ``None`` restores the
    knob."""
    global _depth_override
    _depth_override = None if depth is None else max(1, int(depth))


def count_degrade(reason):
    """One degrade-to-blocking event, attributed (``fetch-error`` /
    ``pool-exhausted`` / ``no-footer`` / ``no-columns`` /
    ``deserialize`` / ``fetch-timeout`` / ``cache``) — the "Decode is
    waiting on storage" runbook in docs/troubleshoot.md reads these."""
    if not metrics_disabled():
        get_registry().counter(READAHEAD_DEGRADED, reason=reason).inc()


def live_manager_count():
    """Managers currently alive in THIS process (the autotuner only
    moves the depth override where it can reach a manager)."""
    return len(_live_managers)


def pool_status():
    """``(bytes_in_use, budget_bytes)`` summed over this process's live
    managers — the autotuner's memory-pressure signal."""
    used = 0
    budget = 0
    for manager in list(_live_managers):
        used += manager._pool.used
        budget += manager._pool.budget
    return used, budget


def health_snapshot():
    """JSON-safe per-process readahead state for ``/health`` (reader and
    service worker-server endpoints): live counters + pool occupancy."""
    used, budget = pool_status()
    registry = get_registry()
    return {
        'enabled': readahead_enabled(),
        'managers': live_manager_count(),
        'depth': current_depth(),
        'hits': int(registry.counter_value(READAHEAD_HITS)),
        'misses': int(registry.counter_value(READAHEAD_MISSES)),
        'pool_bytes': int(used),
        'pool_budget_bytes': int(budget),
    }


def _reset_for_tests():
    global _enabled, _depth_override
    _enabled = None
    _depth_override = None
    for manager in list(_live_managers):
        manager.close()
    # a WeakSet sheds closed managers with their owners; clearing keeps
    # pool_status from reading engines a test deliberately abandoned
    _live_managers.clear()


# -- the ventilation-sequence plan (built consumer-side, rides worker_args) --


def build_plan(items, pieces, randomize, seed, iterations, exclude,
               workers=None, interleave=None):
    """The Reader's half: a picklable description of the ventilator's
    upcoming-item sequence. ``items`` are the ventilator work items
    (each carrying ``piece_index``), ``pieces`` the row-group list,
    ``seed`` the ventilator's RESOLVED seed (after its None draw),
    ``exclude`` the statistics-pruned item indices (skipped every epoch,
    so they must never fetch), and ``workers`` the pool's worker count —
    it bounds how many items can sit between observe and serve at once,
    which sizes the retire slack (see ``_retire_passed_locked``).

    ``interleave`` (optional) marks this reader as ONE SOURCE of a
    deterministic weighted mixture (:mod:`petastorm_tpu.mixture`): a
    dict carrying the source's exact normalized ``share`` of the
    interleave. The source's ventilation order IS its mixture-local
    upcoming order (the mixture consumes each source strictly in
    ventilation order), so the mirror arithmetic is unchanged — the
    share only scales the prefetch depth, keeping the fleet-wide
    readahead budget split in mixing proportion instead of every
    source greedily prefetching as if it owned the whole consumer."""
    if interleave is not None:
        share = float(interleave.get('share', 1.0))
        if not 0.0 < share <= 1.0:
            raise ValueError('interleave share must be in (0, 1], got %r'
                             % (share,))
        interleave = dict(interleave, share=share)
    return {
        'version': 1,
        # one (path, row_group) per item index; repeated piece paths
        # pickle as one shared string (drop partitions share pieces)
        'items': [(pieces[it['piece_index']].path,
                   pieces[it['piece_index']].row_group) for it in items],
        'randomize': bool(randomize),
        'seed': int(seed),
        'iterations': iterations,
        'exclude': sorted(exclude or ()),
        'workers': workers,
        'interleave': interleave,
    }


def attach(args):
    """Worker-side entry: the per-process manager for this reader's
    worker args (created on first call, refcounted across the thread
    pool's workers, parked on the args dict so it never crosses a
    pickling boundary). None when the plane is off or the reader shipped
    no plan (e.g. a caching reader — warm epochs must not fetch)."""
    plan = args.get('readahead_plan') if isinstance(args, dict) else None
    if plan is None or not readahead_enabled():
        return None
    manager = args.get(_ARGS_KEY)
    if manager is None:
        manager = ReadaheadManager(args['dataset_info'], plan)
        args[_ARGS_KEY] = manager
    manager.acquire()
    return manager


def release(args):
    """Worker-side exit: drop one reference; the last worker out closes
    the manager (fetch threads stopped, pool drained)."""
    manager = args.get(_ARGS_KEY) if isinstance(args, dict) else None
    if manager is not None and manager.release() == 0:
        args.pop(_ARGS_KEY, None)


# -- the bounded shared buffer pool ------------------------------------------


class _BufferPool:
    """Byte-budgeted accounting for in-flight + served fetch buffers.

    ``acquire`` is all-or-nothing (a fetch that does not fit degrades to
    the blocking read rather than evicting someone else's bytes);
    ``free`` returns capacity when the last holder of an entry — the
    registry slot or a served table's finalizer — lets go.
    """

    def __init__(self, budget):
        self.budget = budget
        self.used = 0
        self._lock = threading.Lock()

    def acquire(self, nbytes):
        with self._lock:
            if self.used + nbytes > self.budget:
                return False
            self.used += nbytes
        self._note_gauge()
        return True

    def free(self, nbytes):
        with self._lock:
            self.used = max(0, self.used - nbytes)
        self._note_gauge()

    def _note_gauge(self):
        if not metrics_disabled():
            get_registry().gauge(READAHEAD_POOL_BYTES,
                                 pid=str(os.getpid())).set(self.used)


# -- one prefetched row-group -------------------------------------------------


_PENDING, _READY, _FAILED = 'pending', 'ready', 'failed'


class _Fetch:
    """One scheduled row-group fetch: its coalesced ranges, pool
    accounting and lifetime census. ``refs`` starts at 1 (the manager's
    registry slot); every served table adds one and drops it from a
    ``weakref.finalize`` when the table dies, so the pooled bytes stay
    alive exactly as long as something can still read them zero-copy.
    State transitions and the refcount share one per-entry lock: the
    fetch thread, serving workers, the retire sweep and GC finalizers
    all race here."""

    __slots__ = ('rgkey', 'max_gpos', 'state', 'event', 'columns',
                 'ranges', 'nbytes', 'file_size', 'refs', '_lock',
                 '_pool', '_guards', '__weakref__')

    def __init__(self, rgkey, max_gpos, pool):
        self.rgkey = rgkey
        self.max_gpos = max_gpos
        self.state = _PENDING
        self.event = threading.Event()
        self.columns = frozenset()
        self.ranges = []
        self.nbytes = 0
        self.file_size = None
        self.refs = 1
        self._lock = threading.Lock()
        self._pool = pool
        self._guards = []

    def complete(self, ranges, guards, nbytes, columns, file_size):
        """Fetch thread handing over the bytes. False when the entry was
        retired while the read was in flight — the caller returns the
        acquired pool bytes itself."""
        with self._lock:
            if self.state != _PENDING:
                return False
            self.ranges = ranges
            self._guards = guards
            self.nbytes = nbytes
            self.columns = frozenset(columns)
            self.file_size = file_size
            self.state = _READY
        self.event.set()
        return True

    def fail(self):
        """A pending fetch failed/was declined; idempotent and a no-op
        for entries that already completed. Pool bytes the FETCH thread
        acquired are the fetch thread's to return — it is the only one
        who knows about them."""
        with self._lock:
            if self.state == _PENDING:
                self.state = _FAILED
        self.event.set()

    def retire(self):
        """The manager's registry slot lets go (sequence passed, close):
        a pending entry is cancelled, a ready one drops the registry
        reference."""
        with self._lock:
            if self.state == _PENDING:
                self.state = _FAILED
                drop = False
            else:
                drop = self.state == _READY
        self.event.set()
        if drop:
            self.drop_ref()

    def try_add_ref(self):
        """One more holder — only while the entry is still servable."""
        with self._lock:
            if self.state != _READY:
                return False
            self.refs += 1
            return True

    def drop_ref(self):
        """May run from a GC finalizer on any thread. The last holder
        out reclaims: canaries checked, buffers dropped, pool bytes
        returned — exactly once."""
        with self._lock:
            self.refs -= 1
            reclaim = self.refs <= 0 and self.state == _READY
            if reclaim:
                self.state = _FAILED  # terminal; nothing may serve now
                ranges, self.ranges = self.ranges, []
                guards, self._guards = self._guards, []
        if reclaim:
            self._check_guards(guards)
            del ranges
            self._pool.free(self.nbytes)

    def _check_guards(self, guards):
        """Red-zone verification at reclaim time (armed pool buffers are
        allocated between canaries): a trampled zone means something
        wrote through a served zero-copy view."""
        if not guards:
            return
        from petastorm_tpu import sanitizer
        for guard in guards:
            if not sanitizer.check_canaries(guard):
                sanitizer.record_violation(
                    'readahead-canary',
                    {'path': self.rgkey[0], 'row_group': self.rgkey[1]})


# -- zero-copy range-backed file ---------------------------------------------


class _OutsideRanges(Exception):
    """A read fell outside the fetched ranges (metadata surprise — page
    index, bloom filter): the serve degrades to the blocking read."""


class _RangeSource:
    """Minimal file-like over the fetched byte ranges, for
    ``pq.ParquetFile(..., metadata=)``: reads inside a fetched range
    return zero-copy :class:`pyarrow.Buffer` slices (via a per-range
    ``pa.BufferReader``); anything else raises :class:`_OutsideRanges`
    so the caller falls back instead of guessing."""

    def __init__(self, ranges, file_size):
        import pyarrow as pa
        # Intentional borrow of the entry's pooled buffers: the serving
        # caller holds an entry reference for the lifetime of this
        # source and of every buffer slice the deserialization keeps
        # (weakref.finalize on the served table).  # pipesan: owns
        self._readers = [(start, len(buf), pa.BufferReader(buf))
                         for start, buf in ranges]
        self._size = file_size
        self._pos = 0
        self.closed = False

    def seekable(self):
        return True

    def readable(self):
        return True

    def writable(self):
        return False

    def tell(self):
        return self._pos

    def size(self):
        if self._size is None:
            raise _OutsideRanges('file size unknown')
        return self._size

    def seek(self, offset, whence=0):
        if whence == 0:
            self._pos = offset
        elif whence == 1:
            self._pos += offset
        else:
            self._pos = self.size() + offset
        return self._pos

    def read(self, nbytes=-1):
        if nbytes is None or nbytes < 0:
            raise _OutsideRanges('unbounded read at %d' % self._pos)
        for start, length, reader in self._readers:
            if start <= self._pos and self._pos + nbytes <= start + length:
                reader.seek(self._pos - start)
                out = reader.read_buffer(nbytes)
                self._pos += nbytes
                return out
        raise _OutsideRanges('read [%d, +%d) outside fetched ranges'
                             % (self._pos, nbytes))

    def close(self):
        self.closed = True


# -- range planning -----------------------------------------------------------


def coalesce_ranges(chunk_ranges, gap, max_range):
    """Merge sorted ``(start, length)`` column-chunk ranges into large
    sequential reads: adjacent ranges closer than ``gap`` bytes coalesce
    (the gap is fetched too — one request beats two on real storage)
    while no merged read exceeds ``max_range`` — except that a single
    chunk larger than ``max_range`` keeps its own undivided read."""
    merged = []
    for start, length in sorted(chunk_ranges):
        if merged:
            last_start, last_len = merged[-1]
            end = last_start + last_len
            if (start - end <= gap
                    and max(end, start + length) - last_start <= max_range):
                merged[-1] = (last_start,
                              max(end, start + length) - last_start)
                continue
        merged.append((start, length))
    return merged


# -- the per-process manager --------------------------------------------------


class ReadaheadManager:
    """One decode process's readahead scheduler (module docstring).

    Thread-safe: every thread-pool worker in the process calls
    :meth:`observe`/:meth:`serve`; the fetch threads complete entries.
    Correctness never depends on prediction — a mispredicted order (a
    resumed epoch's exclusions, a checkpoint-restored seed) only costs
    misses, which are exactly the blocking read.
    """

    def __init__(self, dataset_info, plan):
        from petastorm_tpu.pushdown import StatsIndex
        self._info = dataset_info
        self._items = [tuple(item) for item in plan['items']]
        self._randomize = plan['randomize']
        self._seed = plan['seed']
        self._iterations = plan.get('iterations')
        self._exclude = frozenset(plan.get('exclude') or ())
        self._stats = StatsIndex(dataset_info)
        self._pool = _BufferPool(pool_budget_bytes())
        self._gap = gap_bytes()
        self._max_range = max_range_bytes()
        # retire slack: with N concurrent workers, up to N siblings can
        # sit between their observe() (which advances the clock) and
        # their serve() — an entry that far behind the clock may still
        # be awaited, so only entries beyond the slack retire. Purely an
        # efficiency bound: a too-small slack costs misses, never rows.
        self._workers = plan.get('workers') or 1
        self._retire_slack = max(4, 2 * self._workers)
        # mixture source share (build_plan interleave=): scales this
        # source's prefetch depth to its exact mixing proportion
        self._mix_share = (plan.get('interleave') or {}).get('share')
        self._lock = threading.Lock()
        self._footer_lock = threading.Lock()
        self._columns = None
        self._entries = {}
        self._orders = {}     # (sweep, epoch) -> (order, pos_map, base)
        self._next_base = 0
        self._clock = -1
        self._sweep = 0
        self._seen_by_epoch = {}
        self._max_epoch = -1
        self._dup_streak = 0
        self._refs = 0
        self._closed = False
        self._queue = None
        self._threads = []
        _live_managers.add(self)

    # -- lifecycle ----------------------------------------------------------

    def acquire(self):
        with self._lock:
            self._refs += 1

    def release(self):
        with self._lock:
            self._refs -= 1
            refs = self._refs
        if refs <= 0:
            self.close()
        return refs

    def close(self):
        with self._lock:
            if self._closed:
                return
            self._closed = True
            threads, self._threads = self._threads, []
            entries, self._entries = dict(self._entries), {}
            q = self._queue
        if q is not None:
            # the fetch threads are daemons; one sentinel each lets them
            # exit promptly, and a fetch still mid-read completes into a
            # retired entry and returns its bytes itself
            for _ in threads:
                q.put(None)
        for entry in entries.values():
            entry.retire()

    # -- worker-facing API --------------------------------------------------

    def observe(self, item_index, epoch, columns):
        """The worker is loading ``item_index`` of ``epoch``: advance
        the sequence clock, retire passed entries, and schedule fetches
        ``depth`` positions ahead. ``columns`` is the prefetchable
        column set — fixed at the first call (predicate columns under a
        predicate, the needed file columns otherwise)."""
        if item_index is None or epoch is None:
            return
        tasks = []
        with self._lock:
            if self._closed:
                return
            if self._columns is None:
                self._columns = frozenset(columns)
            sweep = self._advance_sweep_locked(item_index, epoch)
            placed = self._position_locked(sweep, epoch, item_index)
            if placed is None:
                return
            gpos = placed
            if gpos > self._clock:
                self._clock = gpos
            self._retire_passed_locked()
            # window depth in GLOBAL sequence positions: a process-pool
            # worker only handles every ~Nth item, so the window must at
            # least span the worker stride to ever reach this process's
            # own next item (thread pools observe every position and are
            # unaffected when depth >= workers, the defaults)
            depth = current_depth()
            if self._mix_share:
                # one mixture source: its fair slice of the window,
                # rounded up (floor 1 keeps every source prefetching)
                depth = max(1, int(math.ceil(depth * self._mix_share)))
            depth = max(depth, self._workers)
            for offset in range(1, depth + 1):
                upcoming = self._at_locked(sweep, epoch, gpos + offset)
                if upcoming is None:
                    break
                next_gpos, next_item = upcoming
                rgkey = self._items[next_item]
                entry = self._entries.get(rgkey)
                if entry is not None:
                    if next_gpos > entry.max_gpos:
                        entry.max_gpos = next_gpos
                    continue
                entry = _Fetch(rgkey, next_gpos, self._pool)
                self._entries[rgkey] = entry
                tasks.append(entry)
        for entry in tasks:
            self._submit(entry)

    def serve(self, pf, path, row_group, columns):
        """A prefetched row-group as a :class:`pyarrow.Table`, or None
        (the caller's blocking read is the fallback — and the oracle).
        ``pf`` supplies the parsed footer (``metadata=``), so a hit
        performs zero storage I/O."""
        wanted = set(columns)
        with self._lock:
            entry = self._entries.get((path, row_group))
            configured = self._columns
        if configured is None or not wanted <= configured:
            # on-demand column sets (late-materialized heavy columns)
            # bypass silently — never waiting on, nor counting against,
            # a fetch that by design cannot serve them
            return None
        if entry is None:
            self._count(READAHEAD_MISSES)
            return None
        if not entry.event.wait(timeout=_SERVE_WAIT_S):
            count_degrade('fetch-timeout')
            self._count(READAHEAD_MISSES)
            return None
        if entry.state != _READY or not wanted <= entry.columns \
                or not entry.try_add_ref():
            self._count(READAHEAD_MISSES)
            return None
        table = None
        try:
            import pyarrow.parquet as pq
            source = _RangeSource(entry.ranges, entry.file_size)
            table = pq.ParquetFile(source, metadata=pf.metadata) \
                .read_row_group(row_group, columns=sorted(wanted))
        except Exception:  # noqa: BLE001 - degrade, never a wrong answer
            logger.debug('readahead: serving %s#rg%d from the pool '
                         'failed; degrading to the blocking read',
                         path, row_group, exc_info=True)
            count_degrade('deserialize')
            self._count(READAHEAD_MISSES)
            return None
        finally:
            if table is None:
                entry.drop_ref()
        # the served table may hold zero-copy slices of the pooled
        # buffers: the finalizer is the census that keeps the pool
        # accounting honest for exactly the table's lifetime
        weakref.finalize(table, entry.drop_ref)
        self._count(READAHEAD_HITS)
        return table

    # -- sequence arithmetic (mirrors workers/ventilator.py) ----------------

    def _advance_sweep_locked(self, item_index, epoch):
        """Detect a ventilator ``reset()`` sweep (epoch numbering
        restarts at 0, seed advances by the reset stride) from the item
        stream itself, two complementary ways: TWO CONSECUTIVE repeated
        (epoch, item) pairs can only be a new sweep — a reset replays
        the whole epoch, while a lone service re-ventilation/retry
        redelivers exactly one item and must NOT desync the mirrored
        seed for the rest of the run — and an epoch regressing by ≥3
        can only be a restart (covers long runs whose early seen-sets
        were evicted; pool pipelining straddles at most a couple of
        adjacent epoch boundaries, never three). A wrong guess costs
        mispredicted fetches, never wrong data."""
        restarted = epoch <= self._max_epoch - 3
        seen = self._seen_by_epoch.get(epoch)
        duplicate = seen is not None and item_index in seen
        self._dup_streak = self._dup_streak + 1 if duplicate else 0
        if restarted or self._dup_streak >= 2:
            self._sweep += 1
            self._dup_streak = 0
            self._seen_by_epoch = {}
            self._orders = {}
            self._max_epoch = epoch
            seen = None
        elif epoch > self._max_epoch:
            self._max_epoch = epoch
        if seen is None:
            seen = self._seen_by_epoch.setdefault(epoch, set())
            while len(self._seen_by_epoch) > _SEEN_EPOCHS_MAX:
                self._seen_by_epoch.pop(min(self._seen_by_epoch))
        seen.add(item_index)
        return self._sweep

    def _epoch_order(self, sweep, epoch):
        """EXACTLY the ventilator's epoch order — the SHARED
        ``workers.ventilator.epoch_order`` helper (one owner, so the
        arithmetic cannot drift), at the sweep-advanced seed
        (``seed + sweep·stride``), with the always-excluded (pruned)
        items filtered the way the ventilator filters them."""
        from petastorm_tpu.workers.ventilator import (
            _RESET_SEED_STRIDE, epoch_order,
        )
        seed = (self._seed + sweep * _RESET_SEED_STRIDE) % (2 ** 32)
        order = epoch_order(len(self._items), seed, epoch,
                            self._randomize)
        if self._exclude:
            order = [i for i in order if i not in self._exclude]
        return order

    def _order_for_locked(self, sweep, epoch):
        key = (sweep, epoch)
        cached = self._orders.get(key)
        if cached is None:
            order = self._epoch_order(sweep, epoch)
            pos_map = {item: i for i, item in enumerate(order)}
            cached = (order, pos_map, self._next_base)
            self._next_base += max(1, len(order))
            self._orders[key] = cached
            while len(self._orders) > _ORDER_CACHE_MAX:
                self._orders.pop(min(self._orders))
        return cached

    def _position_locked(self, sweep, epoch, item_index):
        order, pos_map, base = self._order_for_locked(sweep, epoch)
        pos = pos_map.get(item_index)
        return None if pos is None else base + pos

    def _at_locked(self, sweep, epoch, gpos):
        """``(gpos, item_index)`` of the sequence position ``gpos``,
        spilling past the epoch boundary into the next epoch when the
        iteration count allows — or None past the end of ventilation."""
        for _ in range(2):
            order, _, base = self._order_for_locked(sweep, epoch)
            if base <= gpos < base + len(order):
                return gpos, order[gpos - base]
            if gpos < base:
                return None
            if self._iterations is not None \
                    and epoch + 1 >= self._iterations:
                return None
            epoch += 1
        return None

    def _retire_passed_locked(self):
        """Drop entries whose last sequence position fell behind the
        clock by more than the retire slack: either served already or
        consumed by a worker in another process — their pool bytes fund
        the fetches still ahead. The slack keeps entries alive for
        concurrent siblings that observed (advancing the clock) but
        have not served yet."""
        if not self._entries:
            return
        horizon = self._clock - self._retire_slack
        passed = [key for key, entry in self._entries.items()
                  if entry.max_gpos < horizon]
        for key in passed:
            self._entries.pop(key).retire()

    # -- the fetch side ------------------------------------------------------

    def _submit(self, entry):
        import queue as queue_mod
        with self._lock:
            if self._closed:
                entry.fail()
                return
            if self._queue is None:
                self._queue = queue_mod.Queue()
                for i in range(readahead_threads()):
                    thread = threading.Thread(
                        target=self._fetch_loop, daemon=True,
                        name='petastorm-tpu-readahead-%d' % i)
                    thread.start()
                    self._threads.append(thread)
            q = self._queue
        q.put(entry)

    def _fetch_loop(self):
        while True:
            entry = self._queue.get()
            if entry is None:
                return
            if entry.state != _PENDING:
                continue  # retired while queued
            try:
                with span('readahead_fetch'):
                    self._fetch(entry)
            except Exception:  # noqa: BLE001 - degrade, never crash
                logger.warning('readahead: fetch of %s#rg%d failed; the '
                               'worker will read it blocking',
                               entry.rgkey[0], entry.rgkey[1],
                               exc_info=True)
                count_degrade('fetch-error')
                entry.fail()

    def _fetch(self, entry):
        path, row_group = entry.rgkey
        if faults.ARMED:
            # the same seam as the worker's blocking read: chaos specs
            # target fetches alone with match=readahead (a fetch fault
            # must degrade to the blocking path, never lose a row)
            faults.fault_hit('io.read',
                             key='%s#rg%d#readahead' % (path, row_group))
        planned, decline = self._plan_ranges(path, row_group)
        if planned is None:
            # 'no-footer': the footer was unreadable/never memoized;
            # 'no-columns': the footer is fine but no configured column
            # has file chunks here (e.g. a partition-only predicate) —
            # two different runbook steps, never conflated
            count_degrade(decline)
            entry.fail()
            return
        ranges, colnames = planned
        nbytes = sum(length for _, length in ranges)
        if not self._pool.acquire(nbytes):
            count_degrade('pool-exhausted')
            entry.fail()
            return
        try:
            buffers, guards, file_size = self._read_ranges(path, ranges)
        except Exception:
            entry.fail()
            self._pool.free(nbytes)
            raise
        if not entry.complete(buffers, guards, nbytes, colnames,
                              file_size):
            # retired while the bytes were in flight: give them back
            self._pool.free(nbytes)
            return
        if not metrics_disabled():
            registry = get_registry()
            registry.counter(READAHEAD_BYTES).inc(nbytes)
            registry.counter(READAHEAD_COALESCED_READS).inc(len(ranges))

    def _plan_ranges(self, path, row_group):
        """``((coalesced reads, column names), None)`` covering the
        configured columns' chunks of one row-group, from the memoized
        footer metadata — or ``(None, reason)`` when planning declines
        (``no-footer``: footer unreadable; ``no-columns``: no configured
        column has file chunks here)."""
        with self._footer_lock:
            self._stats.prefetch([path])
            chunk_ranges = self._stats.get_ranges(path, row_group)
        if not chunk_ranges:
            return None, 'no-footer'
        with self._lock:
            columns = self._columns or frozenset()
        chunks = []
        colnames = []
        for name in sorted(columns):
            col_chunks = chunk_ranges.get(name)
            if col_chunks:
                chunks.extend(col_chunks)
                colnames.append(name)
        if not chunks:
            return None, 'no-columns'
        return (coalesce_ranges(chunks, self._gap, self._max_range),
                colnames), None

    def _read_ranges(self, path, ranges):
        """The wire reads: one open, one sequential read per coalesced
        range. Returns pyarrow buffers (zero-copy over the owned bytes),
        the sanitizer guard arrays (armed only), and the file size."""
        import pyarrow as pa

        from petastorm_tpu import sanitizer
        armed = sanitizer.sanitize_enabled()
        buffers = []
        guards = []
        with self._info.open(path) as f:
            file_size = getattr(f, 'size', None)
            if callable(file_size):
                file_size = file_size()
            for start, length in ranges:
                f.seek(start)
                data = f.read(length)
                if len(data) != length:
                    raise IOError('short read of %s [%d, +%d): got %d'
                                  % (path, start, length, len(data)))
                if armed:
                    import numpy as np
                    guarded = sanitizer.allocate_guarded((length,),
                                                         np.uint8)
                    guarded[:] = memoryview(data)
                    guards.append(guarded)
                    buffers.append((start,
                                    pa.py_buffer(memoryview(guarded))))
                else:
                    buffers.append((start, pa.py_buffer(data)))
        return buffers, guards, file_size

    # -- internals -----------------------------------------------------------

    @staticmethod
    def _count(name):
        if not metrics_disabled():
            get_registry().counter(name).inc()


__all__ = ['ReadaheadManager', 'attach', 'build_plan', 'coalesce_ranges',
           'count_degrade', 'current_depth', 'health_snapshot',
           'live_manager_count', 'pool_status', 'readahead_enabled',
           'release', 'set_depth_override']
