"""Row-level predicates evaluated on decode workers.

Parity with ``petastorm/predicates.py:26-183``: composable predicates that
declare the fields they need (``get_fields``) and vote per row
(``do_include``). Predicates on partition columns are additionally pushed down
to whole row-groups by the Reader (``reader.py:577-608`` in the reference).

``in_pseudorandom_split`` keeps the reference's md5 bucketing so existing
train/val/test splits reproduce bit-for-bit across frameworks and hosts
(multi-host determinism without communication, SURVEY.md §7.3).
"""

import hashlib
from abc import ABCMeta, abstractmethod


class PredicateBase(metaclass=ABCMeta):
    @abstractmethod
    def get_fields(self):
        """Set of field names this predicate reads."""

    @abstractmethod
    def do_include(self, values):
        """True to keep the row; ``values`` is a dict of the requested fields."""


class in_set(PredicateBase):
    """Keep rows whose field value is in a given set."""

    def __init__(self, inclusion_values, predicate_field):
        self._values = set(inclusion_values)
        self._field = predicate_field

    def get_fields(self):
        return {self._field}

    def do_include(self, values):
        return values[self._field] in self._values


class in_intersection(PredicateBase):
    """Keep rows whose (array) field intersects a given set."""

    def __init__(self, inclusion_values, predicate_field):
        self._values = set(inclusion_values)
        self._field = predicate_field

    def get_fields(self):
        return {self._field}

    def do_include(self, values):
        return not self._values.isdisjoint(values[self._field])


class in_lambda(PredicateBase):
    """Arbitrary user function over a set of fields (runs on workers, host-side)."""

    def __init__(self, predicate_fields, predicate_func, state_arg=None):
        self._fields = set(predicate_fields)
        self._func = predicate_func
        self._state_arg = state_arg

    def get_fields(self):
        return self._fields

    def do_include(self, values):
        if self._state_arg is not None:
            return self._func(values, self._state_arg)
        return self._func(values)


class in_negate(PredicateBase):
    def __init__(self, predicate):
        self._predicate = predicate

    def get_fields(self):
        return self._predicate.get_fields()

    def do_include(self, values):
        return not self._predicate.do_include(values)


class in_reduce(PredicateBase):
    """Combine several predicates with a reduction (e.g. ``all``/``any``)."""

    def __init__(self, predicate_list, reduce_func):
        self._predicates = list(predicate_list)
        self._reduce_func = reduce_func

    def get_fields(self):
        return set().union(*(p.get_fields() for p in self._predicates))

    def do_include(self, values):
        return self._reduce_func([p.do_include(values) for p in self._predicates])


def _md5_fraction(value):
    """Deterministic hash of a value onto [0, 1) — identical to the
    reference's bucketing (``predicates.py:39-41``) for cross-compat."""
    digest = hashlib.md5(str(value).encode('utf-8')).hexdigest()
    return int(digest, 16) % 10 ** 8 / float(10 ** 8)


class in_pseudorandom_split(PredicateBase):
    """Deterministic fractional split on a hash of a field value.

    ``fraction_list`` partitions [0,1); a row belongs to subset ``i`` when the
    md5-fraction of its field value falls in the i-th interval.
    """

    def __init__(self, fraction_list, subset_index, predicate_field):
        if not 0 <= subset_index < len(fraction_list):
            raise ValueError('subset_index out of range')
        if sum(fraction_list) > 1.0 + 1e-9:
            raise ValueError('fractions must sum to at most 1')
        self._field = predicate_field
        starts = [0.0]
        for f in fraction_list:
            starts.append(starts[-1] + f)
        self._lo = starts[subset_index]
        self._hi = starts[subset_index + 1]

    def get_fields(self):
        return {self._field}

    def do_include(self, values):
        frac = _md5_fraction(values[self._field])
        return self._lo <= frac < self._hi
