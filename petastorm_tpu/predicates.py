"""Row-level predicates evaluated on decode workers.

Parity with ``petastorm/predicates.py:26-183``: composable predicates that
declare the fields they need (``get_fields``) and vote per row
(``do_include``). Predicates on partition columns are additionally pushed down
to whole row-groups by the Reader (``reader.py:577-608`` in the reference).

``in_pseudorandom_split`` keeps the reference's md5 bucketing so existing
train/val/test splits reproduce bit-for-bit across frameworks and hosts
(multi-host determinism without communication, SURVEY.md §7.3).
"""

import hashlib
from abc import ABCMeta, abstractmethod

import numpy as np


class PredicateBase(metaclass=ABCMeta):
    @abstractmethod
    def get_fields(self):
        """Set of field names this predicate reads."""

    @abstractmethod
    def do_include(self, values):
        """True to keep the row; ``values`` is a dict of the requested fields."""

    def do_include_batch(self, columns):
        """Columnar evaluation: ``columns`` maps each requested field to a
        full column (ndarray or list); returns a boolean mask over rows, or
        **None** when this predicate cannot evaluate columnar (the worker
        then falls back to the per-row ``do_include`` loop).

        This is the TPU-first departure from the reference (its workers
        build a Python dict per row, ``py_dict_reader_worker.py:188-236``):
        built-in predicates evaluate over whole columns so predicate cost
        stops being O(rows) dict constructions.
        """
        return None


class in_set(PredicateBase):
    """Keep rows whose field value is in a given set.

    Note ``in_set`` is a plain membership test: ``None`` in the value
    set **matches null rows** — unlike DNF ``filters`` terms, where
    nulls never match. The statistics planner
    (:mod:`petastorm_tpu.pushdown`) relies on this distinction for
    null-safe row-group pruning.
    """

    def __init__(self, inclusion_values, predicate_field):
        self._values = set(inclusion_values)
        self._field = predicate_field

    @property
    def values(self):
        """The inclusion set (read-only view for the pushdown planner)."""
        return frozenset(self._values)

    @property
    def field(self):
        return self._field

    def get_fields(self):
        return {self._field}

    def do_include(self, values):
        return values[self._field] in self._values

    def do_include_batch(self, columns):
        col = columns[self._field]
        if isinstance(col, np.ndarray) and col.dtype.kind in 'iufb':
            # np.isin only when BOTH sides are plainly numeric: numpy
            # coerces mixed-type value lists (e.g. {1, 'a'} -> strings),
            # which would silently diverge from `in`-set semantics
            values_arr = np.asarray(list(self._values))
            if values_arr.dtype.kind in 'iufb':
                return np.isin(col, values_arr)
        # everything else: set-membership semantics must match the row
        # path exactly, so hash-based `in` per value (no per-row dicts)
        return np.fromiter((v in self._values for v in col),
                           dtype=bool, count=len(col))


class in_intersection(PredicateBase):
    """Keep rows whose (array) field intersects a given set."""

    def __init__(self, inclusion_values, predicate_field):
        self._values = set(inclusion_values)
        self._field = predicate_field

    def get_fields(self):
        return {self._field}

    def do_include(self, values):
        return not self._values.isdisjoint(values[self._field])

    def do_include_batch(self, columns):
        col = columns[self._field]
        return np.fromiter((not self._values.isdisjoint(v) for v in col),
                           dtype=bool, count=len(col))


class in_lambda(PredicateBase):
    """Arbitrary user function over a set of fields (runs on workers, host-side)."""

    def __init__(self, predicate_fields, predicate_func, state_arg=None):
        self._fields = set(predicate_fields)
        self._func = predicate_func
        self._state_arg = state_arg

    def get_fields(self):
        return self._fields

    def do_include(self, values):
        if self._state_arg is not None:
            return self._func(values, self._state_arg)
        return self._func(values)


class in_negate(PredicateBase):
    def __init__(self, predicate):
        self._predicate = predicate

    def get_fields(self):
        return self._predicate.get_fields()

    def do_include(self, values):
        return not self._predicate.do_include(values)

    def do_include_batch(self, columns):
        mask = self._predicate.do_include_batch(columns)
        return None if mask is None else ~np.asarray(mask, dtype=bool)


class in_reduce(PredicateBase):
    """Combine several predicates with a reduction (e.g. ``all``/``any``)."""

    def __init__(self, predicate_list, reduce_func):
        self._predicates = list(predicate_list)
        self._reduce_func = reduce_func

    @property
    def predicates(self):
        """The child predicates (read-only view for the pushdown
        planner, which prunes through ``all``/``any`` compositions)."""
        return tuple(self._predicates)

    @property
    def reduce_func(self):
        return self._reduce_func

    def get_fields(self):
        return set().union(*(p.get_fields() for p in self._predicates))

    def do_include(self, values):
        return self._reduce_func([p.do_include(values) for p in self._predicates])

    def do_include_batch(self, columns):
        masks = []
        for p in self._predicates:
            mask = p.do_include_batch(columns)
            if mask is None:  # any non-columnar child defeats the fast path
                return None
            masks.append(np.asarray(mask, dtype=bool))
        if not masks:
            return None
        if self._reduce_func is all:
            return np.logical_and.reduce(masks)
        if self._reduce_func is any:
            return np.logical_or.reduce(masks)
        n = len(masks[0])
        return np.fromiter(
            (self._reduce_func([m[i] for m in masks]) for i in range(n)),
            dtype=bool, count=n)


def _string_to_bucket(value):
    """md5 of ``str(value)`` mapped onto [0, sys.maxsize) — BIT-IDENTICAL to
    the reference's bucketing (``predicates.py:39-41``), so splits computed
    by either framework agree value-for-value."""
    import sys
    digest = hashlib.md5(str(value).encode('utf-8')).hexdigest()
    return int(digest, 16) % sys.maxsize


class in_pseudorandom_split(PredicateBase):
    """Deterministic fractional split on a hash of a field value.

    ``fraction_list`` partitions [0,1); a row belongs to subset ``i`` when
    its md5 bucket falls in the i-th interval. The bucket math reproduces
    the reference's exactly (``predicates.py:144-183``: bucket =
    ``int(md5, 16) % sys.maxsize`` against ``fraction * (sys.maxsize - 1)``
    borders).
    """

    def __init__(self, fraction_list, subset_index, predicate_field):
        import sys
        if not 0 <= subset_index < len(fraction_list):
            raise ValueError('subset_index out of range')
        if sum(fraction_list) > 1.0 + 1e-9:
            raise ValueError('fractions must sum to at most 1')
        self._field = predicate_field
        starts = [0.0]
        for f in fraction_list:
            starts.append(starts[-1] + f)
        self._bucket_low = starts[subset_index] * (sys.maxsize - 1)
        self._bucket_high = starts[subset_index + 1] * (sys.maxsize - 1)

    def get_fields(self):
        return {self._field}

    def do_include(self, values):
        if self._field not in values:
            raise ValueError('Tested values do not have split key: %s'
                             % self._field)
        bucket = _string_to_bucket(values[self._field])
        return self._bucket_low <= bucket < self._bucket_high

    def do_include_batch(self, columns):
        # md5 is inherently per-value, but evaluating straight off the
        # column still skips the per-row dict the fallback path builds
        return np.fromiter(
            (self._bucket_low <= _string_to_bucket(v) < self._bucket_high
             for v in columns[self._field]),
            dtype=bool, count=len(columns[self._field]))
