"""Faultpoints: deterministic fault injection for every failure domain.

The pipeline's failure story is only as good as its worst untested path,
and before this module the ONLY fault the suite could provoke on demand
was a worker SIGKILL. Everything else — a corrupt Parquet page, a
poisoned JPEG, a full cache disk, a dropped heartbeat, a lost WORK
frame — required real hardware to misbehave. The tf.data service paper
(PAPERS.md, arxiv 2210.14826) treats worker churn and dispatcher
restarts as the NORMAL operating regime for disaggregated input
processing; operating there demands that every failure domain be
*injectable*, the same way PR 8's sanitizer made memory bugs injectable.

Design, mirroring the sanitizer's arming discipline:

* **Registry**: every faultpoint site is registered in
  :data:`petastorm_tpu.analysis.contracts.FAULTPOINTS` (one source of
  truth; the pipecheck ``faultpoint`` rule statically holds every
  ``fault_hit()`` call site to it, and an armed hit of an unregistered
  name raises).
* **Spec**: ``PETASTORM_TPU_FAULTS`` holds comma-separated clauses::

      site:mode[:rate][:opt=value...]

      io.read:error:0.05:seed=7          # 5% of reads raise, seeded
      zmq.heartbeat:drop:after=20        # drop heartbeats after 20
      cache.write:oserror:1:errno=28     # every store hits ENOSPC
      decode.rowgroup:error:1:match=#rg3  # poison one row-group

  Modes: ``error`` raises :class:`FaultInjected`; ``oserror`` raises
  :class:`FaultInjectedOSError` (``errno=N``, default EIO — the shape
  disk-fault hardening must handle); ``drop`` returns ``'drop'`` so the
  site skips its action (message sends); ``delay`` sleeps ``ms=N``.
  Options: ``seed=N`` (decision seed, default 0), ``after=N`` (first N
  eligible hits pass unharmed), ``times=N`` (at most N fires),
  ``match=S`` (only keys containing substring S are eligible).
* **Determinism**: decisions are counter-based, not clock- or
  random-module-based — the n-th eligible hit of a clause fires iff
  ``sha1(seed:site:n)`` maps below ``rate``. Two runs with the same
  spec and the same per-site call sequence inject the same faults;
  tests replay exactly.
* **Zero unarmed cost**: call sites guard with ``if faults.ARMED:`` —
  one module-attribute read; with the knob unset no parse happens, no
  state is allocated and no branch beyond that read exists
  (structurally asserted by ``tests/test_faults.py``, the pattern of
  PR 10's zero-thread guard).

Wired sites (see contracts.FAULTPOINTS for the authoritative list):
parquet IO and row-group/batch decode (:mod:`~petastorm_tpu
.arrow_worker`, :mod:`~petastorm_tpu.codecs`), decoded-cache read/write
(:mod:`~petastorm_tpu.materialized_cache`), the service wire — WORK /
DONE / HEARTBEAT / STOP / inbound recv (:mod:`~petastorm_tpu.service`)
— and staging H2D dispatch (:mod:`~petastorm_tpu.jax.staging`).
Authoring guide: docs/development.md, "Faultpoints".
"""

import hashlib
import logging
import threading
import time

from petastorm_tpu.analysis.contracts import FAULTPOINTS

logger = logging.getLogger(__name__)

#: injected-fault counter (docs/telemetry.md); labeled by site so a
#: chaos run's report shows exactly which seams fired how often
FAULTS_INJECTED = 'petastorm_tpu_faults_injected_total'

_MODES = ('error', 'oserror', 'drop', 'delay')

#: default errno for ``oserror`` mode: EIO, the "disk went bad" shape
_DEFAULT_ERRNO = 5


class FaultInjected(RuntimeError):
    """An injected fault from a ``PETASTORM_TPU_FAULTS`` clause.

    Deliberately a plain exception (not a subclass of any domain error):
    hardening must treat it like the arbitrary worker/IO failure it
    stands in for, and a test can always tell an injected fault from a
    real one by type."""


class FaultInjectedOSError(FaultInjected, OSError):
    """Injected OS-level fault (``oserror`` mode): carries a real
    ``errno`` so disk-full/EIO/permission hardening paths (the decoded
    cache's degrade-to-decode) exercise their errno dispatch exactly as
    they would on a failing filesystem. ``errno`` is set explicitly:
    ``OSError.__new__``'s two-arg parsing does not run for multiple-
    inheritance subclasses."""

    def __init__(self, errno_, message):
        super().__init__(errno_, message)
        self.errno = errno_


class _Clause:
    """One parsed spec clause with its per-clause decision state.
    ``salt`` (set at plan build) joins the decision digest so two
    clauses on ONE site draw independently — without it, same-seed
    clauses would fire fully correlated and combinations like
    "delay without error" would be structurally unreachable."""

    __slots__ = ('site', 'mode', 'rate', 'seed', 'after', 'times',
                 'match', 'errno', 'delay_ms', 'salt', 'hits', 'fired')

    def __init__(self, site, mode, rate, seed, after, times, match,
                 errno_, delay_ms):
        self.site = site
        self.mode = mode
        self.rate = rate
        self.seed = seed
        self.after = after
        self.times = times
        self.match = match
        self.errno = errno_
        self.delay_ms = delay_ms
        self.salt = site   # plan build appends mode + clause index
        self.hits = 0      # eligible (match-passing) hits seen
        self.fired = 0     # faults actually injected

    def describe(self):
        return '%s:%s(rate=%g, seed=%d, after=%d, times=%s, match=%r)' % (
            self.site, self.mode, self.rate, self.seed, self.after,
            self.times, self.match)


class _Plan:
    """The armed state: parsed clauses by site + one decision lock.

    The lock serializes counter advances so concurrent hits of one site
    each get a unique decision index; determinism then only requires the
    per-site call SEQUENCE to be deterministic, not the thread timing of
    unrelated sites."""

    __slots__ = ('spec', 'by_site', 'lock')

    def __init__(self, spec, clauses):
        self.spec = spec
        self.by_site = {}
        for clause in clauses:
            siblings = self.by_site.setdefault(clause.site, [])
            clause.salt = '%s:%s:%d' % (clause.site, clause.mode,
                                        len(siblings))
            siblings.append(clause)
        self.lock = threading.Lock()

    def stats(self):
        """``{site: {'hits': n, 'fired': n}}`` — chaos-test accounting."""
        out = {}
        with self.lock:
            for site, clauses in self.by_site.items():
                out[site] = {
                    'hits': sum(c.hits for c in clauses),
                    'fired': sum(c.fired for c in clauses),
                }
        return out


#: the ONE hot-path guard: ``None`` when unarmed (the knob is unset or
#: unparseable); a :class:`_Plan` when armed. Call sites read this and
#: nothing else before calling :func:`fault_hit`.
ARMED = None


def _parse_clause(text):
    fields = [f.strip() for f in text.strip().split(':')]
    if len(fields) < 2:
        raise ValueError('clause %r needs at least site:mode' % (text,))
    site, mode = fields[0], fields[1].lower()
    if site not in FAULTPOINTS:
        raise ValueError(
            'unregistered faultpoint %r (register it in '
            'petastorm_tpu/analysis/contracts.py FAULTPOINTS)' % (site,))
    if mode not in _MODES:
        raise ValueError('unknown fault mode %r (one of %s)'
                         % (mode, ', '.join(_MODES)))
    rate = 1.0
    seed, after, times, match = 0, 0, None, None
    errno_, delay_ms = _DEFAULT_ERRNO, 10
    for field in fields[2:]:
        if '=' not in field:
            rate = float(field)
            continue
        key, _, value = field.partition('=')
        key = key.strip().lower()
        if key == 'seed':
            seed = int(value)
        elif key == 'after':
            after = int(value)
        elif key == 'times':
            times = int(value)
        elif key == 'match':
            match = value
        elif key == 'errno':
            errno_ = int(value)
        elif key == 'ms':
            delay_ms = int(value)
        else:
            raise ValueError('unknown fault option %r in clause %r'
                             % (key, text))
    if not 0.0 <= rate <= 1.0:
        raise ValueError('fault rate %r outside [0, 1] in clause %r'
                         % (rate, text))
    return _Clause(site, mode, rate, seed, after, times, match, errno_,
                   delay_ms)


def parse_spec(spec):
    """Parse a full ``PETASTORM_TPU_FAULTS`` value into a :class:`_Plan`.
    Raises ``ValueError`` on any malformed clause — a chaos run with a
    typo'd spec must fail loudly, not silently test nothing."""
    clauses = [_parse_clause(c) for c in spec.split(',') if c.strip()]
    if not clauses:
        raise ValueError('empty PETASTORM_TPU_FAULTS spec %r' % (spec,))
    return _Plan(spec, clauses)


def _decision(seed, salt, n):
    """Deterministic uniform draw in [0, 1) for the n-th eligible hit of
    a clause — pure function of (seed, clause salt, n), so replay is
    exact AND same-site clauses draw independent sequences (the salt
    carries site, mode and clause index)."""
    digest = hashlib.sha1(
        ('%d:%s:%d' % (seed, salt, n)).encode()).digest()
    return int.from_bytes(digest[:8], 'big') / float(1 << 64)


def fault_hit(site, key=None):
    """One faultpoint hit. Returns ``None`` (no fault) or ``'drop'``
    (the site must skip its action); raises :class:`FaultInjected` /
    :class:`FaultInjectedOSError` for the error modes; sleeps for
    ``delay`` clauses.

    Only ever called behind the ``if faults.ARMED:`` guard, so the
    unarmed hot path never enters this function. ``key`` is the site's
    item identity (a row-group path, an item id, a message type) —
    ``match=`` clauses select on its string form."""
    plan = ARMED
    if plan is None:
        return None
    if site not in FAULTPOINTS:
        raise ValueError('fault_hit(%r): unregistered faultpoint '
                         '(contracts.FAULTPOINTS)' % (site,))
    action = None
    for clause in plan.by_site.get(site, ()):
        if clause.match is not None and clause.match not in str(key):
            continue
        with plan.lock:
            n = clause.hits
            clause.hits += 1
            if n < clause.after:
                continue
            if clause.times is not None and clause.fired >= clause.times:
                continue
            if clause.rate < 1.0 and \
                    _decision(clause.seed, clause.salt, n) >= clause.rate:
                continue
            clause.fired += 1
        _count_injection(site)
        logger.info('Faultpoint %s fired (%s; hit %d, key=%r)',
                    site, clause.mode, n, key)
        if clause.mode == 'error':
            raise FaultInjected(
                'injected fault at %s (hit %d, key=%r, clause %s)'
                % (site, n, key, clause.describe()))
        if clause.mode == 'oserror':
            raise FaultInjectedOSError(
                clause.errno,
                'injected OS fault at %s (hit %d, key=%r)'
                % (site, n, key))
        if clause.mode == 'delay':
            time.sleep(clause.delay_ms / 1000.0)
        elif clause.mode == 'drop':
            action = 'drop'
    return action


def _count_injection(site):
    # function-level imports: the armed path may pay them; the unarmed
    # path never reaches here, and module import stays telemetry-free
    # so early arming (worker-server boot) cannot cycle
    from petastorm_tpu.telemetry.registry import get_registry
    from petastorm_tpu.telemetry.spans import metrics_disabled
    if not metrics_disabled():
        get_registry().counter(FAULTS_INJECTED, site=site).inc()


def injection_stats():
    """Per-site ``{'hits', 'fired'}`` counts of the armed plan (empty
    when unarmed) — chaos tests assert exact replay against this."""
    plan = ARMED
    return plan.stats() if plan is not None else {}


def refresh_faults():
    """Re-read ``PETASTORM_TPU_FAULTS`` (hooked into
    ``telemetry.refresh()``): re-arming RESETS all clause counters, so a
    test that refreshes with the same spec replays the same schedule.
    An unparseable spec logs and disarms — a broken chaos config must
    never take the injection harness down with undefined behavior."""
    global ARMED
    from petastorm_tpu.telemetry import knobs
    spec = knobs.get_str('PETASTORM_TPU_FAULTS')
    if not spec:
        ARMED = None
        return
    try:
        ARMED = parse_spec(spec)
    except ValueError:
        logger.exception('Ignoring unparseable PETASTORM_TPU_FAULTS=%r',
                         spec)
        ARMED = None
        return
    logger.warning('Fault injection ARMED: %s', spec)


def _register_refresh():
    from petastorm_tpu import telemetry
    telemetry.register_refresh(refresh_faults)


_register_refresh()
refresh_faults()
