"""Reader: the read-path front end (``make_reader`` / ``make_batch_reader``).

Re-design of ``petastorm/reader.py`` for TPU hosts. Same contract — open a
(materialized or plain) Parquet dataset, enumerate row-groups, filter them by
predicate/selector/shard, ventilate them to a decode pool, iterate results —
with these deliberate differences:

* Column-major core: every pool result is a decoded :class:`ColumnBatch`;
  ``make_reader`` row iteration is a view over it (SURVEY.md §7.1).
* Sharding defaults come from ``jax.process_index()/process_count()`` when a
  distributed JAX runtime is initialized (:mod:`petastorm_tpu.parallel.sharding`)
  instead of manual ``cur_shard``/Horovod env checks.
* Checkpointable iteration state (``state_dict``/``load_state_dict``) — the
  reference can only restart epochs from scratch (SURVEY.md §5.4).
"""

import logging
import time
import warnings

from petastorm_tpu.arrow_worker import RowGroupWorker, defer_config_ok
from petastorm_tpu.telemetry import note_consumer_wait, span, tracing
from petastorm_tpu.cache import LocalDiskCache, NullCache
from petastorm_tpu.errors import MetadataError, NoDataAvailableError
from petastorm_tpu.etl.dataset_metadata import (
    ParquetDatasetInfo, infer_or_load_unischema, load_row_groups,
)
from petastorm_tpu.transform import transform_schema
from petastorm_tpu.workers import EmptyResultError
from petastorm_tpu.workers.dummy_pool import DummyPool
from petastorm_tpu.workers.thread_pool import ThreadPool
from petastorm_tpu.workers.ventilator import ConcurrentVentilator

logger = logging.getLogger(__name__)

# Extra row-groups ventilated beyond worker count: bounds host memory while
# keeping workers busy (reference: ``reader.py:44-46``).
_VENTILATE_EXTRA_ROWGROUPS = 2

# Stall-note floor for reader pulls, higher than the generic
# STALL_NOTE_FLOOR_S: a pool's get_results includes per-result work even
# when a result was already queued (process/service pools deserialize the
# payload inside the call — easily >1ms for image batches), and billing
# that as starvation would steadily inflate producer-bound evidence in a
# pipeline that is actually keeping up. Genuine starvation blocks in
# poll-interval (≥50ms) chunks, far above this floor.
_PULL_NOTE_FLOOR_S = 0.01


def make_reader(dataset_url, schema_fields=None, reader_pool_type='thread',
                workers_count=None, results_queue_size=50, shuffle_row_groups=True,
                shuffle_row_drop_partitions=1, predicate=None,
                rowgroup_selector=None, num_epochs=1, cur_shard=None,
                shard_count=None, seed=0, cache_type='null', cache_location=None,
                cache_size_limit=None, cache_row_size_estimate=None,
                transform_spec=None, ngram=None, filters=None,
                storage_options=None, filesystem=None, poison_policy=None):
    """Reader over a petastorm_tpu/petastorm materialized dataset, iterating
    rows as namedtuples with all codecs decoded.

    Parity: ``petastorm/reader.py:61-196``. Use :func:`make_batch_reader` for
    plain Parquet stores or column-batch output.

    :param poison_policy: service pools only (docs/service.md, "Failure
        semantics") — what a quarantined (retry-budget-exhausted)
        row-group does to this reader: ``'raise'`` (default) surfaces
        the poison; ``'skip'`` reads past it, with the loss recorded on
        the pool's ``poisoned_items`` and the dispatcher's ``/health``.

    :param filters: pyarrow-style DNF filters (``[(col, op, value), ...]`` or
        an OR-list of such AND-lists). Row-groups that provably cannot match
        (hive partition values + parquet min/max statistics) are skipped
        without any I/O; surviving rows are filtered exactly on the workers.
    :param cache_type: ``'null'`` (default; upgraded to ``'decoded'``
        when ``PETASTORM_TPU_DECODED_CACHE=1``), ``'local-disk'`` (raw
        pickled reads, pre-transform), or ``'decoded'`` — the
        materialized decoded-row-group cache: finished post-transform
        column batches in Arrow IPC files, zero-copy mmap'd back on hit,
        shared across processes/jobs via one directory
        (``cache_location`` or ``PETASTORM_TPU_DECODED_CACHE_DIR``);
        ``cache_size_limit`` bounds the disk tier
        (default ``PETASTORM_TPU_DECODED_CACHE_DISK_MB``).
    :param filesystem: an already-constructed fsspec filesystem (e.g. a
        pre-authenticated gcsfs/s3fs instance) used instead of resolving
        one from the URL scheme; mutually exclusive with
        ``storage_options`` (reference ``reader.py:61`` ``filesystem=``).
    """
    info = ParquetDatasetInfo(dataset_url, storage_options,
                              filesystem=filesystem)
    try:
        from petastorm_tpu.etl.dataset_metadata import get_schema
        get_schema(info)
    except MetadataError:
        warnings.warn('Dataset at %s is missing petastorm metadata; the schema '
                      'will be inferred. Consider make_batch_reader for plain '
                      'Parquet stores' % dataset_url)

    return Reader(info, schema_fields=schema_fields,
                  reader_pool_type=reader_pool_type, workers_count=workers_count,
                  results_queue_size=results_queue_size,
                  shuffle_row_groups=shuffle_row_groups,
                  shuffle_row_drop_partitions=shuffle_row_drop_partitions,
                  predicate=predicate, rowgroup_selector=rowgroup_selector,
                  num_epochs=num_epochs, cur_shard=cur_shard,
                  shard_count=shard_count, seed=seed,
                  cache=_make_cache(cache_type, cache_location, cache_size_limit,
                                    cache_row_size_estimate,
                                    predicate=predicate),
                  transform_spec=transform_spec, ngram=ngram, filters=filters,
                  batched_output=False, poison_policy=poison_policy)


def make_batch_reader(dataset_url_or_urls, schema_fields=None,
                      reader_pool_type='thread', workers_count=None,
                      results_queue_size=50, shuffle_row_groups=True,
                      shuffle_row_drop_partitions=1, predicate=None,
                      rowgroup_selector=None, num_epochs=1, cur_shard=None,
                      shard_count=None, seed=0, cache_type='null',
                      cache_location=None, cache_size_limit=None,
                      cache_row_size_estimate=None, transform_spec=None,
                      filters=None, storage_options=None, filesystem=None,
                      defer_image_decode=False, poison_policy=None,
                      mixture_interleave=None, max_staleness_s=None):
    """Reader yielding whole row-groups as namedtuples of column arrays.

    Works on any Parquet store, petastorm metadata or not
    (parity: ``petastorm/reader.py:198-328``). ``filters`` and
    ``filesystem`` as in :func:`make_reader`.

    :param defer_image_decode: the fused-decode hand-shake
        (:mod:`petastorm_tpu.fused`): eligible image columns are
        published as still-encoded
        :class:`~petastorm_tpu.fused.EncodedImageColumn` stubs instead of
        decoded pixels, for a consumer (the JAX loader's staging arena)
        that decodes them straight into its destination buffers. Plain
        batch consumers should leave this off — namedtuple batches would
        carry encoded stubs.
    :param mixture_interleave: set by the mixture engine
        (:mod:`petastorm_tpu.mixture`) when this reader serves one source
        of a weighted mixture: a dict with the source's exact interleave
        ``share``, annotated into the readahead plan so per-worker
        prefetch depth follows the mixing ratio.
    :param max_staleness_s: bounded-staleness opt-in for growing
        (append-mode) datasets: requires a committed manifest
        (:mod:`petastorm_tpu.write`) and resolves the file set from a
        manifest snapshot taken at open — so the reader sees every row
        committed before the open, and rows written seconds ago are
        picked up by simply reopening (or by
        :class:`petastorm_tpu.write.AppendFollower`, which tails
        continuously within this bound). Raises ``ValueError`` on a
        manifest-less dataset — there is no commit point to bound
        staleness against.
    """
    info = ParquetDatasetInfo(dataset_url_or_urls, storage_options,
                              filesystem=filesystem)
    if max_staleness_s is not None:
        from petastorm_tpu.write import manifest as write_manifest
        if isinstance(dataset_url_or_urls, (list, tuple)) or \
                write_manifest.load(info.fs, info.root_path) is None:
            raise ValueError(
                'max_staleness_s requires a single dataset URL with a '
                'committed manifest (written by petastorm_tpu.write); '
                '%r has none' % (dataset_url_or_urls,))
    return Reader(info, schema_fields=schema_fields,
                  reader_pool_type=reader_pool_type, workers_count=workers_count,
                  results_queue_size=results_queue_size,
                  shuffle_row_groups=shuffle_row_groups,
                  shuffle_row_drop_partitions=shuffle_row_drop_partitions,
                  predicate=predicate, rowgroup_selector=rowgroup_selector,
                  num_epochs=num_epochs, cur_shard=cur_shard,
                  shard_count=shard_count, seed=seed,
                  cache=_make_cache(cache_type, cache_location, cache_size_limit,
                                    cache_row_size_estimate,
                                    predicate=predicate),
                  transform_spec=transform_spec, ngram=None, filters=filters,
                  batched_output=True,
                  defer_image_decode=defer_image_decode,
                  poison_policy=poison_policy,
                  mixture_interleave=mixture_interleave)


def _make_cache(cache_type, location, size_limit, row_size_estimate,
                predicate=None):
    from petastorm_tpu.telemetry import knobs
    if cache_type in (None, 'null', 'none'):
        # operators can arm the decoded tier fleet-wide without touching
        # reader call sites: PETASTORM_TPU_DECODED_CACHE=1 upgrades the
        # default no-cache readers to the materialized cache. A
        # FiltersPredicate participates — its clause digest joins the
        # cache key (arrow_worker._cache_key), so filtered results are
        # served from the cache instead of silently bypassing it.
        # Readers with an ARBITRARY predicate stay uncached (no stable
        # identity to key on): the knob must never turn a
        # previously-working job into Reader's cache+predicate
        # RuntimeError — that check is for EXPLICIT cache requests — but
        # the skip is counted, never invisible
        # (petastorm_tpu_decoded_cache_skipped_total{reason=predicate}).
        if knobs.is_enabled('PETASTORM_TPU_DECODED_CACHE'):
            from petastorm_tpu.filters import FiltersPredicate
            if predicate is not None \
                    and not isinstance(predicate, FiltersPredicate):
                from petastorm_tpu.materialized_cache import count_cache_skip
                count_cache_skip('predicate')
                logger.info(
                    'PETASTORM_TPU_DECODED_CACHE=1: reader with an '
                    'arbitrary predicate stays uncached (no stable cache '
                    'identity); use DNF filters/FiltersPredicate for '
                    'cacheable selective reads')
                return NullCache()
            cache_type = 'decoded'
            implicit = True
        else:
            return NullCache()
    else:
        implicit = False
    if cache_type == 'local-disk':
        if not location or not size_limit:
            raise ValueError("cache_type='local-disk' requires cache_location "
                             'and cache_size_limit')
        return LocalDiskCache(location, size_limit, row_size_estimate)
    if cache_type == 'decoded':
        # Materialized decoded-row-group cache (docs/telemetry.md):
        # decode-once-serve-many Arrow IPC tier, zero-copy mmap on hit.
        # Everything defaults from knobs so the fleet can share one
        # directory with no per-reader configuration.
        from petastorm_tpu.materialized_cache import (
            MaterializedRowGroupCache, default_cache_dir,
        )
        location = (location
                    or knobs.get_str('PETASTORM_TPU_DECODED_CACHE_DIR')
                    or default_cache_dir())
        disk_limit = size_limit or knobs.get_int(
            'PETASTORM_TPU_DECODED_CACHE_DISK_MB', 8192, floor=1) * 2 ** 20
        mem_limit = knobs.get_int(
            'PETASTORM_TPU_DECODED_CACHE_MEM_MB', 256, floor=0) * 2 ** 20
        # implicit (knob-upgraded) caches are conservative about
        # TransformSpecs whose determinism was never declared — see
        # MaterializedRowGroupCache.implicit_upgrade
        return MaterializedRowGroupCache(location, disk_limit, mem_limit,
                                         implicit_upgrade=implicit)
    raise ValueError('Unknown cache_type %r' % cache_type)


def _make_pool(reader_pool_type, workers_count, results_queue_size,
               poison_policy=None):
    import os
    if poison_policy not in (None, 'raise', 'skip'):
        raise ValueError("poison_policy must be 'raise' or 'skip'; got %r"
                         % (poison_policy,))
    if not isinstance(reader_pool_type, str):
        # A pre-built pool instance (any object honoring the pool contract):
        # lets callers configure endpoints/timeouts a string cannot carry,
        # e.g. Reader(..., reader_pool_type=ServicePool(endpoint=...)).
        pool = reader_pool_type
        missing = [m for m in ('start', 'ventilate', 'get_results', 'stop',
                               'join', 'workers_count', 'diagnostics')
                   if not hasattr(pool, m)]
        if missing:
            raise ValueError('reader_pool_type instance %r lacks pool '
                             'contract member(s) %s' % (pool, missing))
        if poison_policy is not None:
            if not hasattr(pool, 'poison_policy'):
                # silently dropping the policy would surprise exactly
                # when it matters (a poison arriving) — same fail-loud
                # stance as the local-pool check below
                raise ValueError(
                    'poison_policy given but pool instance %r has no '
                    'poison_policy support' % (pool,))
            pool.poison_policy = poison_policy
        return pool
    if poison_policy is not None and reader_pool_type != 'service':
        # local pools have no quarantine machinery: a worker error is
        # in-process and raises directly — fail loud, not silently no-op
        raise ValueError('poison_policy is only supported with '
                         "reader_pool_type='service'")
    if workers_count is None:
        # Auto-size to the host: decode is CPU-bound (cv2/numpy release the
        # GIL but still need a core each), so extra workers on a small box
        # only thrash. 4 matches the previous fixed default on TPU VMs.
        workers_count = max(1, min(4, os.cpu_count() or 1))
    if reader_pool_type == 'thread':
        return ThreadPool(workers_count, results_queue_size)
    if reader_pool_type == 'process':
        from petastorm_tpu.workers.process_pool import ProcessPool
        return ProcessPool(workers_count, results_queue_size)
    if reader_pool_type == 'service':
        # Disaggregated decode over tcp:// (docs/service.md). With the env
        # var set, the dispatcher binds there and an externally-started
        # worker-server fleet registers with it; without it, a localhost
        # fleet of workers_count servers is spawned (same shape as
        # 'process', but through the full network stack).
        from petastorm_tpu.service import ServicePool
        from petastorm_tpu.telemetry import knobs
        daemon = knobs.get_str('PETASTORM_TPU_SERVICE_DAEMON') or None
        if daemon:
            # STANDING service (docs/service.md, "Standing service"):
            # register this reader as one job with the long-lived daemon
            # at the given endpoint — many concurrent readers share its
            # supervised fleet; no dispatcher runs in this process.
            from petastorm_tpu.service.daemon import DaemonClientPool
            return DaemonClientPool(daemon,
                                    results_queue_size=results_queue_size,
                                    poison_policy=poison_policy or 'raise')
        endpoint = knobs.get_str('PETASTORM_TPU_SERVICE_DISPATCHER') or None
        if endpoint:
            # workers_count deliberately does NOT feed expected_workers: it
            # sizes LOCAL decode parallelism, while the external fleet size
            # is the operator's (default: start as soon as one worker
            # registers; more join live — docs/env_knobs.md).
            expected = knobs.get_str('PETASTORM_TPU_SERVICE_WORKERS') or None
            return ServicePool(endpoint=endpoint,
                               expected_workers=int(expected) if expected
                               else None,
                               results_queue_size=results_queue_size,
                               poison_policy=poison_policy or 'raise')
        return ServicePool(spawn_local_workers=workers_count,
                           results_queue_size=results_queue_size,
                           poison_policy=poison_policy or 'raise')
    if reader_pool_type == 'dummy':
        return DummyPool()
    raise ValueError("reader_pool_type must be one of 'thread', 'process', "
                     "'service', 'dummy' (or a pool instance); got %r"
                     % reader_pool_type)


class Reader:
    """Iterator over dataset rows (or column batches).

    The 5-step construction mirrors ``petastorm/reader.py:384-391``:
    1. resolve dataset + schema, 2. normalize the requested schema view,
    3. enumerate + filter row-groups, 4. build the ventilator, 5. start the
    worker pool.
    """

    def __init__(self, dataset_info, schema_fields=None, reader_pool_type='thread',
                 workers_count=None, results_queue_size=50, shuffle_row_groups=True,
                 shuffle_row_drop_partitions=1, predicate=None,
                 rowgroup_selector=None, num_epochs=1, cur_shard=None,
                 shard_count=None, seed=0, cache=None, transform_spec=None,
                 ngram=None, filters=None, batched_output=True,
                 defer_image_decode=False, poison_policy=None,
                 mixture_interleave=None):
        self.dataset_info = dataset_info
        self.batched_output = batched_output and ngram is None
        self.ngram = ngram

        if ngram is not None and not ngram.timestamp_overlap and \
                shuffle_row_drop_partitions > 1:
            raise NotImplementedError('Using timestamp deduplication with '
                                      'shuffle_row_drop_partitions is not supported')

        from petastorm_tpu.filters import FiltersPredicate
        self._filter_clauses = None
        self._filters_born = None
        if filters:
            filters_predicate = FiltersPredicate(filters)
            self._filter_clauses = filters_predicate.clauses
            if predicate is not None:
                from petastorm_tpu.predicates import in_reduce
                predicate = in_reduce([predicate, filters_predicate], all)
            else:
                # pure-filters predicate: the pre-shard prune below
                # already proved everything statistics can prove, so the
                # post-shard planner run is skipped for exactly this
                # object (a composed predicate may still prune more)
                predicate = filters_predicate
                self._filters_born = filters_predicate

        if predicate is not None and cache is not None and \
                not isinstance(cache, NullCache) and \
                not isinstance(predicate, FiltersPredicate):
            # A cached row-group must carry its predicate's identity in
            # the key; only DNF filters / FiltersPredicate have one (a
            # stable clause digest, see arrow_worker._cache_key) — those
            # cache. Anything else (in_lambda, in_set, a composed
            # in_reduce) cannot: an EXPLICIT cache request fails loud
            # (reference forbids the combination too,
            # ``reader.py:416-418``); the knob-armed implicit upgrade
            # degrades to uncached with the skip counted — the fleet
            # knob must never break a running job.
            if getattr(cache, 'implicit_upgrade', False):
                from petastorm_tpu.materialized_cache import count_cache_skip
                count_cache_skip('predicate')
                logger.info('PETASTORM_TPU_DECODED_CACHE=1: composed '
                            'predicate has no stable cache identity; '
                            'reading uncached')
                cache = NullCache()
            else:
                raise RuntimeError('Local cache is not supported together '
                                   'with predicates')

        # (1) schema
        self.stored_schema = infer_or_load_unischema(dataset_info)

        # (2) requested view (pre-transform), then transform edit
        if ngram is not None:
            ngram.resolve_regex_field_names(self.stored_schema)
            fields = ngram.get_field_names_at_all_timesteps()
            self.loaded_schema = (self.stored_schema.create_schema_view(fields)
                                  if fields else self.stored_schema)
        elif schema_fields is not None:
            self.loaded_schema = self.stored_schema.create_schema_view(schema_fields)
            if schema_fields and not len(self.loaded_schema):
                # all patterns missed: reading zero columns is never what
                # the user meant (reference raises the same way,
                # ``py_dict_reader_worker``'s EmptyResultError path)
                raise ValueError(
                    'No fields matching the criteria %r in schema %s'
                    % (schema_fields, list(self.stored_schema.fields)))
        else:
            self.loaded_schema = self.stored_schema
        if transform_spec is not None:
            self.schema = transform_schema(self.loaded_schema, transform_spec)
        else:
            self.schema = self.loaded_schema

        # (3) row-groups: enumerate, then predicate-pushdown/selector/shard
        all_pieces = load_row_groups(dataset_info)
        self._row_groups = all_pieces
        piece_indices = list(range(len(all_pieces)))
        filters_emptied = False
        if self._filter_clauses is not None:
            from petastorm_tpu.filters import prune_row_group_indices
            piece_indices = prune_row_group_indices(
                dataset_info, all_pieces, piece_indices, self._filter_clauses,
                stored_schema=self.stored_schema)
            filters_emptied = not piece_indices
        piece_indices, worker_predicate = self._apply_predicate_pushdown(
            piece_indices, predicate)
        piece_indices = self._apply_selector(piece_indices, rowgroup_selector)
        piece_indices = self._apply_sharding(piece_indices, cur_shard, shard_count)
        if not piece_indices:
            detail = 'check shard/predicate/selector configuration'
            if filters_emptied:
                from petastorm_tpu.filters import describe_clauses
                detail = 'filters %s matched no row-groups' % describe_clauses(
                    self._filter_clauses)
            raise NoDataAvailableError(
                'No row-groups left to read for this reader (dataset %s): %s'
                % (dataset_info.url, detail))
        self._piece_indices = piece_indices

        # (4) ventilator items
        items = []
        for idx in piece_indices:
            for drop in range(shuffle_row_drop_partitions):
                items.append({'piece_index': idx,
                              'worker_predicate': worker_predicate,
                              'shuffle_row_drop_partition':
                                  (drop, shuffle_row_drop_partitions),
                              'item_index': len(items)})

        # (4b) plan-time statistics pruning (petastorm_tpu/pushdown.py,
        # docs/telemetry.md "Query-shaped reads"): row-groups PROVABLY
        # empty against the predicate never reach the pool. Pruning runs
        # AFTER sharding and keeps every item in the list, so shard
        # assignment, item indices and checkpoint identities are
        # bit-identical to an unpruned (PETASTORM_TPU_PUSHDOWN=0) reader;
        # the pruned items are simply never ventilated and the epoch
        # accounting below treats them as completed-with-zero-rows.
        self._pruned_items = frozenset()
        self._pushdown_plan = None
        if worker_predicate is not None \
                and worker_predicate is not self._filters_born:
            from petastorm_tpu import pushdown
            if pushdown.pushdown_enabled():
                with span('rowgroup_prune'):
                    self._pushdown_plan = pushdown.plan_rowgroup_pruning(
                        dataset_info, all_pieces, piece_indices,
                        predicate=worker_predicate,
                        stored_schema=self.stored_schema)
                if self._pushdown_plan.pruned:
                    pruned_pieces = set(self._pushdown_plan.pruned)
                    self._pruned_items = frozenset(
                        it['item_index'] for it in items
                        if it['piece_index'] in pruned_pieces)

        self._pool = _make_pool(reader_pool_type, workers_count,
                                results_queue_size,
                                poison_policy=poison_policy)
        self._num_epochs = num_epochs
        # The bound is a callable so pools whose fleet grows at runtime
        # (service pool: worker servers can register with a RUNNING job)
        # get proportionally more row-groups in flight without a restart
        # — and so the staging autotuner can raise the in-flight extra
        # live (set_ventilate_extra).
        self._ventilate_extra = _VENTILATE_EXTRA_ROWGROUPS
        self._shuffle_row_groups = shuffle_row_groups
        self._resume_excluded = {}
        self._ventilator = ConcurrentVentilator(
            self._pool.ventilate, items, iterations=num_epochs,
            max_ventilation_queue_size=lambda: (
                self._pool.workers_count + self._ventilate_extra),
            randomize_item_order=shuffle_row_groups, random_seed=seed,
            pass_epoch=True, trace_shard=self.cur_shard,
            always_exclude=self._pruned_items)

        # (4c) readahead plan (petastorm_tpu/readahead.py, docs/telemetry.md
        # "Readahead"): a picklable description of the ventilator's
        # upcoming-item sequence — shard- and shuffle-aware, with the
        # statistics-pruned items excluded so they never fetch — that the
        # workers' per-process readahead manager mirrors arithmetically.
        # Caching readers ship no plan: a warm epoch never touches
        # storage, so prefetching its bytes would be pure waste (counted).
        from petastorm_tpu import readahead
        readahead_plan = None
        if cache is None or isinstance(cache, NullCache):
            readahead_plan = readahead.build_plan(
                items, all_pieces, randomize=shuffle_row_groups,
                seed=self._ventilator.state_dict()['seed'],
                iterations=num_epochs, exclude=self._pruned_items,
                workers=self._pool.workers_count,
                interleave=mixture_interleave)
        elif readahead.readahead_enabled():
            readahead.count_degrade('cache')

        # (5) start workers; ventilation begins lazily on first read so that
        # load_state_dict can reposition the cursor first.
        defer = defer_image_decode and self.batched_output
        if defer and not defer_config_ok(transform_spec, ngram, cache):
            # counted HERE, once per Reader — the N workers each re-derive
            # the same gate silently (docs/troubleshoot.md reads this)
            from petastorm_tpu.fused import count_fallback
            count_fallback('worker-config')
        self._pool.start(RowGroupWorker,
                         worker_args={
                             'dataset_info': dataset_info,
                             'schema': self.schema,
                             'loaded_schema': self.loaded_schema,
                             'stored_schema': self.stored_schema,
                             'transform_spec': transform_spec,
                             'cache': cache,
                             'ngram': ngram,
                             'row_groups': all_pieces,
                             # fused decode (petastorm_tpu/fused.py): only
                             # batched consumers can host encoded stubs
                             'defer_image_decode': defer,
                             # the workers resolve PETASTORM_TPU_READAHEAD
                             # in their OWN process (service fleets set it
                             # fleet-wide, like the pushdown knobs)
                             'readahead_plan': readahead_plan,
                         },
                         ventilator=self._ventilator, start_ventilator=False)

        self.last_row_consumed = False
        self._started = False
        self._stopped = False
        self._current_batch = None
        self._batch_cursor = 0
        # Per-epoch sets of fully-consumed item indices (for exact resume).
        self._consumed_by_epoch = {}
        self._num_items = len(items)
        # Shard-independent identity of each local item — (global piece
        # index, drop partition, drop partition COUNT). This is what makes
        # a checkpoint portable across a pod resize: consumed work can be
        # re-expressed globally and re-localized under a different
        # shard_count (elastic resume). The count is part of the identity
        # because (piece, drop) only names the same ROWS at the same k: a
        # restore under a different shuffle_row_drop_partitions must not
        # match (the old drop's rows are a different subset), making the
        # piece re-read in full — at-least-once, never silent loss.
        self._items_identity = [
            (it['piece_index'],) + tuple(it['shuffle_row_drop_partition'])
            for it in items]

        # live observability plane (docs/telemetry.md): with
        # PETASTORM_TPU_OBS_PORT set this process serves /metrics /report
        # /health /trace over HTTP; the reader contributes its pool
        # gauges + iteration state to /health. Unarmed: a shared no-op
        # handle, no thread, no socket.
        from petastorm_tpu.telemetry import obs_server
        self._obs_mount = obs_server.mount('reader', health=self._obs_health)

    # -- construction helpers ------------------------------------------------

    def _apply_predicate_pushdown(self, piece_indices, predicate):
        """Predicates referencing only partition keys filter whole row-groups;
        others go to the workers (reference: ``reader.py:577-608``)."""
        if predicate is None:
            return piece_indices, None
        pred_fields = predicate.get_fields()
        partition_keys = set(self.dataset_info.partition_keys)
        if pred_fields and pred_fields <= partition_keys:
            from petastorm_tpu.arrow_worker import typed_partition_value
            kept = [i for i in piece_indices
                    if predicate.do_include(
                        {k: typed_partition_value(
                            self.stored_schema.fields.get(k),
                            self._row_groups[i].partition_values.get(k))
                         for k in pred_fields})]
            return kept, None
        return piece_indices, predicate

    def _apply_selector(self, piece_indices, selector):
        if selector is None:
            return piece_indices
        from petastorm_tpu.etl.rowgroup_indexing import get_row_group_indexes
        index_dict = get_row_group_indexes(self.dataset_info)
        needed = selector.get_index_names()
        missing = [n for n in needed if n not in index_dict]
        if missing:
            raise ValueError('Dataset has no row-group index named %s' % missing)
        selected = selector.select_row_groups(index_dict)
        return [i for i in piece_indices if i in selected]

    def _apply_sharding(self, piece_indices, cur_shard, shard_count):
        """Modulo assignment of row-groups to data-parallel ranks.

        Defaults from the JAX distributed runtime when only one of the two
        args is provided (reference requires both, ``reader.py:537-554``).
        """
        from petastorm_tpu.parallel.sharding import default_shard_info
        cur_shard, shard_count = default_shard_info(cur_shard, shard_count)
        # observability parity with the reference Reader's exposed shard
        # attributes: the RESOLVED assignment (post JAX-process defaulting)
        self.cur_shard = cur_shard
        self.shard_count = shard_count
        if shard_count is None:
            return piece_indices
        if shard_count > len(piece_indices):
            raise NoDataAvailableError(
                'Number of row-groups in the dataset (%d) must be greater or '
                'equal to the number of requested shards (%d)'
                % (len(piece_indices), shard_count))
        return [i for n, i in enumerate(piece_indices) if n % shard_count == cur_shard]

    # -- iteration -----------------------------------------------------------

    def __iter__(self):
        return self

    def _ensure_started(self):
        if not self._started:
            self._ventilator.start()
            self._started = True

    def _pull_result(self):
        """One pool result under the ``queue_wait`` stage span; blocked
        time above the noise floor feeds the stall attributor as consumer
        wait (= producer-bound evidence). With tracing on, the wait is
        also stamped onto the ARRIVED item's trace (the context is
        re-derived from the result's item_index/epoch — sampling is
        deterministic, so no wire change on the result path) and the
        producer-bound auto-dump trigger is polled."""
        with span('queue_wait'):
            t0 = time.monotonic()
            result = None
            try:
                result = self._pool.get_results()
                return result
            finally:
                waited = time.monotonic() - t0
                if waited > _PULL_NOTE_FLOOR_S:
                    note_consumer_wait(waited)
                if tracing.trace_enabled():
                    self._note_trace_pull(result, waited)
                    tracing.maybe_autodump()

    def _note_trace_pull(self, result, waited):
        item_index = getattr(result, 'item_index', None)
        epoch = getattr(result, 'epoch', None)
        if item_index is None and isinstance(result, dict):
            item_index = result.get('item_index')
            epoch = result.get('epoch')
        ctx = tracing.ctx_for(item_index, epoch, self.cur_shard)
        if ctx is not None:
            tracing.record_complete('queue_wait', time.time() - waited,
                                    waited, ctx, track='consumer')

    def __next__(self):
        if self._stopped:
            raise RuntimeError('Trying to read a sample from a stopped reader')
        self._ensure_started()
        if self.batched_output:
            columns, _, _ = self.next_batch_info()
            return self.schema.make_namedtuple(**columns)
        if self.ngram is not None:
            try:
                # Workers publish wrapped {timestep: dict} windows (picklable
                # across the process pool); namedtuple-ization happens here on
                # the consumer, as in the reference
                # (py_dict_reader_worker.py:91).
                wrapped = self._pull_result()
            except EmptyResultError:
                self.last_row_consumed = True
                raise StopIteration from None
            if wrapped['last'] and wrapped['epoch'] is not None:
                self._consumed_by_epoch.setdefault(
                    wrapped['epoch'], set()).add(wrapped['item_index'])
            return self.ngram.make_namedtuple(self.schema, wrapped['window'])
        # row-at-a-time view over column batches
        while self._current_batch is None or self._batch_cursor >= self._current_batch.length:
            if self._current_batch is not None:
                self._mark_consumed(self._current_batch)
            try:
                self._current_batch = self._pull_result()
                self._batch_cursor = 0
            except EmptyResultError:
                self.last_row_consumed = True
                self._current_batch = None
                raise StopIteration from None
        row = self._current_batch.row(self._batch_cursor)
        self._batch_cursor += 1
        return self.schema.make_namedtuple(**row)

    def _mark_consumed(self, batch):
        item_index = getattr(batch, 'item_index', None)
        if item_index is not None and batch.epoch is not None:
            self._consumed_by_epoch.setdefault(batch.epoch, set()).add(item_index)

    def next_batch_info(self):
        """``(columns_dict, item_index, epoch)`` for one row-group batch.

        The provenance-carrying flavor of ``__next__`` (batched readers
        only): consumers that buffer rows downstream — the JaxLoader's
        staging pipeline — need to know WHICH row-group each batch came
        from so their checkpoints mark a row-group consumed only once all
        its rows were actually delivered, not merely pulled into a buffer
        (see :meth:`resume_state_from`). Raises StopIteration at the end
        like ``__next__``.
        """
        if not self.batched_output:
            raise TypeError('next_batch_info requires a batched reader')
        if self._stopped:
            raise RuntimeError('Trying to read a sample from a stopped reader')
        self._ensure_started()
        try:
            batch = self._pull_result()
        except EmptyResultError:
            self.last_row_consumed = True
            raise StopIteration from None
        self._mark_consumed(batch)
        # same projection make_namedtuple applies on the __next__ path
        # (schema fields only) — otherwise transform side-products would
        # leak into downstream staging
        columns = {name: batch.columns[name] for name in self.schema.fields
                   if name in batch.columns}
        return columns, batch.item_index, batch.epoch

    def next(self):
        return self.__next__()

    # -- lifecycle -----------------------------------------------------------

    def reset(self):
        """Restart the epoch sweep. Only valid after full consumption
        (reference: ``reader.py:468-492``)."""
        if not self.last_row_consumed:
            raise NotImplementedError(
                'Resetting a reader while in the middle of iteration is not '
                'supported; consume all samples first')
        self._ventilator.reset()
        self.last_row_consumed = False
        self._current_batch = None
        self._batch_cursor = 0
        # The new sweep restarts epoch numbering from 0; stale consumption
        # records would otherwise corrupt state_dict()'s resume math.
        self._consumed_by_epoch = {}
        self._resume_excluded = {}

    def ventilation_order(self, epoch):
        """Item indices the ventilator will emit for ``epoch``, in order.

        The public face of the ventilator's arithmetic order (shared
        with the readahead mirror): the per-epoch permutation from
        :func:`petastorm_tpu.workers.ventilator.epoch_order` under the
        ventilator's LIVE seed, minus the statistics-pruned items and —
        for a restored reader's resume epoch — the items excluded as
        already consumed. Downstream resequencers (the mixture engine's
        ordered sources) use this to turn the pool's completion-order
        deliveries back into a deterministic stream.
        """
        from petastorm_tpu.workers.ventilator import epoch_order
        order = epoch_order(self._num_items,
                            self._ventilator.state_dict()['seed'],
                            epoch, self._shuffle_row_groups)
        skip = set(self._pruned_items)
        skip.update(self._resume_excluded.get(epoch, ()))
        return [int(i) for i in order if i not in skip]

    def _obs_health(self):
        """This reader's /health contribution: iteration state + the
        pool's liveness gauges (JSON-safe scalars only)."""
        health = {
            'started': self._started,
            'stopped': self._stopped,
            'last_row_consumed': self.last_row_consumed,
            'num_epochs': self._num_epochs,
            'row_groups': len(self._piece_indices),
            'cur_shard': self.cur_shard,
            'shard_count': self.shard_count,
            # plan-time pushdown (docs/telemetry.md "Query-shaped
            # reads"): items proven empty and skipped this run
            'pruned_items': len(self._pruned_items),
            # autotunable in-flight bound (docs/telemetry.md "Readahead")
            'ventilate_extra': self._ventilate_extra,
        }
        from petastorm_tpu import readahead
        health['readahead'] = readahead.health_snapshot()
        try:
            health.update(self._pool.diagnostics)
        except Exception:  # noqa: BLE001 - health must answer regardless
            pass
        return health

    def stop(self):
        self._obs_mount.close()
        self._pool.stop()
        self._stopped = True

    def join(self):
        self._pool.join()

    def cleanup(self):
        pass

    def exit(self):
        self.stop()
        self.join()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        self.stop()
        self.join()

    @property
    def num_epochs(self):
        """Requested epoch count (None = infinite)."""
        return self._num_epochs

    @property
    def ventilate_extra(self):
        """Row-groups kept in flight beyond the pool's worker count."""
        return self._ventilate_extra

    def set_ventilate_extra(self, extra):
        """Autotuner seam: adjust the ventilator's in-flight bound
        mid-run (the bound is a callable re-read on every wait cycle, so
        the new value is observed without waking anyone). Returns the
        applied value."""
        self._ventilate_extra = max(1, int(extra))
        return self._ventilate_extra

    @property
    def diagnostics(self):
        return self._pool.diagnostics

    def pipeline_report(self, wall_time_s=None):
        """Per-stage time breakdown + stall attribution for this process's
        pipeline (:func:`petastorm_tpu.telemetry.pipeline_report`) —
        worker-side stages (io/decode/filter/transform) are included for
        every pool flavor because the process/service pools merge worker
        metric deltas back over their result channels."""
        from petastorm_tpu.telemetry import pipeline_report
        return pipeline_report(wall_time_s=wall_time_s)

    def dump_trace(self, path):
        """Export the flight recorder's per-item trace as Chrome
        trace-event JSON (Perfetto-viewable; needs ``PETASTORM_TPU_TRACE=1``
        during the read — docs/telemetry.md). Worker-side events from
        every pool flavor are already merged here via the pools' delta
        channels. Returns the number of events written."""
        from petastorm_tpu.telemetry import dump_trace
        return dump_trace(path)

    # -- checkpointable iteration state --------------------------------------

    def state_dict(self):
        """Row-group-granular iteration state — a capability the reference
        lacks (SURVEY.md §5.4).

        Semantics: **at-least-once**. Resume restarts at the earliest epoch
        with unconsumed row-groups, skipping the ones already fully consumed
        in that epoch; row-groups in flight (or consumed in a *later* epoch
        due to pipelining across the epoch boundary) are re-read.
        """
        return self.resume_state_from(self._consumed_by_epoch)

    def resume_state_from(self, consumed_by_epoch):
        """A ``state_dict``-shaped resume point built from an EXTERNAL
        ``{epoch: {item_index, ...}}`` consumption record — used by
        downstream buffering consumers (JaxLoader) whose notion of
        "consumed" is delivery to the user, which lags this reader's."""
        vent_seed = self._ventilator.state_dict()['seed']
        # Statistics-pruned items (petastorm_tpu/pushdown.py) are
        # completed-with-zero-rows: they are never ventilated, so no
        # delivery can ever mark them consumed — without counting them
        # here, every epoch would read forever-incomplete and resume
        # would rewind to re-read row-groups PROVEN to deliver nothing.
        pruned = self._pruned_items

        def consumed_in(epoch):
            done = set(consumed_by_epoch.get(epoch, ()))
            return done | pruned if pruned else done

        epochs_seen = sorted(consumed_by_epoch)
        if not epochs_seen:
            resume_epoch, consumed = 0, []
        else:
            # Walk epochs from 0 (NOT just the epochs present in the
            # record): a delivery-order record can contain epoch 1 while
            # epoch 0 still has undelivered row-groups in flight — an
            # absent epoch is maximally incomplete, and skipping it would
            # lose its rows on resume.
            resume_epoch = None
            for e in range(epochs_seen[-1] + 1):
                if len(consumed_in(e)) < self._num_items:
                    resume_epoch = e
                    break
            if resume_epoch is None:
                # every seen epoch complete: resume into a FRESH epoch —
                # nothing consumed there yet (the new reader's own
                # planner re-derives its pruned set)
                resume_epoch, consumed = epochs_seen[-1] + 1, []
            else:
                consumed = sorted(consumed_in(resume_epoch))
        if self._num_epochs is None:
            iterations_remaining = None
        else:
            iterations_remaining = max(0, self._num_epochs - resume_epoch)
        return {
            'version': 1,
            'seed': vent_seed,
            'epoch': resume_epoch,
            'iterations_remaining': iterations_remaining,
            'consumed_items': consumed,
            # shard-independent identities: (global piece index, drop,
            # drop count) per LOCAL item, enabling cross-shard-count merge
            # (elastic resume after a pod resize —
            # see jax/checkpoint.merge_loader_states)
            'items_global': [list(ident) for ident in self._items_identity],
            'shard_count': self.shard_count,
            'cur_shard': self.cur_shard,
        }

    def _localize_state(self, state):
        """Normalize a possibly-rescaled state to LOCAL ``consumed_items``.

        A merged (elastic) state carries ``consumed_global`` — shard-
        independent ``(piece_index, drop, drop_count)`` identities of
        consumed items (the 3-tuple shape of ``_items_identity``) —
        instead of local indices. Identities belonging to other shards
        under THIS reader's assignment are simply absent from
        ``_items_identity`` and drop out, which is exactly right: each new
        shard skips the consumed subset of its own items.
        """
        if 'consumed_global' in state:
            consumed = {tuple(ident) for ident in state['consumed_global']}
            local = [i for i, ident in enumerate(self._items_identity)
                     if ident in consumed]
            state = dict(state)
            state['consumed_items'] = local
            return state
        saved = state.get('items_global')
        if saved is not None:
            saved = [tuple(ident) for ident in saved]
            if saved != self._items_identity:
                # Index-space drift: the SAVING reader's item list differs
                # from ours — a PETASTORM_TPU_PUSHDOWN flip across a
                # resume changes the filters= pre-shard prune, rewritten
                # files change the statistics. Local indices would then
                # silently name DIFFERENT row-groups (row loss), so
                # translate through the saver's per-index identities:
                # identities absent from our list drop (their row-groups
                # are not in this sweep), our extra items are simply
                # re-read (at-least-once; zero rows for provably-empty
                # groups). items_global is rewritten to OURS so a second
                # localization (consumption_record_for_resume) is a no-op.
                position = {ident: i for i, ident
                            in enumerate(self._items_identity)}
                local = sorted(
                    position[saved[i]] for i in state['consumed_items']
                    if i < len(saved) and saved[i] in position)
                state = dict(state)
                state['consumed_items'] = local
                state['items_global'] = [list(ident) for ident
                                         in self._items_identity]
        return state

    def load_state_dict(self, state):
        """Reposition the iteration before the first read. Accepts a
        per-shard state from ``state_dict`` or a merged elastic state
        (``consumed_global``) from
        :func:`petastorm_tpu.jax.checkpoint.merge_loader_states`."""
        if self._started:
            raise RuntimeError('load_state_dict must be called before iteration '
                               'starts')
        state = self._localize_state(state)
        self._ventilator.load_state_dict({
            'epoch': state['epoch'],
            'cursor': 0,
            'seed': state['seed'],
            'iterations_remaining': state['iterations_remaining'],
        })
        self._ventilator.exclude_from_next_epoch(state['consumed_items'])
        # ventilation_order must mirror the exclusion: the resume epoch's
        # already-consumed items never ventilate again
        self._resume_excluded = {
            int(state['epoch']): frozenset(state['consumed_items'])}
        # Seed the consumption record to match the restored position: a
        # LATER checkpoint must see epochs before the resume epoch as
        # complete and the resume epoch's pre-restore items as consumed —
        # without this, a checkpoint taken after a restore rewinds to
        # epoch 0 (those epochs would look "absent" to resume_state_from),
        # and the resume epoch could never read complete (the excluded
        # items are never re-delivered).
        self._consumed_by_epoch = self.consumption_record_for_resume(state)

    def consumption_record_for_resume(self, state):
        """``{epoch: {item_index}}`` as of the restored position in
        ``state``: every epoch before the resume epoch complete, the resume
        epoch holding its already-consumed items. Shared with the
        JaxLoader's delivery-accurate record, which must be seeded the same
        way on restore."""
        state = self._localize_state(state)
        record = {e: set(range(self._num_items))
                  for e in range(state['epoch'])}
        record[state['epoch']] = set(state['consumed_items'])
        return record
