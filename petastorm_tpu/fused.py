"""Fused batch-native decode: encoded cells ride to the staging arena.

The decode→collate→fill copy chain used to run in three passes: the
row-group worker decoded image cells into a fresh ``(n,)+shape`` batch,
the JAX loader's collate stage buffered/sliced that batch, and the
staging arena copied the slices into a slot before ``device_put``. This
module collapses the chain to ONE pass, the operator-fusion move of
tf.data (PAPERS.md, arxiv 2101.12127) applied to the decode tentpole
(ROADMAP "Batch-granularity native decode, fused into the staging
arena"):

* the worker, when the reader was built with ``defer_image_decode=True``
  (requested by :func:`petastorm_tpu.jax.make_jax_loader` whenever its
  own batch path can fuse), SKIPS decoding eligible image columns and
  publishes an :class:`EncodedImageColumn` — the still-encoded cells plus
  the field that knows how to decode them;
* the encoded column travels the exact route a decoded one would (noop
  re-batcher chunk views, provenance sidecars, part slicing) — a few KB
  of jpeg bytes per row instead of 150 KB of pixels, so every buffered
  hop is cheaper too; under a predicate it carries ONLY the surviving
  rows' cells (the worker's late-materialization path compacts survivor
  indices over the zero-copy cell views, ``arrow_worker.
  _decode_survivors``), so ``decode_fused`` decodes survivors straight
  into slot-ring rows and non-matching rows never cross the wire;
* the staging engine's fill pass (:mod:`petastorm_tpu.jax.staging`)
  decodes the cells **directly into the arena slot's rows** (or the
  fresh page-aligned assembly buffer on host-backed targets) through the
  codecs' ``decode_batch(..., out=)`` destination API, under the
  ``decode_fused`` stage span — decoded pixels are written exactly once,
  at their final host address, by the native batch decoders' internal
  C-level thread pool.

Eligibility is decided at two gates and every decline falls back to the
classic worker-side batched decode, counted in
``petastorm_tpu_fused_decode_fallbacks_total{reason=…}`` (the
"decode is batched but not fused" runbook in docs/troubleshoot.md reads
these): the worker defers only fixed-shape, numeric, null-free image
columns on the no-transform/no-ngram/no-cache path; the loader
materializes early when staging is off, rows are shuffled, or a dtype
cast retargets the column.

Ownership contract (pipesan): ``EncodedImageColumn.cells`` hold ZERO-COPY
views over the arrow column's data buffer — borrowed memory, registered
as a borrow source in ``analysis/contracts.py`` (``column.cells``). The
column object carries its ``owner`` (the arrow column) so the views
outlive every hop by construction, and pickling across a process/service
pool materializes the cells into owned copies.
"""

import logging

import numpy as np

from petastorm_tpu.codecs import decode_batch_with_nulls
from petastorm_tpu.telemetry import get_registry, metrics_disabled

logger = logging.getLogger(__name__)

#: registry counters (docs/telemetry.md metric reference)
FUSED_ROWS = 'petastorm_tpu_fused_decode_rows_total'
FUSED_BYTES = 'petastorm_tpu_fused_decode_bytes_total'
FUSED_FALLBACKS = 'petastorm_tpu_fused_decode_fallbacks_total'

#: column slabs align to page boundaries: XLA:CPU zero-copies suitably
#: aligned host arrays into device handles (measured, jax/staging.py),
#: and the native decoders' parallel row writes stay cache-line clean
SLAB_ALIGN = 4096


def alloc_column_slab(shape, dtype):
    """A writable ``np.empty(shape, dtype)`` equivalent whose data starts
    on a :data:`SLAB_ALIGN` (page) boundary — the row-group worker's
    decode destination (``decode_batch(out=)``) and the shape of buffer
    the staging engine's fresh-assembly path zero-copies from. The
    backing allocation rides the returned view's ``.base`` chain, so the
    slab owns its memory like any fresh ndarray."""
    dtype = np.dtype(dtype)
    nbytes = int(np.prod(shape, dtype=np.int64)) * dtype.itemsize
    if nbytes <= 0:
        return np.empty(shape, dtype)
    raw = np.empty(nbytes + SLAB_ALIGN, np.uint8)
    offset = (-raw.ctypes.data) % SLAB_ALIGN
    return raw[offset:offset + nbytes].view(dtype).reshape(shape)


def count_fallback(reason):
    """One fused-decode decline, attributed: the bench/runbook read these
    to explain a ``fused_decode_mode`` that is not ``fused-into-slot``."""
    if not metrics_disabled():
        get_registry().counter(FUSED_FALLBACKS, reason=reason).inc()


class EncodedImageColumn:
    """A column whose cells are STILL ENCODED: the deferred-decode
    carrier between the row-group worker and the staging arena.

    Mimics just enough of the decoded dense column's ndarray surface
    (``shape``/``dtype``/``len``/slicing) that the batch path between the
    two — provenance tagging, the noop re-batcher's chunk views, part
    slicing — needs no special cases; the first consumer that needs
    pixels calls :meth:`decode_into` (staging fill, zero extra copies) or
    :meth:`materialize` (fallback paths).

    ``cells`` is a sequence of encoded bytes-like objects (zero-copy
    ``np.uint8`` views over the arrow data buffer on the in-process
    path); ``owner`` pins the arrow column those views alias. Cells may
    not be None here — the worker's eligibility gate routes nullable
    row-groups to the classic decode so null semantics never change —
    but :meth:`decode_into` still zero-fills defensively via
    ``decode_batch_with_nulls``.
    """

    __slots__ = ('field', 'cells', 'owner')

    def __init__(self, field, cells, owner=None):
        self.field = field
        # Intentional transfer of the worker's borrowed cell views: the
        # arrow column that owns their memory rides along in `owner`, so
        # the views stay valid for this object's whole lifetime (and a
        # cross-process pickle materializes owned copies).  # pipesan: owns
        self.cells = cells
        self.owner = owner

    # -- ndarray-like surface -------------------------------------------------

    @property
    def shape(self):
        return (len(self.cells),) + tuple(self.field.shape)

    @property
    def dtype(self):
        return np.dtype(self.field.numpy_dtype)

    @property
    def nbytes(self):
        """DECODED size (what the fused fill will write), not the encoded
        payload size — the surface downstream accounting expects."""
        return int(np.prod(self.shape, dtype=np.int64)) * self.dtype.itemsize

    def __len__(self):
        return len(self.cells)

    def __getitem__(self, index):
        """Slicing returns a VIEW column over the same cells (the noop
        re-batcher splits chunks with ``col[:take]`` / ``col[take:]``);
        anything but a slice is a contract error — per-row access means
        some consumer thinks this is decoded data."""
        if not isinstance(index, slice):
            raise TypeError(
                'EncodedImageColumn is encoded data; decode it '
                '(decode_into/materialize) before per-row indexing')
        return EncodedImageColumn(self.field, self.cells[index],
                                  owner=self.owner)

    # -- decode ---------------------------------------------------------------

    def decode_into(self, out):
        """Decode every cell into the caller's ``(n,) + field.shape``
        destination — a staging-arena slot slice or a fresh assembly
        buffer — in one vectorized pass (native batch decoders' internal
        thread pool; null positions zero-filled). Returns ``out``."""
        return decode_batch_with_nulls(self.field, self.cells, out=out)

    def materialize(self):
        """Decode to a fresh page-aligned owned batch — the fallback for
        consumers that cannot provide a destination (staging disabled,
        shuffled rows, dtype recast)."""
        out = alloc_column_slab(self.shape, self.dtype)
        return self.decode_into(out)

    # -- pickling (process/service pools) ------------------------------------

    def __getstate__(self):
        # drop the arrow owner: the cells pickle as owned byte copies, so
        # the receiving process needs (and must not pay for) no second
        # copy of the arrow buffer riding along
        return (self.field,
                [None if c is None else bytes(c) for c in self.cells])

    def __setstate__(self, state):
        self.field, self.cells = state
        self.owner = None

    def __repr__(self):
        return ('EncodedImageColumn(%r, n=%d, shape=%s)'
                % (self.field.name, len(self.cells), self.shape))
