"""Pod-aware shard assignment.

The reference requires manual ``cur_shard``/``shard_count`` and only
cross-checks them against Horovod/MPI env vars
(``petastorm/spark/spark_dataset_converter.py:122-159``). On TPU pods the
source of truth is the JAX distributed runtime: every host is
``jax.process_index()`` of ``jax.process_count()``, so sharding defaults from
there — zero configuration on a pod, no communication (shards stay disjoint by
construction, SURVEY.md §5.8).
"""

import logging
import sys

logger = logging.getLogger(__name__)

_warned_uninitialized = False


def _jax_process_info():
    """(process_index, process_count) of an ALREADY-LIVE JAX runtime.

    Deliberately never triggers backend initialization: merely constructing a
    reader must not grab an accelerator (or hang on a wedged one). On a pod,
    user code runs ``jax.distributed.initialize()`` (or any jax op) before
    building readers, so the live-backend check passes there.
    """
    if 'jax' not in sys.modules:
        return None, None
    try:
        import jax
        from jax._src import xla_bridge
        if not xla_bridge.backends_are_initialized():
            global _warned_uninitialized
            if not _warned_uninitialized:
                _warned_uninitialized = True
                logger.warning(
                    'jax is imported but its backend is not initialized '
                    'yet; shard defaults are OFF for this reader. On a '
                    'multi-host pod, call jax.distributed.initialize() (or '
                    'pass cur_shard/shard_count explicitly) BEFORE building '
                    'readers, or every host will read the full dataset.')
            return None, None
        return jax.process_index(), jax.process_count()
    except Exception:  # noqa: BLE001 - private API drift or init failure
        return None, None


def default_shard_info(cur_shard, shard_count):
    """Resolve (cur_shard, shard_count), filling defaults from JAX.

    Rules:
    * both None → single process: no sharding unless a multi-process JAX
      runtime is active, in which case shard by process.
    * both set → use them (validated).
    * one set → error (ambiguous), matching the reference's strictness
      (``petastorm/reader.py:376-382``).
    """
    if cur_shard is None and shard_count is None:
        index, count = _jax_process_info()
        if count is not None and count > 1:
            logger.info('Sharding dataset by JAX process: shard %d of %d',
                        index, count)
            return index, count
        return None, None
    if cur_shard is None or shard_count is None:
        raise ValueError('cur_shard and shard_count must be specified together '
                         '(got cur_shard=%r, shard_count=%r)'
                         % (cur_shard, shard_count))
    if not 0 <= cur_shard < shard_count:
        raise ValueError('cur_shard %r must be in [0, shard_count=%r)'
                         % (cur_shard, shard_count))
    return cur_shard, shard_count
