"""Pod-aware shard assignment.

The reference requires manual ``cur_shard``/``shard_count`` and only
cross-checks them against Horovod/MPI env vars
(``petastorm/spark/spark_dataset_converter.py:122-159``). On TPU pods the
source of truth is the JAX distributed runtime: every host is
``jax.process_index()`` of ``jax.process_count()``, so sharding defaults from
there — zero configuration on a pod, no communication (shards stay disjoint by
construction, SURVEY.md §5.8).
"""

import logging
import sys

logger = logging.getLogger(__name__)

_warned_uninitialized = False


def _jax_process_info():
    """(process_index, process_count) of an ALREADY-LIVE JAX runtime.

    Deliberately never triggers backend initialization: merely constructing a
    reader must not grab an accelerator (or hang on a wedged one). On a pod,
    user code runs ``jax.distributed.initialize()`` (or any jax op) before
    building readers, so the live-backend check passes there.
    """
    if 'jax' not in sys.modules:
        return None, None
    try:
        import jax
        from jax._src import xla_bridge
        if not xla_bridge.backends_are_initialized():
            global _warned_uninitialized
            if not _warned_uninitialized:
                _warned_uninitialized = True
                logger.warning(
                    'jax is imported but its backend is not initialized '
                    'yet; shard defaults are OFF for this reader. On a '
                    'multi-host pod, call jax.distributed.initialize() (or '
                    'pass cur_shard/shard_count explicitly) BEFORE building '
                    'readers, or every host will read the full dataset.')
            return None, None
        return jax.process_index(), jax.process_count()
    except Exception:  # noqa: BLE001 - private API drift or init failure
        return None, None


def local_shard_plan(sharding, local_rows, process_count=None):
    """Row-granular dispatch plan for one host's slice of a batch whose
    leading axis is laid out by ``sharding`` (a
    ``jax.sharding.NamedSharding`` partitioning axis 0).

    Returns ``[(device, lo, hi), ...]`` — for every *addressable* device,
    the half-open ``[lo, hi)`` row range of the **process-local** batch
    that device holds (devices replicated along non-data mesh axes each
    appear with their own — possibly identical — range). The staging
    engine slices its slot buffers with these ranges and ships the whole
    pytree in ONE batched ``jax.device_put`` instead of one
    ``make_array_from_process_local_data`` round trip per field.

    Returns None when the plan cannot be proven sound — a non-unit-step
    index, a host whose global rows are not one contiguous block, or a
    sharding this jax cannot map — and the caller must fall back to
    ``make_array_from_process_local_data`` (always correct, never fast).
    """
    import jax
    if process_count is None:
        process_count = jax.process_count()
    global_rows = local_rows * process_count
    try:
        index_map = sharding.addressable_devices_indices_map(
            (global_rows,))
    except Exception:  # noqa: BLE001 - jax version / layout drift
        logger.debug('local_shard_plan: addressable_devices_indices_map '
                     'failed for %r', sharding, exc_info=True)
        return None
    spans = []
    for device, index in index_map.items():
        index = index or (slice(None),)
        lo, hi, step = index[0].indices(global_rows)
        if step != 1 or hi <= lo:
            return None
        spans.append((device, lo, hi))
    if not spans:
        return None
    host_lo = min(lo for _, lo, _ in spans)
    host_hi = max(hi for _, _, hi in spans)
    if host_hi - host_lo != local_rows:
        # this host's devices do not own exactly one local batch of rows
        return None
    # the host block must be contiguously covered (no gaps a local row
    # could fall into): merge the per-device intervals and check
    covered = host_lo
    for lo, hi in sorted((lo, hi) for _, lo, hi in spans):
        if lo > covered:
            return None
        covered = max(covered, hi)
    if covered != host_hi:
        return None
    return [(device, lo - host_lo, hi - host_lo)
            for device, lo, hi in spans]


def default_shard_info(cur_shard, shard_count):
    """Resolve (cur_shard, shard_count), filling defaults from JAX.

    Rules:
    * both None → single process: no sharding unless a multi-process JAX
      runtime is active, in which case shard by process.
    * both set → use them (validated).
    * one set → error (ambiguous), matching the reference's strictness
      (``petastorm/reader.py:376-382``).
    """
    if cur_shard is None and shard_count is None:
        index, count = _jax_process_info()
        if count is not None and count > 1:
            logger.info('Sharding dataset by JAX process: shard %d of %d',
                        index, count)
            return index, count
        return None, None
    if cur_shard is None or shard_count is None:
        raise ValueError('cur_shard and shard_count must be specified together '
                         '(got cur_shard=%r, shard_count=%r)'
                         % (cur_shard, shard_count))
    if not 0 <= cur_shard < shard_count:
        raise ValueError('cur_shard %r must be in [0, shard_count=%r)'
                         % (cur_shard, shard_count))
    return cur_shard, shard_count
