"""Distributed/parallel utilities: pod-aware sharding, mesh helpers, device
staging policy. The reference's distributed contract is shard-per-rank with no
collectives (SURVEY.md §2.2, §5.8); here the rank/size default from the JAX
distributed runtime and the device-mesh utilities integrate with
``jax.sharding``."""
