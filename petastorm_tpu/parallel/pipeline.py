"""Pipeline parallelism: GPipe-style microbatched stage execution.

Layers are stacked on a leading ``(n_stages, ...)`` axis sharded over the
mesh's ``'pipe'`` axis, so each device holds exactly one stage's weights.
Execution runs ``n_microbatches + n_stages - 1`` ticks of a collective
pipeline: every tick, each device applies its stage to the activation it
holds, then rotates the result to the next stage with ``lax.ppermute``
(compute overlaps the ICI hop). Stage 0 feeds fresh microbatches during
the first ``n_microbatches`` ticks; the last stage emits finished
microbatches from tick ``n_stages - 1`` on.

The whole schedule is a ``lax.scan`` (single trace, reverse-differentiable:
``jax.grad`` through the pipeline yields exactly the sequential model's
gradients — ``ppermute``'s transpose is the reverse rotation), with static
shapes throughout, so XLA sees one compact loop instead of an unrolled
schedule.

The reference framework has no model execution layer (SURVEY.md §0); this
is part of the TPU-native consumer layer, alongside tensor parallelism in
:mod:`petastorm_tpu.models.transformer`, expert parallelism in
:mod:`petastorm_tpu.models.moe`, and sequence parallelism in
:mod:`petastorm_tpu.ops.ring_attention` / ``ulysses_attention``.
"""

import functools

import jax
import jax.numpy as jnp
from jax import lax

from petastorm_tpu.parallel.mesh import PIPE_AXIS


def pipeline_supported():
    """Whether this jax can run :func:`pipeline_apply` soundly: the
    modern ``jax.shard_map`` (``check_vma=True``) plus the varying-
    manual-axes primitives (``lax.pcast``/``lax.pvary``) that make the
    replicated-input transpose correct. On older jax builds —
    ``jax.experimental.shard_map``'s ``check_rep=False`` era — input
    gradients through replicated in_specs are silently wrong, which is
    strictly worse than refusing; callers (and tests) should gate on
    this instead of catching the ImportError."""
    try:
        from jax import shard_map  # noqa: F401
    except ImportError:
        return False
    return hasattr(lax, 'pcast') or hasattr(lax, 'pvary')


def _require_shard_map():
    """The guarded import of the modern ``jax.shard_map`` — a clear,
    actionable error instead of a bare ImportError mid-trace."""
    if not pipeline_supported():
        raise RuntimeError(
            'pipeline_apply requires the modern jax.shard_map with '
            'sound vma tracking (jax.shard_map + lax.pcast/pvary; '
            'jax >= 0.6). This jax (%s) lacks it, and the experimental '
            'check_rep=False fallback would produce silently wrong '
            'input gradients — upgrade jax to use pipeline '
            'parallelism; every other parallelism family '
            '(data/tensor/expert/sequence) works on this build.'
            % jax.__version__)
    from jax import shard_map
    return shard_map


def shard_stage_params(stage_params, mesh, axis_name=PIPE_AXIS,
                       inner_specs=None):
    """Place a stacked-stage parameter pytree so each leaf's leading
    (stage) axis is sharded over ``axis_name``: one stage per mesh slice.

    :param inner_specs: optional pytree of PartitionSpecs for the
        dimensions AFTER the stage axis (e.g. Megatron tensor-parallel
        splits over ``'model'``); default replicates them.
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    def place(leaf, inner=None):
        rest = tuple(inner) if inner is not None else ()
        rest = rest + (None,) * (jnp.ndim(leaf) - 1 - len(rest))
        return jax.device_put(leaf,
                              NamedSharding(mesh, P(axis_name, *rest)))

    if inner_specs is None:
        return jax.tree_util.tree_map(place, stage_params)
    # PartitionSpec is a pytree LEAF, so a specs tree mirrors the params
    # tree structurally and tree_map pairs them leaf-for-leaf
    return jax.tree_util.tree_map(place, stage_params, inner_specs)


def _to_varying(x, axis_name):
    """Mark a replicated value as device-varying over ``axis_name``.

    Under ``check_vma=True`` this is what makes the pipeline's transpose
    CORRECT for the input cotangent: ``pcast(to='varying')`` transposes to
    a psum over the axis, so ``jax.grad`` w.r.t. the (replicated) batch
    sums each stage's contribution exactly once. Without it (legacy
    ``check_rep=False`` mode) input gradients through shard_map's
    replicated in_specs are silently wrong.
    """
    if hasattr(lax, 'pcast'):
        return lax.pcast(x, axis_name, to='varying')
    return lax.pvary(x, (axis_name,))


def _pipeline_local(stage_params, x, stage_fn, axis_name, n_stages,
                    n_microbatches, with_aux=False):
    """Per-device body under shard_map: ``stage_params`` leaves have a
    leading stage axis of local size 1; ``x`` is the full (replicated)
    batch.

    With ``with_aux`` the stage returns ``(y, scalar)`` and the scalars
    accumulate ONLY over real (stage, microbatch) pairs — warmup/drain
    bubble ticks run zero activations through the stage and their aux
    contribution (e.g. a Switch router's load statistics over garbage
    tokens) must not leak into the loss. A microbatch m is inside stage s
    exactly at tick ``t = m + s``."""
    stage = lax.axis_index(axis_name)
    params_local = jax.tree_util.tree_map(lambda p: p[0], stage_params)

    mb = x.shape[0] // n_microbatches
    feed = x.reshape((n_microbatches, mb) + x.shape[1:])
    # warmup/drain padding: ticks past the feed carry zeros into stage 0
    pad = jnp.zeros((n_stages - 1,) + feed.shape[1:], x.dtype)
    feed = _to_varying(jnp.concatenate([feed, pad], axis=0), axis_name)
    ticks = _to_varying(jnp.arange(feed.shape[0]), axis_name)
    perm = [(i, i + 1) for i in range(n_stages - 1)]

    def tick(carry, inp):
        act, aux_acc = carry
        x_t, t = inp
        x_in = jnp.where(stage == 0, x_t, act)
        if with_aux:
            y, aux = stage_fn(params_local, x_in)
            offset = t - stage
            real = jnp.logical_and(offset >= 0, offset < n_microbatches)
            aux_acc = aux_acc + jnp.where(real, aux.astype(jnp.float32), 0.0)
        else:
            y = stage_fn(params_local, x_in)
        emit = jnp.where(stage == n_stages - 1, y, jnp.zeros_like(y))
        act_next = lax.ppermute(y, axis_name, perm) if perm else y
        return (act_next, aux_acc), emit

    from petastorm_tpu.parallel.mesh import match_vma
    aux0 = _to_varying(jnp.zeros((), jnp.float32), axis_name)
    act0 = match_vma(jnp.zeros_like(feed[0]), feed)  # pp×sp: + seq axis
    (_, aux_acc), emits = lax.scan(tick, (act0, aux0), (feed, ticks))
    outs = emits[n_stages - 1:]                 # (M, mb, ...) on last stage
    outs = lax.psum(outs, axis_name)            # replicate to every stage
    outs = outs.reshape(x.shape)
    if not with_aux:
        return outs
    # total over stages; mean over microbatches — each microbatch's pass
    # through all stages approximates the sequential model's per-layer aux
    # over the full batch (per-microbatch routing statistics, the standard
    # sharded-MoE estimator)
    aux_total = lax.psum(aux_acc, axis_name) / n_microbatches
    return outs, aux_total


def pipeline_apply(stage_fn, stage_params, x, mesh, axis_name=PIPE_AXIS,
                   n_microbatches=None, with_aux=False, seq_axis=None):
    """Apply ``n_stages`` sequential stages to ``x`` with the stage stack
    sharded over ``mesh[axis_name]``.

    :param stage_fn: ``(params_slice, microbatch) -> microbatch`` — one
        stage's computation; output shape must equal input shape (the
        activation rotates through homogeneous pipeline slots). With
        ``with_aux``, returns ``(microbatch, scalar)`` instead and the
        call returns ``(output, aux)`` where ``aux`` sums the scalars over
        stages and averages over microbatches (bubble ticks excluded) —
        the MoE-loss shape of auxiliary outputs.
    :param stage_params: pytree whose leaves carry a leading
        ``n_stages`` axis (use :func:`shard_stage_params` to place it).
    :param x: (batch, ...) input, replicated over the pipe axis.
    :param n_microbatches: pipeline chunking (default ``n_stages``; more
        microbatches → less bubble, smaller per-tick matmuls). Must divide
        the batch.
    :param seq_axis: pp×sp composition — a second MANUAL mesh axis that
        shards ``x``'s dim 1 (the sequence). ``stage_fn`` then sees local
        sequence blocks and must run collective attention over
        ``seq_axis`` itself (e.g. the ring/Ulysses per-device bodies);
        position-wise ops need no change. Requires ``x.shape[1]``
        divisible by the axis size. Composes with ``with_aux``
        (pp×sp×ep): the stage's aux must come back seq-INVARIANT — psum
        its per-shard statistics over ``seq_axis`` itself, as
        ``moe_forward(seq_axis=...)`` does.
    :return: (batch, ...) output, replicated over the pipe axis — equal to
        sequentially applying the stages; plus the aux scalar when
        ``with_aux``.
    """
    from jax.sharding import PartitionSpec as P

    n_stages = mesh.shape[axis_name]
    if n_microbatches is None:
        n_microbatches = n_stages
    if x.shape[0] % n_microbatches:
        raise ValueError('batch %d not divisible into %d microbatches'
                         % (x.shape[0], n_microbatches))
    axis_names = {axis_name}
    x_spec = P()
    if seq_axis is not None:
        n_seq = mesh.shape[seq_axis]
        if x.ndim < 2 or x.shape[1] % n_seq:
            raise ValueError('x dim 1 (%s) not divisible over %d seq '
                             'shards' % (x.shape[1:2] or 'absent', n_seq))
        axis_names.add(seq_axis)
        x_spec = P(None, seq_axis)

    param_specs = jax.tree_util.tree_map(
        lambda p: P(axis_name, *([None] * (jnp.ndim(p) - 1))), stage_params)
    body = functools.partial(_pipeline_local, stage_fn=stage_fn,
                             axis_name=axis_name, n_stages=n_stages,
                             n_microbatches=n_microbatches,
                             with_aux=with_aux)
    # check_vma=True (replication tracked soundly) is REQUIRED here: the
    # batch enters replicated, and only the varying-manual-axes machinery
    # transposes that correctly (see _to_varying). No check_rep=False
    # fallback — on a jax too old for it, wrong input gradients would be
    # silent, which is strictly worse than an error
    # (pipeline_supported() is the capability probe; _require_shard_map
    # turns its absence into an actionable RuntimeError).
    #
    # Manual ONLY over the pipe axis (+ seq_axis for pp×sp): any other
    # mesh axes (data, model, expert) stay auto, so the batch rides in
    # data-sharded, stage weights keep their tensor-parallel/expert
    # layout, and XLA inserts the dp/tp/ep collectives inside each stage
    # as usual — this is what lets pp compose with the other axes in ONE
    # jitted step.
    shard_map = _require_shard_map()
    # the aux scalar leaves replicated over EVERY manual axis: psum'd over
    # pipe in _pipeline_local, and (for pp×sp×ep) made seq-invariant by
    # the stage's own psum of its routing statistics over seq_axis
    out_specs = (x_spec, P()) if with_aux else x_spec
    fn = shard_map(body, mesh=mesh, in_specs=(param_specs, x_spec),
                   out_specs=out_specs, axis_names=axis_names,
                   check_vma=True)
    return fn(stage_params, x)


def reference_pipeline(stage_fn, stage_params, x, with_aux=False):
    """Sequential oracle: apply each stage in order on the full batch.

    With ``with_aux`` the per-stage scalars sum over stages on the FULL
    batch — what :func:`pipeline_apply` computes exactly at
    ``n_microbatches=1`` and estimates (per-microbatch statistics) above.
    """
    n_stages = jax.tree_util.tree_leaves(stage_params)[0].shape[0]
    aux_total = jnp.zeros((), jnp.float32)
    for s in range(n_stages):
        params_s = jax.tree_util.tree_map(lambda p: p[s], stage_params)
        if with_aux:
            x, aux = stage_fn(params_s, x)
            aux_total = aux_total + aux.astype(jnp.float32)
        else:
            x = stage_fn(params_s, x)
    if with_aux:
        return x, aux_total
    return x
