"""Device-mesh helpers: the framework's canonical mesh axes.

Axes convention used across models, the JAX loader, and the graft entry:

* ``'data'``  — batch (data-parallel) axis; the loader shards batches here.
* ``'model'`` — tensor-parallel axis; models shard weights/heads here.

On a pod this is created once from all devices; in tests from the virtual
8-device CPU platform.
"""

import numpy as np

DATA_AXIS = 'data'
MODEL_AXIS = 'model'


def make_mesh(data=None, model=1, devices=None):
    """A ``jax.sharding.Mesh`` of shape (data, model).

    :param data: data-parallel size (default: all devices / model).
    :param model: tensor-parallel size.
    :param devices: explicit device list (default ``jax.devices()``).
    """
    import jax
    from jax.sharding import Mesh
    devices = list(devices if devices is not None else jax.devices())
    if data is None:
        if len(devices) % model:
            raise ValueError('device count %d not divisible by model=%d'
                             % (len(devices), model))
        data = len(devices) // model
    n = data * model
    if n > len(devices):
        raise ValueError('mesh %dx%d needs %d devices, have %d'
                         % (data, model, n, len(devices)))
    grid = np.asarray(devices[:n]).reshape(data, model)
    return Mesh(grid, (DATA_AXIS, MODEL_AXIS))


def data_sharding(mesh, ndim=1):
    """NamedSharding that shards axis 0 over 'data', replicating the rest."""
    from jax.sharding import NamedSharding, PartitionSpec
    spec = PartitionSpec(DATA_AXIS, *([None] * (ndim - 1)))
    return NamedSharding(mesh, spec)


def replicated(mesh):
    from jax.sharding import NamedSharding, PartitionSpec
    return NamedSharding(mesh, PartitionSpec())
