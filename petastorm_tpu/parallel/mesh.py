"""Device-mesh helpers: the framework's canonical mesh axes.

Axes convention used across models, ops, the JAX loader, and the graft
entry:

* ``'data'``   — batch (data-parallel) axis; the loader shards batches here.
* ``'model'``  — tensor-parallel axis; models shard weights/heads here.
* ``'expert'`` — expert-parallel axis; MoE layers shard experts here.
* ``'pipe'``   — pipeline-parallel axis; stages shard layer stacks here.
* ``'seq'``    — sequence/context-parallel axis (ring / Ulysses attention).

On a pod this is created once from all devices; in tests from the virtual
8-device CPU platform.
"""

import numpy as np

DATA_AXIS = 'data'
MODEL_AXIS = 'model'
EXPERT_AXIS = 'expert'
PIPE_AXIS = 'pipe'
SEQ_AXIS = 'seq'


def make_mesh(data=None, model=1, devices=None):
    """A ``jax.sharding.Mesh`` of shape (data, model).

    :param data: data-parallel size (default: all devices / model).
    :param model: tensor-parallel size.
    :param devices: explicit device list (default ``jax.devices()``).
    """
    import jax
    from jax.sharding import Mesh
    devices = list(devices if devices is not None else jax.devices())
    if data is None:
        if len(devices) % model:
            raise ValueError('device count %d not divisible by model=%d'
                             % (len(devices), model))
        data = len(devices) // model
    n = data * model
    if n > len(devices):
        raise ValueError('mesh %dx%d needs %d devices, have %d'
                         % (data, model, n, len(devices)))
    grid = np.asarray(devices[:n]).reshape(data, model)
    return Mesh(grid, (DATA_AXIS, MODEL_AXIS))


def make_named_mesh(axes, devices=None):
    """A ``jax.sharding.Mesh`` with arbitrary named axes.

    :param axes: ordered ``{axis_name: size}`` mapping (e.g.
        ``{'data': 2, 'pipe': 2, 'model': 2}``). One axis may be ``None``
        to absorb the remaining devices.
    :param devices: explicit device list (default ``jax.devices()``).
    """
    import jax
    from jax.sharding import Mesh
    devices = list(devices if devices is not None else jax.devices())
    names = list(axes)
    sizes = list(axes.values())
    wild = [i for i, s in enumerate(sizes) if s is None]
    if len(wild) > 1:
        raise ValueError('at most one axis size may be None; got %r' % (axes,))
    fixed = 1
    for s in sizes:
        fixed *= (s if s is not None else 1)
    if wild:
        if len(devices) % fixed:
            raise ValueError('device count %d not divisible by fixed axes '
                             'product %d (%r)' % (len(devices), fixed, axes))
        sizes[wild[0]] = len(devices) // fixed
        fixed = len(devices)
    if fixed != len(devices):
        # never silently drop chips: a typo'd axis size halving the pod is
        # far worse than this error; pass one axis as None to auto-fill, or
        # slice the device list explicitly
        raise ValueError('mesh %r covers %d devices but %d were provided; '
                         'use a None axis size to absorb the remainder or '
                         'pass an explicit devices= slice'
                         % (axes, fixed, len(devices)))
    grid = np.asarray(devices).reshape(sizes)
    return Mesh(grid, tuple(names))


def manual_shard_map(body, mesh, in_specs, out_specs,
                     check_replication=False):
    """Version-portable ``shard_map``: the modern ``jax.shard_map``
    (``check_vma``) with fallback to the experimental API (``check_rep``).
    The single home for this shim — ring/Ulysses attention build on it.
    (The pipeline executor deliberately does NOT: it requires the sound
    ``check_vma=True`` transpose and must fail loudly on an older jax.)
    """
    try:
        from jax import shard_map
        return shard_map(body, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, check_vma=check_replication)
    except (ImportError, TypeError):  # older jax: experimental API
        from jax.experimental.shard_map import shard_map as _shard_map
        return _shard_map(body, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_rep=check_replication)


def match_vma(x, ref):
    """Promote ``x``'s varying-manual-axes set to cover ``ref``'s.

    Under ``shard_map(check_vma=True)`` a ``lax.scan``/``fori_loop`` carry
    must keep a stable vma type, so a fresh constant (``jnp.zeros``)
    initializing a carry that accumulates device-varying values needs an
    explicit ``pvary`` over the reference's axes. Outside a checked manual
    region (or on jax without vma tracking) this is a no-op.
    """
    import jax
    from jax import lax
    typeof = getattr(jax, 'typeof', None)
    if typeof is None:  # jax before vma tracking: nothing to promote
        return x
    ref_vma = getattr(typeof(ref), 'vma', None)
    if not ref_vma:
        return x
    have = getattr(typeof(x), 'vma', frozenset())
    missing = tuple(sorted(ref_vma - have))
    return lax.pvary(x, missing) if missing else x


def data_sharding(mesh, ndim=1):
    """NamedSharding that shards axis 0 over 'data', replicating the rest."""
    from jax.sharding import NamedSharding, PartitionSpec
    spec = PartitionSpec(DATA_AXIS, *([None] * (ndim - 1)))
    return NamedSharding(mesh, spec)


def replicated(mesh):
    from jax.sharding import NamedSharding, PartitionSpec
    return NamedSharding(mesh, PartitionSpec())
