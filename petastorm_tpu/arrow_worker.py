"""The row-group decode worker: parquet → decoded numpy column batches.

This merges the reference's two worker flavors
(``petastorm/arrow_reader_worker.py`` and ``py_dict_reader_worker.py``) into a
single **column-major** worker, per the TPU-first design stance (SURVEY.md
§7.1): every row-group is processed as columns end-to-end; the row-at-a-time
``make_reader`` API is a thin slicing view applied at the consumer
(:mod:`petastorm_tpu.reader`), not a separate decode path.

Pipeline per ventilated item (cf. ``arrow_reader_worker.py:116-170``):
rowgroup read (predicate columns first, early-exit) → row mask → shuffle-row-
drop partition → vectorized codec decode of surviving rows → TransformSpec →
publish a :class:`ColumnBatch`.
"""

import hashlib
import logging
from collections import OrderedDict

import numpy as np
import pyarrow.parquet as pq

from petastorm_tpu import faults
# the wire-speed I/O plane (docs/telemetry.md "Readahead"): coalesced
# column-chunk prefetch serving _read_columns zero-copy; a miss IS the
# blocking read below, so parity never depends on it
from petastorm_tpu import readahead
from petastorm_tpu.cache import NullCache
from petastorm_tpu.codecs import CompressedImageCodec, decode_batch_with_nulls
from petastorm_tpu.fused import (
    EncodedImageColumn, alloc_column_slab, count_fallback,
)

#: the worker-side deferral gate, re-derived per worker but COUNTED once
#: per Reader: deferral is sound only when the worker adds nothing after
#: decode (a TransformSpec/NGram needs pixels there; a cache must store
#: finished batches, not deferred stubs)
def defer_config_ok(transform_spec, ngram, cache):
    return (transform_spec is None and ngram is None
            and (cache is None or isinstance(cache, NullCache)))
from petastorm_tpu.materialized_cache import (
    MaterializedRowGroupCache, dataset_file_fingerprint, decode_fingerprint,
)
# the planner half of the selective-read fast path (docs/telemetry.md
# "Query-shaped reads"); also the ONE owner of the
# late-materialized-rows counter name this worker increments
from petastorm_tpu import pushdown
from petastorm_tpu.telemetry import get_registry, metrics_disabled, span
from petastorm_tpu.workers.worker_base import WorkerBase

logger = logging.getLogger(__name__)

_ALL_ROWS = slice(None)

#: bound on the per-worker open-ParquetFile memo: many-file datasets must
#: not grow an unbounded open-file/handle map per worker — least-recently
#: used files are closed and transparently re-opened on the next touch.
#: The trade-off is explicit: an eviction costs a footer re-read/parse on
#: the next touch of that file (a remote round trip on object stores,
#: paid even on readahead hits — serve() needs the parsed metadata), so
#: the bound is sized to make eviction RARE under shuffled many-file
#: reads while keeping worst-case handle count (workers × this) well
#: under default ulimits
_PARQUET_FILE_CACHE_MAX = 64


def _binary_cell_views(arrow_col):
    """Zero-copy ``np.uint8`` views over a binary column's cells.

    Returns None when the column is not binary-typed (caller falls back to
    ``to_pylist``). Null cells become None. The views alias the arrow data
    buffer, so they are only valid while the source column is alive — the
    decode loop consumes them immediately within ``_load_rowgroup``.
    """
    import pyarrow as pa
    chunks = (arrow_col.chunks if isinstance(arrow_col, pa.ChunkedArray)
              else [arrow_col])
    cells = []
    for chunk in chunks:
        if pa.types.is_large_binary(chunk.type):
            offsets_dtype = np.int64
        elif pa.types.is_binary(chunk.type):
            offsets_dtype = np.int32
        else:
            return None
        if chunk.null_count:
            cells.extend(
                np.frombuffer(v.as_buffer(), np.uint8) if v.is_valid else None
                for v in chunk)
            continue
        buffers = chunk.buffers()
        offsets = np.frombuffer(buffers[1], dtype=offsets_dtype,
                                count=chunk.offset + len(chunk) + 1)
        offsets = offsets[chunk.offset:]
        data = buffers[2]
        cells.extend(
            np.frombuffer(data, np.uint8, offsets[i + 1] - offsets[i],
                          offsets[i])
            for i in range(len(chunk)))
    return cells


def typed_partition_value(field, value):
    """Cast a hive-partition path string to the schema field's numpy dtype.

    Without this, predicates would compare typed data values against raw
    partition-directory strings (e.g. ``5 != '5'``) and silently match
    nothing.
    """
    if field is None or value is None:
        return value
    try:
        dtype = np.dtype(field.numpy_dtype)
    except TypeError:  # e.g. Decimal
        return value
    if dtype.kind in 'iuf':
        try:
            return dtype.type(value)
        except (TypeError, ValueError):
            return value
        except OverflowError as e:
            # only reachable with an EXPLICITLY declared dtype (inference
            # bounds-checks); silently returning the string would make
            # predicates mismatch quietly — fail loud and early instead
            raise ValueError(
                'Hive partition value %r of field %r does not fit its '
                'declared dtype %s' % (value, field.name, dtype)) from e
    if dtype.kind == 'b':
        return value in (True, 'true', 'True', '1', 1)
    return value


class ColumnBatch:
    """Decoded columns of (a filtered subset of) one row-group.

    ``item_index`` identifies the ventilated work item that produced the batch
    (set by the worker, used for exact checkpoint/resume accounting).
    """

    __slots__ = ('columns', 'length', 'item_index', 'epoch')

    def __init__(self, columns, length, item_index=None, epoch=None):
        self.columns = columns
        self.length = length
        self.item_index = item_index
        self.epoch = epoch

    def row(self, i):
        return {name: col[i] for name, col in self.columns.items()}


class RowGroupWorker(WorkerBase):
    """Args (dict): dataset_info, loaded_schema (view of stored fields to
    read+decode), schema (final output schema, after TransformSpec),
    stored_schema, transform_spec, cache, ngram, row_groups."""

    def __init__(self, worker_id, publish_func, args):
        super().__init__(worker_id, publish_func, args)
        self._dataset_info = args['dataset_info']
        self._schema = args['schema']
        self._loaded_schema = args['loaded_schema']
        self._stored_schema = args['stored_schema']
        self._transform_spec = args.get('transform_spec')
        self._cache = args.get('cache')
        self._ngram = args.get('ngram')
        self._row_groups = args['row_groups']
        # Fused-decode deferral (petastorm_tpu/fused.py): the consumer
        # (JaxLoader) asked for encoded image cells instead of decoded
        # pixels so the staging arena can decode straight into its slot
        # buffers. The config decline is COUNTED once at Reader
        # construction, not here — N pool workers re-deriving the same
        # gate must not inflate the fallbacks counter by the worker count.
        self._defer_decode = (bool(args.get('defer_image_decode'))
                              and defer_config_ok(self._transform_spec,
                                                  self._ngram, self._cache))
        self._parquet_files = OrderedDict()
        # per-process readahead manager (petastorm_tpu/readahead.py),
        # shared by every thread-pool worker in this process and
        # refcounted across them; None when the plane is off or the
        # reader shipped no plan (caching readers — a warm epoch must
        # not prefetch bytes it will never read)
        self._readahead = readahead.attach(args)
        # PETASTORM_TPU_PUSHDOWN=0: the decode-everything-then-filter
        # oracle shape (exact-parity baseline + the bench's full-scan
        # rung) — resolved once per worker, in the worker's own process
        self._fullscan_oracle = pushdown.fullscan_oracle()
        # decoded-cache key identity, resolved lazily (per process, per
        # parquet file) — see _decoded_fingerprint
        self._decode_fp = None
        self._file_fps = {}

    # -- worker contract ----------------------------------------------------

    def process(self, piece_index, worker_predicate=None,
                shuffle_row_drop_partition=(0, 1), item_index=None, epoch=None):
        from petastorm_tpu.filters import FiltersPredicate
        piece = self._row_groups[piece_index]
        # Cache only content with a stable identity: arbitrary predicates
        # have none, and for the raw pickle cache neither do TransformSpec
        # callables. The MATERIALIZED cache fingerprints the transform
        # (code + closure + schema edits) and the codec/schema view into
        # its key, so it caches the post-transform batch — the whole
        # point of the decoded tier.
        decoded = isinstance(self._cache, MaterializedRowGroupCache)
        # TransformSpec(cacheable=False) marks a STOCHASTIC transform
        # (random augmentation): caching its output would silently replay
        # epoch 1's randomness forever, so those readers always decode.
        # Under the implicit fleet-knob upgrade the bar is higher still:
        # only transforms that DECLARED cacheable=True participate — the
        # operator's knob must not freeze an unmarked transform whose
        # determinism nobody ever vouched for.
        transform_ok = self._transform_spec is None or (
            decoded and self._spec_cacheable())
        if self._cache is not None and not isinstance(self._cache, NullCache) \
                and transform_ok \
                and (worker_predicate is None
                     or isinstance(worker_predicate, FiltersPredicate)):
            cache_key = self._cache_key(piece, worker_predicate,
                                        shuffle_row_drop_partition)
            if decoded:
                cache_key += ':d%s' % self._decoded_fingerprint(piece)
            batch = self._cache.get(
                cache_key,
                lambda: self._load_rowgroup(piece, worker_predicate,
                                            shuffle_row_drop_partition,
                                            item_index=item_index,
                                            epoch=epoch))
        else:
            batch = self._load_rowgroup(piece, worker_predicate,
                                        shuffle_row_drop_partition,
                                        item_index=item_index, epoch=epoch)
        if batch is not None:
            batch.item_index = item_index
            batch.epoch = epoch
        if batch is not None and batch.length > 0:
            if self._ngram is not None:
                windows = self._ngram.form_ngram(batch, self._schema)
                for i, window in enumerate(windows):
                    # Wrapped as a plain dict (picklable across the process
                    # pool); 'last' lets the consumer mark the whole work
                    # item consumed for checkpoint/resume accounting.
                    self.publish_func({'window': window,
                                       'item_index': item_index,
                                       'epoch': epoch,
                                       'last': i == len(windows) - 1})
            else:
                self.publish_func(batch)

    def shutdown(self):
        if self._readahead is not None:
            self._readahead = None
            readahead.release(self.args)
        for f in self._parquet_files.values():
            try:
                f.close()
            except Exception:  # noqa: BLE001 - best-effort close
                pass
        self._parquet_files = OrderedDict()

    # -- internals ----------------------------------------------------------

    def _cache_key(self, piece, worker_predicate, drop_partition):
        # Reader rejects cache + arbitrary predicates up front (no stable
        # content identity to key on). DNF filters ARE content-addressable —
        # plain tuples — so they participate in the key instead.
        from petastorm_tpu.filters import FiltersPredicate
        filter_part = ''
        if worker_predicate is not None:
            assert isinstance(worker_predicate, FiltersPredicate)
            filter_part = ':f%s' % hashlib.md5(
                repr(worker_predicate.clauses).encode('utf-8')).hexdigest()
        # The loaded column set is part of the content: readers with
        # different schema_fields sharing a cache dir must not serve each
        # other truncated batches.
        columns_hash = hashlib.md5(
            ','.join(sorted(self._needed_stored_fields()))
            .encode('utf-8')).hexdigest()[:12]
        url_hash = hashlib.md5(
            str(self._dataset_info.url).encode('utf-8')).hexdigest()
        return '%s:%s:rg%d:%s:c%s%s' % (url_hash,
                                        self._dataset_info.relpath(piece.path),
                                        piece.row_group, drop_partition,
                                        columns_hash, filter_part)

    def _spec_cacheable(self):
        """May the decoded cache store this TransformSpec's output?
        ``cacheable``: False → never; True → always; None (undeclared) →
        only when the reader explicitly asked for the decoded cache, not
        when the fleet knob upgraded it behind the job's back."""
        cacheable = getattr(self._transform_spec, 'cacheable', None)
        if cacheable is not None:
            return cacheable
        return not getattr(self._cache, 'implicit_upgrade', False)

    def _decoded_fingerprint(self, piece):
        """Decode-identity suffix of a materialized-cache key: what was
        decoded (schema view + codecs), what transformed it, and the
        parquet file's bytes identity — any change must miss (serving a
        stale decoded batch is silent corruption). Both halves are
        cached: the decode fingerprint once per worker, the file
        fingerprint once per parquet file."""
        if self._decode_fp is None:
            self._decode_fp = decode_fingerprint(self._loaded_schema,
                                                 self._transform_spec,
                                                 self._ngram)
        file_fp = self._file_fps.get(piece.path)
        if file_fp is None:
            file_fp = dataset_file_fingerprint(self._dataset_info,
                                               piece.path)
            self._file_fps[piece.path] = file_fp
        return '%s:%s' % (self._decode_fp, file_fp)

    def _parquet_file(self, path):
        pf = self._parquet_files.get(path)
        if pf is None:
            pf = pq.ParquetFile(self._dataset_info.open(path))
            self._parquet_files[path] = pf
            while len(self._parquet_files) > _PARQUET_FILE_CACHE_MAX:
                _, evicted = self._parquet_files.popitem(last=False)
                try:
                    evicted.close()
                except Exception:  # noqa: BLE001 - best-effort close
                    pass
        else:
            self._parquet_files.move_to_end(path)
        return pf

    def _needed_stored_fields(self):
        """Names of stored fields to read+decode (pre-transform view)."""
        return [f.name for f in self._loaded_schema
                if f.name in self._stored_schema.fields]

    def _load_rowgroup(self, piece, worker_predicate, drop_partition,
                       item_index=None, epoch=None):
        if self._fullscan_oracle and worker_predicate is not None:
            return self._load_rowgroup_fullscan(piece, worker_predicate,
                                                drop_partition,
                                                item_index=item_index,
                                                epoch=epoch)
        needed = self._needed_stored_fields()
        partition_keys = [k for k in piece.partition_values if k in needed]
        file_columns = [n for n in needed if n not in piece.partition_values]

        if self._readahead is not None:
            # advance the readahead clock BEFORE any read of this item.
            # The prefetchable set respects the late-materialization
            # two-phase split: under a predicate only the predicate
            # columns fetch ahead — survivors' heavy columns stay
            # on-demand (most row-groups never materialize them)
            prefetch = (sorted(worker_predicate.get_fields())
                        if worker_predicate is not None else file_columns)
            self._readahead.observe(item_index, epoch, prefetch)

        pf = self._parquet_file(piece.path)

        pred_columns = {}
        if worker_predicate is not None:
            keep, pred_columns = self._predicate_mask(pf, piece,
                                                      worker_predicate)
            if keep is not None and not keep.any():
                return None
        else:
            keep = None

        # Late materialization (docs/telemetry.md "Query-shaped reads"):
        # under a predicate, the two-phase read is the general shape —
        # the heavy non-predicate columns are read only HERE, after the
        # surviving-row mask proved non-empty, and the predicate columns
        # already decoded for the mask are reused instead of being read
        # and decoded a second time.
        late = keep is not None
        reuse = {n: pred_columns[n] for n in file_columns
                 if n in pred_columns}
        read_columns = [n for n in file_columns if n not in reuse]

        overlap = self._ngram.length - 1 if self._ngram is not None else 0
        if late:
            # survivors + row-drop partition decided BEFORE the heavy
            # read (the mask already knows the row count): a drop
            # partition whose survivors all landed elsewhere must not
            # pay the heavy-column I/O just to return None
            num_rows = len(keep)
            row_indices = self._apply_row_drop(np.flatnonzero(keep),
                                               drop_partition, overlap)
            if row_indices.size == 0:
                return None
            table = (self._read_columns(pf, piece, read_columns)
                     if read_columns else None)
        else:
            table = self._read_columns(pf, piece, read_columns)
            num_rows = table.num_rows
            row_indices = self._apply_row_drop(np.arange(num_rows),
                                               drop_partition, overlap)
            if row_indices.size == 0:
                return None

        select_all = row_indices.size == num_rows

        if faults.ARMED:
            # inside a decode span so an injected delay is charged to the
            # decode stage — the critical-path ground-truth drill depends
            # on the slowdown being attributable; unarmed runs never enter
            with span('decode'):
                faults.fault_hit('decode.rowgroup', key='%s#rg%d'
                                 % (piece.path, piece.row_group))
        columns = {}
        if read_columns:
            if late:
                # the late-materialization specialization of the `decode`
                # stage: only SURVIVING rows of the heavy columns decode
                with span('late_materialize'):
                    for name in read_columns:
                        columns[name] = self._decode_survivors(
                            name, table.column(name), row_indices,
                            select_all)
                if not metrics_disabled():
                    get_registry().counter(
                        pushdown.LATE_MATERIALIZED_ROWS).inc(
                            int(row_indices.size))
            else:
                with span('decode'):
                    for name in read_columns:
                        arrow_col = table.column(name)
                        selected = (arrow_col if select_all
                                    else arrow_col.take(row_indices))
                        columns[name] = self._decode_column(
                            name, selected, allow_defer=True)
        for name, decoded in reuse.items():
            # projection pushdown: the predicate phase decoded the full
            # column; serving survivors is a select, not a re-decode
            columns[name] = decoded if select_all else decoded[row_indices]
        return self._finish_batch(columns, piece, partition_keys,
                                  row_indices.size)

    def _read_columns(self, pf, piece, read_columns):
        """One row-group read under the ``io`` span and its faultpoint.
        Faultpoint key: one stable identity per row-group, so chaos
        specs can poison a specific one (match=) or rate-sample reads;
        '#' not ':' as the separator — ':' is the spec grammar's own
        field separator, so a match= value could never contain it.

        A readahead hit serves the same columns zero-copy from the
        prefetched coalesced ranges (pa.BufferReader-backed, zero
        storage I/O); a miss — or ``PETASTORM_TPU_READAHEAD=0``, the
        exact-parity oracle — is the blocking read below."""
        if faults.ARMED:
            faults.fault_hit('io.read', key='%s#rg%d'
                             % (piece.path, piece.row_group))
        with span('io'):
            if self._readahead is not None:
                table = self._readahead.serve(pf, piece.path,
                                              piece.row_group, read_columns)
                if table is not None:
                    return table
            return pf.read_row_group(piece.row_group, columns=read_columns)

    def _finish_batch(self, columns, piece, partition_keys, count):
        """Shared batch tail: fill partition-key columns from the hive
        path values, then run the TransformSpec."""
        for name in partition_keys:
            field = self._stored_schema.fields.get(name)
            value = self._typed_partition_value(field, piece.partition_values[name])
            dtype = np.dtype(field.numpy_dtype) if field is not None else np.dtype(object)
            columns[name] = np.full(count, value,
                                    dtype=dtype if dtype.kind in 'iufb' else object)

        batch = ColumnBatch(columns, count)
        if self._transform_spec is not None:
            with span('transform'):
                batch = self._apply_transform(batch)
        return batch

    def _load_rowgroup_fullscan(self, piece, worker_predicate,
                                drop_partition, item_index=None,
                                epoch=None):
        """The decode-everything-then-filter ORACLE
        (``PETASTORM_TPU_PUSHDOWN=0``): one read of every needed +
        predicate column, every row of every column decoded, the
        predicate evaluated over the fully-decoded columns, survivors
        sliced out after the fact. The exact-parity comparison baseline
        and the bench ``selective_read`` section's full-scan-priced
        rung — never the production path (the default is the two-phase
        late-materializing read above)."""
        needed = self._needed_stored_fields()
        partition_keys = [k for k in piece.partition_values if k in needed]
        file_columns = [n for n in needed if n not in piece.partition_values]
        pred_fields = sorted(worker_predicate.get_fields())
        missing = [f for f in pred_fields
                   if f not in self._stored_schema.fields
                   and f not in piece.partition_values]
        if missing:
            raise ValueError('Predicate references unknown fields: %s'
                             % missing)
        pred_file_fields = [f for f in pred_fields
                            if f not in piece.partition_values]
        read_columns = list(dict.fromkeys(file_columns + pred_file_fields))

        if self._readahead is not None:
            # the oracle's one read wants everything at once, so the
            # whole union is the prefetchable set here
            self._readahead.observe(item_index, epoch, read_columns)
        pf = self._parquet_file(piece.path)
        table = self._read_columns(pf, piece, read_columns)
        num_rows = table.num_rows
        if faults.ARMED:
            # see the sibling site above: armed-only decode span so
            # injected delays read as decode time in the critical path
            with span('decode'):
                faults.fault_hit('decode.rowgroup', key='%s#rg%d'
                                 % (piece.path, piece.row_group))
        with span('decode'):
            decoded = {name: self._decode_column(name, table.column(name))
                       for name in read_columns}

        values = {}
        for name in pred_fields:
            if name in piece.partition_values:
                field = self._stored_schema.fields.get(name)
                value = self._typed_partition_value(
                    field, piece.partition_values[name])
                values[name] = np.full(num_rows, value, dtype=object)
            else:
                values[name] = decoded[name]
        with span('filter'):
            mask = worker_predicate.do_include_batch(values)
            if mask is None:
                mask = np.fromiter(
                    (worker_predicate.do_include(
                        {f: values[f][i] for f in pred_fields})
                     for i in range(num_rows)), dtype=bool, count=num_rows)
            mask = np.asarray(mask, dtype=bool)
            if mask.shape != (num_rows,):
                # same fail-loud contract as _predicate_mask: a
                # malformed predicate must not silently mis-select in
                # the ORACLE either — a parity mismatch would otherwise
                # be blamed on the fast path
                raise ValueError(
                    'Predicate %s.do_include_batch returned mask of '
                    'shape %s for %d rows'
                    % (type(worker_predicate).__name__, mask.shape,
                       num_rows))
        row_indices = np.flatnonzero(mask)

        overlap = self._ngram.length - 1 if self._ngram is not None else 0
        row_indices = self._apply_row_drop(row_indices, drop_partition,
                                           overlap)
        if row_indices.size == 0:
            return None
        select_all = row_indices.size == num_rows
        columns = {name: (decoded[name] if select_all
                          else decoded[name][row_indices])
                   for name in file_columns}
        return self._finish_batch(columns, piece, partition_keys,
                                  row_indices.size)

    def _predicate_mask(self, pf, piece, predicate):
        """Two-phase read: evaluate the predicate on its own columns first
        (reference: ``py_dict_reader_worker.py:188-236``).

        Returns ``(mask, decoded)`` where ``decoded`` maps each predicate
        FILE column to its decoded full-row-group array — the
        late-materialization path reuses these for output columns so a
        predicate column is read and decoded exactly once per row-group.
        """
        pred_fields = sorted(predicate.get_fields())
        missing = [f for f in pred_fields
                   if f not in self._stored_schema.fields
                   and f not in piece.partition_values]
        if missing:
            raise ValueError('Predicate references unknown fields: %s' % missing)
        file_fields = [f for f in pred_fields if f not in piece.partition_values]
        with span('io'):
            # phase-1 of the two-phase split: exactly the columns the
            # readahead plane prefetches under a predicate
            pred_table = None
            if self._readahead is not None:
                pred_table = self._readahead.serve(pf, piece.path,
                                                   piece.row_group,
                                                   file_fields)
            if pred_table is None:
                pred_table = pf.read_row_group(piece.row_group,
                                               columns=file_fields)
        with span('decode'):
            decoded = {name: self._decode_column(name,
                                                 pred_table.column(name))
                       for name in file_fields}
        n = pred_table.num_rows
        for name in pred_fields:
            if name in piece.partition_values:
                field = self._stored_schema.fields.get(name)
                value = self._typed_partition_value(field, piece.partition_values[name])
                decoded[name] = np.full(n, value, dtype=object)
        reusable = {name: decoded[name] for name in file_fields}
        with span('filter'):
            mask = predicate.do_include_batch(
                {f: decoded[f] for f in pred_fields})
            if mask is not None:
                mask = np.asarray(mask, dtype=bool)
                if mask.shape != (n,):
                    raise ValueError(
                        'Predicate %s.do_include_batch returned mask of '
                        'shape %s for %d rows'
                        % (type(predicate).__name__, mask.shape, n))
                return mask, reusable
            # fallback: per-row loop for predicates without a columnar form
            # (e.g. in_lambda), matching the reference's evaluation exactly
            mask = np.empty(n, dtype=bool)
            for i in range(n):
                mask[i] = predicate.do_include(
                    {f: decoded[f][i] for f in pred_fields})
        return mask, reusable

    @staticmethod
    def _typed_partition_value(field, value):
        """Hive partition values are stored as path strings; cast them to the
        schema's dtype so predicates and output columns see typed values."""
        return typed_partition_value(field, value)

    @staticmethod
    def _apply_row_drop(row_indices, drop_partition, overlap=0):
        """Keep 1/k of the rows (contiguous split ``j`` of ``k``), improving
        shuffle decorrelation (reference: ``_read_with_shuffle_row_drop``).

        With an NGram, each partition borrows the first ``overlap``
        (= ngram length - 1) rows of the next partition so windows spanning
        the split boundary are not lost (``py_dict_reader_worker.py:266-271``).
        """
        j, k = drop_partition
        if k <= 1:
            return row_indices
        parts = np.array_split(row_indices, k)
        selected = parts[j]
        if overlap and j + 1 < k:
            borrow = np.concatenate(parts[j + 1:])[:overlap]
            selected = np.concatenate([selected, borrow])
        return selected

    def _decode_survivors(self, name, arrow_col, row_indices, select_all):
        """Decode ONLY the surviving rows of a heavy column — the
        late-materialization path. Image columns compact the survivor
        indices over zero-copy cell views of the FULL arrow column (no
        ``take()`` copy of the encoded bytes) and feed the compacted
        cells to the batched decode — or ship them still-encoded when
        the consumer deferred decode (``EncodedImageColumn`` carries
        only survivor cells, so ``decode_fused`` in the staging arena
        decodes survivors straight into slot rows). Other codecs fall
        back to ``take()`` + the classic decode."""
        if select_all:
            return self._decode_column(name, arrow_col, allow_defer=True)
        field = (self._loaded_schema.fields.get(name)
                 or self._stored_schema.fields.get(name))
        if field is not None and isinstance(field.codec, CompressedImageCodec):
            cells = _binary_cell_views(arrow_col)
            if cells is not None:
                survivors = [cells[i] for i in row_indices]
                return self._image_column(field, survivors, arrow_col,
                                          allow_defer=True)
        return self._decode_column(name, arrow_col.take(row_indices),
                                   allow_defer=True)

    def _decode_column(self, name, arrow_col, allow_defer=False):
        """Arrow column → decoded numpy values (vectorized where possible).

        Collation semantics follow ``arrow_reader_worker.py:38-80``: scalars
        to typed numpy arrays, strings to unicode arrays, codec'd binary cells
        through the codec's batched decode; outputs with uniform shapes are
        stacked into ``(n,) + shape`` ndarrays, ragged outputs stay object
        arrays.

        Row-group-granularity image dispatch: fixed-shape, null-free image
        columns decode in ONE vectorized call per row-group into a
        page-aligned column slab (``decode_batch(out=)``, the fused-decode
        destination API) — and, when the consumer deferred decode
        (``allow_defer`` + the reader's ``defer_image_decode``), skip
        decoding here entirely and publish an
        :class:`~petastorm_tpu.fused.EncodedImageColumn` for the staging
        arena to decode straight into its slot buffers. Predicate-column
        decode (``allow_defer=False``) always yields pixels — predicates
        compare values.
        """
        field = self._loaded_schema.fields.get(name) or self._stored_schema.fields.get(name)
        if field is not None and field.codec is not None:
            if isinstance(field.codec, CompressedImageCodec):
                # image cells go to cv2 untouched: zero-copy views over the
                # arrow data buffer instead of a per-cell bytes copy
                cells = _binary_cell_views(arrow_col)
                if cells is not None:
                    return self._image_column(field, cells, arrow_col,
                                              allow_defer)
            return self._stack(decode_batch_with_nulls(
                field, arrow_col.to_pylist()))
        return self._collate_plain(field, arrow_col, arrow_col.to_pylist())

    def _image_column(self, field, cells, arrow_col, allow_defer):
        """One image column of one row-group: defer (fused), decode dense
        into a page-aligned slab, or fall back to the per-cell path."""
        shape = field.shape
        dense_ok = (shape and not any(d is None for d in shape)
                    and not any(c is None for c in cells))
        if dense_ok:
            try:
                dtype = np.dtype(field.numpy_dtype)
            except TypeError:
                dense_ok = False
        if self._defer_decode and allow_defer:
            if dense_ok and dtype.kind in 'iuf':
                return EncodedImageColumn(field, cells, owner=arrow_col)
            count_fallback('column-shape')
        if dense_ok:
            try:
                return decode_batch_with_nulls(
                    field, cells,
                    out=alloc_column_slab((len(cells),) + tuple(shape),
                                          dtype))
            except Exception:  # noqa: BLE001 - slab path is an accelerator
                logger.debug('Dense slab image decode failed; falling back '
                             'to the per-cell path', exc_info=True)
        return self._stack(decode_batch_with_nulls(field, cells))

    def _collate_plain(self, field, arrow_col, values):
        """Codec-less columns (plain parquet / make_batch_reader path)."""
        if field is not None and field.shape:
            # list<primitive> column → per-row ndarrays
            dtype = field.numpy_dtype
            arrays = [None if v is None else np.asarray(v, dtype=dtype) for v in values]
            return self._stack(arrays)
        try:
            out = arrow_col.combine_chunks().to_numpy(zero_copy_only=False)
        except Exception:  # noqa: BLE001 - fall back for exotic arrow types
            out = np.asarray(values, dtype=object)
        if (out.dtype == object and field is not None
                and field.numpy_dtype in (np.str_, np.bytes_)
                and not any(v is None for v in values)):
            # String columns collate to unicode/bytes arrays, matching the
            # reference (``arrow_reader_worker.py:64-65``).
            out = out.astype(field.numpy_dtype)
        return out

    @staticmethod
    def _stack(items):
        """Stack per-row values: uniform ndarray shapes → one (n,)+shape array;
        anything ragged/None-bearing → 1-d object array. A pre-stacked
        contiguous batch (from the native decode path) passes through."""
        if isinstance(items, np.ndarray) and items.dtype.kind not in 'OU':
            return items
        if not len(items):
            return np.empty(0, dtype=object)
        first = items[0]
        if isinstance(first, np.ndarray) and first.dtype.kind not in 'OU':
            shape = first.shape
            if all(isinstance(x, np.ndarray) and x.shape == shape for x in items):
                return np.stack(items)
        if isinstance(first, (int, float, bool, np.generic)) and \
                all(x is not None and not isinstance(x, np.ndarray) for x in items):
            return np.asarray(items)
        out = np.empty(len(items), dtype=object)
        for i, x in enumerate(items):
            out[i] = x
        return out

    def _apply_transform(self, batch):
        """Run the TransformSpec on a pandas view of the whole row-group
        (reference: ``arrow_reader_worker.py:146-152``)."""
        import pandas as pd
        spec = self._transform_spec
        frame = pd.DataFrame({name: list(col) for name, col in batch.columns.items()})
        if spec.func is not None:
            frame = spec.func(frame)
        for name in spec.removed_fields:
            if name in frame.columns:
                frame = frame.drop(columns=[name])
        if spec.selected_fields is not None:
            frame = frame[[c for c in spec.selected_fields]]
        columns = {name: self._stack(list(frame[name])) for name in frame.columns}
        return ColumnBatch(columns, len(frame))
