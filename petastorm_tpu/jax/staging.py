"""Zero-copy staging arena + double-buffered async host→device transfer.

The final hop of the JAX path used to be copy-heavy and single-buffered:
``_pad``/``_to_device`` allocated fresh host arrays per batch
(``np.asarray``/``astype``/``np.concatenate`` all copy — up to twice per
batch when a spanning batch also casts) and the staging thread had no
dispatch/recycle discipline, so the host buffers feeding a transfer could
not be prepared ahead. This module replaces that path with a
:class:`StagingEngine` that picks the cheapest correct strategy per batch
and per backend:

* **Direct dispatch** — a batch that needs NO assembly (one chunk view, no
  dtype cast, no tail pad) skips every copy: the source views go straight
  to ``put_fn`` (``jax.device_put`` or the sharded
  ``make_array_from_process_local_data`` build), which transfers
  asynchronously. The source chunk is never written again, so this is safe
  on every backend. Under ``last_batch='pad'`` a full batch rides with one
  shared immutable all-true ``valid_mask``.
* **Arena ring** (accelerator backends) — per batch signature (field
  shapes + target dtypes; one signature per bucket under
  ``bucket_boundaries``), a ring of ``PETASTORM_TPU_STAGING_SLOTS``
  (default 2, ≥2 so the filler never races the in-flight transfer)
  preallocated host slots at ``(batch_size, *shape)`` in the TARGET dtype.
  Collate/pad/cast write INTO the slot (``np.copyto`` with
  cast-during-copy — one pass, no intermediate ``astype`` array), the
  transfer dispatches asynchronously, and the slot is recycled only after
  the transfer *previously dispatched from that slot* reports complete
  (``block_until_ready`` on the PREVIOUS handoff, never the current one).
  Steady state performs **zero per-batch host-buffer allocations**
  (``tests/test_staging.py`` holds this with tracemalloc), so the staging
  thread stops paying allocator+page-fault costs per batch while transfers
  overlap consumption.
* **Fresh assembly** (host-backed backends, e.g. XLA:CPU) — reusing slots
  is counterproductive there: the runtime zero-copies suitably-aligned
  host arrays into device handles (measured on this jaxlib: a 64-byte-
  aligned array aliases; a fresh large numpy allocation is page-aligned),
  so a reused slot would either alias (corrupting a held batch on
  recycle) or force a second real copy. Instead each batch assembles into
  fresh buffers — the ONE copy legacy paid — and the dispatch aliases
  them for free; the buffers are never touched again, making aliasing
  harmless by construction.

The engine starts on the ring path and switches to fresh assembly as soon
as the first dispatch reveals a host-backed (``'cpu'`` platform) target;
unknown array types conservatively count as host-backed (fresh assembly is
the always-correct strategy).

Knobs (docs/env_knobs.md): ``PETASTORM_TPU_STAGING=0`` disables the engine
(the loader falls back to the pre-arena copy path — also the reference
behavior for exact-value comparison tests); ``PETASTORM_TPU_STAGING_SLOTS``
sizes the ring. Both are read once and cached; :func:`refresh_staging`
re-reads (the established knob discipline).

Telemetry (docs/telemetry.md): per-batch ``stage_fill`` /
``h2d_dispatch`` / ``h2d_ready`` spans (which double as trace events when
``PETASTORM_TPU_TRACE=1``, so Perfetto dumps show fill/transfer/consume
overlap) and the ``petastorm_tpu_h2d_bytes_total`` counter;
``pipeline_report`` derives ``h2d_overlap_share`` from the three stages.

Sanitizer (docs/troubleshoot.md): ``PETASTORM_TPU_SANITIZE=1`` arms the
pipesan runtime guards on the ring path — slot slabs get poisoned red
zones verified before every refill, and a weakref census of each
dispatch's outbound views aborts a recycle (fresh buffers, escaped
holder keeps the old memory) when a consumer held a view past the slot's
documented lifetime. Resolved once at engine construction: the unarmed
hot path pays nothing.
"""

import logging

import numpy as np

from petastorm_tpu import faults, sanitizer
from petastorm_tpu.fused import (
    FUSED_BYTES, FUSED_ROWS, EncodedImageColumn, count_fallback,
)
from petastorm_tpu.telemetry import (
    get_registry, knobs, metrics_disabled, register_refresh, span,
)

logger = logging.getLogger(__name__)

#: registry counter: bytes handed to the device transfer path
H2D_BYTES = 'petastorm_tpu_h2d_bytes_total'

#: name of the validity-mask column added under ``last_batch='pad'`` — the
#: canonical definition (jax.loader re-exports it)
MASK_FIELD = 'valid_mask'

_MIN_SLOTS = 2

# knob caches (refresh_staging() re-reads); None = not yet resolved
_enabled = None
_slots = None


def staging_enabled():
    """True unless ``PETASTORM_TPU_STAGING`` disables the arena."""
    global _enabled
    if _enabled is None:
        _enabled = not knobs.is_disabled('PETASTORM_TPU_STAGING')
    return _enabled


def staging_slots():
    """Ring size from ``PETASTORM_TPU_STAGING_SLOTS`` (default and floor:
    2 — one slot filling while the other's transfer is in flight)."""
    global _slots
    if _slots is None:
        _slots = knobs.get_int('PETASTORM_TPU_STAGING_SLOTS', _MIN_SLOTS,
                               floor=_MIN_SLOTS)
    return _slots


def refresh_staging():
    """Re-read both staging knobs (tests, long-lived processes flipping
    the env); the next :func:`make_stager` call sees the new values.
    Also runs as part of ``petastorm_tpu.telemetry.refresh()``, the
    process's one re-read-every-knob entry point."""
    global _enabled, _slots
    _enabled = None
    _slots = None


register_refresh(refresh_staging)


def make_stager(batch_size, dtypes, last_batch, put_fn):
    """A :class:`StagingEngine` for one staging pass, or None when
    ``PETASTORM_TPU_STAGING=0`` tells the loader to use its pre-arena
    copy path."""
    if not staging_enabled():
        return None
    return StagingEngine(batch_size, dtypes, last_batch, put_fn,
                         num_slots=staging_slots())


def _is_host_backed(leaf):
    """True when the dispatched array lives in host memory ('cpu'
    platform) — where ``device_put`` can alias the source buffer and
    fresh assembly beats slot reuse. Unknown array types count as
    host-backed (fresh assembly is the always-correct strategy)."""
    devices = getattr(leaf, 'devices', None)
    if devices is None:
        return True
    try:
        return all(getattr(d, 'platform', 'cpu') == 'cpu'
                   for d in devices())
    except Exception:  # noqa: BLE001 - duck-typed runtimes
        return True


def _check_deviceable(name, arr):
    """The shared undevicable-column diagnosis (object → classified ragged
    message, fixed-width strings → string message)."""
    if arr.dtype == object:
        from petastorm_tpu.ragged import reject_object_column
        reject_object_column(name, arr)
    if arr.dtype.kind in 'US':
        from petastorm_tpu.ragged import STRING_MESSAGE
        raise TypeError(STRING_MESSAGE % name)


class _Slot:
    """One ring slot: preallocated per-field host buffers plus the device
    arrays of the transfer most recently dispatched from it."""

    __slots__ = ('buffers', 'in_flight', 'census')

    def __init__(self, buffers, census=None):
        self.buffers = buffers      # {field: ndarray(batch_size, *shape)}
        self.in_flight = None       # leaves of the last dispatch
        self.census = census        # sanitizer.ViewCensus when armed

    def await_retired(self):
        """Block until the transfer previously dispatched from this slot
        completes — only then may the buffers be overwritten (an in-flight
        ``device_put`` may still be reading them)."""
        leaves = self.in_flight
        if leaves is not None:
            for leaf in leaves:
                leaf.block_until_ready()
            self.in_flight = None


class _Ring:
    """Round-robin ring of slots for one batch signature."""

    __slots__ = ('slots', 'cursor')

    def __init__(self, slots):
        self.slots = slots
        self.cursor = 0

    def next_slot(self):
        slot = self.slots[self.cursor]
        self.cursor = (self.cursor + 1) % len(self.slots)
        return slot


class StagingEngine:
    """Per-pass staging engine for :class:`JaxLoader`.

    Single-threaded by contract: only the loader's staging thread calls
    :meth:`stage`. ``put_fn(host_pytree) -> device_pytree`` is the
    loader's dispatch (plain ``device_put`` or the sharded build).
    """

    def __init__(self, batch_size, dtypes, last_batch, put_fn, num_slots=2):
        self._batch_size = batch_size
        self._dtypes = dict(dtypes or {})
        self._last_batch = last_batch
        self._put_fn = put_fn
        self._num_slots = max(_MIN_SLOTS, num_slots)
        self._rings = {}            # signature -> _Ring (ring mode only)
        # None until the first dispatch reveals the backend; True routes
        # every assembled batch to fresh buffers (see module docstring)
        self._host_backed = None
        # shared immutable all-true mask for full batches on the direct
        # path; allocated once on first use
        self._full_mask = None
        # test/diagnostic hooks: in ring mode, slot-slab allocations are
        # startup-only (steady growth = the arena is not being reused)
        self.slabs_allocated = 0
        self.batches_staged = 0
        # pipesan: resolved ONCE here so the unarmed per-batch path costs
        # a single attribute read, not a knob parse
        self._sanitize = sanitizer.sanitize_enabled()
        self.slabs_quarantined = 0
        # fused-decode accounting (petastorm_tpu/fused.py): rows decoded
        # straight into destination buffers by _fill, and WHERE the last
        # fused fill landed — 'fused-into-slot' (recycled arena slot,
        # the zero-extra-copy regime) or 'fused-into-slab' (host-backed
        # fresh assembly; still one decode pass, buffer not recycled)
        self.fused_rows = 0
        self.fused_mode = None

    # -- arena ---------------------------------------------------------------

    def _target_dtype(self, name, arr):
        want = self._dtypes.get(name)
        return np.dtype(want) if want is not None else arr.dtype

    def _resolve_dtypes(self, parts):
        """Per-field assembly dtype: the ``dtypes=`` policy wins;
        otherwise mixed-dtype parts PROMOTE exactly like the
        ``np.concatenate`` the pre-arena path performed (an int32 chunk
        followed by an int64 one must yield int64, never a wrapping
        downcast into the first chunk's dtype)."""
        resolved = {}
        for name, arr in parts[0].items():
            want = self._dtypes.get(name)
            if want is not None:
                resolved[name] = np.dtype(want)
                continue
            dtype = arr.dtype
            if any(p[name].dtype != dtype for p in parts[1:]):
                dtype = np.result_type(*[p[name].dtype for p in parts])
            resolved[name] = dtype
        return resolved

    def _signature(self, columns, dtype_map, with_mask):
        # leading (batch) dim excluded: a short tail reuses the full-size
        # slots through [:n] views instead of allocating a one-off ring
        return (with_mask,) + tuple(
            (name, arr.shape[1:], dtype_map[name].str)
            for name, arr in sorted(columns.items()))

    def _new_buffers(self, columns, dtype_map, with_mask, guarded=None):
        # armed sanitizer: slabs carry poisoned red zones on both sides
        # of the visible array, verified before every refill. Only ring
        # slots are worth guarding (``guarded=False`` on the fresh-
        # assembly path): fresh buffers are never recycled, so their red
        # zones would never be checked.
        if guarded is None:
            guarded = self._sanitize
        alloc = sanitizer.allocate_guarded if guarded else np.empty
        buffers = {
            name: alloc((self._batch_size,) + arr.shape[1:],
                        dtype_map[name])
            for name, arr in columns.items()}
        if with_mask:
            buffers[MASK_FIELD] = alloc((self._batch_size,), bool)
        return buffers

    def _ring_for(self, columns, dtype_map, with_mask):
        sig = self._signature(columns, dtype_map, with_mask)
        ring = self._rings.get(sig)
        if ring is None:
            slots = [_Slot(self._new_buffers(columns, dtype_map, with_mask),
                           census=(sanitizer.ViewCensus()
                                   if self._sanitize else None))
                     for _ in range(self._num_slots)]
            self.slabs_allocated += len(slots)
            ring = self._rings[sig] = _Ring(slots)
        elif len(ring.slots) < self._num_slots:
            # the autotuner deepened the ring mid-pass (set_num_slots):
            # grow lazily, at the ring's next use, on the staging thread —
            # the only thread allowed to touch slot state
            add = self._num_slots - len(ring.slots)
            ring.slots.extend(
                _Slot(self._new_buffers(columns, dtype_map, with_mask),
                      census=(sanitizer.ViewCensus()
                              if self._sanitize else None))
                for _ in range(add))
            self.slabs_allocated += add
        return ring

    @property
    def num_slots(self):
        """Current ring depth target (slots per batch signature)."""
        return self._num_slots

    def set_num_slots(self, num_slots):
        """Deepen (never shrink) the per-signature ring depth — the
        staging autotuner's adjustment seam. Existing rings grow lazily
        at their next use on the staging thread; shrinking is not
        supported (a removed slot's in-flight transfer would lose its
        recycle gate). Returns the effective depth."""
        self._num_slots = max(self._num_slots,
                              max(_MIN_SLOTS, int(num_slots)))
        return self._num_slots

    # -- staging -------------------------------------------------------------

    def stage(self, columns, n_valid):
        """Assemble + dispatch one batch; ``columns`` is one column dict
        or a LIST of column-dict parts (chunk views from the noop
        re-batcher, copied in sequentially so the concatenated
        intermediate never exists). Parts may carry still-encoded
        :class:`~petastorm_tpu.fused.EncodedImageColumn` columns — those
        DECODE during the fill, straight into the destination buffer
        (the fused path), so such a batch always takes an assembly path,
        never the direct dispatch. Returns the device batch WITHOUT
        waiting for the transfer to complete."""
        parts = columns if isinstance(columns, list) else [columns]
        parts = [{name: (arr if isinstance(arr, EncodedImageColumn)
                         else np.asarray(arr))
                  for name, arr in p.items()}
                 for p in parts]
        has_encoded = False
        for p in parts:
            for name, arr in p.items():
                if isinstance(arr, EncodedImageColumn):
                    # fixed-shape numeric by the worker's deferral gate
                    has_encoded = True
                    continue
                _check_deviceable(name, arr)
        with_mask = self._last_batch == 'pad'
        full = n_valid >= self._batch_size
        if (not has_encoded and len(parts) == 1 and (full or not with_mask)
                and all(self._target_dtype(name, arr) == arr.dtype
                        for name, arr in parts[0].items())):
            # one ready chunk view, no cast, no pad: dispatch the source
            # directly — it is never written again, so no copy is needed
            # on any backend; the transfer is still async
            return self._stage_direct(parts[0], with_mask)
        dtype_map = self._resolve_dtypes(parts)
        if self._host_backed:
            return self._stage_fresh(parts, dtype_map, n_valid, with_mask)
        return self._stage_ring(parts, dtype_map, n_valid, with_mask)

    def _stage_direct(self, cols, with_mask):
        """Zero-copy dispatch of a ready single-chunk batch (plus the
        shared immutable all-true mask under ``last_batch='pad'``)."""
        if with_mask:
            if self._full_mask is None:
                self._full_mask = np.ones(self._batch_size, bool)
            cols = dict(cols)
            cols[MASK_FIELD] = self._full_mask
        with span('h2d_dispatch'):
            device_batch = self._put(cols)
        self._account(cols.values())
        self._learn_backend(device_batch)
        return device_batch

    def _stage_fresh(self, parts, dtype_map, n, with_mask):
        """Host-backed backends: assemble into FRESH buffers (the one
        copy the legacy path also paid) and let the runtime zero-copy
        them into the device handle — never reused, so aliasing is
        harmless by construction."""
        with span('stage_fill'):
            buffers = self._new_buffers(parts[0], dtype_map, with_mask,
                                        guarded=False)
            views = self._fill(buffers, parts, n, with_mask)
        with span('h2d_dispatch'):
            device_batch = self._put(views)
        self._account(views.values())
        return device_batch

    def _stage_ring(self, parts, dtype_map, n, with_mask):
        """Accelerator backends: fill a recycled arena slot (no per-batch
        host allocation) and dispatch the async transfer."""
        ring = self._ring_for(parts[0], dtype_map, with_mask)
        slot = ring.next_slot()
        with span('h2d_ready'):
            # gate recycling on the slot's PREVIOUS handoff — with ≥2
            # slots this is never the batch just returned to the consumer
            slot.await_retired()
        if self._sanitize:
            self._sanitize_recycle(slot, parts, dtype_map, with_mask)
        with span('stage_fill'):
            views = self._fill(slot.buffers, parts, n, with_mask)
        if self._sanitize:
            # hand out fresh VIEW OBJECTS over the slot memory (still
            # zero-copy) so the census can tell a consumer-held view
            # from the slot's own reference to its buffers
            views = {name: v[:] for name, v in views.items()}
        with span('h2d_dispatch'):
            device_batch = self._put(views)
        self._account(views.values())
        if self._learn_backend(device_batch):
            # first dispatch revealed a host-backed target: the runtime
            # may have aliased this slot into the returned arrays, so the
            # ring (including this slot) is abandoned, never recycled —
            # every later batch takes the fresh-assembly path
            self._rings = {}
        else:
            slot.in_flight = list(device_batch.values())
            if self._sanitize:
                # census the views just handed to the transfer: any that
                # still resolve when THIS slot comes up for recycling
                # were kept past the documented lifetime
                slot.census.register(views.values())
        return device_batch

    def _sanitize_recycle(self, slot, parts, dtype_map, with_mask):
        """pipesan recycle gate (armed mode only): abort the recycle when
        a previously-dispatched view is still alive (the escaped holder
        keeps the old slab — quarantine, no corruption), and verify the
        red zones before letting the fill overwrite the slab."""
        escaped = slot.census.escaped() if slot.census is not None else 0
        if escaped:
            sanitizer.record_violation(
                'staging-use-after-recycle',
                '%d staged view(s) still alive when their slot came up '
                'for recycling; recycle aborted — slot re-backed with '
                'fresh buffers' % escaped)
            slot.buffers = self._new_buffers(parts[0], dtype_map,
                                             with_mask)
            slot.census = sanitizer.ViewCensus()
            self.slabs_quarantined += 1
            return
        for name, buf in slot.buffers.items():
            if not sanitizer.check_canaries(buf):
                sanitizer.record_violation(
                    'staging-canary-trampled',
                    'red zone around staging slot field %r overwritten '
                    'while the slot was out — an escaped view wrote past '
                    'its bounds' % name)

    def _fill(self, buffers, parts, n, with_mask):
        """Cast/pad/mask-assemble ``parts`` into ``buffers``; returns the
        dict to dispatch (``[:n]`` views for a maskless short tail).
        Encoded image parts DECODE here — the fused pass: the native
        batch decoders write pixels straight into the destination rows
        (``decode_batch(out=)``, internal C thread pool), so decoded
        bytes exist exactly once, at their final host address."""
        full = n >= self._batch_size
        for name in parts[0]:
            dst = buffers[name]
            offset = 0
            for p in parts:
                column = p[name]
                m = len(column)
                if column.shape[1:] != dst.shape[1:]:
                    # explicit, BEFORE the copy: np.copyto would happily
                    # BROADCAST a narrower chunk into the slot — silent
                    # corruption where the legacy np.concatenate raised
                    raise ValueError(
                        'staging: field %r chunk of shape %s does not '
                        'fit the batch slot of shape %s; variable-shape '
                        'fields need pad_ragged= or bucket_boundaries='
                        % (name, column.shape, dst.shape))
                if isinstance(column, EncodedImageColumn):
                    self._fill_fused(column, dst[offset:offset + m])
                else:
                    # cast-during-copy: the single copy this path performs
                    # (same 'unsafe' semantics as .astype())
                    np.copyto(dst[offset:offset + m], column,
                              casting='unsafe')
                offset += m
            if with_mask and not full:
                dst[n:] = 0
        if with_mask:
            mask = buffers[MASK_FIELD]
            mask[:n] = True
            mask[n:] = False
        if full or with_mask:
            return buffers
        return {name: buf[:n] for name, buf in buffers.items()}

    def _fill_fused(self, column, dst):
        """Decode one encoded part into its destination rows under the
        ``decode_fused`` stage. The destination is the fused contract's
        whole point: a recycled arena slot (ring mode) or the fresh
        page-aligned assembly buffer (host-backed mode) — either way the
        transfer dispatches from the very rows the decoder wrote. A
        dtype-retargeted slot (defensive; the loader materializes those
        upstream) decodes to a scratch batch and cast-copies — that
        branch is a FALLBACK and must not count as fused: the rows/bytes
        counters and ``fused_mode`` are exactly what the troubleshoot
        runbook and the bench attribution read."""
        if dst.dtype != column.dtype:
            count_fallback('dtype-cast')
            with span('decode'):
                np.copyto(dst, column.materialize(), casting='unsafe')
            return
        with span('decode_fused'):
            column.decode_into(dst)
        self.fused_rows += len(column)
        self.fused_mode = ('fused-into-slab' if self._host_backed
                           else 'fused-into-slot')
        if not metrics_disabled():
            registry = get_registry()
            registry.counter(FUSED_ROWS).inc(len(column))
            registry.counter(FUSED_BYTES).inc(dst.nbytes)

    def release(self):
        """Pass end: drop the slot slabs and the in-flight device-array
        references they hold — otherwise up to ``num_slots`` device
        batches per signature (plus every host slab) stay pinned between
        epochs. The engine object itself survives for the diagnostics
        counters."""
        self._rings = {}
        self._full_mask = None

    def _put(self, cols):
        """The one H2D dispatch seam (all three staging modes route
        here): the ``staging.h2d`` faultpoint sits in front of the
        loader's ``put_fn`` so chaos runs can inject transfer errors or
        link latency without touching a runtime."""
        if faults.ARMED:
            faults.fault_hit('staging.h2d')
        return self._put_fn(cols)

    def _account(self, arrays):
        self.batches_staged += 1
        if not metrics_disabled():
            get_registry().counter(H2D_BYTES).inc(
                sum(arr.nbytes for arr in arrays))

    def _learn_backend(self, device_batch):
        """Resolve ``_host_backed`` from the first dispatched batch;
        returns True exactly once, when a host-backed target is first
        detected (the ring-mode caller must then retire its slots)."""
        if self._host_backed is None:
            self._host_backed = _is_host_backed(
                next(iter(device_batch.values())))
            if self._host_backed:
                logger.debug('staging: host-backed target; using fresh '
                             'assembly (zero-copy dispatch) over slot '
                             'reuse')
                return True
        return False
