"""Staging autotuner: the closed control loop over the live rollups.

tf.data's core result (PAPERS.md, arxiv 2101.12127) is that static knob
settings always lose to dynamic tuning — and since PR 10 the pipeline
emits exactly the signal dynamic tuning needs: windowed rollups with
per-stage rates and a stall verdict. This module closes the loop for the
staging layer. A :class:`StagingAutotuner` rides the loader's staging
thread (no thread of its own: :meth:`maybe_tick` is a monotonic compare
until a window is due), closes a :class:`~petastorm_tpu.telemetry
.timeseries.WindowedRollup` window per ``PETASTORM_TPU_STAGING_AUTOTUNE_
WINDOW_SEC``, and adjusts three knobs from the window stream:

* **h2d starvation** (``h2d_ready`` share of the window at or above the
  ``PETASTORM_TPU_OBS_SATURATED_SHARE`` threshold — the same signal the
  anomaly detector's ``h2d_starvation`` event fires on — for 3
  consecutive windows) → **deepen**: one more slot per signature ring
  (``StagingEngine.set_num_slots``, bounded by
  ``PETASTORM_TPU_STAGING_AUTOTUNE_MAX_SLOTS``) and one more prefetch
  queue entry (bounded by ``PETASTORM_TPU_STAGING_AUTOTUNE_MAX_
  PREFETCH``): more transfers in flight hide more completion latency.
* **consumer-bound** verdict for 3 consecutive windows (the training
  step is the wall; the producer sits blocked on a full queue) →
  **shed decode threads**: the host's CPUs are not the problem, so hand
  them back — one decoder thread at a time down to 1, via an in-process
  override of the ``PETASTORM_TPU_IMAGE_DECODER_THREADS`` parse
  (``codecs.set_image_decoder_threads_override``; never a mutation of
  ``os.environ``, which child processes inherit and later readers in
  this process would silently keep).
* **producer-bound** verdict for 3 consecutive windows (the consumer
  waits on data) → **restore decode threads** back toward the
  construction-time baseline, one at a time — and **raise the
  ventilator's in-flight bound** (``Reader.set_ventilate_extra``, the
  remaining knob of the ROADMAP self-tuning item, bounded) so the pool
  never drains between pulls; a consumer-bound streak lowers it back
  toward the construction-time baseline.
* **io-wait saturation** (``io`` stage seconds-per-second at or above
  the saturation share while producer-bound, 3 consecutive windows) →
  **deepen readahead**: one more row-group of prefetch depth on the
  wire-speed I/O plane (:mod:`petastorm_tpu.readahead`; an in-process
  depth override, bounded by ``PETASTORM_TPU_READAHEAD_MAX_DEPTH``) —
  storage latency hides behind decode. Sustained **buffer-pool memory
  pressure** (occupancy ≥ 85% of the pool budget) sheds the depth back
  one step at a time instead.

Every decision lands three ways, so Perfetto and ``pipeline_report()``
show *why* throughput changed: a canonical ``autotune_decision`` trace
instant, the ``petastorm_tpu_staging_autotune_decisions_total{action=…}``
counter (fleet-aggregated over the pool delta channels like every other
metric), and a bounded in-process decision ring served by
``pipeline_report()['staging_autotune']``, the loader's
``autotune_report()``, and bench's ``sharded_staging`` section.

``PETASTORM_TPU_STAGING_AUTOTUNE=0`` disables the loop entirely (the
exact-parity oracle pin: batch VALUES are identical either way — the
tuner only moves buffering depth and thread counts — but a pinned run
also reproduces today's exact timing shape). Depth only ever deepens and
thread shedding is restored when the loader stops, so a tuner can never
wedge a pipeline below its static configuration.
"""

import collections
import logging
import threading
import time

from petastorm_tpu.telemetry import (
    get_registry, knobs, metrics_disabled, register_refresh, span, tracing,
)
from petastorm_tpu.telemetry.stall import CONSUMER_BOUND, PRODUCER_BOUND
from petastorm_tpu.telemetry.timeseries import (
    WindowedRollup, h2d_ready_share, io_wait_share,
)

logger = logging.getLogger(__name__)

#: registry counter: autotuner adjustments by action label
AUTOTUNE_DECISIONS = 'petastorm_tpu_staging_autotune_decisions_total'

#: decisions kept in the in-process ring (oldest dropped)
_DECISION_RING_CAPACITY = 100

_decisions_lock = threading.Lock()
_decisions = collections.deque(maxlen=_DECISION_RING_CAPACITY)
_decision_seq = 0

#: the tuner currently holding the process-wide decoder-thread override
#: (codecs.set_image_decoder_threads_override is one slot per process):
#: other tuners leave the threads knob alone while it is held, a closing
#: owner clears only its own setting, and a second loader's tuner can
#: never mistake the tuned-down width for the configured baseline
_override_owner = None

#: same single-owner discipline for the readahead DEPTH override
#: (readahead.set_depth_override is one slot per process too)
_readahead_owner = None

#: pool-occupancy share at which the readahead depth sheds (memory
#: pressure: the pool is the bound, not the wire)
_READAHEAD_POOL_PRESSURE = 0.85

#: ceiling on the autotuned ventilator in-flight extra (row-groups kept
#: in flight beyond the pool's worker count; the knobless satellite of
#: the self-tuning item — each step costs one more decoded row-group of
#: consumer-side queue memory at most)
_MAX_INFLIGHT_EXTRA = 16

# knob caches (refresh_autotune() re-reads); None = not yet resolved
_enabled = None


def autotune_enabled():
    """True unless ``PETASTORM_TPU_STAGING_AUTOTUNE`` disables the loop
    (on by default: the tuner changes buffering depth and thread counts,
    never batch values, so enabling it is parity-safe)."""
    global _enabled
    if _enabled is None:
        _enabled = not knobs.is_disabled('PETASTORM_TPU_STAGING_AUTOTUNE')
    return _enabled


def autotune_window_sec():
    return knobs.get_float('PETASTORM_TPU_STAGING_AUTOTUNE_WINDOW_SEC',
                           1.0, floor=0.05)


def autotune_max_slots():
    return knobs.get_int('PETASTORM_TPU_STAGING_AUTOTUNE_MAX_SLOTS', 8,
                         floor=2)


def autotune_max_prefetch():
    return knobs.get_int('PETASTORM_TPU_STAGING_AUTOTUNE_MAX_PREFETCH', 8,
                         floor=1)


def refresh_autotune():
    """Re-read the cached enablement knob (part of
    ``petastorm_tpu.telemetry.refresh()``, the one re-read-every-knob
    entry point); the per-decision bounds are read at each tuner's
    construction."""
    global _enabled
    _enabled = None


register_refresh(refresh_autotune)


def record_decision(action, **detail):
    """Record one autotuner adjustment: bounded in-process ring + the
    ``petastorm_tpu_staging_autotune_decisions_total{action=…}`` counter
    + a canonical ``autotune_decision`` trace instant on the
    ``autotuner`` track (no-op when tracing is off)."""
    global _decision_seq
    entry = {'action': action, 'ts': time.time()}
    entry.update(detail)
    with _decisions_lock:
        _decision_seq += 1
        seq = _decision_seq
        _decisions.append(entry)
    if not metrics_disabled():
        get_registry().counter(AUTOTUNE_DECISIONS, action=action).inc()
    tracing.record_instant('autotune_decision', tracing.mint(seq),
                           'autotuner', action=action, **detail)
    logger.info('staging autotune: %s (%s)', action, detail)
    return entry


def recent_decisions(last_n=20):
    """The most recent decisions (oldest first; this process only)."""
    with _decisions_lock:
        out = list(_decisions)
    return out[-last_n:]


def decision_counts():
    """``{action: n}`` of ring-resident decisions (this process only;
    the registry counter holds the fleet-wide totals)."""
    counts = {}
    with _decisions_lock:
        for entry in _decisions:
            counts[entry['action']] = counts.get(entry['action'], 0) + 1
    return counts


def _reset_for_tests():
    global _override_owner, _readahead_owner
    with _decisions_lock:
        _decisions.clear()
    _override_owner = None
    _readahead_owner = None


class StagingAutotuner:
    """Per-loader control loop; lives on the loader's staging thread.

    Single-threaded by the same contract as the engine it tunes: only
    the staging thread calls :meth:`maybe_tick`, so slot-ring growth,
    prefetch-bound writes and the decoder-thread override all happen
    from the one thread that owns them. The tuner survives epoch
    replays (the loader re-applies its learned depth to each pass's
    fresh engine via :meth:`apply_learned`) and restores the
    decoder-thread override at :meth:`close`.
    """

    #: consecutive windows a condition must hold before acting — the
    #: same streak discipline as the anomaly detector, so one noisy
    #: window can never move a knob
    _CONSECUTIVE = 3

    def __init__(self, loader, window_s=None):
        self._loader = loader
        self.window_s = window_s or autotune_window_sec()
        self._rollup = WindowedRollup(max_windows=32)
        self._next_sample = time.monotonic() + self.window_s
        self._saturated_share = knobs.get_float(
            'PETASTORM_TPU_OBS_SATURATED_SHARE', 0.5, floor=0.05)
        self._max_slots = autotune_max_slots()
        self._max_prefetch = autotune_max_prefetch()
        from petastorm_tpu import codecs
        self._codecs = codecs
        #: restore ceiling for the shed/restore pair: the KNOB's own
        #: width — never another tuner's live override
        self._baseline_threads = codecs.image_decoder_threads_from_knob()
        self._thread_override = None
        #: ring depth carried across passes (each pass gets a fresh
        #: engine; a learned deepening must not reset at the epoch gap)
        self._learned_slots = None
        self._h2d_streak = 0
        self._consumer_streak = 0
        self._producer_streak = 0
        # readahead-depth control (petastorm_tpu/readahead.py): deepen
        # while the fleet's io-wait share stays saturated, shed under
        # buffer-pool memory pressure
        from petastorm_tpu import readahead
        self._readahead = readahead
        self._readahead_max_depth = readahead.readahead_max_depth()
        self._readahead_override = None
        self._io_streak = 0
        self._pool_streak = 0
        # ventilator in-flight bound (Reader.set_ventilate_extra): the
        # remaining knob of the ROADMAP self-tuning item — baseline
        # captured lazily at the first adjustment
        self._inflight_baseline = None
        #: total adjustments made by THIS tuner (loader diagnostics)
        self.decisions = 0

    # -- loader integration ---------------------------------------------------

    def apply_learned(self, stager):
        """Carry the learned ring depth into a new pass's fresh engine."""
        if self._learned_slots is not None:
            stager.set_num_slots(self._learned_slots)

    def maybe_tick(self, now=None):
        """The staging-thread cadence gate: one monotonic compare until
        the next window is due, then a full :meth:`tick`."""
        now = time.monotonic() if now is None else now
        if now < self._next_sample:
            return None
        self._next_sample = now + self.window_s
        with span('autotune'):
            return self.tick(now)

    def tick(self, now=None):
        """Close one rollup window against the registry and act on it.
        Returns the actions taken (empty list when none; None while the
        first window primes)."""
        window = self._rollup.sample(get_registry().snapshot(), now=now)
        if window is None:
            return None
        return self.observe(window)

    # -- the policy -----------------------------------------------------------

    def observe(self, window):
        """Feed one closed window; applies any due adjustment and
        returns the decision entries."""
        actions = []
        ready_share = h2d_ready_share(window)
        starved = ready_share >= self._saturated_share
        self._h2d_streak = self._h2d_streak + 1 if starved else 0
        verdict = window.get('verdict')
        self._consumer_streak = (self._consumer_streak + 1
                                 if verdict == CONSUMER_BOUND else 0)
        self._producer_streak = (self._producer_streak + 1
                                 if verdict == PRODUCER_BOUND else 0)
        # readahead: the io-wait share only argues for more depth while
        # the consumer actually starves (producer-bound) — an io-heavy
        # but keeping-up pipeline gains nothing from deeper prefetch.
        # io_wait_share is fleet-SUMMED seconds-per-second (N parallel
        # workers can push it past 1.0), so the 0..1 saturation knob is
        # scaled by the pool's worker count: the trigger means "each
        # worker spends ≥ the share blocked in io", not "the fleet's
        # summed io crumbs add up to it"
        io_share = io_wait_share(window)
        io_starved = (io_share >= self._saturated_share
                      * self._io_share_scale()
                      and verdict == PRODUCER_BOUND)
        self._io_streak = self._io_streak + 1 if io_starved else 0
        used, budget = self._readahead.pool_status()
        pressured = budget > 0 and used / budget >= _READAHEAD_POOL_PRESSURE
        self._pool_streak = self._pool_streak + 1 if pressured else 0
        if self._h2d_streak >= self._CONSECUTIVE:
            self._h2d_streak = 0
            actions += self._deepen(ready_share)
        if self._pool_streak >= self._CONSECUTIVE:
            self._pool_streak = 0
            self._io_streak = 0
            actions += self._shed_readahead(used, budget)
        elif self._io_streak >= self._CONSECUTIVE:
            self._io_streak = 0
            actions += self._deepen_readahead(io_share)
        if self._consumer_streak >= self._CONSECUTIVE:
            self._consumer_streak = 0
            actions += self._shed_decode_threads()
            actions += self._lower_inflight()
        elif self._producer_streak >= self._CONSECUTIVE:
            self._producer_streak = 0
            actions += self._restore_decode_threads()
            actions += self._raise_inflight()
        self.decisions += len(actions)
        return actions

    def _deepen(self, ready_share):
        """h2d starvation: more transfers in flight — one more ring slot
        per signature and one more prefetch entry, each within its
        bound."""
        actions = []
        stager = self._loader._stager
        if stager is not None and stager.num_slots < self._max_slots:
            before = stager.num_slots
            after = stager.set_num_slots(before + 1)
            self._learned_slots = after
            actions.append(record_decision(
                'deepen_slots', slots_from=before, slots_to=after,
                h2d_ready_share=round(ready_share, 4)))
        prefetch = self._loader._prefetch
        if prefetch < self._max_prefetch:
            after = self._loader._set_prefetch(prefetch + 1)
            actions.append(record_decision(
                'deepen_prefetch', prefetch_from=prefetch,
                prefetch_to=after, h2d_ready_share=round(ready_share, 4)))
        return actions

    # -- readahead depth (petastorm_tpu/readahead.py) -------------------------

    def _io_share_scale(self):
        """The io-saturation normalizer: the reader pool's worker count
        (re-read each window — service fleets grow live), floor 1 for
        loaders whose reader exposes no pool."""
        reader = self._tunable_reader()
        pool = getattr(reader, '_pool', None) if reader is not None \
            else None
        workers = getattr(pool, 'workers_count', None)
        return max(1, workers) if isinstance(workers, int) else 1

    def _owns_readahead(self):
        """Single-owner guard for the process-wide readahead depth
        override — same discipline as the decoder-thread slot."""
        global _readahead_owner
        if _readahead_owner is None:
            _readahead_owner = self
        return _readahead_owner is self

    def _deepen_readahead(self, io_share):
        """Sustained io-wait while the consumer starves: fetch further
        ahead so storage latency hides behind decode — bounded by
        ``PETASTORM_TPU_READAHEAD_MAX_DEPTH``, and only where a live
        manager can observe the override (thread-pool workers share
        this process; remote fleets tune from their own windows)."""
        if self._readahead.live_manager_count() == 0 \
                or not self._owns_readahead():
            return []
        current = self._readahead.current_depth()
        if current >= self._readahead_max_depth:
            return []
        self._readahead_override = current + 1
        self._readahead.set_depth_override(self._readahead_override)
        return [record_decision('deepen_readahead', depth_from=current,
                                depth_to=self._readahead_override,
                                io_wait_share=round(io_share, 4))]

    def _shed_readahead(self, used, budget):
        """Sustained buffer-pool pressure: the pool, not the wire, is
        the bound — back the depth off one step, never below the KNOB's
        own width (the static configuration is the floor, as with every
        shed), so fetches stop being declined (``pool-exhausted``) at
        the budget edge."""
        if self._readahead.live_manager_count() == 0 \
                or not self._owns_readahead():
            return []
        current = self._readahead.current_depth()
        if current <= self._readahead.readahead_depth():
            return []
        self._readahead_override = current - 1
        self._readahead.set_depth_override(self._readahead_override)
        return [record_decision(
            'shed_readahead', depth_from=current,
            depth_to=self._readahead_override,
            pool_share=round(used / budget, 4) if budget else None)]

    def _release_readahead(self):
        global _readahead_owner
        if self._readahead_override is not None:
            self._readahead.set_depth_override(None)
            self._readahead_override = None
        if _readahead_owner is self:
            _readahead_owner = None

    # -- ventilator in-flight bound (Reader.set_ventilate_extra) --------------

    def _tunable_reader(self):
        reader = getattr(self._loader, 'reader', None)
        if reader is None or not hasattr(reader, 'set_ventilate_extra'):
            return None
        return reader

    def _raise_inflight(self):
        """Producer-bound: the consumer waits on data — let the
        ventilator keep more row-groups in flight so the pool never
        drains between pulls (bounded; each step is at most one more
        decoded row-group queued consumer-side)."""
        reader = self._tunable_reader()
        if reader is None:
            return []
        current = reader.ventilate_extra
        if self._inflight_baseline is None:
            self._inflight_baseline = current
        if current >= _MAX_INFLIGHT_EXTRA:
            return []
        after = reader.set_ventilate_extra(current + 1)
        return [record_decision('raise_inflight', inflight_from=current,
                                inflight_to=after)]

    def _lower_inflight(self):
        """Consumer-bound: the training step is the wall — give queued
        row-group memory back, one step at a time toward the
        construction-time baseline (never below it: the static
        configuration is the floor, as with every shed)."""
        reader = self._tunable_reader()
        if reader is None or self._inflight_baseline is None:
            return []
        current = reader.ventilate_extra
        if current <= self._inflight_baseline:
            return []
        after = reader.set_ventilate_extra(current - 1)
        return [record_decision('lower_inflight', inflight_from=current,
                                inflight_to=after)]

    # -- decoder threads ------------------------------------------------------

    def _owns_override(self):
        """True when THIS tuner may move the process-wide decoder-thread
        override: it already holds it, or the slot is free. The thread
        knob is one per process — two loaders' tuners must not fight
        over it or wipe each other's setting."""
        global _override_owner
        if _override_owner is None:
            _override_owner = self
        return _override_owner is self

    def _shed_decode_threads(self):
        """Consumer-bound: the training step is the wall — hand decoder
        CPUs back, one thread at a time down to 1."""
        if not self._owns_override():
            return []
        current = self._codecs.image_decoder_threads()
        if current <= 1:
            return []
        self._thread_override = current - 1
        self._codecs.set_image_decoder_threads_override(
            self._thread_override)
        return [record_decision('shed_decode_threads',
                                threads_from=current,
                                threads_to=self._thread_override)]

    def _restore_decode_threads(self):
        """Producer-bound: the consumer waits on data — give shed
        decoder threads back, toward the knob baseline."""
        current = self._codecs.image_decoder_threads()
        if self._thread_override is None \
                or current >= self._baseline_threads:
            return []
        self._thread_override = current + 1
        if self._thread_override >= self._baseline_threads:
            # fully restored: drop the override so the knob rules again
            self._release_override()
            restored_to = self._baseline_threads
        else:
            self._codecs.set_image_decoder_threads_override(
                self._thread_override)
            restored_to = self._thread_override
        return [record_decision('restore_decode_threads',
                                threads_from=current,
                                threads_to=restored_to)]

    def _release_override(self):
        global _override_owner
        self._codecs.set_image_decoder_threads_override(None)
        self._thread_override = None
        if _override_owner is self:
            _override_owner = None

    # -- lifecycle / reporting ------------------------------------------------

    def close(self):
        """Loader stop: drop the decoder-thread and readahead-depth
        overrides — only those THIS tuner holds — so learned settings
        die with the loader instead of leaking into later readers (or
        wiping another live tuner's). The decision log survives in the
        module ring and the counter."""
        global _override_owner
        if self._thread_override is not None:
            self._release_override()
        elif _override_owner is self:
            _override_owner = None
        self._release_readahead()

    def summary(self):
        """The report-facing view: current depths, bounds, streaks and
        the recent decision log."""
        stager = self._loader._stager
        reader = self._tunable_reader()
        return {
            'window_s': self.window_s,
            'slots': stager.num_slots if stager is not None else None,
            'max_slots': self._max_slots,
            'prefetch': self._loader._prefetch,
            'max_prefetch': self._max_prefetch,
            'decoder_threads': self._codecs.image_decoder_threads(),
            'readahead_depth': self._readahead.current_depth(),
            'readahead_max_depth': self._readahead_max_depth,
            'ventilate_extra': (reader.ventilate_extra
                                if reader is not None else None),
            'decisions': self.decisions,
            'recent': recent_decisions(10),
        }
