"""JAX bridge: sharded ``jax.Array`` batch loaders (the TPU-native
equivalent of the reference's tf/torch consumer layers)."""

from petastorm_tpu.jax.loader import (  # noqa: F401
    JaxLoader, LEN_SUFFIX, MASK_FIELD, make_jax_loader,
)


def __getattr__(name):
    # TrainCheckpointer imports orbax; keep that off the base import path
    if name == 'TrainCheckpointer':
        from petastorm_tpu.jax.checkpoint import TrainCheckpointer
        return TrainCheckpointer
    raise AttributeError(name)
