"""JAX bridge: sharded ``jax.Array`` batch loaders (the TPU-native
equivalent of the reference's tf/torch consumer layers)."""

from petastorm_tpu.jax.loader import (  # noqa: F401
    JaxLoader, LEN_SUFFIX, MASK_FIELD, make_jax_loader,
)


def __getattr__(name):
    # checkpoint.py imports orbax; keep that off the base import path
    if name in ('TrainCheckpointer', 'merge_loader_states'):
        from petastorm_tpu.jax import checkpoint
        return getattr(checkpoint, name)
    raise AttributeError(name)
