"""JAX device stage: Parquet row-groups → sharded ``jax.Array`` batches.

This is the layer the reference does not have (its consumers stop at numpy /
torch / tf tensors): decoded column batches are re-batched to a fixed size,
optionally shuffled, cast per a dtype policy, and staged into device HBM as
``jax.Array``s laid out for a ``jax.sharding.Mesh`` — with the host→device
transfer overlapped with consumption (double/triple buffering, bounded by
``prefetch``).

Design notes (SURVEY.md §7.1/§7.2 step 4):

* Fixed batch sizes + 'drop'/'pad' last-batch policies keep every step's
  shapes static, so the training step compiles once (XLA requirement).
* Sharding uses ``jax.make_array_from_process_local_data``: each host feeds
  only its own shard (the reader is already sharded by
  ``jax.process_index()``), and the resulting global array's batch axis is
  laid out over the mesh's data axes — collectives then ride ICI.
* All decode/shuffle/cast work happens on a background staging thread; the
  consumer thread only dequeues ready device arrays.
"""

import logging
import queue
import threading
import time

import numpy as np

from petastorm_tpu import fused
from petastorm_tpu.fused import EncodedImageColumn
from petastorm_tpu.jax import staging
from petastorm_tpu.telemetry import (
    STALL_NOTE_FLOOR_S, StallAttributor, note_consumer_wait,
    note_producer_wait, span, tracing,
)

logger = logging.getLogger(__name__)

_SENTINEL_END = object()
_NO_ITEM = object()

#: name of the validity-mask column added under ``last_batch='pad'``
#: (one canonical definition, shared with the staging engine)
MASK_FIELD = staging.MASK_FIELD
#: suffix of the true-size companion column added per ``pad_ragged`` field
LEN_SUFFIX = '_len'
# hidden per-row provenance column riding through the staging buffers; maps
# each row back to the reader pull (row-group) it came from so checkpoints
# can be delivery-accurate. Added after the reader, stripped before device.
_PULL_FIELD = '__petastorm_tpu_pull__'


def make_jax_loader(dataset_url_or_urls, batch_size, mesh=None, data_axes=None,
                    fields=None, shuffle_rows=False,
                    shuffling_queue_capacity=None, min_after_retrieve=None,
                    extra_capacity=None, seed=0, last_batch='drop',
                    dtypes=None, prefetch=2, num_epochs=1,
                    inmemory_cache_all=False, pad_ragged=None,
                    bucket_boundaries=None,
                    reader_factory=None, mixture=None, **reader_kwargs):
    """Create a :class:`JaxLoader` over a Parquet dataset.

    :param batch_size: rows per emitted batch **per host**. With a mesh, must
        divide evenly over the mesh's data-axis shards.
    :param mesh: optional :class:`jax.sharding.Mesh`; batches become global
        ``jax.Array``s whose leading axis is sharded over ``data_axes``.
    :param data_axes: mesh axis name(s) to shard the batch axis over
        (default: all mesh axis names).
    :param fields: field name/regex list forwarded to the reader
        (column projection).
    :param shuffle_rows: decorrelate rows across row-groups with a
        :class:`~petastorm_tpu.buffers.BatchedRandomShufflingBuffer`.
    :param last_batch: ``'drop'`` (default: constant shapes), ``'pad'``
        (zero-pad + ``valid_mask`` bool column), or ``'short'`` (emit the
        ragged tail batch — breaks shape stability under jit).
    :param dtypes: optional ``{field: numpy dtype}`` cast applied on host
        before staging (e.g. ``{'image': jnp.bfloat16}``).
    :param prefetch: number of device batches staged ahead of the consumer.
    :param inmemory_cache_all: decode once, replay epochs from device
        memory (see :class:`InMemoryCachedLoader`); requires
        ``num_epochs=1`` — re-iterate for more epochs.
    :param pad_ragged: ``{field: size or (sizes...)}`` — densify
        variable-shape fields (``(None, ...)`` Unischema dims, the shape
        class the reference's batched reader simply rejects,
        ``arrow_reader_worker.py:176-178``) to STATIC shapes: each
        variable dim pads with zeros (or truncates) to the given size, and
        a companion ``<field>_len`` int32 column carries every row's TRUE
        size(s) — ``(B,)`` for one variable dim, ``(B, k)`` for ``k``. A
        truncated row's stored length exceeds the padded extent, so
        truncation stays detectable and ``arange(L) < len`` masks
        saturate correctly. Static shapes are the XLA-idiomatic answer to
        raggedness: the train step compiles once, and masks built from
        ``<field>_len`` replace dynamic shapes.
    :param bucket_boundaries: ``{field: [b1, b2, ...]}`` (one field) —
        length-bucketed batching, the XLA re-design of tf.data's
        ``bucket_by_sequence_length``: rows route to the smallest
        boundary ≥ their leading length, each bucket fills its own
        fixed-``batch_size`` batches, and the field pads to the BUCKET's
        bound (rows past the largest boundary truncate into it; the
        ``<field>_len`` column keeps true lengths). Emitted shapes are
        static per bucket, so jit compiles one step per bucket and
        padding waste drops from pad-to-max to pad-to-bucket. Composes
        with ``pad_ragged`` for OTHER fields. Memory/latency contract:
        when ``shuffle_rows`` is on, EACH bucket keeps its own shuffle
        buffer, so host memory scales as ``shuffling_queue_capacity ×
        len(boundaries)`` — divide the capacity you would use unbucketed
        by the boundary count to keep the same footprint. A batch emits
        only when ITS bucket fills, so rows routed to a rarely-hit bucket
        can be held until the END OF THE STREAM — the final epoch's
        exhaustion, when every bucket flushes (none are lost); with
        ``num_epochs=None`` the stream never ends and an unfilled
        bucket's rows are held indefinitely. Prefer boundaries that match
        the actual length distribution over a uniform grid.
    :param reader_factory: reader constructor (defaults to
        :func:`petastorm_tpu.reader.make_batch_reader`).
    :param mixture: a :class:`petastorm_tpu.mixture.MixtureSpec` — feed
        the loader a deterministic weighted multi-dataset mixture of
        packed token rows (``tokens`` / ``loss_mask`` / ``segment_ids``
        columns; the spec needs ``seq_len``) instead of one dataset
        (``dataset_url_or_urls`` must then be None; the sources carry
        their own URLs). ``reader_kwargs`` flow to every source's
        reader; with ``reader_pool_type='service'`` and a standing
        daemon configured, each source registers as its own
        QoS-weighted job on the shared fleet (docs/mixture.md).
    :param reader_kwargs: forwarded to the reader factory (predicates,
        sharding overrides, pool type, ...).

    .. warning:: **Multi-host epochs.** Row-group sharding can hand hosts
        unequal row counts, so per-host loaders may emit different numbers of
        batches per epoch. A host that exhausts its shard stops stepping
        while the others still issue collectives — a pod-wide hang. For
        multi-host training drive a FIXED number of steps per epoch (e.g.
        ``steps = global_rows // (batch_size * jax.process_count())``) over
        an infinite loader (``num_epochs=None``), the standard TPU-pod
        pattern — :meth:`JaxLoader.iter_steps` packages exactly that.

    A fully consumed loader may be iterated again: re-iteration resets the
    reader and replays the dataset, matching the torch loaders' ergonomics.
    Replays reshuffle at whatever levels shuffling is enabled —
    ``shuffle_row_groups`` (reader, on by default) and/or ``shuffle_rows``.
    """
    from petastorm_tpu.reader import make_batch_reader
    factory = reader_factory or make_batch_reader
    if mixture is not None:
        if dataset_url_or_urls is not None:
            raise ValueError('mixture= and dataset_url_or_urls are mutually '
                             'exclusive: the MixtureSpec sources carry their '
                             'own URLs')
        if reader_factory is not None:
            raise ValueError('mixture= builds its own source readers; give '
                             'per-source factories on the MixtureSource '
                             'entries instead of reader_factory=')
        if fields is not None:
            raise ValueError('mixture= emits fixed packed columns (tokens/'
                             'loss_mask/segment_ids); fields= does not apply')
        if inmemory_cache_all:
            raise ValueError('mixture= does not support inmemory_cache_all')
        from petastorm_tpu.mixture import MixtureBatchReader, MixtureStream
        stream = MixtureStream(mixture, num_epochs=num_epochs,
                               **reader_kwargs)
        reader = MixtureBatchReader(stream, rows_per_pull=batch_size)
        try:
            return JaxLoader(reader, batch_size, mesh=mesh,
                             data_axes=data_axes, shuffle_rows=shuffle_rows,
                             shuffling_queue_capacity=shuffling_queue_capacity,
                             min_after_retrieve=min_after_retrieve,
                             extra_capacity=extra_capacity, seed=seed,
                             last_batch=last_batch, dtypes=dtypes,
                             prefetch=prefetch, pad_ragged=pad_ragged,
                             bucket_boundaries=bucket_boundaries)
        except Exception:
            reader.stop()
            reader.join()
            raise
    if inmemory_cache_all and num_epochs not in (1, None):
        raise ValueError(
            'inmemory_cache_all caches exactly one epoch and replays it; '
            'pass num_epochs=1 (the default) and re-iterate the loader for '
            'more epochs (got num_epochs=%r)' % (num_epochs,))
    if reader_factory is None:
        # Fused decode hand-shake (petastorm_tpu/fused.py): ask the
        # worker for still-encoded image cells whenever this loader's
        # batch path can decode them straight into its staging buffers —
        # the no-row-shuffle noop re-batcher with the arena live. Other
        # configurations (and per-field surprises like a dtype recast)
        # fall back via the loader's own materialization, so requesting
        # here is an optimization hint, never a correctness bet. Custom
        # reader factories are left untouched — their signatures may not
        # know the kwarg.
        reader_kwargs.setdefault(
            'defer_image_decode',
            not shuffle_rows and bucket_boundaries is None
            and staging.staging_enabled())
    reader = factory(dataset_url_or_urls, schema_fields=fields,
                     num_epochs=1 if inmemory_cache_all else num_epochs,
                     **reader_kwargs)
    try:
        loader = JaxLoader(reader, batch_size, mesh=mesh, data_axes=data_axes,
                           shuffle_rows=shuffle_rows,
                           shuffling_queue_capacity=shuffling_queue_capacity,
                           min_after_retrieve=min_after_retrieve,
                           extra_capacity=extra_capacity, seed=seed,
                           last_batch=last_batch, dtypes=dtypes,
                           prefetch=prefetch, pad_ragged=pad_ragged,
                           bucket_boundaries=bucket_boundaries)
    except Exception:
        reader.stop()
        reader.join()
        raise
    if inmemory_cache_all:
        return InMemoryCachedLoader(loader, seed=seed)
    return loader


class JaxLoader:
    """Iterator of ``{field: jax.Array}`` batches over a batched reader."""

    def __init__(self, reader, batch_size, mesh=None, data_axes=None,
                 shuffle_rows=False, shuffling_queue_capacity=None,
                 min_after_retrieve=None, extra_capacity=None, seed=0,
                 last_batch='drop', dtypes=None, prefetch=2,
                 pad_ragged=None, bucket_boundaries=None):
        if last_batch not in ('drop', 'pad', 'short'):
            raise ValueError("last_batch must be 'drop', 'pad' or 'short'; "
                             'got %r' % (last_batch,))
        self._pad_ragged = {
            name: (sizes,) if np.ndim(sizes) == 0 else tuple(sizes)
            for name, sizes in (pad_ragged or {}).items()}
        for name, sizes in self._pad_ragged.items():
            if not all(isinstance(s, (int, np.integer)) and s > 0
                       for s in sizes):
                raise ValueError('pad_ragged[%r] must be a positive int or '
                                 'tuple of positive ints; got %r'
                                 % (name, sizes))
        self._bucket_field = None
        self._bucket_bounds = None
        if bucket_boundaries:
            if len(bucket_boundaries) != 1:
                raise ValueError('bucket_boundaries supports exactly one '
                                 'field; got %s'
                                 % sorted(bucket_boundaries))
            ((name, bounds),) = bucket_boundaries.items()
            bounds = [int(b) for b in bounds]
            if not bounds or bounds != sorted(set(bounds)) or bounds[0] <= 0:
                raise ValueError('bucket_boundaries[%r] must be strictly '
                                 'ascending positive ints; got %r'
                                 % (name, bounds))
            if name in self._pad_ragged:
                raise ValueError('field %r cannot be in both pad_ragged and '
                                 'bucket_boundaries (the boundaries define '
                                 'its padding)' % name)
            self._bucket_field = name
            self._bucket_bounds = np.asarray(bounds, np.int64)
        if not getattr(reader, 'batched_output', True):
            raise ValueError(
                'JaxLoader requires a batched reader (make_batch_reader); '
                'make_batch_reader decodes codec fields too, so a row reader '
                'is never needed here')
        self._reader = reader
        self._batch_size = batch_size
        self._mesh = mesh
        self._last_batch = last_batch
        self._dtypes = dict(dtypes or {})
        self._prefetch = max(1, prefetch)
        self._seed = seed
        self._shuffle_rows = shuffle_rows
        self._shuffling_queue_capacity = shuffling_queue_capacity
        self._min_after_retrieve = min_after_retrieve
        self._extra_capacity = extra_capacity
        self._sharding = self._resolve_sharding(mesh, data_axes, batch_size)
        # per-batch-length sharded row plans (see _shard_plan_for); None
        # values pin the make_array_from_process_local_data fallback
        self._shard_plans = {}
        self._stager = None   # per-pass staging arena (stage thread only)
        # staging autotuner (jax/autotune.py): created lazily at the
        # first pass when staging is on and the knob allows, kept across
        # passes so its learned settings and decision log survive replays
        self._autotuner = None
        self._stage_thread = None
        self._out_queue = None
        self._stop_event = threading.Event()
        self._stage_error = None
        self._exhausted = False
        self._drain_lock = threading.Lock()
        # items drained out of the queue by __iter__'s boundary probe that
        # turned out to be real batches; __next__ serves these FIRST.
        # Putting them BACK into the bounded queue instead would race the
        # producer's possibly-still-blocked sentinel put (overflow, or the
        # sentinel ordered ahead of real batches).
        self._leftovers = []
        self._epoch = 0
        self._produce_done = threading.Event()
        # delivery-accurate checkpoint provenance (see state_dict): the
        # reader marks a row-group consumed when the STAGE thread pulls it,
        # but rows can sit in the shuffling buffer / prefetch queue long
        # after — so we track per-pull delivered-row counts and only count
        # a row-group once every row reached the consumer.
        self._prov_lock = threading.Lock()
        self._pull_info = {}        # pull_id -> [epoch, item_index, n_rows]
        self._pull_delivered = {}   # pull_id -> rows delivered to consumer
        self._delivered_by_epoch = {}   # epoch -> {item_index, ...}
        self._next_pull_id = 0
        self._uses_provenance = hasattr(reader, 'next_batch_info')
        # trace context of the most recent reader pull (staging thread
        # only): batches mix rows across pulls, so staging-side trace
        # events (collate/h2d) attribute to the pull being folded in —
        # the honest approximation for a batching stage
        self._last_pull_ctx = None
        # staging gauges (see diagnostics): who is waiting on whom?
        self._consumer_wait_s = 0.0   # consumer blocked on get → input-bound
        self._stage_blocked_s = 0.0   # producer blocked on put → compute-bound
        # loader-local stall attributor: the same two clocks, bucketed into
        # sampling windows (telemetry.StallAttributor) — what
        # autotune_report classifies from. Reset at each pass's FIRST
        # delivered batch, so spin-up (reader/decoder startup) latency
        # never misattributes a compute-bound pipeline as input-bound.
        self._attributor = StallAttributor()
        self._awaiting_first_delivery = True
        self._batches_delivered = 0
        # registry snapshot taken at each pass's first delivery: scopes
        # autotune's decoded-cache verdict to the CURRENT pass (lifetime
        # counters would blend epoch 1's full decode cost into every
        # later epoch's hit rate and misread healthy warm passes)
        self._pass_baseline = None
        # fused decode (petastorm_tpu/fused.py): the reason this loader
        # last MATERIALIZED a deferred column instead of letting the
        # arena fuse it (None = never declined); feeds fused_decode_mode
        self._fused_fallback = None
        # live observability plane (docs/telemetry.md): the loader
        # contributes its staging-side gauges to /health and the live
        # autotune verdict to /report; no-op when unarmed
        from petastorm_tpu.telemetry import obs_server
        self._obs_mount = obs_server.mount(
            'jax-loader', health=self._obs_health,
            report=self._obs_report)

    # -- sharding ------------------------------------------------------------

    @staticmethod
    def _resolve_sharding(mesh, data_axes, batch_size):
        if mesh is None:
            return None
        import jax
        from jax.sharding import NamedSharding, PartitionSpec
        axes = tuple(data_axes) if data_axes is not None else tuple(mesh.axis_names)
        n_shards = 1
        for a in axes:
            n_shards *= mesh.shape[a]
        if batch_size * jax.process_count() % max(1, n_shards):
            raise ValueError(
                'global batch (%d per host x %d hosts) must divide evenly '
                'over the %d data shards of mesh axes %s'
                % (batch_size, jax.process_count(), n_shards, axes))
        return NamedSharding(mesh, PartitionSpec(axes))

    # -- iteration -----------------------------------------------------------

    def __iter__(self):
        """Start a pass — or, per the iterator protocol, resume the pass in
        progress (``iter(it) is it``), replaying the dataset only when the
        previous pass is exhausted.

        .. note:: a mid-pass ``iter()`` whose prefetch queue is momentarily
           empty blocks until the staging thread either stages a batch
           (resume) or finishes the pass (replay) — it cannot tell which
           side of the epoch boundary it is on until one happens. With a
           stalled reader that wait is unbounded; ``stop()`` unblocks it.
        """
        if self._stage_thread is not None:
            if self._stop_event.is_set():
                raise RuntimeError('JaxLoader was stopped; construct a new '
                                   'loader to iterate again')
            if not self._exhausted:
                # The pass may have ended with its sentinel unobserved and
                # still in flight — the NORMAL state right after consuming
                # exactly to the boundary (iter_steps, or a drop-tail
                # batch): the producer is only now unblocking to enqueue
                # the sentinel. Wait for the pass state to settle: either a
                # real batch lands (mid-pass → resume) or the producer
                # finishes. _produce_done is set BEFORE the sentinel put,
                # so sentinel-visible implies done-visible: "queue
                # non-empty while done is unset" deterministically means
                # real batches. The lock keeps drain + put-back atomic
                # w.r.t. a consumer's exhaustion check in __next__.
                while True:
                    with self._drain_lock:
                        if (self._produce_done.is_set()
                                or not self._stage_thread.is_alive()):
                            pending = list(self._leftovers)
                            self._leftovers = []
                            try:
                                while True:
                                    pending.append(
                                        self._out_queue.get_nowait())
                            except queue.Empty:
                                pass
                            if pending == [_SENTINEL_END]:
                                self._exhausted = True  # boundary: complete
                                break
                            if pending:
                                # unconsumed tail (possibly incl. a
                                # trailing sentinel): park it for __next__
                                # to serve ahead of the queue
                                self._leftovers = pending
                                break
                            if not self._stage_thread.is_alive():
                                # dead without a sentinel (put gave up or
                                # died): __next__ surfaces stop/error
                                break
                            # done set, sentinel put in flight: retry
                        elif self._leftovers or not self._out_queue.empty():
                            # done was unset just above and sentinel puts
                            # strictly follow the done flag, so re-check
                            # before trusting the queue contents
                            if not self._produce_done.is_set():
                                break  # real batches staged: resume below
                            continue  # take the drain branch next round
                    if self._stop_event.is_set():
                        break
                    if self._produce_done.is_set():
                        time.sleep(0.001)  # sentinel put in flight: yield
                    else:
                        self._produce_done.wait(0.05)
                if not self._exhausted:
                    # Same pass resumes: ``iter(it) is it`` per the iterator
                    # protocol, so peek-then-loop (``next(loader)`` then
                    # ``for b in loader``) and ``for b in iter(loader)``
                    # both work. A staging error, if any, surfaces in
                    # __next__ where every consumption style sees it
                    # deterministically.
                    return self
            if self._stage_error is not None:
                raise RuntimeError('JaxLoader cannot restart after a staging '
                                   'error') from self._stage_error
            # The consumer can observe the end sentinel a beat before the
            # stage thread finishes its teardown; it is exiting, so join
            # rather than misreading aliveness as an in-progress pass.
            self._stage_thread.join(timeout=10)
            # Epoch replay: restart the (fully consumed) reader and stage a
            # fresh pass — same ergonomics as the torch loaders' re-iteration.
            self._reader.reset()
            self._exhausted = False
            self._epoch += 1
            # each pass's spin-up wait is excluded from autotune's
            # steady-state attribution (the attributor resets at the new
            # pass's first delivery)
            self._awaiting_first_delivery = True
            # reset() restarts the reader's epoch numbering from 0; stale
            # provenance would corrupt the delivery-accurate checkpoint
            with self._prov_lock:
                self._pull_info.clear()
                self._pull_delivered.clear()
                self._delivered_by_epoch = {}
            with self._drain_lock:
                self._leftovers = []  # exhausted implies empty; belt+braces
        # fresh event per pass: a predecessor thread in teardown may still
        # set the previous pass's event after this point
        self._produce_done = threading.Event()
        # fresh arena per pass, created BEFORE the thread starts so
        # diagnostics never observe a started pass without its stager: a
        # replay must not inherit slots whose in-flight transfers
        # belonged to the previous pass, and knob changes (after
        # staging.refresh_staging()) take effect here
        self._stager = staging.make_stager(
            self._batch_size, self._dtypes, self._last_batch,
            self._put_to_device)
        if self._stager is not None:
            from petastorm_tpu.jax import autotune
            if self._autotuner is None and autotune.autotune_enabled():
                self._autotuner = autotune.StagingAutotuner(self)
            if self._autotuner is not None:
                # the new pass's stager starts at the depth the tuner
                # already learned (decisions survive epoch replays)
                self._autotuner.apply_learned(self._stager)
        self._out_queue = queue.Queue(maxsize=self._prefetch)
        self._stage_thread = threading.Thread(target=self._stage_loop,
                                              daemon=True)
        self._stage_thread.start()
        return self

    def __next__(self):
        if self._out_queue is None:
            iter(self)
        if self._exhausted:
            raise StopIteration
        while True:
            with self._drain_lock:
                item = (self._leftovers.pop(0) if self._leftovers
                        else _NO_ITEM)
            if item is _NO_ITEM:
                try:
                    t0 = time.monotonic()
                    try:
                        item = self._out_queue.get(timeout=0.1)
                    finally:
                        waited = time.monotonic() - t0
                        self._consumer_wait_s += waited
                        if waited > STALL_NOTE_FLOOR_S:
                            # queue_wait is a canonical stage AND
                            # producer-bound evidence (both the loader's
                            # own attributor and the process-wide one)
                            self._attributor.note_consumer_wait(waited)
                            note_consumer_wait(waited)
                except queue.Empty:
                    if self._stage_error is not None:
                        raise self._stage_error
                    # stop() may race an in-flight iteration: _put_blocking
                    # gives up on delivering _SENTINEL_END once the stop
                    # event is set, so a consumer blocked here would
                    # otherwise spin forever. Same if next() is called
                    # after stop(), or the stage thread died without
                    # managing to enqueue the sentinel.
                    if self._stop_event.is_set():
                        self._exhausted = True
                        raise StopIteration
                    with self._drain_lock:
                        if (self._stage_thread is not None
                                and not self._stage_thread.is_alive()
                                and not self._leftovers
                                and self._out_queue.empty()):
                            self._exhausted = True
                            raise StopIteration
                    continue
            if item is _SENTINEL_END:
                self._exhausted = True
                if self._stage_error is not None:
                    raise self._stage_error
                raise StopIteration
            batch, pull_counts = item
            if pull_counts:
                self._record_delivery(pull_counts)
            self._batches_delivered += 1
            if self._awaiting_first_delivery:
                # spin-up over: drop the startup waits so autotune's
                # attribution covers steady state of the current pass only
                self._attributor.reset()
                self._awaiting_first_delivery = False
                from petastorm_tpu.telemetry import get_registry
                self._pass_baseline = get_registry().snapshot()
            return batch

    def _record_delivery(self, pull_counts):
        """Credit delivered rows to their pulls; a pull whose every row has
        reached the consumer marks its row-group delivered-for-checkpoint."""
        with self._prov_lock:
            for pull_id, n in pull_counts.items():
                info = self._pull_info.get(pull_id)
                if info is None:
                    continue  # stale (pre-replay) sidecar
                seen = self._pull_delivered.get(pull_id, 0) + n
                if seen >= info[2]:
                    epoch, item_index, _ = info
                    if epoch is not None:
                        self._delivered_by_epoch.setdefault(
                            epoch, set()).add(item_index)
                    del self._pull_info[pull_id]
                    self._pull_delivered.pop(pull_id, None)
                else:
                    self._pull_delivered[pull_id] = seen

    def iter_steps(self, num_steps):
        """Yield exactly ``num_steps`` batches, continuing across calls.

        The multi-host-safe epoch idiom (see the warning on
        :func:`make_jax_loader`): over an infinite loader
        (``num_epochs=None``), every host steps the same fixed count per
        "epoch" regardless of shard imbalance, so collectives stay aligned::

            steps = global_rows // (batch_size * jax.process_count())
            for epoch in range(epochs):
                for batch in loader.iter_steps(steps):
                    ...

        Raises :class:`RuntimeError` if the loader runs dry before
        ``num_steps`` (finite ``num_epochs`` with too little data) — on a
        pod that would mean a silent divergence of step counts across hosts.
        """
        if self._out_queue is None or self._exhausted:
            iter(self)  # start — or replay, matching plain iteration
        for step in range(num_steps):
            try:
                yield next(self)
                continue
            except StopIteration:
                pass
            # A prior call may have consumed the pass exactly to its end,
            # leaving the end sentinel unobserved (_exhausted was False
            # until just now). That is an epoch boundary, not running dry:
            # replay and retry, consistent with a fresh iter_steps call.
            if (step == 0 and not self._stop_event.is_set()
                    and self._stage_error is None):
                iter(self)
                try:
                    yield next(self)
                    continue
                except StopIteration:
                    pass
            if self._stop_event.is_set():
                raise RuntimeError(
                    'loader was stopped after %d of %d steps' % (step, num_steps))
            raise RuntimeError(
                'loader exhausted after %d of %d steps; use '
                'num_epochs=None so fixed-step epochs never run dry'
                % (step, num_steps)) from None

    # -- staging pipeline (background thread) --------------------------------

    def _make_buffer(self):
        from petastorm_tpu.buffers import (
            BatchedNoopShufflingBuffer, BatchedRandomShufflingBuffer,
        )
        if not self._shuffle_rows:
            return BatchedNoopShufflingBuffer(self._batch_size)
        capacity = self._shuffling_queue_capacity or 4 * self._batch_size
        min_after = (self._min_after_retrieve
                     if self._min_after_retrieve is not None
                     else capacity // 2)
        # Extra capacity absorbs one whole row-group on top of capacity.
        # It is EAGERLY preallocated per column, so the default stays
        # proportional to capacity (not a huge constant) — datasets with
        # row-groups larger than `capacity` rows should pass extra_capacity
        # explicitly (the overflow error says so).
        extra = (self._extra_capacity if self._extra_capacity is not None
                 else capacity)
        # seed offset by epoch: replay must not repeat epoch 0's order
        seed = (None if self._seed is None
                else (self._seed + self._epoch) % (2 ** 32))
        return BatchedRandomShufflingBuffer(
            capacity, min_after, self._batch_size,
            extra_capacity=extra, seed=seed)

    def _pull_batches(self):
        """Yield column dicts from the reader, tagging each row with its
        pull id when the reader exposes provenance (next_batch_info)."""
        if not self._uses_provenance:
            for batch in self._reader:
                yield dict(batch._asdict() if hasattr(batch, '_asdict')
                           else batch)
            return
        while True:
            try:
                columns, item_index, epoch = self._reader.next_batch_info()
            except StopIteration:
                return
            if tracing.trace_enabled():
                self._last_pull_ctx = tracing.ctx_for(
                    item_index, epoch, getattr(self._reader, 'cur_shard',
                                               None))
            n = len(next(iter(columns.values()))) if columns else 0
            with self._prov_lock:
                pull_id = self._next_pull_id
                self._next_pull_id += 1
                self._pull_info[pull_id] = (epoch, item_index, n)
            columns[_PULL_FIELD] = np.full(n, pull_id, np.int64)
            yield columns

    def _stage_loop(self):
        try:
            if self._bucket_field is not None:
                self._stage_loop_bucketed()
                return
            buf = self._make_buffer()
            for columns in self._pull_batches():
                # staging-side trace events (collate/h2d spans below)
                # attach to the pull just folded in; no-op when untraced
                with tracing.activate(self._last_pull_ctx, track='stager'):
                    # deferred image columns that THIS pass cannot fuse
                    # (staging off, shuffled rows, dtype recast) decode
                    # now, in one vectorized pass per column
                    columns = self._materialize_encoded(columns)
                    with span('collate'):
                        # densify BEFORE the buffer: a variable field
                        # arrives as a dense (n, ...) array from a uniform
                        # row-group but as an object array from a ragged
                        # one, and the buffers cannot mix the two forms
                        # (nor two dense widths); after this, every chunk
                        # has ONE static shape and the shuffle buffer
                        # preallocates correctly
                        if self._pad_ragged:
                            columns = self._densify_ragged(columns)
                        buf.add_many(columns)
                    while buf.can_retrieve:
                        self._retrieve_and_emit(buf)
                        if self._stop_event.is_set():
                            return
                if self._stop_event.is_set():
                    return
            buf.finish()
            while buf.can_retrieve:
                self._retrieve_and_emit(buf)
                if self._stop_event.is_set():
                    return
        except Exception as e:  # noqa: BLE001 - surfaced to consumer
            self._stage_error = e
        finally:
            if self._stager is not None:
                # drop slot slabs + their in-flight device refs: a ring
                # kept across the epoch gap would pin host and device
                # memory the post-epoch consumer (eval, checkpointing)
                # may need
                self._stager.release()
            # set happens-before put: a sentinel can only be OBSERVED in
            # the queue after _produce_done is visible, which is what lets
            # __iter__'s probe distinguish "real mid-pass batches" from "a
            # just-landed sentinel" deterministically (no timing
            # heuristics). No lock here — holding _drain_lock across a
            # blocking put deadlocks against __iter__'s probe when the
            # queue is full.
            self._produce_done.set()
            self._put_blocking(_SENTINEL_END)

    def _stage_loop_bucketed(self):
        """Length-bucketed staging (the ``bucket_by_sequence_length``
        shape of tf.data, re-designed for XLA): each bucket keeps its own
        fixed-``batch_size`` buffer, every chunk is split by the bucket
        field's per-row length and densified to the bucket's bound, and a
        batch emits whenever any bucket fills. Emitted shapes are static
        PER BUCKET — jit compiles one step per bucket (bounded by the
        boundary count), and padding waste drops from pad-to-max to
        pad-to-bucket."""
        buffers = {}
        for columns in self._pull_batches():
            with tracing.activate(self._last_pull_ctx, track='stager'):
                # bucketed batching gathers per-row — always materialize
                columns = self._materialize_encoded(columns)
                with span('collate'):
                    if self._pad_ragged:
                        columns = self._densify_ragged(columns)
                    split = list(self._split_by_bucket(columns))
                for bound, subcols in split:
                    buf = buffers.get(bound)
                    if buf is None:
                        buf = buffers[bound] = self._make_buffer()
                    with span('collate'):
                        buf.add_many(subcols)
                    while buf.can_retrieve:
                        self._retrieve_and_emit(buf)
                        if self._stop_event.is_set():
                            return
            if self._stop_event.is_set():
                return
        for buf in buffers.values():
            buf.finish()
            while buf.can_retrieve:
                self._retrieve_and_emit(buf)
                if self._stop_event.is_set():
                    return

    @staticmethod
    def _object_cells(col, name, policy):
        """Normalize an object column to per-row ndarrays (None kept) and
        return ``(cells, first_non_none)`` — shared None/dtype-inference
        contract of ``pad_ragged`` and ``bucket_boundaries``."""
        cells = [None if c is None else np.asarray(c) for c in col]
        first = next((c for c in cells if c is not None), None)
        if first is None:
            raise ValueError(
                '%s[%r]: every cell in this batch is None; cell dtype/'
                'trailing shape cannot be inferred. Filter all-null '
                'batches with a predicate, or drop the field'
                % (policy, name))
        return cells, first

    @staticmethod
    def _reserve_len_column(columns, name, policy):
        """The ``<name>_len`` companion column's name, after checking the
        batch does not already carry one."""
        len_name = name + LEN_SUFFIX
        if len_name in columns:
            raise ValueError(
                '%s would add column %r but the batch already has one; '
                'rename the source column' % (policy, len_name))
        return len_name

    def _split_by_bucket(self, columns):
        """Split one chunk by the bucket field's per-row leading length.
        Yields ``(bound, subcolumns)`` with the bucket field densified to
        ``(n_rows, bound, *trailing)`` plus its true-length column; rows
        longer than the largest boundary truncate into the last bucket
        (true length preserved, same contract as ``pad_ragged``)."""
        name = self._bucket_field
        if name not in columns:
            raise ValueError(
                'bucket_boundaries field %r is not in the batch '
                '(available: %s); check the name against fields=/the '
                'schema' % (name, sorted(n for n in columns
                                         if n != _PULL_FIELD)))
        len_name = self._reserve_len_column(columns, name,
                                            'bucket_boundaries')
        col = columns[name]
        n = len(col)
        if n == 0:
            return
        if col.dtype == object:
            cells, first = self._object_cells(col, name,
                                              'bucket_boundaries')
            if first.ndim < 1:
                raise ValueError(
                    'bucket_boundaries[%r]: cells are scalars; bucketing '
                    'needs a leading sequence dim' % name)
            lens = np.asarray([0 if c is None else c.shape[0]
                               for c in cells], np.int32)
            trailing = first.shape[1:]
            dtype = first.dtype
        else:
            if col.ndim < 2:
                raise ValueError(
                    'bucket_boundaries[%r]: column is scalar per row; '
                    'bucketing needs a leading sequence dim' % name)
            cells = None  # uniform dense chunk: one length for all rows
            lens = np.full(n, col.shape[1], np.int32)
            trailing = col.shape[2:]
            dtype = col.dtype
        bounds = self._bucket_bounds
        # searchsorted('left') → index of the smallest bound >= len;
        # longer-than-largest rows clamp into the last bucket (truncate)
        bucket_idx = np.minimum(np.searchsorted(bounds, lens, side='left'),
                                len(bounds) - 1)
        for b in np.unique(bucket_idx):
            bound = int(bounds[b])
            rows = np.flatnonzero(bucket_idx == b)
            dense = np.zeros((len(rows), bound) + trailing, dtype)
            if cells is None:
                keep = min(col.shape[1], bound)
                dense[:, :keep] = col[rows, :keep]
            else:
                for j, i in enumerate(rows):
                    cell = cells[i]
                    if cell is None:
                        continue
                    keep = min(cell.shape[0], bound)
                    dense[j, :keep] = cell[:keep]
            subcols = {k: (v[rows] if k != name else dense)
                       for k, v in columns.items()}
            subcols[len_name] = lens[rows]
            yield bound, subcols

    def _materialize_encoded(self, columns):
        """Decode deferred image columns the CURRENT pass cannot fuse —
        the staging arena is off, rows are shuffled (the random buffer
        gathers decoded rows), batching is bucketed, or a ``dtypes=``
        policy retargets the column's dtype (the fused fill writes the
        codec's native dtype only). One vectorized ``materialize()`` per
        column (native batch decoders, internal thread pool) — still the
        batched regime, just not fused into the destination; each decline
        is counted in ``petastorm_tpu_fused_decode_fallbacks_total`` so
        the troubleshoot runbook can name the reason."""
        out = None
        for name, column in columns.items():
            if not isinstance(column, EncodedImageColumn):
                continue
            if self._stager is None:
                reason = 'staging-off'
            elif self._shuffle_rows:
                reason = 'shuffled-rows'
            elif self._bucket_field is not None:
                reason = 'bucketed'
            else:
                want = self._dtypes.get(name)
                if want is None or np.dtype(want) == column.dtype:
                    continue  # fusable: the arena fill decodes it
                reason = 'dtype-cast'
            if out is None:
                out = dict(columns)
            with span('decode'):
                out[name] = column.materialize()
            fused.count_fallback(reason)
            self._fused_fallback = reason
        return out if out is not None else columns

    def _retrieve_and_emit(self, buf):
        """Pull one batch from ``buf`` and emit it. With the staging arena
        on and a buffer that can hand out parts (the noop re-batcher),
        the batch travels as a LIST of chunk views — the arena fills its
        slot from the parts directly, skipping the concatenated
        intermediate the plain ``retrieve()`` would allocate."""
        with span('collate'):
            if self._stager is not None and hasattr(buf, 'retrieve_parts'):
                batch = buf.retrieve_parts()
            else:
                batch = buf.retrieve()
        self._emit(batch)

    def _emit(self, host_batch):
        with span('collate'):
            if isinstance(host_batch, list):
                # parts form (arena path only; see _retrieve_and_emit)
                parts = [dict(p) for p in host_batch]
                pulls = [p.pop(_PULL_FIELD, None) for p in parts]
                if pulls[0] is None:
                    pull_col = None
                elif len(pulls) == 1:
                    pull_col = pulls[0]   # common aligned case: no copy
                else:
                    pull_col = np.concatenate(pulls)
                n = sum(len(next(iter(p.values()))) for p in parts)
                host_batch = parts
            else:
                host_batch = dict(host_batch)
                pull_col = host_batch.pop(_PULL_FIELD, None)
                n = len(next(iter(host_batch.values())))
            if n < self._batch_size and self._last_batch == 'drop':
                # dropped rows: their pulls stay incomplete (sound)
                return
            if pull_col is None:
                pull_counts = None
            else:
                ids, counts = np.unique(np.asarray(pull_col),
                                        return_counts=True)
                pull_counts = dict(zip(ids.tolist(), counts.tolist()))
            stager = self._stager
            if stager is None:
                # PETASTORM_TPU_STAGING=0: the pre-arena copy path (pad
                # allocates, _to_device casts) — the reference behavior
                # the arena's round-trip tests compare against
                if n < self._batch_size:
                    if self._last_batch == 'pad':
                        host_batch = self._pad(host_batch, n)
                    # 'short': ship as-is
                elif self._last_batch == 'pad':
                    host_batch[MASK_FIELD] = np.ones(n, dtype=bool)
        if stager is None:
            with span('h2d'):
                device_batch = self._to_device(host_batch)
        else:
            # arena path: cast/pad/mask write into a preallocated slot and
            # the transfer is dispatched async (stage_fill/h2d_dispatch/
            # h2d_ready spans) — the consumer of batch N computes while
            # batch N+1's transfer is in flight
            device_batch = stager.stage(host_batch, n)
        # provenance rides the queue as a sidecar: rows count as delivered
        # only when the consumer actually receives this item in __next__
        self._put_blocking((device_batch, pull_counts))
        if self._autotuner is not None:
            # staging-thread cadence gate: a no-op monotonic compare
            # until the next autotune window is due
            self._autotuner.maybe_tick()

    def _densify_ragged(self, columns):
        """Apply the ``pad_ragged`` policy to one reader chunk: variable
        -shape columns become static-shape dense arrays plus a
        ``<name>_len`` TRUE-size column (a truncated row's stored length
        exceeds the padded extent — that is how truncation stays
        detectable; masks built as ``arange(L) < len`` saturate correctly).

        Runs BEFORE the staging buffer (see ``_stage_loop``): a variable
        field arrives as a 1-d OBJECT array from a ragged row-group but as
        an already-dense ``(n, ...)`` array from a uniform one, and the
        buffers can mix neither the two forms nor two dense widths."""
        out = dict(columns)
        for name, targets in self._pad_ragged.items():
            if name not in out:
                # unconditional (every chunk): readers yield a stable
                # schema, so a field absent mid-stream is a bug upstream —
                # silently skipping would emit batches with inconsistent
                # column sets that fail later with an unrelated error
                raise ValueError(
                    'pad_ragged field %r is not in the batch (available: '
                    '%s); check the name against fields=/the schema'
                    % (name, sorted(n for n in columns
                                    if n != _PULL_FIELD)))
            len_name = self._reserve_len_column(out, name, 'pad_ragged')
            col = out[name]
            k = len(targets)
            n = len(col)
            if n == 0:
                continue
            if col.dtype == object:
                # None cells (nullable fields) densify as all-zero rows
                # with true size 0 — the natural mask value downstream
                cells, first = self._object_cells(col, name, 'pad_ragged')
                trailing = first.shape[k:]
                dense = np.zeros((n,) + targets + trailing, first.dtype)
                lens = np.zeros((n, k), np.int32)
                for i, cell in enumerate(cells):
                    if cell is None:
                        continue  # lens stay 0, dense row stays zeros
                    if cell.ndim != k + len(trailing):
                        raise ValueError(
                            'pad_ragged[%r]: row has %d dims but the policy '
                            'names %d variable dim(s) over trailing shape %r'
                            % (name, cell.ndim, k, trailing))
                    lens[i] = cell.shape[:k]
                    clipped = tuple(min(cell.shape[d], targets[d])
                                    for d in range(k))
                    region = (i,) + tuple(slice(0, c) for c in clipped)
                    dense[region] = cell[tuple(slice(0, c) for c in clipped)]
            else:
                # pre-stacked dense chunk: every row shares one shape, so
                # one vectorized slice assignment replaces the row loop
                if col.ndim < 1 + k:
                    raise ValueError(
                        'pad_ragged[%r]: dense chunk has %d row dims but '
                        'the policy names %d variable dim(s)'
                        % (name, col.ndim - 1, k))
                trailing = col.shape[1 + k:]
                dense = np.zeros((n,) + targets + trailing, col.dtype)
                clipped = tuple(min(col.shape[1 + d], targets[d])
                                for d in range(k))
                region = (slice(None),) + tuple(slice(0, c) for c in clipped)
                dense[region] = col[region]
                lens = np.broadcast_to(
                    np.asarray(col.shape[1:1 + k], np.int32), (n, k)).copy()
            out[name] = dense
            out[len_name] = lens[:, 0] if k == 1 else lens
        return out

    def _pad(self, host_batch, n):
        out = {}
        for name, arr in host_batch.items():
            arr = np.asarray(arr)
            pad_shape = (self._batch_size - n,) + arr.shape[1:]
            out[name] = np.concatenate([arr, np.zeros(pad_shape, arr.dtype)])
        mask = np.zeros(self._batch_size, dtype=bool)
        mask[:n] = True
        out[MASK_FIELD] = mask
        return out

    def _to_device(self, host_batch):
        """Pre-arena staging: validate + cast (allocating) + dispatch."""
        staged = {}
        for name, arr in host_batch.items():
            arr = np.asarray(arr)
            # shared classified diagnosis (ragged vs string vs null); the
            # ragged message names pad_ragged/bucket_boundaries, and
            # fixed-width numpy strings get the same treatment instead of
            # jax's raw 'not a valid JAX array type'
            staging._check_deviceable(name, arr)
            want = self._dtypes.get(name)
            if want is not None:
                arr = arr.astype(want)
            staged[name] = arr
        return self._put_to_device(staged)

    def _put_to_device(self, host_batch):
        """Dispatch one host batch to the device(s) — the transfer leg the
        staging arena and the legacy path share (validation and dtype
        casting already happened upstream)."""
        import jax
        if self._sharding is not None:
            return self._put_sharded(host_batch)
        # one device_put of the whole pytree: a single dispatch covering
        # every field's transfer, instead of one runtime round trip each
        return jax.device_put(host_batch)

    def _put_sharded(self, host_batch):
        """Sharded (mesh) dispatch. With the staging arena live the
        engine already wraps this call in ``h2d_dispatch`` and counts
        ``petastorm_tpu_h2d_bytes_total``; on the legacy path
        (``PETASTORM_TPU_STAGING=0``) the same instrumentation lives
        here — shard-slice bytes (what THIS host puts on the wire, not
        the global batch), so ``h2d_overlap_share`` and the stall
        attributor work on meshes in both modes."""
        if self._stager is not None:
            return self._dispatch_sharded(host_batch)
        from petastorm_tpu.telemetry import get_registry, metrics_disabled
        with span('h2d_dispatch'):
            device_batch = self._dispatch_sharded(host_batch)
        if not metrics_disabled():
            get_registry().counter(staging.H2D_BYTES).inc(
                sum(arr.nbytes for arr in host_batch.values()))
        return device_batch

    def _dispatch_sharded(self, host_batch):
        """One dispatch covering the whole pytree: every field's local
        shard slices ride a single batched ``jax.device_put`` (one
        runtime round trip) and reassemble into global ``jax.Array``s via
        ``make_array_from_single_device_arrays`` — instead of one
        ``make_array_from_process_local_data`` round trip per field.
        Falls back to the per-field build when the row plan cannot be
        proven sound for this sharding (always correct, never fast)."""
        import jax
        if not host_batch:
            return {}
        n_local = len(next(iter(host_batch.values())))
        plan = self._shard_plan_for(n_local)
        if plan is None:
            return {name: jax.make_array_from_process_local_data(
                        self._sharding, arr)
                    for name, arr in host_batch.items()}
        slices = []
        devices = []
        for arr in host_batch.values():
            for device, lo, hi in plan:
                slices.append(arr[lo:hi])
                devices.append(device)
        shards = jax.device_put(slices, devices)
        out = {}
        k = len(plan)
        global_rows = n_local * jax.process_count()
        for i, (name, arr) in enumerate(host_batch.items()):
            out[name] = jax.make_array_from_single_device_arrays(
                (global_rows,) + arr.shape[1:], self._sharding,
                shards[i * k:(i + 1) * k])
        return out

    def _shard_plan_for(self, n_local):
        """Cached per-batch-length row plan (short tails get their own);
        None pins the ``make_array_from_process_local_data`` fallback for
        that length."""
        if n_local in self._shard_plans:
            return self._shard_plans[n_local]
        from petastorm_tpu.parallel.sharding import local_shard_plan
        plan = local_shard_plan(self._sharding, n_local)
        if plan is None:
            logger.debug(
                'sharded staging: no sound row plan for %d local rows on '
                '%r; using the per-field make_array_from_process_local_'
                'data fallback', n_local, self._sharding)
        self._shard_plans[n_local] = plan
        return plan

    def _set_prefetch(self, depth):
        """Autotuner seam: deepen the prefetch queue mid-pass. Writing
        ``queue.Queue.maxsize`` is safe here — the producer retries its
        bounded put every 0.1s (``_put_blocking``), so a raised bound is
        observed on the next attempt without waking any waiter."""
        depth = max(1, int(depth))
        self._prefetch = depth
        if self._out_queue is not None:
            self._out_queue.maxsize = depth
        return depth

    def _put_blocking(self, item):
        start = time.monotonic()
        try:
            while not self._stop_event.is_set():
                try:
                    self._out_queue.put(item, timeout=0.1)
                    return
                except queue.Full:
                    continue
        finally:
            # time the producer spent blocked on a full queue: back-pressure
            # from a consumer that is NOT input-bound
            blocked = time.monotonic() - start
            self._stage_blocked_s += blocked
            if blocked > STALL_NOTE_FLOOR_S:
                self._attributor.note_producer_wait(blocked)
                note_producer_wait(blocked)

    # -- lifecycle -----------------------------------------------------------

    @property
    def schema(self):
        return self._reader.schema

    @property
    def reader(self):
        return self._reader

    @property
    def batch_size(self):
        return self._batch_size

    @property
    def last_batch(self):
        """The last-batch policy: ``'drop'``, ``'pad'`` or ``'short'``."""
        return self._last_batch

    @property
    def shuffle_rows(self):
        return self._shuffle_rows

    @property
    def bucket_field(self):
        """The ``bucket_boundaries`` field name, or None."""
        return self._bucket_field

    @property
    def sharding(self):
        """The resolved :class:`~jax.sharding.NamedSharding` batches are
        staged with, or None without a mesh."""
        return self._sharding

    @property
    def epoch(self):
        """Number of completed replay passes (0 during the first pass)."""
        return self._epoch

    @property
    def diagnostics(self):
        """Reader pool gauges plus the staging layer's own: queue depth,
        delivered-batch count, and the two wait clocks that say WHO is the
        bottleneck — high ``consumer_wait_s`` means the input pipeline is
        too slow (add decode workers / prefetch); high
        ``stage_backpressure_s`` means the training step is (keep prefetch
        small, the input side is not the problem)."""
        diag = dict(self._reader.diagnostics)
        stager = self._stager
        diag.update({
            'stage_queue_depth': (self._out_queue.qsize()
                                  if self._out_queue is not None else 0),
            'stage_leftovers': len(self._leftovers),
            'batches_delivered': self._batches_delivered,
            'consumer_wait_s': round(self._consumer_wait_s, 3),
            'stage_backpressure_s': round(self._stage_blocked_s, 3),
            'pulls_in_flight': len(self._pull_info),
            # staging arena (docs/telemetry.md "Host→device staging"):
            # slot slabs only grow at startup / on a new bucket shape —
            # steady growth here means the arena is not being reused.
            # Between passes the stager of the FINISHED pass is not the
            # truth about the knob, so report the knob itself then.
            'staging_enabled': (stager is not None
                                if self._stage_thread is not None
                                and self._stage_thread.is_alive()
                                else staging.staging_enabled()),
            'staging_slots_allocated': (stager.slabs_allocated
                                        if stager is not None else 0),
            # fused decode (docs/troubleshoot.md "decode is batched but
            # not fused"): where decode ran for this pass's batches
            'fused_decode_mode': self._fused_decode_mode(),
            'fused_decode_rows': (stager.fused_rows
                                  if stager is not None else 0),
            # staging autotuner (jax/autotune.py): live depth settings +
            # how many adjustments this loader has made
            'staging_prefetch': self._prefetch,
            'staging_slot_depth': (stager.num_slots
                                   if stager is not None else 0),
            'staging_autotune': self._autotuner is not None,
            'staging_autotune_decisions': (self._autotuner.decisions
                                           if self._autotuner is not None
                                           else 0),
        })
        if self._fused_fallback is not None:
            diag['fused_decode_fallback'] = self._fused_fallback
        return diag

    def _fused_decode_mode(self):
        """Where image decode ran for this pass: ``'fused-into-slot'``
        (arena slot ring — the zero-extra-copy regime),
        ``'fused-into-slab'`` (host-backed fresh assembly, still one
        decode-to-destination pass), ``'batched'`` (classic worker-side
        or loader-materialized batch decode), or ``'pending'`` before
        the first delivery says which."""
        stager = self._stager
        if stager is not None and stager.fused_rows:
            return stager.fused_mode
        if self._fused_fallback is not None or self._batches_delivered:
            return 'batched'
        return 'pending'

    def pipeline_report(self, wall_time_s=None):
        """Process-wide per-stage breakdown + stall attribution
        (:func:`petastorm_tpu.telemetry.pipeline_report`); includes the
        reader's worker stages for every pool flavor via the pools' metric
        delta channels."""
        from petastorm_tpu.telemetry import pipeline_report
        return pipeline_report(wall_time_s=wall_time_s)

    def dump_trace(self, path):
        """Export the per-item trace (ventilate → worker stages →
        queue_wait → collate/h2d, across every pool flavor) as Chrome
        trace-event JSON; needs ``PETASTORM_TPU_TRACE=1`` during the run
        (docs/telemetry.md). Returns the number of events written."""
        from petastorm_tpu.telemetry import dump_trace
        return dump_trace(path)

    def autotune_report(self):
        """Bottleneck attribution + concrete tuning advice, tf.data-style
        (its AUTOTUNE observes the same signals: who waits on whom).

        Consumes this loader's stall-attribution windows
        (:class:`~petastorm_tpu.telemetry.StallAttributor`): the two wait
        clocks — consumer blocked on the prefetch queue (input-bound
        evidence) vs stage blocked pushing into it (compute-bound
        evidence) — bucketed into sampling windows and classified per
        window. The attributor resets at each pass's first delivered
        batch, so spin-up (reader/decoder startup) is pipeline latency,
        never contention, and the report covers the CURRENT pass's steady
        state only — the right scope for tuning (the current pass reflects
        the current settings) but not a whole-run accumulator. Returns
        ``{'bottleneck': 'input'|'compute'|'balanced'|'undetermined',
        'input_stall_fraction': float, 'window_verdicts': {verdict: n},
        'advice': [str, ...], ...}`` — advisory only; nothing is
        changed."""
        from petastorm_tpu.telemetry import (
            BALANCED, CONSUMER_BOUND, PRODUCER_BOUND,
        )
        from petastorm_tpu.telemetry.stall import classify_window
        # everything below reads ONE source — the attributor's window set
        # (bounded deque, so a very long pass reports its recent ~minutes)
        # — so the fraction, the verdict and the advice can never
        # contradict each other
        windows = self._attributor.windows()
        stage = sum(w['producer_wait_s'] for w in windows)
        consumer = sum(w['consumer_wait_s'] for w in windows)
        total = consumer + stage
        verdict_counts = {}
        for w in windows:
            verdict_counts[w['verdict']] = \
                verdict_counts.get(w['verdict'], 0) + 1
        report = {
            'consumer_wait_s': round(consumer, 3),
            'stage_backpressure_s': round(stage, 3),
            'batches_delivered': self._batches_delivered,
            'window_verdicts': verdict_counts,
        }
        if self._batches_delivered < 4 or total < 0.05:
            report['bottleneck'] = 'undetermined'
            report['input_stall_fraction'] = 0.0
            report['advice'] = ['not enough iteration observed yet; '
                                'consume more batches before tuning']
            # the cache section is observational, not verdict-derived —
            # a short pass still shows whether the decoded tier served
            self._add_decoded_cache_advice(report)
            if self._autotuner is not None:
                report['staging_autotune'] = self._autotuner.summary()
            return report
        frac = consumer / total
        report['input_stall_fraction'] = round(frac, 3)
        # aggregate verdict over the same windows (summed clocks with the
        # attributor's dominance threshold — robust to one noisy window)
        verdict = classify_window(
            stage, consumer, self._attributor.window_s * len(windows))
        if verdict == PRODUCER_BOUND:
            report['bottleneck'] = 'input'
            report['advice'] = [
                'the consumer waits on data %.0f%% of contended time: add '
                'decode workers (workers_count), raise prefetch, move '
                'heavy TransformSpec work off the row path, or switch '
                "GIL-heavy transforms to reader_pool_type='process'"
                % (frac * 100),
            ]
            # tf.data-service-style escalation: when the host's own CPUs
            # are the wall, scale the DECODE FLEET, not this host
            pool_diag = {}
            try:
                pool_diag = dict(self._reader.diagnostics)
            except Exception:  # noqa: BLE001 - custom readers may lack it
                pass
            if 'workers_registered' in pool_diag:
                report['advice'].append(
                    'the remote decode fleet (%d live worker server(s)) is '
                    'the lagging stage: start more worker servers — they '
                    'register with the running dispatcher, no restart '
                    'needed (docs/service.md)'
                    % pool_diag.get('workers_alive', 0))
            else:
                report['advice'].append(
                    'if this host is out of CPU, disaggregate decode to '
                    "remote CPU hosts with reader_pool_type='service' "
                    '(docs/service.md)')
        elif verdict == CONSUMER_BOUND:
            report['bottleneck'] = 'compute'
            report['advice'] = [
                'the training step is the bottleneck (staging blocked '
                '%.0f%% of contended time): the input pipeline is NOT the '
                'problem; keep prefetch small to save host RAM'
                % ((1 - frac) * 100),
            ]
        else:
            assert verdict == BALANCED
            report['bottleneck'] = 'balanced'
            report['advice'] = ['producer and consumer are balanced; '
                                'tune the model step first']
        self._add_decoded_cache_advice(report)
        if self._autotuner is not None:
            # the closed loop's own record: current depths + the recent
            # decision log, so "what changed and why" rides the report
            report['staging_autotune'] = self._autotuner.summary()
        return report

    def _add_decoded_cache_advice(self, report):
        """Cache-aware autotune: when the materialized decoded-row-group
        cache is live, the right advice changes — a cache-bound pass
        makes io/decode tuning pointless, and a warm-epoch pass that is
        NOT cache-bound points at fingerprint churn or an undersized
        tier (docs/troubleshoot.md has the runbook). The section comes
        from the full pipeline_report so the verdict sees the stage
        timings — a bare hit-rate verdict could claim 'cache-bound'
        while the misses' decode time dominates the wall, directly
        contradicting the attributor's 'add decode workers' advice —
        and is baselined at this pass's first delivery so epoch 1's
        fill cost never dilutes a healthy warm pass's hit rate."""
        from petastorm_tpu.telemetry import pipeline_report
        section = pipeline_report(
            baseline=self._pass_baseline).get('decoded_cache')
        if section is None:
            return
        report['decoded_cache'] = section
        advice = report.setdefault('advice', [])
        if section['verdict'] == 'cache-bound':
            advice.append(
                'the decoded row-group cache serves this pass (%.0f%% '
                'hits): epoch 2+ is cache-bound as designed, so decode '
                'workers/io tuning will not help — look at collate/H2D '
                'and the model step' % (100 * section['hit_rate']))
        elif section['hit_rate'] < 0.5 and report.get('bottleneck') == \
                'input':
            advice.append(
                'a decoded cache is configured but only %.0f%% of reads '
                'hit: if this is epoch 2+, check for cache-key churn '
                '(unstable TransformSpec closure, rewritten dataset '
                'files) or an undersized tier evicting the working set '
                "(docs/troubleshoot.md, 'epoch 2 is not cache-bound')"
                % (100 * section['hit_rate']))

    def state_dict(self):
        """Row-group-granular, at-least-once checkpoint of the DATA
        POSITION AS DELIVERED to the consumer.

        Unlike the raw reader's ``state_dict`` (which marks a row-group
        consumed when the staging thread pulls it), this counts a
        row-group only once every one of its rows has left the shuffling
        buffer and prefetch queue and reached ``__next__`` — rows still
        in flight are re-read on resume, never skipped.
        """
        if self._uses_provenance:
            with self._prov_lock:
                delivered = {epoch: set(items) for epoch, items
                             in self._delivered_by_epoch.items()}
            return self._reader.resume_state_from(delivered)
        return self._reader.state_dict()

    def load_state_dict(self, state):
        self._reader.load_state_dict(state)
        # mirror the reader: later checkpoints must account the restored
        # position (earlier epochs complete, resume epoch partly consumed)
        with self._prov_lock:
            self._delivered_by_epoch = \
                self._reader.consumption_record_for_resume(state)

    def _obs_health(self):
        """The loader's /health contribution: who waits on whom, right
        now (the reader mounts its own section with the pool gauges)."""
        return {
            'epoch': self._epoch,
            'exhausted': self._exhausted,
            'batches_delivered': self._batches_delivered,
            'stage_queue_depth': (self._out_queue.qsize()
                                  if self._out_queue is not None else 0),
            'prefetch': self._prefetch,
            'consumer_wait_s': round(self._consumer_wait_s, 3),
            'stage_backpressure_s': round(self._stage_blocked_s, 3),
            'staging_enabled': self._stager is not None,
            'fused_decode_mode': self._fused_decode_mode(),
            # per-host staging view (the "One host starves the mesh"
            # runbook reads these across every host's /health endpoint)
            'h2d_overlap_share': self._h2d_overlap_share(),
            'staging_prefetch': self._prefetch,
            'staging_slot_depth': (self._stager.num_slots
                                   if self._stager is not None else 0),
            'staging_autotune_decisions': (self._autotuner.decisions
                                           if self._autotuner is not None
                                           else 0),
        }

    def _h2d_overlap_share(self):
        """THIS host's live fill/transfer overlap share (None before the
        arena has staged anything) — the per-host member of the mesh-wide
        overlap picture. Computed from the three stage counters directly
        (sharing the report's formula), never by building a whole
        pipeline_report: /health is polled, and must stay cheap."""
        from petastorm_tpu.telemetry import get_registry
        from petastorm_tpu.telemetry.export import _h2d_overlap_share
        from petastorm_tpu.telemetry.registry import metric_key
        from petastorm_tpu.telemetry.spans import STAGE_SECONDS
        counters = get_registry().counters_with_prefix(STAGE_SECONDS)
        stages = {
            stage: {'seconds': counters.get(
                metric_key(STAGE_SECONDS, {'stage': stage}), 0.0)}
            for stage in ('stage_fill', 'h2d_dispatch', 'h2d_ready')}
        return _h2d_overlap_share(stages)

    def _obs_report(self):
        """The loader's /report contribution: the live autotune verdict
        + advice, so "what should I change" is scrapeable mid-run."""
        return {'autotune': self.autotune_report()}

    def stop(self):
        self._obs_mount.close()
        if self._autotuner is not None:
            # drops the in-process decoder-thread override so a stopped
            # loader's learned setting cannot leak into later readers
            self._autotuner.close()
        self._stop_event.set()
        # Stop the reader FIRST: it is what a staging thread blocked in
        # reader.__next__ is actually waiting on; the stop event alone
        # cannot wake it.
        self._reader.stop()
        if self._stage_thread is not None:
            self._stage_thread.join(timeout=10)
        self._reader.join()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        self.stop()


class InMemoryCachedLoader:
    """Epoch replay from device memory: decode once, train many epochs.

    Wraps a single-epoch :class:`JaxLoader`. The first pass streams
    normally while retaining every delivered device batch; subsequent
    passes serve those arrays directly — no Parquet read, no codec decode,
    no host→device transfer — in a per-epoch reshuffled batch order. The
    flagship-loader counterpart of the torch bridge's
    ``BatchedDataLoader(inmemory_cache_all=True)`` (reference:
    ``petastorm/pytorch.py:344-407``), with the cache living in HBM as
    ``jax.Array``s instead of torch tensors.

    Fit check is the caller's job: the whole epoch must fit in device (or
    host, for CPU-backed arrays) memory. Iteration state checkpointing is
    unsupported — replay epochs have no reader position (resume by
    replaying the cached epoch from its start).

    **Replay shuffling.** When the wrapped loader row-shuffles
    (``shuffle_rows=True``), replay epochs re-draw BATCH MEMBERSHIP too:
    the cached epoch's rows are pooled on device (one concatenated array
    per field, built lazily at the first replay) and re-batched under a
    fresh permutation each epoch — matching the reference torch loader's
    behavior of re-feeding cached rows through a fresh shuffling buffer
    (``petastorm/pytorch.py:344-407``), but as device-side gathers instead
    of a host-side buffer. Without ``shuffle_rows`` only the batch ORDER
    is shuffled (row composition is frozen after epoch 1). Row-level
    replay is single-host only: on a multi-process run the cached arrays'
    local shards cannot be re-gathered host-locally, so it degrades to
    batch-order shuffling with a warning.
    """

    def __init__(self, loader, seed=0):
        self._loader = loader
        self._seed = seed
        self._cache = []
        self._row_cache = None     # field -> one concatenated device array
        self._row_count = 0
        self._cache_epoch = None
        self._complete = False
        self._produced_any = False
        self._stopped = False
        self._replay_epoch = 0
        self._steps_iter = None

    # -- iteration -----------------------------------------------------------

    def __iter__(self):
        if self._stopped:
            raise RuntimeError('InMemoryCachedLoader was stopped (its cache '
                               'is released); construct a new loader to '
                               'iterate again')
        if not self._complete:
            return self._first_pass()
        return self._replay()

    def _first_pass(self):
        it = iter(self._loader)
        if self._loader.epoch != self._cache_epoch:
            # the underlying loader began a NEW pass (e.g. the previous
            # first-pass generator was abandoned exactly at the epoch
            # boundary, so its epilogue never ran): batches cached from the
            # stale pass would otherwise duplicate every row
            self._cache = []
            self._cache_epoch = self._loader.epoch
        for batch in it:
            self._cache.append(batch)
            self._produced_any = True
            yield batch
        self._complete = True

    def _replay(self):
        self._replay_epoch += 1
        rng = np.random.RandomState(
            None if self._seed is None
            else (self._seed + self._replay_epoch) % (2 ** 32))
        if self._loader.shuffle_rows and self._row_replay_supported():
            yield from self._replay_rows(rng)
            return
        cache = self._cache
        order = np.arange(len(cache))
        rng.shuffle(order)
        for i in order:
            if self._stopped:
                raise RuntimeError('InMemoryCachedLoader was stopped (its '
                                   'cache is released) while a replay '
                                   'iterator was live')
            yield cache[i]

    def _row_replay_supported(self):
        import jax
        if self._loader.bucket_field is not None:
            # bucketed batches carry per-bucket widths; pooling them into
            # one array per field cannot concatenate. Batch-order replay
            # (shapes preserved per batch) is the sound fallback.
            if not getattr(self, '_warned_bucketed', False):
                logger.warning(
                    'inmemory_cache_all: bucket_boundaries batches have '
                    'per-bucket shapes; replay reshuffles batch order only')
                self._warned_bucketed = True
            return False
        if jax.process_count() == 1:
            return True
        if not getattr(self, '_warned_multiprocess', False):
            logger.warning(
                'inmemory_cache_all: row-level replay shuffling needs the '
                'whole epoch addressable on this host; on a %d-process run '
                'replay reshuffles batch order only',
                jax.process_count())
            self._warned_multiprocess = True
        return False

    def _ensure_row_cache(self):
        """Pool the cached epoch into one device array per field (valid
        rows only), releasing the per-batch cache — the pooled copy
        replaces it, keeping peak HBM at ~one epoch (plus one field's
        pooled copy while it concatenates)."""
        if self._row_cache is not None:
            return
        if not self._cache:
            self._row_cache = {}
            self._row_count = 0
            return
        import jax.numpy as jnp
        names = [n for n in self._cache[0] if n != MASK_FIELD]
        parts = {n: [] for n in names}
        for b in self._cache:
            mask = b.get(MASK_FIELD)
            for n in names:
                arr = b[n]
                if mask is not None:
                    arr = arr[np.asarray(mask)]
                parts[n].append(arr)
        # drop the per-batch refs BEFORE materializing pooled copies
        # (`parts` keeps the arrays alive) and release each field's pieces
        # as its pooled copy lands, so peak HBM stays ~one epoch. Publish
        # to self only on success: a mid-pooling failure (device OOM) must
        # leave the loader observably broken (retry re-raises), not with
        # an empty row cache that silently replays zero batches.
        self._cache = []
        pooled = {}
        try:
            for n in names:
                pooled[n] = jnp.concatenate(parts.pop(n), axis=0)
        except Exception:
            # per-batch refs are gone; poison further replays explicitly
            self._stopped = True
            raise
        self._row_cache = pooled
        self._row_count = int(next(iter(pooled.values())).shape[0])

    def _replay_rows(self, rng):
        import jax
        import jax.numpy as jnp
        self._ensure_row_cache()
        n = self._row_count
        if n == 0:
            return
        bs = self._loader.batch_size
        policy = self._loader.last_batch
        sharding = self._loader.sharding
        # snapshot: stop() nulls _row_cache under a live generator; the
        # per-batch _stopped check below turns that into the intended
        # RuntimeError instead of an AttributeError mid-comprehension
        row_cache = self._row_cache
        perm = rng.permutation(n)
        stop = n - (n % bs) if policy == 'drop' else n
        for start in range(0, stop, bs):
            if self._stopped:
                raise RuntimeError('InMemoryCachedLoader was stopped (its '
                                   'cache is released) while a replay '
                                   'iterator was live')
            idx = jnp.asarray(perm[start:start + bs])
            k = int(idx.shape[0])
            batch = {name: jnp.take(arr, idx, axis=0)
                     for name, arr in row_cache.items()}
            if policy == 'pad':
                if k < bs:
                    batch = {name: jnp.concatenate(
                        [a, jnp.zeros((bs - k,) + a.shape[1:], a.dtype)])
                        for name, a in batch.items()}
                mask = np.zeros(bs, dtype=bool)
                mask[:k] = True
                batch[MASK_FIELD] = jnp.asarray(mask)
            if sharding is not None:
                batch = {name: jax.device_put(a, sharding)
                         for name, a in batch.items()}
            yield batch

    def iter_steps(self, num_steps):
        """Exactly ``num_steps`` batches, continuing across calls and epoch
        boundaries (see :meth:`JaxLoader.iter_steps`)."""
        if self._stopped:
            raise RuntimeError('InMemoryCachedLoader was stopped (its cache '
                               'is released); construct a new loader to '
                               'iterate again')
        it = self._steps_iter
        for _ in range(num_steps):
            while True:
                if it is None:
                    it = iter(self)
                try:
                    yield next(it)
                    break
                except StopIteration:
                    if not self._produced_any:
                        raise RuntimeError(
                            'inmemory_cache_all loader produced no batches; '
                            'the dataset is empty (or every batch was '
                            "dropped by last_batch='drop')") from None
                    it = None
        self._steps_iter = it

    # -- passthrough ---------------------------------------------------------

    @property
    def schema(self):
        return self._loader.schema

    @property
    def reader(self):
        return self._loader.reader

    @property
    def diagnostics(self):
        # the full JaxLoader merge (pool + staging gauges), so the
        # tpu_guide's consumer_wait_s/backpressure advice applies here too
        return self._loader.diagnostics

    def dump_trace(self, path):
        """See :meth:`JaxLoader.dump_trace` (replay epochs add no events —
        they never touch the reader)."""
        return self._loader.dump_trace(path)

    def state_dict(self):
        raise RuntimeError(
            'inmemory_cache_all loaders have no checkpointable reader '
            'position (replay epochs never touch the reader); checkpoint '
            'the train state alone and replay the cached epoch on resume')

    def load_state_dict(self, state):
        raise RuntimeError(
            'inmemory_cache_all loaders have no checkpointable reader '
            'position to restore; replay the cached epoch from its start '
            'instead')

    def stop(self):
        self._stopped = True
        self._loader.stop()
        self._cache = []
        self._row_cache = None
        # a saved iter_steps cursor over the now-released cache must not
        # survive: resuming it would IndexError instead of the intended
        # 'was stopped' RuntimeError above
        self._steps_iter = None

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        self.stop()
