"""Joint training + data-position checkpointing (orbax-backed).

The reference has NO checkpoint/resume story for its readers — an epoch can
only restart from scratch (SURVEY.md §5.4). This framework's readers carry
row-group-granular iteration state (``state_dict``/``load_state_dict``);
this module pairs that state with the model's train state in one atomic,
step-indexed orbax checkpoint, so a preempted TPU pod resumes BOTH
consistently:

* the model resumes from the exact step,
* the loader resumes from the same point in the same epoch with the same
  shuffle seed (at-least-once row-group semantics — in-flight row-groups
  are re-read, none are lost).

Usage::

    ckpt = TrainCheckpointer('/tmp/run1')
    step = ckpt.restore_loader(loader)           # no-op on a fresh run
    state = ckpt.restore_state(state_template)   # or template on fresh run
    for batch in loader.iter_steps(...):
        state, loss = train_step(state, batch)
        step += 1
        if step % 100 == 0:
            ckpt.save(step, state, loader)

On a multi-host pod every process must call ``save`` (orbax coordinates the
write). Each host's reader holds a DIFFERENT row-group shard, so loader
states are allgathered and stored keyed by process index — on restore every
host picks its own entry (orbax's JSON handler alone would persist only the
primary host's state, silently giving every host shard 0's position).

**Elastic resume**: when the pod is RESIZED between save and restore
(N writers, M readers), ``restore_loader`` merges all N shards' progress
via :func:`merge_loader_states` — states carry shard-independent item
identities, so the union re-localizes under the new M-shard layout.
At-least-once throughout: the resume epoch is the earliest any old shard
was still in; nothing is lost, in-flight rows are re-read.
"""

import logging

logger = logging.getLogger(__name__)

_STATE_KEY = 'train_state'
_LOADER_KEY = 'loader_state'


def merge_loader_states(states):
    """Merge per-process loader states into one ELASTIC resume state.

    Used when a checkpoint written by N data-parallel processes is
    restored on M != N (a pod resize): each saved state carries its
    shard's consumed work as shard-independent
    ``(piece_index, drop, drop_count)`` identities (``items_global`` —
    the drop-partition count is part of the identity, see
    ``Reader._items_identity``), so the union re-expresses global
    progress that any new shard layout can re-localize
    (``Reader.load_state_dict`` with ``consumed_global``).

    Semantics stay **at-least-once**: the resume epoch is the EARLIEST
    epoch any old shard was still in (a shard already past it consumed
    its whole item set there); rows in flight anywhere are re-read,
    none are lost. Requires every state to carry ``items_global`` —
    states from before elastic support raise ``ValueError`` (callers
    fall back to fresh-start).
    """
    states = list(states)
    if not states:
        raise ValueError('no loader states to merge')
    if any(not isinstance(s, dict) for s in states):
        # a malformed payload entry (partially written checkpoint) must
        # surface as ValueError so restore_loader's starts-fresh fallback
        # catches it, not as a TypeError that aborts the whole restore
        raise ValueError('malformed loader state entries: %s'
                         % sorted({type(s).__name__ for s in states}))
    if any('items_global' not in s for s in states):
        raise ValueError('loader state(s) predate elastic resume '
                         '(no items_global); cannot merge')
    # integrity: the states must be one complete shard family — every
    # shard exactly once, all agreeing on the count (a duplicated or
    # dropped entry would silently mark the missing shard's rows consumed
    # or double-count another's)
    shard_counts = {s.get('shard_count') for s in states}
    shards = [s.get('cur_shard') for s in states]
    if shard_counts == {None}:
        pass  # unsharded loaders carry no family to validate
    elif None in shard_counts:
        # one legacy/malformed entry must not bypass the family check for
        # the rest: ValueError lands in the starts-fresh fallback
        raise ValueError('loader states mix sharded and unsharded '
                         'entries; cannot merge')
    else:
        if len(shard_counts) != 1:
            raise ValueError('loader states disagree on shard_count: %s'
                             % sorted(shard_counts))
        (count,) = shard_counts
        if any(not isinstance(sh, int) for sh in shards):
            # a missing/null cur_shard must land in the same ValueError
            # starts-fresh fallback as every other malformed payload, not
            # escape as a TypeError from sorting None against ints
            raise ValueError('loader state(s) carry shard_count without '
                             'an integer cur_shard: %s' % shards)
        if sorted(shards) != list(range(count)):
            raise ValueError('loader states are not one complete shard '
                             'family: got shards %s of %s'
                             % (sorted(shards), count))
    # The payload arrives as dict.values() of a JSON object — entry order
    # is arbitrary, so a first-wins seed pick would be nondeterministic.
    # Shards can legitimately disagree (readers built with seed=None draw
    # an independent uint32 per process, workers/ventilator.py:77-79), and
    # at-least-once resume needs no particular seed — any deterministic
    # pick serves; repr-sort handles None mixed with ints.
    seed = sorted({s.get('seed') for s in states}, key=repr)[0]
    epoch = min(s['epoch'] for s in states)
    consumed = set()
    for s in states:
        idents = [tuple(ident) for ident in s['items_global']]
        if s['epoch'] > epoch:
            # this shard finished the resume epoch entirely
            consumed.update(idents)
        else:
            consumed.update(idents[i] for i in s['consumed_items'])
    if any(s['iterations_remaining'] is None for s in states):
        iterations_remaining = None  # infinite epochs
    else:
        # epoch + remaining = total configured epochs on every shard;
        # max() is the conservative (re-read, never lose) choice if the
        # shards ever disagreed
        iterations_remaining = max(
            s['epoch'] + s['iterations_remaining'] for s in states) - epoch
    return {
        'version': 1,
        'seed': seed,
        'epoch': epoch,
        'iterations_remaining': iterations_remaining,
        # JSON-shaped (lists, not tuples): the state may round-trip
        # through orbax's JSON handler before any reader localizes it
        'consumed_global': [list(ident) for ident in sorted(consumed)],
    }


def _gather_per_process(state):
    """``{str(process_index): state}`` with every host's entry present on
    every host (JSON round-trip over a padded uint8 allgather)."""
    import jax
    if jax.process_count() == 1:
        return {'0': state}
    import json

    import numpy as np
    from jax.experimental import multihost_utils

    payload = np.frombuffer(json.dumps(state).encode('utf-8'), np.uint8)
    lengths = np.asarray(
        multihost_utils.process_allgather(
            np.asarray([payload.size], np.int64))).reshape(-1)
    padded = np.zeros(int(lengths.max()), np.uint8)
    padded[:payload.size] = payload
    gathered = np.asarray(multihost_utils.process_allgather(padded))
    return {str(idx): json.loads(bytes(gathered[idx][:int(lengths[idx])])
                                 .decode('utf-8'))
            for idx in range(len(lengths))}


class TrainCheckpointer:
    """Step-indexed checkpoints of (train-state pytree, loader position).

    :param directory: checkpoint root (created if missing). Local paths or
        any orbax-supported store (``gs://...``).
    :param max_to_keep: retained checkpoints (older ones pruned).
    """

    def __init__(self, directory, max_to_keep=3):
        import orbax.checkpoint as ocp
        self._ocp = ocp
        self._manager = ocp.CheckpointManager(
            directory,
            options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep,
                                                 create=True))

    @property
    def latest_step(self):
        """Most recent checkpointed step, or None on a fresh run."""
        return self._manager.latest_step()

    def save(self, step, train_state, loader=None, force=False):
        """Write one checkpoint: the train-state pytree plus (optionally)
        the loader/reader's iteration state.

        ``loader`` may be a JaxLoader, a Reader, or any object with
        ``state_dict()`` — or None to checkpoint the model only.
        """
        ocp = self._ocp
        composite = {_STATE_KEY: ocp.args.StandardSave(train_state)}
        if loader is not None:
            composite[_LOADER_KEY] = ocp.args.JsonSave(
                _gather_per_process(loader.state_dict()))
        saved = self._manager.save(step, args=ocp.args.Composite(**composite),
                                   force=force)
        self._manager.wait_until_finished()
        return saved

    def restore_state(self, train_state_template, step=None):
        """The checkpointed train state (shapes/dtypes/shardings from the
        template), or the template itself on a fresh run."""
        step = self.latest_step if step is None else step
        if step is None:
            return train_state_template
        ocp = self._ocp
        restored = self._manager.restore(
            step, args=ocp.args.Composite(**{
                _STATE_KEY: ocp.args.StandardRestore(train_state_template)}))
        return restored[_STATE_KEY]

    def restore_loader(self, loader, step=None):
        """Reposition ``loader`` to the checkpointed data position (must be
        called before iteration starts). Returns the restored step, or 0 on
        a fresh run (or when the checkpoint carried no loader state)."""
        step = self.latest_step if step is None else step
        if step is None:
            return 0
        ocp = self._ocp
        import jax
        # Probe the checkpoint's item inventory instead of relying on the
        # restore call's exception type (orbax surfaces a missing Composite
        # item differently across versions — KeyError today, but not
        # contractually), so the documented "starts fresh" fallback cannot
        # be broken by an orbax upgrade.
        try:
            items = set(self._manager.item_metadata(step).keys())
            # only trust an inventory that lists the always-present train
            # state: some orbax versions omit items they cannot infer a
            # handler for, and a false "absent" would silently skip a
            # recoverable data-position restore
            has_loader = (_LOADER_KEY in items if _STATE_KEY in items
                          else None)
        except Exception:  # noqa: BLE001 - probe unsupported: try restore
            has_loader = None
        if has_loader is False:
            logger.warning('checkpoint step %s was saved without loader '
                           'state; data position starts fresh', step)
            return step
        try:
            restored = self._manager.restore(
                step, args=ocp.args.Composite(**{
                    _LOADER_KEY: ocp.args.JsonRestore()}))
            payload = restored[_LOADER_KEY]
        except (KeyError, FileNotFoundError, ValueError, TypeError) as e:
            # Orbax does not contract the exception type for a missing
            # composite item (KeyError and FileNotFoundError observed;
            # ValueError/TypeError plausible across versions — ADVICE r2
            # #3), so the model-only-checkpoint fallback covers all of
            # them — but ONLY them: transient I/O failures (OSError,
            # TimeoutError, connection errors) still propagate, because
            # silently converting a retryable storage hiccup into a fresh
            # data position would duplicate training data with no hard
            # failure. When the inventory POSITIVELY said loader state
            # exists, even these types mean corruption — surface them.
            if has_loader:
                raise
            logger.warning('checkpoint step %s has no restorable loader '
                           'state (%s: %s); data position starts fresh',
                           step, type(e).__name__, e)
            return step
        if payload is None:
            # some orbax versions return None for an absent item instead
            # of raising
            logger.warning('checkpoint step %s was saved without loader '
                           'state; data position starts fresh', step)
            return step
        if (isinstance(payload, dict)
                and len(payload) != jax.process_count()):
            # Pod resized between save and restore (N writers, M readers):
            # merge every shard's globally-identified progress and let this
            # reader re-localize it under the NEW shard layout — the
            # elastic path; at-least-once, nothing lost. Pre-elastic
            # checkpoints (no items_global) keep the documented
            # starts-fresh fallback.
            try:
                values = list(payload.values())
                if all(isinstance(s, dict) and 'mixture' in s
                       for s in values):
                    # Mixture checkpoints re-shard at interleave-position
                    # granularity, not row-group granularity: the packed
                    # row ordinal is the unit (docs/mixture.md).
                    from petastorm_tpu.mixture import merge_mixture_states
                    merged = merge_mixture_states(values)
                    position = 'ordinal %s' % merged.get('resume_ordinal')
                else:
                    merged = merge_loader_states(values)
                    position = 'epoch %s' % merged['epoch']
                loader.load_state_dict(merged)
                logger.info(
                    'checkpoint step %s: loader state merged from %d '
                    'processes onto %d (elastic resume, %s)',
                    step, len(payload), jax.process_count(), position)
                return step
            except ValueError as e:
                logger.warning('checkpoint step %s: cannot merge resized '
                               'loader state (%s); data position starts '
                               'fresh', step, e)
                return step
        try:
            loader_state = payload[str(jax.process_index())]
        except (KeyError, TypeError) as e:
            # loader state exists but not for this process index and the
            # count matches (malformed payload): this host's data position
            # legitimately starts fresh
            logger.warning('checkpoint step %s has no loader state for this '
                           'process (%s); data position starts fresh',
                           step, e)
            return step
        loader.load_state_dict(loader_state)
        return step

    def close(self):
        self._manager.close()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        self.close()
