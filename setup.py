"""Packaging (reference: ``petastorm/setup.py``).

Console scripts mirror the reference's three CLIs (``setup.py:91-97``) under
tpu-suffixed names; extras gate the optional consumer stacks.
"""

from setuptools import find_packages, setup

setup(
    name='petastorm-tpu',
    version='0.1.0',
    description='TPU-native Parquet data access library for deep learning',
    packages=find_packages(exclude=('tests', 'tests.*', 'examples',
                                    'examples.*')),
    python_requires='>=3.9',
    install_requires=[
        'numpy',
        'pyarrow>=4.0.0',
        'fsspec',
        'pandas',
        'dill',
        'psutil',
        'pyzmq',
    ],
    extras_require={
        'jax': ['jax', 'flax', 'optax'],
        'tf': ['tensorflow'],
        'torch': ['torch'],
        'opencv': ['opencv-python'],
        'spark': ['pyspark>=3.0.0'],
        'test': ['pytest'],
        # run tests/test_spark_integration.py's integration class too:
        'test-spark': ['pytest', 'pyspark>=3.0.0'],
    },
    entry_points={
        'console_scripts': [
            'petastorm-tpu-throughput = petastorm_tpu.benchmark.cli:main',
            'petastorm-tpu-copy-dataset = petastorm_tpu.tools.copy_dataset:main',
            'petastorm-tpu-generate-metadata = '
            'petastorm_tpu.etl.petastorm_generate_metadata:main',
            'petastorm-tpu-metadata-util = petastorm_tpu.etl.metadata_util:main',
        ],
    },
)
