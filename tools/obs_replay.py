"""Post-mortem rendering of the on-disk observability flight log.

The :mod:`petastorm_tpu.telemetry.obslog` black box appends every closed
rollup window, anomaly, SLO verdict and periodic critical-path digest to
``$PETASTORM_TPU_OBS_LOG_DIR/obslog.jsonl`` while the process runs. This
tool is the read side: point it at that directory AFTER the process is
gone (crashed, OOM-killed, drained) and it reconstructs what the live
``/health`` / ``/report`` endpoints would have shown in the final
minutes:

    python tools/obs_replay.py /var/log/petastorm-obs
    python tools/obs_replay.py /var/log/petastorm-obs --last 50
    python tools/obs_replay.py /var/log/petastorm-obs --json

Three sections:

* **timeline** — one line per window (throughput, stall verdict,
  producer/consumer wait split), with anomaly markers inlined at their
  window position so "what happened right before the crash" reads top to
  bottom;
* **SLO burn report** — per target: windows evaluated/bad, the worst
  short/long burn rates observed, final budget remaining, and every
  breach interval;
* **critical path** — the last recorded digest: bottleneck stage, the
  top what-if projections and the one-line recommendation.

``--json`` emits the folded summary as one JSON document instead (for
scripting / CI artifact upload).
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from petastorm_tpu.telemetry.obslog import read_log  # noqa: E402


def _fmt_ts(ts):
    if ts is None:
        return '--:--:--'
    try:
        return time.strftime('%H:%M:%S', time.localtime(float(ts)))
    except (ValueError, OverflowError):
        return str(ts)


def split_records(records):
    """Bucket raw log lines by record kind (one pass, order kept)."""
    buckets = {'window': [], 'anomaly': [], 'slo': [], 'critpath': []}
    for rec in records:
        buckets.setdefault(rec.get('kind'), []).append(rec)
    return buckets


def fold_slo(slo_records):
    """Per-target burn summary over every SLO verdict line: totals,
    worst burns, final budget, and [start_ts, end_ts] breach spans
    (an open breach at end-of-log gets end_ts None)."""
    targets = {}
    for rec in slo_records:
        ts = rec.get('ts')
        for verdict in rec.get('targets') or []:
            name = verdict.get('target')
            if name is None:
                continue
            agg = targets.setdefault(name, {
                'target': name,
                'op': verdict.get('op'),
                'threshold': verdict.get('threshold'),
                'windows_evaluated': 0,
                'windows_bad': 0,
                'worst_short_burn': 0.0,
                'worst_long_burn': 0.0,
                'final_budget_remaining': None,
                'last_value': None,
                'breaches': [],
                '_breaching': False,
            })
            agg['windows_evaluated'] += 1
            if verdict.get('bad'):
                agg['windows_bad'] += 1
            agg['worst_short_burn'] = max(agg['worst_short_burn'],
                                          verdict.get('short_burn') or 0.0)
            agg['worst_long_burn'] = max(agg['worst_long_burn'],
                                         verdict.get('long_burn') or 0.0)
            agg['final_budget_remaining'] = verdict.get('budget_remaining')
            agg['last_value'] = verdict.get('value')
            breaching = bool(verdict.get('breaching'))
            if breaching and not agg['_breaching']:
                agg['breaches'].append([ts, None])
            elif not breaching and agg['_breaching']:
                agg['breaches'][-1][1] = ts
            agg['_breaching'] = breaching
    for agg in targets.values():
        agg['breaching_at_end'] = agg.pop('_breaching')
    return list(targets.values())


def fold_summary(records):
    """The whole post-mortem as one JSON-safe document."""
    buckets = split_records(records)
    windows = buckets['window']
    summary = {
        'records': len(records),
        'windows': len(windows),
        'anomalies': len(buckets['anomaly']),
        'anomaly_kinds': {},
        'slo': fold_slo(buckets['slo']),
        'critical_path': buckets['critpath'][-1] if buckets['critpath']
        else None,
    }
    for rec in buckets['anomaly']:
        kind = rec.get('anomaly') or '?'
        summary['anomaly_kinds'][kind] = (
            summary['anomaly_kinds'].get(kind, 0) + 1)
    if windows:
        first, last = windows[0], windows[-1]
        summary['span'] = {
            'first_window_ts': first.get('start'),
            'last_window_ts': last.get('start'),
            'last_throughput': last.get('throughput'),
            'last_verdict': last.get('verdict'),
        }
    return summary


def render_timeline(buckets, last=None, out=print):
    windows = buckets['window']
    if last:
        windows = windows[-last:]
    if not windows:
        out('timeline: no window records')
        return
    # anomalies are inlined after the latest window that precedes them
    anomalies = sorted(buckets['anomaly'],
                       key=lambda r: r.get('ts') or 0.0)
    ai = 0
    out('timeline (%d window(s)%s):' %
        (len(windows), ', last %d shown' % last if last else ''))
    for win in windows:
        start = win.get('start')
        out('  %s  %8.1f rows/s  %-14s  p-wait %.2fs  c-wait %.2fs' % (
            _fmt_ts(start),
            win.get('throughput') or 0.0,
            win.get('verdict') or '-',
            win.get('producer_wait_s') or 0.0,
            win.get('consumer_wait_s') or 0.0))
        horizon = (start or 0.0) + (win.get('dur_s') or 0.0)
        while ai < len(anomalies) and (anomalies[ai].get('ts')
                                       or 0.0) <= horizon:
            rec = anomalies[ai]
            out('  %s  !! %s %s' % (_fmt_ts(rec.get('ts')),
                                    rec.get('anomaly') or '?',
                                    json.dumps(rec.get('detail') or {},
                                               sort_keys=True)))
            ai += 1
    for rec in anomalies[ai:]:
        out('  %s  !! %s %s' % (_fmt_ts(rec.get('ts')),
                                rec.get('anomaly') or '?',
                                json.dumps(rec.get('detail') or {},
                                           sort_keys=True)))


def render_burn_report(slo_summary, out=print):
    if not slo_summary:
        out('slo: no verdict records (PETASTORM_TPU_SLO not set?)')
        return
    out('slo burn report:')
    for agg in slo_summary:
        out('  %s %s %g: %d/%d window(s) bad, worst burn short %.1fx '
            'long %.1fx, budget %.0f%% left%s' % (
                agg['target'], agg['op'], agg['threshold'],
                agg['windows_bad'], agg['windows_evaluated'],
                agg['worst_short_burn'], agg['worst_long_burn'],
                100.0 * (agg['final_budget_remaining'] or 0.0),
                ' — BREACHING at end of log'
                if agg['breaching_at_end'] else ''))
        for start, end in agg['breaches']:
            out('    breach %s -> %s' % (_fmt_ts(start),
                                         _fmt_ts(end) if end is not None
                                         else 'end of log'))


def render_critpath(digest, out=print):
    if digest is None:
        out('critical path: no digest recorded (trace off, or the run '
            'ended before the first periodic digest)')
        return
    out('critical path (last digest, %s):' % _fmt_ts(digest.get('ts')))
    out('  bottleneck %s over %d event(s), span %.2fs' % (
        digest.get('bottleneck'), digest.get('events') or 0,
        digest.get('span_s') or 0.0))
    for scenario in (digest.get('what_if') or [])[:3]:
        out('  what-if %s => epoch %+.1f%% (saves %.2fs)' % (
            scenario.get('scenario'), scenario.get('epoch_delta_pct')
            or 0.0, scenario.get('saving_s') or 0.0))
    if digest.get('recommendation'):
        out('  recommendation: %s' % digest['recommendation'])


def main(argv=None):
    parser = argparse.ArgumentParser(
        description='Render the petastorm_tpu observability flight log '
                    '(PETASTORM_TPU_OBS_LOG_DIR) as a post-mortem.')
    parser.add_argument('log_dir',
                        help='directory holding obslog.jsonl[.1]')
    parser.add_argument('--last', type=int, default=None,
                        help='only the last N windows in the timeline')
    parser.add_argument('--json', action='store_true',
                        help='emit the folded summary as one JSON doc')
    args = parser.parse_args(argv)
    records = read_log(args.log_dir)
    if not records:
        print('no records under %s (is PETASTORM_TPU_OBS_LOG_DIR '
              'pointing here?)' % args.log_dir)
        return 1
    summary = fold_summary(records)
    if args.json:
        print(json.dumps(summary, sort_keys=True, default=str))
        return 0
    buckets = split_records(records)
    print('flight log: %d record(s) (%d windows, %d anomalies, %d slo '
          'verdicts, %d critpath digests)' % (
              len(records), summary['windows'], summary['anomalies'],
              len(buckets['slo']), len(buckets['critpath'])))
    print()
    render_timeline(buckets, last=args.last)
    print()
    render_burn_report(summary['slo'])
    print()
    render_critpath(summary['critical_path'])
    return 0


if __name__ == '__main__':
    sys.exit(main())
