"""Fold every BENCH_r*.json and MULTICHIP_r*.json round into one trend.

The driver stores each benchmark round as ``BENCH_r0N.json`` — a wrapper
``{"n": N, "rc": ..., "tail": "<last stdout chars>"}`` whose tail ends
with bench.py's compact headline JSON line (the cumulative line may be
clipped by the tail window; the headline line is emitted last and sized
to always fit — see bench.py). Rounds were not previously
self-describing as a SEQUENCE: answering "did the hello-world rate
regress between r03 and r05" meant hand-parsing five tails. This tool is
the fold:

    python tools/bench_trend.py            # table + one trend JSON line
    python tools/bench_trend.py --fail-on-regression   # CI gate shape

``MULTICHIP_r0N.json`` (the 8-virtual-device dryrun scoreboard) folds
into the same per-round table: the dryrun's trailing ``MULTICHIP_METRICS
{...}`` JSON line carries its self-counted checkpoint total and the
sharded-staging numbers (keys land as ``multichip_<key>``); legacy
rounds without the line fall back to counting ``dryrun_multichip:``
lines in the tail (an undercount when the tail clipped — which only
lowers the bar, never fails it). Before this fold the mesh scoreboard
had no regression gate at all.

A **regression** is flagged when a tracked higher-is-better metric's
latest value falls below ``--threshold`` (default 0.9) x the best value
any earlier round recorded. Missing values (skipped sections, wedged
chips) are shown as ``-`` and never flagged — absence of evidence is not
a regression.
"""

import argparse
import glob
import json
import os
import re
import sys

#: higher-is-better metrics tracked for the regression flag, in display
#: order. ``value`` is the headline metric itself (hello-world rows/sec).
TRACKED = (
    'value',
    'vs_tfdata',
    'hello_world_warm_epoch_rows_per_sec',
    'cache_hit_share',
    'selective_read_1pct_rows_per_sec',
    # wire-speed I/O plane (bench io_overlap section)
    'io_overlap_speedup',
    'io_overlap_readahead_rows_per_sec',
    # streaming mixture engine (bench mixture_stream section)
    'mixture_packed_tokens_per_sec',
    'mixture_fill_ratio',
    # distributed write plane (bench write_throughput section)
    'write_rows_per_sec',
    'write_compact_read_speedup',
    # critical-path engine (bench critpath section): the analysis'
    # share of a traced epoch — LOWER is better, so the Makefile gate
    # carries a standing --allow and the column is display-only (a
    # genuine overhead blow-up is caught by the perf-marked test's <2%
    # budget, not this trend)
    'critpath_overhead_share',
    'native_decode_speedup',
    'imagenet_batch_rows_per_sec',
    'imagenet_jax_rows_per_sec',
    'imagenet_jax_h2d_overlap_share',
    'vit_train_steps_per_sec',
    'vit_train_mfu',
    'lm_train_steps_per_sec',
    'lm_train_mfu',
    'lm_train_tuned_mfu',
    'lm_decode_decode_tokens_per_sec',
    'lm_decode_gqa_decode_speedup',
    # sharded staging (bench sharded_staging section)
    'sharded_staging_gb_per_sec',
    'sharded_staging_h2d_efficiency',
    # standing-service HA (bench service section): warm-placement share
    # (the blackout is lower-is-better and stays out of this gate)
    'service_placement_hit_share',
    # fleet cache tier (bench peer_cache section): share of warm-epoch
    # row-groups served without a fresh decode (local hit or peer fetch)
    'peer_hit_share',
    # the mesh scoreboard (MULTICHIP_r*.json dryrun rounds)
    'multichip_checks',
    'multichip_sharded_overlap_share',
    'multichip_sharded_h2d_mb_per_sec',
)

_ROUND_RE = re.compile(r'BENCH_r(\d+)\.json$')
_MULTICHIP_RE = re.compile(r'MULTICHIP_r(\d+)\.json$')
_MULTICHIP_METRICS_PREFIX = 'MULTICHIP_METRICS '


def parse_round(path):
    """``(round_number, headline_dict)`` from one BENCH_r*.json wrapper,
    or None when no parseable headline line survives in the tail."""
    match = _ROUND_RE.search(os.path.basename(path))
    if not match:
        return None
    with open(path) as f:
        record = json.load(f)
    number = int(record.get('n', match.group(1)))
    tail = record.get('tail', '')
    headline = None
    for line in tail.splitlines():
        line = line.strip()
        if not (line.startswith('{') and line.endswith('}')):
            continue
        try:
            parsed = json.loads(line)
        except ValueError:
            continue
        if isinstance(parsed, dict) and 'value' in parsed:
            headline = parsed  # keep the LAST parseable headline line
    if headline is None:
        return None
    return number, headline


def parse_multichip_round(path):
    """``(round_number, metrics_dict)`` from one MULTICHIP_r*.json
    wrapper (keys prefixed ``multichip_``), or None when the round
    carries nothing foldable. Prefers the dryrun's self-counted
    ``MULTICHIP_METRICS`` JSON line (emitted LAST, so it survives tail
    clipping); legacy rounds fall back to counting the checkpoint lines
    still visible in the tail."""
    match = _MULTICHIP_RE.search(os.path.basename(path))
    if not match:
        return None
    with open(path) as f:
        record = json.load(f)
    number = int(record.get('n', match.group(1)))
    tail = record.get('tail', '')
    metrics = None
    for line in tail.splitlines():
        line = line.strip()
        if not line.startswith(_MULTICHIP_METRICS_PREFIX):
            continue
        try:
            parsed = json.loads(line[len(_MULTICHIP_METRICS_PREFIX):])
        except ValueError:
            continue
        if isinstance(parsed, dict):
            metrics = parsed  # keep the LAST parseable metrics line
    if metrics is None:
        if not record.get('ok'):
            return None
        checks = tail.count('dryrun_multichip:')
        if not checks:
            return None
        metrics = {'checks': checks}
    return number, {'multichip_' + key: value
                    for key, value in metrics.items()}


def load_rounds(directory):
    """Every parseable round in ``directory``, oldest first:
    ``[(n, headline), ...]`` — BENCH headlines with the same-numbered
    MULTICHIP round's metrics merged into ``extra`` (a MULTICHIP-only
    round gets a value-less headline, so the mesh scoreboard is gated
    even when a bench round was lost). Unparseable wrappers (clipped
    tails of the rounds lost to the old single-line format) are skipped,
    not fatal — the trend is built from whatever rounds survive."""
    by_round = {}
    for path in sorted(glob.glob(os.path.join(directory, 'BENCH_r*.json'))):
        try:
            parsed = parse_round(path)
        except (OSError, ValueError):
            parsed = None
        if parsed is not None:
            by_round[parsed[0]] = parsed[1]
    for path in sorted(glob.glob(os.path.join(directory,
                                              'MULTICHIP_r*.json'))):
        try:
            parsed = parse_multichip_round(path)
        except (OSError, ValueError):
            parsed = None
        if parsed is None:
            continue
        number, metrics = parsed
        headline = by_round.setdefault(number, {'value': None, 'extra': {}})
        headline.setdefault('extra', {}).update(metrics)
    return sorted(by_round.items())


def metric_value(headline, key):
    if key == 'value':
        value = headline.get('value')
    else:
        value = (headline.get('extra') or {}).get(key)
    return value if isinstance(value, (int, float)) \
        and not isinstance(value, bool) else None


def trend(rounds, threshold=0.9):
    """The fold: per-metric series across rounds plus regression flags.

    Returns ``{'rounds': [n, ...], 'metrics': {key: {'series': [...],
    'latest': x, 'best': y, 'regressed': bool}}, 'regressions': [key,
    ...]}``. A metric regresses when its LATEST recorded value is below
    ``threshold`` x the best of the EARLIER rounds (so a new all-time
    best can never flag, and a metric first measured this round has no
    baseline to regress from).
    """
    numbers = [n for n, _ in rounds]
    metrics = {}
    regressions = []
    for key in TRACKED:
        series = [metric_value(headline, key) for _, headline in rounds]
        present = [(i, v) for i, v in enumerate(series) if v is not None]
        if not present:
            continue
        latest_i, latest = present[-1]
        earlier = [v for i, v in present if i < latest_i]
        best_earlier = max(earlier) if earlier else None
        # only the LATEST round's own measurement can flag: a metric the
        # recent rounds stopped recording (skipped section, wedged chip)
        # must not fail CI forever on stale data
        regressed = (latest_i == len(series) - 1
                     and best_earlier is not None
                     and latest < threshold * best_earlier)
        metrics[key] = {
            'series': series,
            'latest': latest,
            'best': max(v for _, v in present),
            'regressed': regressed,
        }
        if regressed:
            regressions.append(key)
    return {'rounds': numbers, 'metrics': metrics,
            'threshold': threshold, 'regressions': regressions}


def format_table(report):
    """Human rendering: one metric per row, one column per round, the
    regression flag trailing."""
    numbers = report['rounds']
    header = ['metric'.ljust(38)] + ['r%02d' % n for n in numbers] \
        + ['flag']
    lines = ['  '.join(h.rjust(10) if i else h
                       for i, h in enumerate(header))]
    for key, info in report['metrics'].items():
        cells = [key.ljust(38)]
        for value in info['series']:
            cells.append(('%.4g' % value if value is not None
                          else '-').rjust(10))
        cells.append('REGRESSED' if info['regressed'] else 'ok')
        lines.append('  '.join(cells))
    if report['regressions']:
        lines.append('regressions (latest < %.0f%% of best earlier '
                     'round): %s' % (100 * report['threshold'],
                                     ', '.join(report['regressions'])))
    else:
        lines.append('no regressions at the %.0f%% threshold'
                     % (100 * report['threshold']))
    return '\n'.join(lines)


def main(argv=None):
    parser = argparse.ArgumentParser(
        description='Fold BENCH_r*.json rounds into a perf trend table '
                    'with a regression flag')
    parser.add_argument('--dir', default=os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))),
        help='directory holding the BENCH_r*.json round records '
             '(default: the repo root)')
    parser.add_argument('--threshold', type=float, default=0.9,
                        help='regression threshold: latest < threshold x '
                             'best earlier round (default 0.9)')
    parser.add_argument('--json', action='store_true',
                        help='print only the machine-readable trend line')
    parser.add_argument('--fail-on-regression', action='store_true',
                        help='exit 1 when any tracked metric regressed')
    parser.add_argument('--allow', action='append', default=[],
                        metavar='METRIC',
                        help='accept a known regression of METRIC '
                             '(repeatable): it stays flagged in the '
                             'report but does not fail the gate — the '
                             'strict-on-new-code shape pipecheck\'s '
                             '--baseline uses. Each allowance should '
                             'carry a written justification at the '
                             'call site (see the Makefile trend target)')
    args = parser.parse_args(argv)

    rounds = load_rounds(args.dir)
    if not rounds:
        print('no parseable BENCH_r*.json rounds under %s' % args.dir)
        return 2
    report = trend(rounds, threshold=args.threshold)
    unknown = sorted(set(args.allow) - set(TRACKED))
    if unknown:
        # an allowance for a metric that does not exist silently waives
        # nothing today and the WRONG thing after a rename — fail loud
        print('unknown --allow metric(s): %s' % ', '.join(unknown))
        return 2
    blocking = [k for k in report['regressions'] if k not in args.allow]
    report['allowed_regressions'] = sorted(
        set(report['regressions']) & set(args.allow))
    if args.json:
        print(json.dumps(report, sort_keys=True))
    else:
        print(format_table(report))
        print(json.dumps(report, sort_keys=True))
    if args.fail_on_regression and blocking:
        return 1
    return 0


if __name__ == '__main__':
    sys.exit(main())
