"""Round benchmark: row, batch, image-pipeline and JAX H2D read rates.

Primary metric replicates the reference's only published absolute number —
the ``petastorm-throughput.py`` hello-world read rate of 709.84 samples/sec
with 3 thread workers (``docs/benchmarks_tutorial.rst:20-21``) — against this
framework's row-at-a-time reader on an equivalent dataset.

``extra`` carries the flagship-path numbers the row metric cannot see
(VERDICT r1 #4): the batched column reader, a jpeg-heavy 224x224x3
imagenet-style pipeline (rows/sec and decoded MB/s, native C decoders on vs
off), and the host→device-staged JAX path (rows/sec into device HBM + H2D
GB/s with uint8-vs-f32 staging accounting).

A like-for-like run of the reference reader on this machine is not possible:
its read stack needs long-removed pyarrow APIs (``pyarrow.filesystem``,
``pyarrow.hdfs``, the legacy ``ParquetDataset`` pieces API) that pyarrow 25
no longer ships, so ``vs_baseline`` compares against its published number.

Wedge-proofing (VERDICT r3 #1 — round 3 lost its whole perf record to an
outer-timeout kill, rc=124):

* The cumulative result JSON is printed (flushed) after EVERY section, so a
  kill at any point still leaves the driver a parseable last line carrying
  every section that finished. The final line is the complete report.
* The TPU is probed ONCE up front in a guarded subprocess; if the probe
  fails (wedged chip/tunnel) all remaining device sections run on the CPU
  backend immediately — marked ``tpu_unavailable`` — instead of each
  burning its own subprocess timeout against a dead link. A MID-RUN wedge
  likewise pins the rest of the run to CPU. ``lm_train`` (the MFU /
  input-bound-util capture, the most valuable device number) runs
  immediately after the probe so it sees the freshest possible link.
* A global wall-clock budget (``BENCH_BUDGET_SECONDS``, default 1100s —
  chosen to undercut any plausible driver timeout) clamps every section's
  subprocess timeout to the remaining budget and skips sections that no
  longer fit, recording them under ``skipped_sections``.
* ``BENCH_SMOKE=1`` shrinks every dataset/sample count so the whole
  benchmark finishes in well under a minute on CPU — used by
  ``tests/test_bench_wedgeproof.py`` to assert the contract above under a
  poisoned platform.

Reference contract matched: one-shot metrics report, the reference's
``benchmark/throughput.py:112`` (single process prints a final report).
"""

import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

BASELINE_SAMPLES_PER_SEC = 709.84  # reference: docs/benchmarks_tutorial.rst:20

SMOKE = os.environ.get('BENCH_SMOKE') == '1'

WARMUP_SAMPLES = 50 if SMOKE else 300
MEASURE_SAMPLES = 300 if SMOKE else 3000
HELLO_ROWS = 300 if SMOKE else 1000

IMAGENET_ROWS = 96 if SMOKE else 384
IMAGENET_SHAPE = (224, 224, 3)
# 5 runs for the cheap in-process rates (hello row, imagenet batch):
# single runs on this shared box swing ±10%, and a median-of-5 is
# decisively tighter for a few seconds more wall. The tf.data side pays a
# fresh TF subprocess (import + runtime startup) per run, so it stays at
# median-of-3 — medians of unequal sample counts are still unbiased on
# both sides of the ratio, and the ~2 extra TF startups (up to minutes on
# a loaded box) are exactly the budget the late device sections need.
MEDIAN_RUNS = 1 if SMOKE else 5
TFDATA_RUNS = 1 if SMOKE else 3

C4_DOCS = 256 if SMOKE else 2048

# selective_read section (ISSUE 12): enough row-groups that 1%
# selectivity leaves most of them provably empty, with a decode cost per
# row (96² jpeg) that makes decode-everything-then-filter measurably
# full-scan-priced
SELECTIVE_ROWS = 512 if SMOKE else 4096
SELECTIVE_SHAPE = (128, 128, 3)
SELECTIVE_ROWGROUP_ROWS = 32

# io_overlap section (ISSUE 15): cheap-to-decode rows over many
# row-groups behind an injected-latency filesystem, so the wall is
# storage round trips — exactly what the readahead plane overlaps. The
# delay models a remote/cold object store's per-request latency.
IO_OVERLAP_ROWS = 768 if SMOKE else 4096
IO_OVERLAP_ROWGROUP_ROWS = 32
IO_OVERLAP_READ_DELAY_S = 0.004 if SMOKE else 0.005

# streaming mixture engine (mixture_stream section): two token corpora
# mixed 3:1 and packed to fixed rows; sized so a full pass exercises the
# readahead plane across many row-groups without dominating the budget
MIXTURE_DOCS_A = 384 if SMOKE else 3072
MIXTURE_DOCS_B = 128 if SMOKE else 1024
MIXTURE_SEQ_LEN = 512

# distributed write plane (write_throughput section, ISSUE 18): enough
# rows that encode+flush dominates pool/commit fixed costs, sharded so
# both backends exercise multi-shard dispatch; the compaction probe
# stacks small appended generations so the before/after read contrast
# is file-count-driven, not noise
WRITE_BENCH_ROWS = 4000 if SMOKE else 20000
WRITE_BENCH_SHARD_ROWS = 500 if SMOKE else 2500
WRITE_COMPACT_GENS = 6
WRITE_COMPACT_GEN_ROWS = 250 if SMOKE else 1000

# ONE owner of the staged-batch size shared by the real imagenet H2D
# section and its dummy-source decomposition (the share math divides by
# it — two hardcoded 64s would drift apart silently)
IMAGENET_JAX_BATCH = 64

# bf16 peak of each TPU generation (the MFU denominator), interpolated
# into every snippet that reports MFU so the table cannot fork
TPU_PEAKS = (('v5 lite', 197e12), ('v5e', 197e12), ('v5p', 459e12),
             ('v6 lite', 918e12), ('v6e', 918e12), ('v4', 275e12),
             ('v3', 123e12), ('v2', 45e12))

# The ONE flagship LM shape (~335M params), interpolated into BOTH the
# lm_train and lm_decode subprocess snippets so the decode benchmark can
# never silently measure a different model than the training one.
FLAGSHIP_LM_KW = dict(vocab_size=16384, d_model=1536, n_heads=16,
                      n_layers=10, d_ff=6144)

BUDGET_SECONDS = float(os.environ.get('BENCH_BUDGET_SECONDS',
                                      '240' if SMOKE else '1100'))
_START = time.monotonic()

# The driver records only a 2,000-char stdout TAIL and parses the LAST
# line (rounds 3 and 4 both lost their machine-parsed perf record — r03
# to an outer-timeout kill, r04 because the cumulative line outgrew the
# tail). So every emit prints TWO lines: the full cumulative dict for
# humans, then a compact headline-only line that is always last and
# asserted under _HEADLINE_MAX_CHARS. Ordered by importance: if the line
# ever approaches the cap, the least important tail keys drop first.
# raised 1500 → 1600 for the selective_read headline key, → 1700 for
# the two sharded_staging keys, → 1800 for the two service HA keys,
# → 1900 for the two mixture_stream keys (worst case ~1845), → 1950
# for the write_throughput headline key, → 1980 for the two critpath
# keys, → 2000 for the peer_hit_share key (worst case 1999; +newline
# is exactly the 2,000-char driver tail, so the key list is now FULL —
# the next key must drop or shorten one)
# — the emit loop still drops tail keys at the cap
_HEADLINE_MAX_CHARS = 2000
_HEADLINE_EXTRA_KEYS = (
    'vs_tfdata',
    'hello_world_warm_epoch_rows_per_sec',
    'cache_hit_share',
    # query-shaped reads: effective scan rate at 1% selectivity (the
    # speedups, other selectivities and pruning attribution stay in the
    # full cumulative dict)
    'selective_read_1pct_rows_per_sec',
    # wire-speed I/O plane: cold-read speedup readahead-on vs the
    # blocking oracle under injected storage latency (rates, hit share
    # and coalesced-size attribution stay in the full cumulative dict)
    'io_overlap_speedup',
    # streaming mixture engine (bench mixture_stream section): packed
    # throughput and fill ratio; deviation, hit share and the oracle
    # rate stay in the full cumulative dict
    'mixture_packed_tokens_per_sec',
    'mixture_fill_ratio',
    # distributed write plane (bench write_throughput section): local
    # backend commit-to-commit write rate; MB/s, the fleet backend and
    # the compaction read delta stay in the full cumulative dict
    'write_rows_per_sec',
    # critical-path engine (bench critpath section): the sweep-line
    # analysis' share of a traced epoch (budget <2%) and its best
    # what-if projection; bottleneck and event count stay in the full
    # cumulative dict
    'critpath_overhead_share',
    'critpath_top_whatif',
    # standing-service HA (bench service section): kill-to-first-row
    # blackout through a warm-standby promotion, and the share of
    # bindings that landed on a fingerprint-warm host
    'service_failover_blackout_s',
    'service_placement_hit_share',
    # fleet cache tier (bench peer_cache section): share of warm-epoch
    # row-groups a two-worker fleet served WITHOUT a fresh decode
    # (local disk hit or peer fetch); decode counts and the warm
    # speedup stay in the full cumulative dict
    'peer_hit_share',
    'lm_train_mfu',
    'lm_train_input_bound_util',
    'lm_train_tuned_mfu',
    'lm_decode_decode_tokens_per_sec',
    'lm_decode_gqa_decode_speedup',
    'native_decode_speedup',
    # the granularity ladder's other rungs (fused_decode_batched_* etc.)
    # stay in the full cumulative dict; only the MODE is headline-worthy
    # (it says whether the imagenet numbers rode the fused path at all)
    'fused_decode_mode',
    'imagenet_batch_rows_per_sec',
    'imagenet_jax_rows_per_sec',
    'jax_framework_share',
    'h2d_link_degraded',
    'imagenet_jax_h2d_efficiency',
    'imagenet_jax_h2d_overlap_share',
    # shard-aware staging engine (mesh-wide slot rings + autotuner): the
    # decision log, per-host overlap rows and raw GB/s stay in the full
    # cumulative dict
    'sharded_staging_h2d_efficiency',
    'sharded_staging_gb_per_sec',
    'vit_train_steps_per_sec',
    'vit_train_mfu',
    'lm_train_steps_per_sec',
    'hello_world_rss_mb',
    'hello_world_cpu_percent',
    'probe_platform',
    'skipped_sections',
    'bench_elapsed_sec',
)


def _remaining():
    return BUDGET_SECONDS - (time.monotonic() - _START)


def _clamp_timeout(default):
    """A subprocess timeout that can never outlive the global budget."""
    return max(15, min(default, _remaining() - 10))


def _hello_world_schema():
    import numpy as np
    import pyarrow as pa

    from petastorm_tpu.codecs import (
        CompressedImageCodec, NdarrayCodec, ScalarCodec,
    )
    from petastorm_tpu.unischema import Unischema, UnischemaField

    return Unischema('HelloWorldSchema', [
        UnischemaField('id', np.int32, (), ScalarCodec(pa.int32()), False),
        UnischemaField('array_4d', np.uint8, (128,), NdarrayCodec(), False),
        UnischemaField('image1', np.uint8, (32, 32, 3),
                       CompressedImageCodec('png'), False),
    ])


def _build_hello_world(url):
    import numpy as np

    from petastorm_tpu.etl.dataset_metadata import write_dataset

    rng = np.random.RandomState(42)
    rows = [{
        'id': i,
        'array_4d': rng.randint(0, 255, (128,), dtype=np.uint8),
        'image1': rng.randint(0, 255, (32, 32, 3), dtype=np.uint8),
    } for i in range(HELLO_ROWS)]
    write_dataset(url, _hello_world_schema(), rows,
                  rowgroup_size_rows=100, num_files=4)


def _build_imagenet_like(url):
    """224x224x3 jpeg rows: the BASELINE.json north-star shape."""
    import cv2
    import numpy as np
    import pyarrow as pa

    from petastorm_tpu.codecs import CompressedImageCodec, ScalarCodec
    from petastorm_tpu.etl.dataset_metadata import write_dataset
    from petastorm_tpu.unischema import Unischema, UnischemaField

    schema = Unischema('ImagenetLikeSchema', [
        UnischemaField('noun_id', np.str_, (), ScalarCodec(pa.string()), False),
        UnischemaField('image', np.uint8, IMAGENET_SHAPE,
                       CompressedImageCodec('jpeg', quality=90), False),
    ])
    rng = np.random.RandomState(7)

    def _smooth():
        # low-frequency content so jpeg sizes resemble natural images
        base = (rng.rand(8, 8, 3) * 180).astype(np.uint8)
        return cv2.resize(base, (224, 224),
                          interpolation=cv2.INTER_CUBIC).astype(np.float64)

    smooth = _smooth()
    rows = []
    for i in range(IMAGENET_ROWS):
        noise = rng.rand(*IMAGENET_SHAPE) * 60
        rows.append({'noun_id': 'n%08d' % i,
                     'image': np.clip(smooth + noise, 0, 255).astype(np.uint8)})
        if i % 64 == 63:
            smooth = _smooth()
    write_dataset(url, schema, rows, rowgroup_size_rows=64, num_files=2)


def _build_selective(url):
    """Sorted-id rows with a decode-heavy jpeg column: the query-shaped
    (selective) workload. Sorted ids give tight per-row-group min/max
    statistics, so a range predicate's selectivity maps directly onto
    prunable row-groups — the shape of an eval-slice / per-user read."""
    import cv2
    import numpy as np
    import pyarrow as pa

    from petastorm_tpu.codecs import CompressedImageCodec, ScalarCodec
    from petastorm_tpu.etl.dataset_metadata import write_dataset
    from petastorm_tpu.unischema import Unischema, UnischemaField

    schema = Unischema('SelectiveSchema', [
        UnischemaField('id', np.int32, (), ScalarCodec(pa.int32()), False),
        UnischemaField('image', np.uint8, SELECTIVE_SHAPE,
                       CompressedImageCodec('jpeg', quality=90), False),
    ])
    rng = np.random.RandomState(11)
    base = cv2.resize((rng.rand(8, 8, 3) * 180).astype(np.uint8),
                      SELECTIVE_SHAPE[:2],
                      interpolation=cv2.INTER_CUBIC).astype(np.float64)
    rows = [{'id': i,
             'image': np.clip(base + rng.rand(*SELECTIVE_SHAPE) * 60,
                              0, 255).astype(np.uint8)}
            for i in range(SELECTIVE_ROWS)]
    write_dataset(url, schema, rows,
                  rowgroup_size_rows=SELECTIVE_ROWGROUP_ROWS, num_files=4)


def _build_io_overlap(url):
    """Scalar rows across many row-groups: decode is nearly free, so an
    injected-latency filesystem makes storage round trips the wall —
    the readahead plane's home turf (a jpeg workload would hide the
    contrast behind decode time)."""
    import numpy as np
    import pyarrow as pa

    from petastorm_tpu.codecs import ScalarCodec
    from petastorm_tpu.etl.dataset_metadata import write_dataset
    from petastorm_tpu.unischema import Unischema, UnischemaField

    schema = Unischema('IoOverlapSchema', [
        UnischemaField('id', np.int64, (), ScalarCodec(pa.int64()), False),
        UnischemaField('value', np.float64, (),
                       ScalarCodec(pa.float64()), False),
        UnischemaField('tag', np.str_, (), ScalarCodec(pa.string()),
                       False),
    ])
    rows = [{'id': i, 'value': i * 0.25, 'tag': 'row-%06d' % i}
            for i in range(IO_OVERLAP_ROWS)]
    # TWO files: footer/open costs amortize over many row-groups (as on
    # any real store) while multi-file path handling still exercises
    write_dataset(url, schema, rows,
                  rowgroup_size_rows=IO_OVERLAP_ROWGROUP_ROWS, num_files=2)


def _write_bench_schema():
    """Scalar id + a repeated string payload: enough bytes per row that
    the MB/s number reflects flush/serialization work, while encode
    stays cheap enough that the local-vs-fleet contrast is about
    dispatch, not codec time."""
    import numpy as np
    import pyarrow as pa

    from petastorm_tpu.codecs import ScalarCodec
    from petastorm_tpu.unischema import Unischema, UnischemaField

    return Unischema('WriteBenchSchema', [
        UnischemaField('id', np.int64, (), ScalarCodec(pa.int64()), False),
        UnischemaField('payload', np.str_, (), ScalarCodec(pa.string()),
                       False),
    ])


def _write_bench_fs(url):
    from petastorm_tpu.fs import get_filesystem_and_path_or_paths
    return get_filesystem_and_path_or_paths(url)


def _build_mixture_source(url, num_docs, seed):
    """Plain-parquet token corpus (list<int64> ``tokens``) across many
    row-groups — the mixture engine's input shape."""
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    path = url[len('file://'):]
    os.makedirs(path, exist_ok=True)
    rng = np.random.RandomState(seed)
    per_file = (num_docs + 1) // 2
    doc_id = 0
    for file_idx in range(2):
        n = min(per_file, num_docs - doc_id)
        tokens = [rng.randint(2, 1000, size=int(rng.randint(20, 400)))
                  .tolist() for _ in range(n)]
        table = pa.table({'doc_id': np.arange(doc_id, doc_id + n),
                          'tokens': tokens})
        pq.write_table(table, os.path.join(path,
                                           'part-%d.parquet' % file_idx),
                       row_group_size=64)
        doc_id += n


class _SlowFile:
    """One fixed round-trip of latency per read request — the
    per-request cost shape of remote/cold object storage."""

    def __init__(self, wrapped, delay_s):
        self._f = wrapped
        self._delay = delay_s

    def read(self, *args):
        time.sleep(self._delay)
        return self._f.read(*args)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self._f.close()
        return False

    def __getattr__(self, name):
        return getattr(self._f, name)


class _SlowFS:
    """fsspec-delegating wrapper whose opened files pay the read
    latency; everything else (protocol checks, listings, footers at
    construction) passes straight through."""

    def __init__(self, fs, delay_s):
        self._fs = fs
        self._delay = delay_s

    def open(self, *args, **kwargs):
        return _SlowFile(self._fs.open(*args, **kwargs), self._delay)

    def __getattr__(self, name):
        return getattr(self._fs, name)


def _measure_rows(url):
    from petastorm_tpu.reader import make_reader
    # workers_count=None auto-sizes to the host (the reference's published
    # number used 3 workers on its own box; ours adapts the same way)
    with make_reader(url, reader_pool_type='thread',
                     num_epochs=None, shuffle_row_groups=True) as reader:
        for _ in range(WARMUP_SAMPLES):
            next(reader)
        start = time.monotonic()
        for _ in range(MEASURE_SAMPLES):
            next(reader)
        return MEASURE_SAMPLES / (time.monotonic() - start)


def _build_c4_like(url):
    from examples.lm.pretrain_example import generate_c4_like
    generate_c4_like(url, num_docs=C4_DOCS)


def _measure_lm_tokens(url, seq_len=128, warmup_rows=64, measure_rows=2048):
    """BASELINE config 5: variable-length token docs packed to fixed
    ``seq_len`` rows on the decode workers — packed tokens/sec."""
    from examples.lm.pretrain_example import packing_transform

    if SMOKE:
        warmup_rows, measure_rows = 16, 128
    rate, _ = _measure_batch(url, warmup_rows, measure_rows,
                             transform_spec=packing_transform(seq_len))
    return rate * seq_len


def _measure_batch(url, warmup_rows, measure_rows, bytes_per_row=0,
                   transform_spec=None):
    """Batched column reader: rows/sec (and decoded MB/s when sized)."""
    from petastorm_tpu.reader import make_batch_reader
    with make_batch_reader(url, reader_pool_type='thread',
                           num_epochs=None, shuffle_row_groups=True,
                           transform_spec=transform_spec) as reader:
        seen = 0
        while seen < warmup_rows:
            batch = next(reader)
            seen += len(next(iter(batch._asdict().values())))
        seen = 0
        start = time.monotonic()
        while seen < measure_rows:
            batch = next(reader)
            seen += len(next(iter(batch._asdict().values())))
        elapsed = time.monotonic() - start
    rate = seen / elapsed
    return rate, rate * bytes_per_row / 2 ** 20


_TFDATA_SNIPPET = r'''
import json, os, sys, time
os.environ.setdefault('TF_CPP_MIN_LOG_LEVEL', '3')
import numpy as np
import tensorflow as tf
tfrecord_path, warmup, measure = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])

def parse(example):
    feat = tf.io.parse_single_example(example, {
        'image': tf.io.FixedLenFeature([], tf.string),
        'noun_id': tf.io.FixedLenFeature([], tf.string),
    })
    return tf.io.decode_jpeg(feat['image'], channels=3)

dataset = (tf.data.TFRecordDataset(tfrecord_path)
           .repeat()
           .map(parse, num_parallel_calls=tf.data.AUTOTUNE)
           .batch(64)
           .prefetch(tf.data.AUTOTUNE))
it = iter(dataset)
seen = 0
while seen < warmup:
    seen += int(next(it).shape[0])
seen = 0
start = time.monotonic()
while seen < measure:
    seen += int(next(it).shape[0])
elapsed = time.monotonic() - start
print(json.dumps({"rows_per_sec": seen / elapsed}))
'''


def _run_subprocess(argv, timeout):
    """Run a helper subprocess → ``(completed_process, None)`` on success or
    ``(None, error_string)``; the benchmark never dies on helper failures.
    On timeout the partial stdout (if any) rides along in the error tuple
    as ``(stdout_str, 'timeout')`` so measurement snippets that print
    intermediate result lines don't lose them to the kill."""
    try:
        out = subprocess.run(argv, capture_output=True, timeout=timeout,
                             text=True)
    except subprocess.TimeoutExpired as e:
        partial = e.stdout
        if isinstance(partial, bytes):
            partial = partial.decode('utf-8', 'replace')
        return partial, 'timeout'
    if out.returncode != 0:
        return None, (out.stderr or 'failed').strip()[-300:]
    return out, None


def _parse_last_json_line(text):
    try:
        return json.loads(text.strip().splitlines()[-1])
    except (ValueError, IndexError, AttributeError):
        return None


def _run_json_subprocess(argv, timeout):
    """Run a measurement subprocess; parse its last stdout line as JSON.

    Same last-line contract the driver applies to bench.py itself — and
    the same salvage rule: a snippet killed by the timeout still yields
    whatever cumulative result line it had already printed (marked
    ``partial_after_timeout`` so the artifact says what happened)."""
    out, error = _run_subprocess(argv, timeout)
    if error is not None:
        salvaged = _parse_last_json_line(out) if isinstance(out, str) else None
        if salvaged is not None:
            salvaged['partial_after_timeout'] = True
            return salvaged
        return {'error': error}
    result = _parse_last_json_line(out.stdout)
    return result if result is not None else {'error': 'unparseable output'}


_PROBE_SNIPPET = r'''
import json
import jax
d = jax.devices()[0]
print(json.dumps({"platform": d.platform, "device_kind": d.device_kind}))
'''


def _probe_tpu(extra, timeout=75):
    """One upfront device probe in a guarded subprocess (VERDICT r3 #1b).

    A wedged chip/tunnel hangs ``jax.devices()`` indefinitely (observed on
    this box: backend init hung for hours); probing once bounds that cost to
    ``timeout`` seconds for the WHOLE benchmark instead of every device
    section burning its own subprocess timeout. On failure the remaining
    device sections are pinned to the CPU backend via ``BENCH_JAX_PLATFORM``
    and the run is marked ``tpu_unavailable``.
    """
    if os.environ.get('BENCH_JAX_PLATFORM'):
        extra['forced_platform'] = os.environ['BENCH_JAX_PLATFORM']
        return
    result = _run_json_subprocess(
        [sys.executable, '-c', _PROBE_SNIPPET], _clamp_timeout(timeout))
    if 'error' in result:
        os.environ['BENCH_JAX_PLATFORM'] = 'cpu'
        extra['tpu_unavailable'] = result['error']
    else:
        extra['probe_platform'] = result.get('platform')
        extra['probe_device_kind'] = result.get('device_kind')
        if result.get('platform') == 'cpu':
            # default backend IS cpu (no accelerator registered): pin it so
            # the per-section cpu-retry logic doesn't run everything twice
            os.environ['BENCH_JAX_PLATFORM'] = 'cpu'
            extra['tpu_unavailable'] = 'default backend is cpu'


def _build_tfrecord(url, timeout=240):
    """Re-encode the parquet dataset's jpeg cells into a TFRecord file.
    Returns the path, or an error string."""
    code = r'''
import glob, sys
import pyarrow.parquet as pq
import tensorflow as tf
out, pattern = sys.argv[1], sys.argv[2]
with tf.io.TFRecordWriter(out) as writer:
    for path in sorted(glob.glob(pattern)):
        table = pq.read_table(path, columns=['noun_id', 'image'])
        for nid, img in zip(table.column('noun_id').to_pylist(),
                            table.column('image').to_pylist()):
            ex = tf.train.Example(features=tf.train.Features(feature={
                'noun_id': tf.train.Feature(
                    bytes_list=tf.train.BytesList(value=[nid.encode()])),
                'image': tf.train.Feature(
                    bytes_list=tf.train.BytesList(value=[bytes(img)])),
            }))
            writer.write(ex.SerializeToString())
'''
    root = url[len('file://'):]
    tfrecord_path = root + '.tfrecord'
    _, error = _run_subprocess(
        [sys.executable, '-c', code, tfrecord_path, root + '/*.parquet'],
        _clamp_timeout(timeout))
    if error is not None:
        return None, 'tfrecord build: %s' % error
    return tfrecord_path, None


def _measure_tfdata(tfrecord_path, warmup, measure, timeout=240):
    """BASELINE.json north star: the same jpeg bytes through a
    tf.data+TFRecord input pipeline, for a like-for-like rows/sec ratio.
    Runs in a subprocess so TF's runtime never pollutes this process."""
    return _run_json_subprocess(
        [sys.executable, '-c', _TFDATA_SNIPPET, tfrecord_path,
         str(warmup), str(measure)], _clamp_timeout(timeout))


_JAX_SNIPPET = r'''
import json, os, sys, time
sys.path.insert(0, %(repo)r)
import jax
if os.environ.get('BENCH_JAX_PLATFORM'):
    # env JAX_PLATFORMS alone loses to a preregistered TPU plugin
    jax.config.update('jax_platforms', os.environ['BENCH_JAX_PLATFORM'])
# arm the live observability plane (ephemeral loopback port): the
# section's result embeds the final live /report snapshot — rollup
# windows + any anomaly events — so BENCH_r0x rounds are self-describing
# about HOW the measured rate was produced, not just its value
os.environ.setdefault('PETASTORM_TPU_OBS_PORT', '0')
os.environ.setdefault('PETASTORM_TPU_OBS_WINDOW_SEC', '0.5')
from petastorm_tpu.jax import make_jax_loader
url, batch_size, warmup, measure, fields = %(url)r, %(batch)d, %(warmup)d, %(measure)d, %(fields)r
with make_jax_loader(url, batch_size=batch_size, fields=fields,
                     num_epochs=None,
                     shuffle_row_groups=True) as loader:
    import jax.numpy as jnp
    it = iter(loader)
    seen = 0
    fence = jnp.zeros((), jnp.float32)
    while seen < warmup:
        b = next(it); seen += batch_size
        for arr in b.values():
            # warm the fence ops' compiles outside the measured window
            fence = fence + jnp.sum(arr[..., :1].astype(jnp.float32))
    float(fence)
    # Steady-state gate: the fence read above fully drained the transfer
    # pipeline, so the next batch pays the un-overlapped refill (and any
    # dispatch-path compile) alone — a first-batch outlier that belongs to
    # warmup, not to the steady-state rate the h2d_* metrics claim.
    # Consume ONE batch outside the timed window to exclude it.
    for arr in next(it).values():
        arr.block_until_ready()
    from petastorm_tpu.telemetry import pipeline_report, get_registry
    stage_baseline = get_registry().snapshot()
    seen = 0
    nbytes = 0
    fence = jnp.zeros((), jnp.float32)
    start = time.monotonic()
    while seen < measure:
        b = next(it)
        for arr in b.values():
            arr.block_until_ready()
            nbytes += arr.nbytes
            # device-side touch of every staged array: the final host READ
            # of `fence` (below) transitively requires every transfer to
            # have really completed, even if an experimental runtime's
            # ready-signal fires early
            fence = fence + jnp.sum(arr[..., :1].astype(jnp.float32))
        seen += batch_size
    float(fence)
    elapsed = time.monotonic() - start
    # fill/transfer overlap achieved over the measured window only (the
    # registry baseline scopes it); None when telemetry is off or the
    # staging arena is disabled
    overlap_share = pipeline_report(
        baseline=stage_baseline).get('h2d_overlap_share')
    # where image decode ran for this pass (fused-into-slot / -slab /
    # batched) — makes BENCH_r0x rounds attributable when the fusion
    # silently falls back (docs/troubleshoot.md)
    fused_mode = loader.diagnostics.get('fused_decode_mode')
    # final LIVE /report snapshot through the real HTTP endpoint (the
    # same bytes an operator's curl would get), trimmed to the
    # attribution keys: rollup headline, stall verdict, anomaly events.
    # Optional: a scrape failure must never cost the measured rate.
    live_report = None
    try:
        import urllib.request
        from petastorm_tpu.telemetry import obs_server
        obs_port = obs_server.server_port()
        if obs_port:
            live = json.loads(urllib.request.urlopen(
                'http://127.0.0.1:%%d/report' %% obs_port,
                timeout=10).read())
            live_report = {
                'stall_verdict': (live.get('stall') or {}).get('verdict'),
                'rollup': (live.get('rollup') or {}).get('headline'),
                'anomalies': (live.get('anomalies') or {}).get('by_kind'),
                'anomaly_recent': [
                    {'kind': e.get('kind'), 'detail': e.get('detail')}
                    for e in (live.get('anomalies') or {})
                    .get('recent', [])],
            }
    except Exception as e:
        live_report = {'error': repr(e)[:200]}

# Raw H2D calibration: device_put the SAME host batch shapes in a tight
# loop — the link's achievable bandwidth with zero pipeline around it.
# h2d_efficiency = loader H2D / raw H2D attributes the host-vs-jax gap:
# ~1.0 means the staging layer saturates the link (the gap IS the link,
# e.g. a tunneled chip); <1.0 means staging overhead steals bandwidth.
import numpy as np
hosts = [{k: np.array(v) for k, v in b.items()} for _ in range(2)]
batch_bytes = sum(a.nbytes for a in hosts[0].values())


def raw_h2d_mb(batches, reps_budget_bytes=3e8):
    """Tight device_put loop over alternating host batches → MB/s."""
    nbytes = sum(a.nbytes for a in batches[0].values())
    reps = max(4, min(64, int(reps_budget_bytes / max(1, nbytes))))
    # warm lazy init AND the fence ops' compiles outside the timed window
    for arr in jax.device_put(batches[0]).values():
        np.asarray(arr.ravel()[:1])
    start = time.monotonic()
    put = None
    for i in range(reps):
        put = jax.device_put(batches[i %% 2])  # alternate: defeat caching
        for arr in put.values():
            arr.block_until_ready()
    # final-rep D2H value reads: transfers execute in dispatch order on the
    # device, so forcing the LAST rep's arrays to concrete host values
    # bounds the whole sequence even if intermediate ready-signals fired
    # early (a per-rep device-op fence would dominate the measurement with
    # dispatch overhead on fast links)
    for arr in put.values():
        np.asarray(arr.ravel()[:1])  # device-side slice: 1-element D2H only
    return reps * nbytes / (time.monotonic() - start) / 2 ** 20


raw_mb = raw_h2d_mb(hosts)
loader_mb = nbytes / elapsed / 2 ** 20
result = {"rows_per_sec": seen / elapsed,
          "h2d_mb_per_sec": loader_mb,
          "h2d_gb_per_sec": loader_mb / 1024,
          "raw_h2d_mb_per_sec": raw_mb,
          "raw_h2d_gb_per_sec": raw_mb / 1024,
          "staged_bytes_per_batch": batch_bytes,
          "staged_dtypes": sorted({str(a.dtype) for a in hosts[0].values()}),
          "h2d_efficiency": loader_mb / raw_mb}
if overlap_share is not None:
    result["h2d_overlap_share"] = overlap_share
if fused_mode is not None:
    result["fused_decode_mode"] = fused_mode
if live_report is not None:
    result["live_report"] = live_report

# Bytes accounting for the uint8-staging design (VERDICT r3 #3): image
# pipelines stage uint8 over the link and cast/normalize ON DEVICE
# (ops/normalize.py), quartering link bytes vs staging f32. Measure the
# same pixels staged as f32 for the like-for-like rate, and report the
# f32-EQUIVALENT delivery rate of the uint8 path (pixels that arrive per
# second, scaled to f32 width) after demonstrating the on-device cast.
# Guarded: this block is OPTIONAL extra accounting, and a late failure
# (link wedge, device OOM) must not discard the core numbers already
# measured above — same never-lose-measured-data contract as the
# per-section emission in bench.py itself.
if all(a.dtype == np.uint8 for a in hosts[0].values()):
    try:
        f32_hosts = [{k: v.astype(np.float32) for k, v in h.items()}
                     for h in hosts]
        result["raw_h2d_f32_gb_per_sec"] = raw_h2d_mb(f32_hosts) / 1024
        # prove the on-device cast path runs (bf16 normalize of the
        # staged uint8 batch) — the f32-equivalent claim is only honest
        # if it does
        staged = jax.device_put(hosts[0])
        arr = next(iter(staged.values()))
        jnp.mean((arr.astype(jnp.bfloat16) - 127.5)
                 / 58.0).block_until_ready()
        result["f32_equiv_delivery_gb_per_sec"] = 4.0 * raw_mb / 1024
    except Exception as e:
        result["f32_accounting_error"] = repr(e)[:200]
print(json.dumps(result))
'''


def _measure_jax(url, batch_size, warmup, measure, fields, timeout=150):
    """JAX H2D staging in a guarded subprocess (default device = real chip
    under the driver). Returns dict or an {"error": ...} marker."""
    code = _JAX_SNIPPET % {
        'repo': os.path.dirname(os.path.abspath(__file__)), 'url': url,
        'batch': batch_size, 'warmup': warmup, 'measure': measure,
        'fields': fields}
    return _run_json_subprocess([sys.executable, '-c', code],
                                _clamp_timeout(timeout))


_JAX_DUMMY_SNIPPET = r'''
import json, os, sys, time
sys.path.insert(0, %(repo)r)
import numpy as np
if os.environ.get('BENCH_JAX_PLATFORM'):
    import jax
    jax.config.update('jax_platforms', os.environ['BENCH_JAX_PLATFORM'])
import jax
import jax.numpy as jnp
from petastorm_tpu.benchmark.dummy_reader import DummyBatchReader
from petastorm_tpu.jax import make_jax_loader

batch, warmup, measure, shape = %(batch)d, %(warmup)d, %(measure)d, %(shape)r


def factory(url, **kw):
    # zero I/O, zero decode: pre-generated in-RAM batches of the SAME
    # decoded shape the real pipeline stages
    return DummyBatchReader(fields={'image': (tuple(shape), np.uint8)},
                            batch_size=batch, num_batches=None)


with make_jax_loader('dummy://calibration', batch_size=batch,
                     num_epochs=None, reader_factory=factory) as loader:
    it = iter(loader)
    fence = jnp.zeros((), jnp.float32)
    seen = 0
    while seen < warmup:
        b = next(it); seen += batch
        for arr in b.values():
            fence = fence + jnp.sum(arr[..., :1].astype(jnp.float32))
    float(fence)
    seen = 0
    fence = jnp.zeros((), jnp.float32)
    start = time.monotonic()
    while seen < measure:
        b = next(it)
        for arr in b.values():
            arr.block_until_ready()
            fence = fence + jnp.sum(arr[..., :1].astype(jnp.float32))
        seen += batch
    float(fence)
    elapsed = time.monotonic() - start
print(json.dumps({"rows_per_sec": seen / elapsed}))
'''


_SHARDED_STAGING_SNIPPET = r'''
import json, os, sys, time
sys.path.insert(0, %(repo)r)
# 8 virtual host-platform devices when the run lands on CPU (the flag
# only affects the host platform, so it is harmless on real chips) —
# set BEFORE jax initializes a backend
os.environ['XLA_FLAGS'] = (os.environ.get('XLA_FLAGS', '')
                           + ' --xla_force_host_platform_device_count=8')
# fast autotune windows so the decision log can show work inside a short
# bench section (actions stay bounded by the MAX knobs as always)
os.environ.setdefault('PETASTORM_TPU_STAGING_AUTOTUNE_WINDOW_SEC', '0.25')
import numpy as np
import jax
if os.environ.get('BENCH_JAX_PLATFORM'):
    jax.config.update('jax_platforms', os.environ['BENCH_JAX_PLATFORM'])
import jax.numpy as jnp
from petastorm_tpu.benchmark.dummy_reader import DummyBatchReader
from petastorm_tpu.jax import autotune
from petastorm_tpu.jax.loader import make_jax_loader
from petastorm_tpu.parallel.mesh import DATA_AXIS, make_mesh
from petastorm_tpu.parallel.sharding import local_shard_plan
from petastorm_tpu.telemetry import get_registry, pipeline_report

batch, warmup, measure, shape = %(batch)d, %(warmup)d, %(measure)d, %(shape)r
devices = jax.devices()
mesh = make_mesh(data=len(devices))


def factory(url, **kw):
    # zero I/O, zero decode: the sharded staging + H2D cost in isolation
    # (the real-pipeline rates are the imagenet_jax section's job)
    return DummyBatchReader(fields={'image': (tuple(shape), np.uint8)},
                            batch_size=batch, num_batches=None)


with make_jax_loader('dummy://sharded', batch_size=batch, num_epochs=None,
                     mesh=mesh, data_axes=(DATA_AXIS,),
                     reader_factory=factory) as loader:
    it = iter(loader)
    fence = jnp.zeros((), jnp.float32)
    seen = 0
    while seen < warmup:
        b = next(it); seen += batch
        for arr in b.values():
            fence = fence + jnp.sum(arr[..., :1].astype(jnp.float32))
    float(fence)
    # steady-state gate (same contract as the jax section): one batch
    # outside the timed window absorbs the un-overlapped refill
    for arr in next(it).values():
        arr.block_until_ready()
    stage_baseline = get_registry().snapshot()
    seen = 0
    nbytes = 0
    fence = jnp.zeros((), jnp.float32)
    start = time.monotonic()
    while seen < measure:
        b = next(it)
        for arr in b.values():
            arr.block_until_ready()
            # shard-slice accounting: the bytes THIS host put on the wire
            nbytes += arr.nbytes // jax.process_count()
            fence = fence + jnp.sum(arr[..., :1].astype(jnp.float32))
        seen += batch
    float(fence)
    elapsed = time.monotonic() - start
    overlap = pipeline_report(
        baseline=stage_baseline).get('h2d_overlap_share')
    diag = loader.diagnostics

# Raw sharded-put calibration: the same per-device row plan the loader
# dispatches with, in a tight loop with zero pipeline around it — the
# mesh link's achievable wire speed. h2d_efficiency = loader / raw.
sharding = loader.sharding
plan = local_shard_plan(sharding, batch)
rng = np.random.RandomState(3)
hosts = [{'image': rng.randint(0, 255, (batch,) + tuple(shape),
                               dtype=np.uint8)} for _ in range(2)]


def put_planned(tree):
    slices, devs = [], []
    for arr in tree.values():
        for dev, lo, hi in plan:
            slices.append(arr[lo:hi])
            devs.append(dev)
    return jax.device_put(slices, devs)


raw_mb = None
if plan is not None:
    nb = sum(a.nbytes for a in hosts[0].values())
    reps = max(4, min(64, int(3e8 / max(1, nb))))
    for arr in put_planned(hosts[0]):
        arr.block_until_ready()
    t0 = time.monotonic()
    out = None
    for i in range(reps):
        out = put_planned(hosts[i %% 2])
        for arr in out:
            arr.block_until_ready()
    for arr in out:
        np.asarray(arr.ravel()[:1])
    raw_mb = reps * nb / (time.monotonic() - t0) / 2 ** 20

loader_mb = nbytes / elapsed / 2 ** 20
result = {
    'devices': len(devices),
    'rows_per_sec': seen / elapsed,
    'gb_per_sec': loader_mb / 1024,
    'slot_depth': diag.get('staging_slot_depth'),
    'prefetch_depth': diag.get('staging_prefetch'),
    'autotune_decisions': autotune.decision_counts(),
    'autotune_recent': autotune.recent_decisions(5),
}
if raw_mb:
    result['raw_gb_per_sec'] = raw_mb / 1024
    result['h2d_efficiency'] = loader_mb / raw_mb
if overlap is not None:
    result['overlap_share'] = overlap
    # per-host rows of the mesh-wide overlap picture (one process here;
    # a pod job reports one row per host through its own endpoint)
    result['per_host_overlap_share'] = {str(jax.process_index()): overlap}
print(json.dumps(result))
'''


def _measure_sharded_staging(batch_size, warmup, measure, shape,
                             timeout=150):
    """Shard-aware staging engine on a data mesh over every visible
    device (8 virtual CPU devices when the run lands on the host
    platform): aggregate GB/s into NamedSharding batches, per-host
    overlap share, staged-vs-raw h2d efficiency, and the autotuner's
    decision log."""
    code = _SHARDED_STAGING_SNIPPET % {
        'repo': os.path.dirname(os.path.abspath(__file__)),
        'batch': batch_size, 'warmup': warmup, 'measure': measure,
        'shape': tuple(shape)}
    return _run_json_subprocess([sys.executable, '-c', code],
                                _clamp_timeout(timeout))


def _measure_jax_dummy(batch_size, warmup, measure, shape, timeout=120):
    """The SAME make_jax_loader consumer over a DummyBatchReader source
    (zero I/O, zero decode): the framework-staging + H2D cost in
    isolation, so the real imagenet_jax sec/row decomposes — the
    reference's dummy-reader method (``benchmark/throughput.py:112-149``
    via ``benchmark/dummy_reader.py``)."""
    code = _JAX_DUMMY_SNIPPET % {
        'repo': os.path.dirname(os.path.abspath(__file__)),
        'batch': batch_size, 'warmup': warmup, 'measure': measure,
        'shape': tuple(shape)}
    return _run_json_subprocess([sys.executable, '-c', code],
                                _clamp_timeout(timeout))


_VIT_TRAIN_SNIPPET = r'''
import json, os, sys, time
sys.path.insert(0, %(repo)r)
import numpy as np
if os.environ.get('BENCH_JAX_PLATFORM'):
    import jax
    jax.config.update('jax_platforms', os.environ['BENCH_JAX_PLATFORM'])
import jax
import jax.numpy as jnp
import optax
from petastorm_tpu.models.vit import (
    ViTConfig, init_vit_params, vit_train_step,
)

# Image-family silicon throughput (VERDICT r4 #7): ViT-Base dims on a
# 32x32 patch grid — image 384 / patch 12 gives S=1024 patches, a
# multiple of the fused kernel's 128 block, so attention rides the
# bidirectional flash path (models/vit.py).
on_cpu = jax.default_backend() == 'cpu'
if on_cpu:
    cfg_kw = dict(image_size=32, patch_size=8, n_classes=10, d_model=64,
                  n_heads=2, n_layers=2, d_ff=128)
    batch, warmup, measure = 4, 1, 4
else:
    cfg_kw = dict(image_size=384, patch_size=12, n_classes=1000,
                  d_model=768, n_heads=12, n_layers=12, d_ff=3072)
    batch, warmup, measure = 16, 2, 12

attn_impl = 'dense'
config = ViTConfig(**cfg_kw)
rng = np.random.RandomState(0)
# two synthetic in-HBM batches, alternated: this is the COMPUTE-side
# number (the ingest side of the image family is the imagenet_jax
# section); two buffers defeat any single-buffer caching
images = [jnp.asarray(rng.rand(batch, config.image_size, config.image_size,
                               3).astype(np.float32)) for _ in range(2)]
labels = [jnp.asarray(rng.randint(0, cfg_kw['n_classes'], (batch,),
                                  np.int32)) for _ in range(2)]
optimizer = optax.adamw(1e-3)


def build(cfg):
    p = init_vit_params(jax.random.PRNGKey(0), cfg)
    return p, optimizer.init(p), vit_train_step(cfg, optimizer)


from petastorm_tpu.ops.flash_attention import kernel_supported
use_flash = kernel_supported(config.n_patches)  # honest label: 'flash'
try:                                            # means the kernel RAN
    if not use_flash:
        raise RuntimeError('n_patches=%%d below the kernel block'
                           %% config.n_patches)
    flash_cfg = ViTConfig(attn_impl='flash', **cfg_kw)
    params, opt_state, step = build(flash_cfg)
    p2, o2, l2 = step(params, opt_state, images[0], labels[0])
    float(l2)
    config, attn_impl = flash_cfg, 'flash'
    params, opt_state = p2, o2
except Exception as e:
    print('vit flash unavailable, dense fallback: %%r' %% (e,),
          file=sys.stderr)
    params, opt_state, step = build(config)
    params, opt_state, _ = step(params, opt_state, images[0], labels[0])
for i in range(max(0, warmup - 1)):
    params, opt_state, loss = step(params, opt_state, images[i %% 2],
                                   labels[i %% 2])
float(jnp.sum(jax.tree_util.tree_leaves(params)[0]
              .astype(jnp.float32)))  # D2H fence before the timed window
start = time.monotonic()
for i in range(measure):
    params, opt_state, loss = step(params, opt_state, images[i %% 2],
                                   labels[i %% 2])
final_loss = float(loss)  # D2H value fence bounds every prior step
elapsed = time.monotonic() - start

# Analytic matmul FLOPs per step (fwd 2 FLOP/MAC, bwd 2x fwd): patch
# embed + per-layer qkv/proj/ffn + attention scores + head.
c = config
S = c.n_patches
n_matmul = (c.patch_dim * c.d_model
            + c.n_layers * (4 * c.d_model ** 2
                            + 2 * c.d_model * c.d_ff))
flops_per_step = (6 * n_matmul * batch * S
                  + 12 * c.n_layers * batch * S ** 2 * c.d_model
                  + 6 * batch * c.d_model * c.n_classes)
_PEAKS = %(peaks)r
kind = jax.devices()[0].device_kind.lower()
peak = next((p for key, p in _PEAKS if key in kind), None)
result = {
    "steps_per_sec": measure / elapsed,
    "images_per_sec": measure * batch / elapsed,
    "final_loss": final_loss,
    "attn_impl": attn_impl,
    "n_patches": S,
    "model_params_m": round((n_matmul + S * c.d_model
                             + c.d_model * c.n_classes) / 1e6, 1),
    "device_kind": jax.devices()[0].device_kind,
}
if peak is not None:
    result["mfu"] = flops_per_step * measure / elapsed / peak
print(json.dumps(result))
'''


def _measure_vit_train(timeout=300):  # room for 2 tunnel compiles
                                      # (flash try + dense fallback)
    """ViT train throughput on the default device: the image family's
    compute-side silicon number (steps/s, images/s, MFU)."""
    code = _VIT_TRAIN_SNIPPET % {
        'repo': os.path.dirname(os.path.abspath(__file__)),
        'peaks': TPU_PEAKS}
    return _run_json_subprocess([sys.executable, '-c', code],
                                _clamp_timeout(timeout))


_LM_TRAIN_SNIPPET = r'''
import json, os, sys, time
sys.path.insert(0, %(repo)r)
import numpy as np
if os.environ.get('BENCH_JAX_PLATFORM'):
    import jax
    jax.config.update('jax_platforms', os.environ['BENCH_JAX_PLATFORM'])
import jax
import optax
from petastorm_tpu.jax import make_jax_loader
from petastorm_tpu.models.transformer import (
    TransformerConfig, init_transformer_params, transformer_train_step,
)
from examples.lm.pretrain_example import packing_transform

url, batch, seq_len, warmup, measure = (
    %(url)r, %(batch)d, %(seq)d, %(warmup)d, %(measure)d)
warmup = max(1, warmup)  # the impl-selection step below consumes one batch
# Realistically-sized decoder (~335M params, 308M in matmul weights):
# large enough that the per-step matmuls tile the MXU and MFU is
# meaningful (BASELINE.json metric; a toy model would measure dispatch
# latency, not feeding capacity). The d_model=1536/10-layer shape was
# picked by measurement on the v5e-16GB: with the donated train state it
# reaches ~0.435 MFU, vs 0.406 for 1536/8 and 0.29 for the original
# 1024/12 config (wider matmuls tile the MXU better at the same FLOP
# budget); deeper/wider or batch>8 exhausts HBM with adamw state. On a
# CPU backend (chip-unavailable fallback) any such model would blow the
# subprocess timeout by an order of magnitude, so fall back to a small
# config — the loader-vs-synthetic ratio stays meaningful, MFU does not
# (no 'peak' for CPU, so it is omitted anyway).
on_cpu = jax.default_backend() == 'cpu'
if on_cpu:
    # seq 1024 attention alone is ~minutes/step on CPU; shrink the whole
    # shape so the fallback still finishes inside the subprocess timeout
    seq_len = min(seq_len, 256)
    batch = min(batch, 8)
    measure = min(measure, 8)
    model_kw = dict(vocab_size=256, d_model=128, n_heads=4,
                    n_layers=4, d_ff=512, max_seq_len=seq_len)
else:
    # loss_chunk: the (B, S, V) logits at this vocab are ~0.5 GB f32;
    # chunked CE keeps peak loss memory at one 256-position chunk.
    # 10 layers fit (vs 8 undonated) because the step donates the train
    # state — measured MFU 0.435 at this shape vs 0.406 for L8.
    model_kw = dict(max_seq_len=seq_len, loss_chunk=256,
                    **%(flagship)r)
# tuned-variant knobs (VERDICT r4 #3): model DIMENSIONS stay the
# flagship's for cross-round comparability; overrides may only add
# execution levers (remat, loss_chunk) — batch rides the %%(batch)d param
model_kw.update(%(overrides)r)
config = TransformerConfig(**model_kw)
params = init_transformer_params(jax.random.PRNGKey(0), config)
optimizer = optax.adamw(1e-3)
opt_state = optimizer.init(params)
# donate=True: the train state updates in place (the whole measured loop
# is state = step(state, ...)), freeing a params+opt_state copy of HBM
step = transformer_train_step(config, optimizer, donate=True)

# Analytic matmul FLOPs per optimizer step (fwd 2 FLOP/MAC, bwd 2x fwd):
# parameter matmuls 6*N_matmul*tokens + attention scores 12*L*B*S^2*d.
# The train step next-token-shifts to S-1 positions.
c = config
s_eff = seq_len - 1
# qkv projection width varies with GQA; FFN matrix count with swiglu
qkv_params = c.d_model * (c.n_heads + 2 * c.kv_heads) * (c.d_model
                                                         // c.n_heads)
ffn_mats = 3 if c.ffn == 'swiglu' else 2
n_matmul = (c.n_layers * (qkv_params + c.d_model ** 2
                          + ffn_mats * c.d_model * c.d_ff)
            + c.d_model * c.vocab_size)
flops_per_step = (6 * n_matmul * batch * s_eff
                  + 12 * c.n_layers * batch * s_eff ** 2 * c.d_model)

# bf16 peak of the chip actually running the step (MFU denominator)
_PEAKS = %(peaks)r
kind = jax.devices()[0].device_kind.lower()
peak = next((p for key, p in _PEAKS if key in kind), None)


def measured_matmul_tflops(n=4096, reps_lo=64, reps_hi=320):
    """Achievable bf16 matmul rate on THIS device, measured: a chained
    (sequentially dependent) square-matmul loop under one jit, fenced by
    a device-to-host value read. Cross-checks the book peak: if the
    device_kind's table entry disagrees wildly with what the silicon
    actually does, MFU numbers against the book value are meaningless
    (e.g. a tunnel that misreports its device kind).

    TWO rep counts, rate from the delta: one timed call carries a fixed
    dispatch + tunnel-RTT + D2H-fence cost (observed ~100ms on a tunneled
    chip — comparable to the compute itself), and (t_hi - t_lo) cancels
    exactly that constant. The per-rep rescale keeps values finite and
    fuses into the matmul epilogue (unlike a tanh, which would add a
    separate HBM-bound elementwise pass to every rep)."""
    import jax.numpy as jnp

    def chain(a, reps):
        scale = jnp.bfloat16(4.0 / n)  # keeps x bounded: |row sum| ~ n/4
        def body(x, _):
            return (x @ a) * scale, None
        x, _ = jax.lax.scan(body, a, None, length=reps)
        return x

    a = jnp.asarray(np.random.RandomState(0).rand(n, n) * 0.5,
                    jnp.bfloat16)
    run = jax.jit(chain, static_argnums=1)

    def timed(reps):
        float(run(a, reps)[0, 0])  # compile + warm
        start = time.monotonic()
        float(run(a, reps)[0, 0])  # D2H fence
        return time.monotonic() - start

    t_lo, t_hi = timed(reps_lo), timed(reps_hi)
    if t_hi <= t_lo:
        # timing noise swallowed the compute delta: report a calibration
        # failure, never a clamped garbage rate posing as a measurement
        raise RuntimeError('non-positive timing delta (t_lo=%%.4fs, '
                           't_hi=%%.4fs): timing too noisy to calibrate'
                           %% (t_lo, t_hi))
    return 2.0 * n ** 3 * (reps_hi - reps_lo) / (t_hi - t_lo) / 1e12

attn_impl = 'dense'
with make_jax_loader(url, batch_size=batch, num_epochs=None,
                     transform_spec=packing_transform(seq_len),
                     shuffle_row_groups=True) as loader:
    it = loader.iter_steps(warmup + measure)
    staged = []
    first = next(it)['tokens']
    staged.append(first)
    from petastorm_tpu.ops.flash_attention import kernel_supported
    if kernel_supported(seq_len):
        # try the fused Pallas flash-attention step first (no HBM score
        # tensor -> higher MFU); an unsupported kernel on this chip just
        # falls back to the dense step. kernel_supported is the wrapper
        # module's own gate, so 'flash' in the output always means the
        # fused kernel actually ran. The steps DONATE the train state, so
        # the except path re-inits rather than reusing possibly-donated
        # buffers (compile failures leave them intact, but a runtime
        # failure after dispatch would not — re-init is deterministic and
        # cheap next to the step compile itself).
        try:
            flash_cfg = TransformerConfig(attn_impl='flash', **model_kw)
            flash_step = transformer_train_step(flash_cfg, optimizer,
                                                donate=True)
            p2, o2, l2 = flash_step(params, opt_state, first)
            float(l2)
            config, step, attn_impl = flash_cfg, flash_step, 'flash'
            params, opt_state, loss = p2, o2, l2
        except Exception as e:
            print('flash attention unavailable, dense fallback: %%r' %% (e,),
                  file=sys.stderr)
            params = init_transformer_params(jax.random.PRNGKey(0), config)
            opt_state = optimizer.init(params)
            params, opt_state, loss = step(params, opt_state, first)
    else:
        params, opt_state, loss = step(params, opt_state, first)
    for _ in range(warmup - 1):
        tokens = next(it)['tokens']
        if len(staged) < 4:
            staged.append(tokens)
        params, opt_state, loss = step(params, opt_state, tokens)
    # Timing fence: a DEVICE-TO-HOST VALUE READ, not block_until_ready.
    # The value of step N's loss transitively requires every prior step's
    # compute, and a concrete host float cannot be delivered early by an
    # experimental runtime the way a too-eager ready-signal can.
    float(loss)
    start = time.monotonic()
    for _ in range(measure):
        params, opt_state, loss = step(params, opt_state, next(it)['tokens'])
    # the reported loss is the LOADER-FED run's final loss; the synthetic
    # re-feed below keeps training and must not redefine it
    final_loss = float(loss)
    loader_elapsed = time.monotonic() - start

# Same step count fed from batches ALREADY in HBM: the loader-free step
# time. input_bound_util = loader-fed / in-HBM step time; <=1.05 means the
# input pipeline steals <=5%% of the step (BASELINE.json "input-bound
# step util"). Needs warmup>0 (staged batches are captured there).
synthetic_elapsed = None
if staged:
    start = time.monotonic()
    for i in range(measure):
        params, opt_state, loss = step(params, opt_state,
                                       staged[i %% len(staged)])
    float(loss)  # same D2H fence as the loader-fed loop
    synthetic_elapsed = time.monotonic() - start

result = {
    "steps_per_sec": measure / loader_elapsed,
    "train_tokens_per_sec": measure * batch * seq_len / loader_elapsed,
    "final_loss": final_loss,
    "model_params_m": round((n_matmul + c.vocab_size * c.d_model
                             + c.max_seq_len * c.d_model) / 1e6, 1),
    "device_kind": jax.devices()[0].device_kind,
    "attn_impl": attn_impl,
}
if synthetic_elapsed is not None:
    result["input_bound_util"] = loader_elapsed / synthetic_elapsed
if peak is not None:
    result["mfu"] = flops_per_step * measure / loader_elapsed / peak
    if synthetic_elapsed is not None:
        result["synthetic_mfu"] = (flops_per_step * measure
                                   / synthetic_elapsed / peak)
if not on_cpu:
    # self-validate the MFU denominator against the silicon (skipped on
    # CPU, where 3.3 TFLOP of matmul is a minute of wall time)
    try:
        measured = measured_matmul_tflops()
        result["measured_matmul_tflops"] = measured
        if peak is not None:
            result["measured_vs_book_peak"] = measured * 1e12 / peak
    except Exception as e:
        print('matmul calibration failed: %%r' %% (e,), file=sys.stderr)
print(json.dumps(result))
'''


_MFU_BREAKDOWN_SNIPPET = r'''
import json, os, sys, time
sys.path.insert(0, %(repo)r)
import numpy as np
if os.environ.get('BENCH_JAX_PLATFORM'):
    import jax
    jax.config.update('jax_platforms', os.environ['BENCH_JAX_PLATFORM'])
import jax
import jax.numpy as jnp

from petastorm_tpu.models.transformer import (
    _chunked_next_token_nll, _rmsnorm,
)
from petastorm_tpu.ops.flash_attention import flash_causal_attention

# Where the non-MXU 50+%% of the flagship step goes (VERDICT r4 #3):
# the flash-attention fwd+VJP, the rmsnorms, and the chunked
# loss+lm_head, each timed AT THE FLAGSHIP SHAPE. One timed call
# carries ~100ms of dispatch + tunnel RTT + D2H fence on a tunneled
# chip (naive per-call timing reports parts LARGER than the whole
# step), so each part runs as ONE chained scan of R sequentially-
# dependent reps and the separately-measured dispatch constant is
# subtracted: per_rep = (t_chain - t_dispatch) / R. One scan length per
# part keeps the compile count at 3 — the two-length-delta variant's 6+
# compiles blow the subprocess timeout over a tunnel. The parent
# combines the part times with lm_train's step time and matmul
# calibration into shares.
if jax.default_backend() == 'cpu':
    # minutes per part on CPU and no meaningful MFU story: skip, marked
    print(json.dumps({"skipped": "cpu backend"}))
    sys.exit(0)

kw = dict(max_seq_len=%(seq)d, **%(flagship)r)
B, S = %(batch)d, kw['max_seq_len']
d, H, L = kw['d_model'], kw['n_heads'], kw['n_layers']
V, dff = kw['vocab_size'], kw['d_ff']
Dh = d // H
rng = np.random.RandomState(1)

# the fixed cost of one fenced call: jit dispatch + tunnel RTT + D2H
_tiny = jax.jit(lambda x: x + 1.0)
float(_tiny(jnp.zeros((), jnp.float32)))  # compile
_samples = []
for _ in range(5):
    _t0 = time.monotonic()
    float(_tiny(jnp.zeros((), jnp.float32)))
    _samples.append(time.monotonic() - _t0)
_samples.sort()
t_dispatch = _samples[len(_samples) // 2]


def chain_time(vg_fn, x0, reps, *rest):
    """Per-rep seconds of vg_fn from one chained scan: x feeds back
    through its own gradient so reps cannot overlap; one D2H read
    fences the chain; the dispatch constant is subtracted."""
    @jax.jit
    def chain(x):
        def body(xc, _):
            val, gx = vg_fn(xc, *rest)
            return xc + jnp.bfloat16(1e-6) * gx, val
        _, vals = jax.lax.scan(body, x, None, length=reps)
        return vals[-1]

    float(chain(x0))  # compile + warm
    times = []
    for _ in range(2):
        start = time.monotonic()
        float(chain(x0))
        times.append(time.monotonic() - start)
    t = min(times)
    if t <= t_dispatch:
        raise RuntimeError('chain faster than the dispatch constant')
    return (t - t_dispatch) / reps


# CUMULATIVE emission per part (the parent parses the last stdout line
# and salvages it on a timeout kill — same contract as bench.py itself):
# a slow compile on a later part can never cost the parts already
# measured. Ordered by value: attention first.
result = {"dispatch_ms": t_dispatch * 1e3}

q0 = jnp.asarray(rng.randn(B, S, H, Dh) * 0.1, jnp.bfloat16)
kk = jnp.asarray(rng.randn(B, S, H, Dh) * 0.1, jnp.bfloat16)
vv = jnp.asarray(rng.randn(B, S, H, Dh) * 0.1, jnp.bfloat16)
attn_vg = jax.value_and_grad(
    lambda q, k, v: flash_causal_attention(q, k, v)
    .astype(jnp.float32).sum())
result["attn_total_ms"] = chain_time(
    lambda q: attn_vg(q, kk, vv), q0, 8) * L * 1e3
print(json.dumps(result), flush=True)

lm_head = jnp.asarray(rng.randn(d, V) * 0.02, jnp.bfloat16)
targets = jnp.asarray(rng.randint(0, V, (B, S - 1), np.int32))
mask = jnp.ones((B, S - 1), jnp.float32)
xs0 = jnp.asarray(rng.randn(B, S - 1, d) * 0.1, jnp.bfloat16)


def _nll(xc, w):
    nll, cnt = _chunked_next_token_nll(xc, w, targets, mask, 256,
                                       jnp.bfloat16)
    return nll / cnt


_nll_vg = jax.value_and_grad(_nll, argnums=(0, 1))


def _nll_part(xc):
    # consume BOTH grads: the real step also computes d(lm_head) — a
    # full (d, V) matmul, ~1/3 of the head's backward FLOPs — and a
    # grad-wrt-x-only timer would drop it. The 1e-30-scaled full
    # reduction of gw forces its computation without changing the value
    # (a 0.0 scale would invite multiply-by-zero folding).
    val, (gx, gw) = _nll_vg(xc, lm_head)
    return (val + jnp.float32(1e-30)
            * jnp.sum(gw).astype(jnp.float32), gx)


result["loss_head_ms"] = chain_time(_nll_part, xs0, 8) * 1e3
print(json.dumps(result), flush=True)

x0 = jnp.asarray(rng.randn(B, S, d) * 0.1, jnp.bfloat16)
gain = jnp.ones((d,), jnp.float32)
norm_vg = jax.value_and_grad(
    lambda x, g: _rmsnorm(x, g).astype(jnp.float32).sum())
result["norm_total_ms"] = chain_time(
    lambda x: norm_vg(x, gain), x0, 64) * (2 * L + 1) * 1e3
print(json.dumps(result), flush=True)
'''


# the breakdown MUST time the same (batch, seq) lm_train measures — its
# shares divide part-times by lm_train's step time (SMOKE shrinks both)
BREAKDOWN_BATCH, BREAKDOWN_SEQ = (2, 64) if SMOKE else (8, 1024)


def compute_staging_shares(real_rows_s, dummy_rows_s, raw_h2d_mb_s,
                           bytes_per_batch, batch_size):
    """Three-way split of the real pipeline's sec/row (VERDICT r4 #4):

    * ``jax_h2d_share`` — the link cost of the staged bytes, capped at
      the dummy path's whole time: on a degraded tunnel the loader's
      overlapped H2D beats the raw tight loop (sec_dummy < sec_h2d), and
      attributing MORE than the dummy time to the link would make the
      three shares sum past 1;
    * ``jax_framework_share`` — what the dummy-fed loader adds on top of
      the (capped) link term: staging machinery; 0 in the overlap regime;
    * ``jax_io_decode_share`` — the remainder: parquet I/O + decode.

    The three shares partition sec/row(real) by construction (sum 1,
    up to rounding). Returns None unless all inputs are positive.
    """
    if not (real_rows_s and dummy_rows_s and raw_h2d_mb_s
            and bytes_per_batch):
        return None
    sec_real = 1.0 / real_rows_s
    sec_dummy = min(1.0 / dummy_rows_s, sec_real)
    sec_h2d = min((bytes_per_batch / batch_size)
                  / (raw_h2d_mb_s * 2 ** 20), sec_dummy)
    return {
        'jax_h2d_share': round(sec_h2d / sec_real, 4),
        'jax_framework_share': round(
            (sec_dummy - sec_h2d) / sec_real, 4),
        'jax_io_decode_share': round(
            (sec_real - sec_dummy) / sec_real, 4),
    }


def compute_mfu_breakdown(steps_per_sec, input_bound_util, tflops,
                          part_ms, flagship=None, batch=None, seq=None):
    """Combine measured part-times (ms) with the step rate into shares
    of the COMPUTE step (VERDICT r4 #3). ``tflops`` (lm_train's matmul
    calibration) adds the ideal-time term for the parameter matmuls
    outside the measured parts; ``input_wait_of_step`` reports the
    loader's share of the WALL step when input_bound_util > 1. Returns
    None without a step rate or any measured part."""
    measured = {key: v for key, v in part_ms.items() if v is not None}
    if not steps_per_sec or not measured:
        return None
    if tflops:
        k = flagship or FLAGSHIP_LM_KW
        d = k['d_model']
        b = batch or BREAKDOWN_BATCH
        s_eff = (seq or BREAKDOWN_SEQ) - 1
        no_head = k['n_layers'] * (3 * d * d + d * d
                                   + 2 * d * k['d_ff'])
        measured['param_matmul_ideal'] = (
            6 * no_head * b * s_eff / (tflops * 1e12) * 1e3)
    step_ms = 1000.0 / steps_per_sec
    util = input_bound_util
    compute_ms = step_ms / util if util and util > 1 else step_ms
    shares = {key: round(v / compute_ms, 4) for key, v in measured.items()}
    if len(measured) == 4:  # all parts present: close the sum
        shares['other'] = round(max(0.0, 1.0 - sum(shares.values())), 4)
    if util and util > 1:
        shares['input_wait_of_step'] = round(1.0 - 1.0 / util, 4)
    return shares


def _measure_mfu_breakdown(timeout=480):
    """Part-times of the flagship step's big consumers, for the
    ``lm_train_mfu_breakdown`` shares computed in ``sec_mfu_breakdown``."""
    code = _MFU_BREAKDOWN_SNIPPET % {
        'repo': os.path.dirname(os.path.abspath(__file__)),
        'flagship': FLAGSHIP_LM_KW, 'batch': BREAKDOWN_BATCH,
        'seq': BREAKDOWN_SEQ}
    return _run_json_subprocess([sys.executable, '-c', code],
                                _clamp_timeout(timeout))


_LM_DECODE_SNIPPET = r'''
import json, os, sys, time
sys.path.insert(0, %(repo)r)
import numpy as np
if os.environ.get('BENCH_JAX_PLATFORM'):
    import jax
    jax.config.update('jax_platforms', os.environ['BENCH_JAX_PLATFORM'])
import jax
import jax.numpy as jnp
from petastorm_tpu.models.transformer import (
    TransformerConfig, init_transformer_params,
)
from petastorm_tpu.models.generate import greedy_generate

# inference throughput on the SAME model family as lm_train: KV-cache
# greedy decode, one jitted prefill+scan; tokens/sec = new tokens over
# wall time after a D2H value fence
on_cpu = jax.default_backend() == 'cpu'
if on_cpu:
    kw = dict(vocab_size=256, d_model=128, n_heads=4, n_layers=4,
              d_ff=512, max_seq_len=160)
    batch, prompt_len, n_lo, n_hi = 4, 16, 8, 32
else:
    kw = dict(max_seq_len=1024, **%(flagship)r)  # = the lm_train shape
    batch, prompt_len, n_lo, n_hi = 8, 128, 64, 256
config = TransformerConfig(**kw)
params = init_transformer_params(jax.random.PRNGKey(0), config)
prompt = jnp.asarray(np.random.RandomState(0).randint(
    0, kw['vocab_size'], (batch, prompt_len), np.int32))

# two decode lengths, rate from the delta: one call's time includes the
# prefill + dispatch/compile-cache costs, and (t_hi - t_lo) cancels them
# so the metric is the PURE per-token decode rate. Median of 3 per
# length: single runs on this box swing about ten percent (same policy
# as the imagenet/tfdata metrics).
import statistics


def make_runs(cfg):
    return {n: jax.jit(lambda p, t, n=n, c=cfg: greedy_generate(p, t, c, n))
            for n in (n_lo, n_hi)}


def timed(run_map, run_params, n):
    """Median-of-3 wall time of one decode length (compile outside)."""
    int(run_map[n](run_params, prompt)[0, -1])  # compile + warm
    samples = []
    for _ in range(3):
        start = time.monotonic()
        int(run_map[n](run_params, prompt)[0, -1])  # D2H fence
        samples.append(time.monotonic() - start)
    return statistics.median(samples)


def delta_rate(run_map, run_params):
    """Tokens/sec from the two-length delta, or None on inverted timing."""
    t_lo, t_hi = (timed(run_map, run_params, n) for n in (n_lo, n_hi))
    if t_hi <= t_lo:
        return None
    return batch * (n_hi - n_lo) / (t_hi - t_lo)

rate = delta_rate(make_runs(config), params)
if rate is None:
    print(json.dumps({"error": "non-positive decode timing delta"}))
    sys.exit(0)
result = {
    "decode_tokens_per_sec": rate,
    "per_stream_tokens_per_sec": rate / batch,
    "batch": batch, "new_tokens": n_hi,
    "device_kind": jax.devices()[0].device_kind,
}
# the base metric is now SAFE: the parent parses the LAST stdout line
# and salvages it even on a timeout kill, so the GQA phase below (two
# more flagship compiles) can never cost the numbers already measured
print(json.dumps(result), flush=True)

# GQA comparison: the SAME shape with grouped K/V heads — at this
# batch x context the KV cache's HBM reads rival the weights', so the
# group factor is a real decode lever and the artifact should show its
# measured worth, not a claim.
try:
    group = 4
    gqa_cfg = TransformerConfig(n_kv_heads=config.n_heads // group, **kw)
    gqa_params = init_transformer_params(jax.random.PRNGKey(0), gqa_cfg)
    gqa_rate = delta_rate(make_runs(gqa_cfg), gqa_params)
    if gqa_rate is None:
        result["gqa_error"] = "non-positive timing delta"
    else:
        result["gqa_decode_tokens_per_sec"] = gqa_rate
        result["gqa_kv_group"] = group
        result["gqa_decode_speedup"] = gqa_rate / rate
except Exception as e:
    result["gqa_error"] = repr(e)[:200]
print(json.dumps(result))
'''


def _measure_lm_decode(timeout=600):
    """KV-cache inference throughput on the flagship model family."""
    code = _LM_DECODE_SNIPPET % {
        'repo': os.path.dirname(os.path.abspath(__file__)),
        'flagship': FLAGSHIP_LM_KW}
    return _run_json_subprocess([sys.executable, '-c', code],
                                _clamp_timeout(timeout))


_PP_BF16_SNIPPET = r'''
import json, os, sys
sys.path.insert(0, %(repo)r)
import jax
if os.environ.get('BENCH_JAX_PLATFORM'):
    jax.config.update('jax_platforms', os.environ['BENCH_JAX_PLATFORM'])
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from petastorm_tpu.models.transformer import (
    TransformerConfig, init_pipelined_transformer_params,
    pipelined_transformer_train_step,
)

# The production dtype (bf16) for the pipelined (shard_map+scan+ppermute)
# step is exactly what XLA:CPU cannot compile (docs/troubleshoot.md), so
# the virtual-mesh dryrun pins f32 — this smoke validates bf16 pipelining
# on REAL hardware: jit-compile + one optimizer step on a 1-stage 'pipe'
# mesh on the chip (the single-device schedule runs the identical
# scan/ppermute lowering with a trivial permutation).
mesh = Mesh(np.array(jax.devices()[:1]), ('pipe',))
config = TransformerConfig(vocab_size=64, d_model=32, n_heads=2, n_layers=2,
                           d_ff=64, max_seq_len=16)  # dtype default: bf16
with mesh:
    params = init_pipelined_transformer_params(jax.random.PRNGKey(0),
                                               config, mesh)
    optimizer = optax.adamw(1e-3)
    step = pipelined_transformer_train_step(config, optimizer, mesh,
                                            n_microbatches=2)
    tokens = jax.device_put(
        np.random.RandomState(0).randint(0, 64, (4, 16), np.int32),
        NamedSharding(mesh, P(None, None)))
    _, _, loss = step(params, optimizer.init(params), tokens)
    loss = float(loss)
assert np.isfinite(loss), loss
print(json.dumps({"loss": loss,
                  "device_kind": jax.devices()[0].device_kind}))
'''


def _measure_pp_bf16(timeout=300):
    """VERDICT r2 #7: the bf16 pipelined train step has never executed
    anywhere (XLA:CPU crashes on it; the dryrun pins f32). Compile + step
    it on the real chip. Two attempts: the tunneled chip's backend init
    is observed to wedge transiently (whole-process hang before
    jax.devices() returns), and a healthy compile+step of this tiny
    config finishes in well under one attempt's timeout."""
    code = _PP_BF16_SNIPPET % {
        'repo': os.path.dirname(os.path.abspath(__file__))}
    argv = [sys.executable, '-c', code]
    result = _run_json_subprocess(argv, _clamp_timeout(timeout))
    if 'error' in result and not os.environ.get('BENCH_JAX_PLATFORM'):
        result = _run_json_subprocess(argv, _clamp_timeout(timeout))
    return result


def _measure_lm_train(url, batch=8, seq_len=1024, warmup=4, measure=16,
                      timeout=900, overrides=None):
    """END-TO-END training throughput on a realistically-sized (~335M
    param) transformer: Parquet docs → packed batches → device staging →
    real optimizer steps on the default device (the TPU chip under the
    driver). Reports MFU and input-bound step utilization — the
    BASELINE.json metric — alongside raw throughput.

    ``overrides`` (the ``lm_train_tuned`` section): execution-lever
    config fields merged over the flagship shape — remat/loss_chunk
    only, never dimensions, so MFU stays cross-round comparable."""
    if SMOKE:
        batch, seq_len, warmup, measure = 2, 64, 1, 2
    code = _LM_TRAIN_SNIPPET % {
        'repo': os.path.dirname(os.path.abspath(__file__)), 'url': url,
        'batch': batch, 'seq': seq_len, 'warmup': warmup,
        'measure': measure, 'flagship': FLAGSHIP_LM_KW,
        'overrides': dict(overrides or {}), 'peaks': TPU_PEAKS}
    return _run_json_subprocess([sys.executable, '-c', code],
                                _clamp_timeout(timeout))


def main():
    import numpy as np

    tmp = tempfile.mkdtemp(prefix='petastorm_tpu_bench_')
    hello_url = 'file://' + tmp + '/hello_world'
    imagenet_url = 'file://' + tmp + '/imagenet_like'
    c4_url = 'file://' + tmp + '/c4_like'
    selective_url = 'file://' + tmp + '/selective'
    io_overlap_url = 'file://' + tmp + '/io_overlap'
    mix_a_url = 'file://' + tmp + '/mixture_web'
    mix_b_url = 'file://' + tmp + '/mixture_code'
    write_bench_dir = tmp + '/write_plane'
    extra = {}
    state = {
        'metric': 'hello_world_read_rate',
        'value': 0.0,
        'unit': 'samples/sec',
        'vs_baseline': 0.0,
        'extra': extra,
    }

    def emit():
        """Cumulative result after every section: a kill at ANY point
        leaves the driver's last-line parse with everything finished so
        far (VERDICT r3 #1a). Small single-line writes + flush keep the
        line intact under an outer SIGKILL.

        Two lines per emit (VERDICT r4 #1): the full cumulative dict,
        then a compact headline line that stays the LAST stdout line and
        always fits the driver's 2,000-char tail — r04's record was lost
        because the single cumulative line outgrew that tail."""
        print(json.dumps(state), flush=True)
        # the wedge flag goes FIRST: popitem() drops most-recent-last, so
        # only genuine tail keys can ever fall off under the length cap
        head_extra = {}
        if 'tpu_wedged_midrun' in extra:
            head_extra['tpu_wedged_midrun'] = True
        head_extra.update((k, extra[k]) for k in _HEADLINE_EXTRA_KEYS
                          if k in extra)
        head = {'metric': state['metric'], 'value': state['value'],
                'unit': state['unit'], 'vs_baseline': state['vs_baseline'],
                'headline': True, 'extra': head_extra}
        line = json.dumps(head)
        while len(line) >= _HEADLINE_MAX_CHARS and head_extra:
            head_extra.popitem()  # insertion-ordered: least important last
            line = json.dumps(head)
        assert len(line) < _HEADLINE_MAX_CHARS, len(line)
        print(line, flush=True)

    def section(name, min_seconds, fn):
        """Deadline-gated, exception-isolated benchmark section."""
        if _remaining() < min_seconds:
            extra.setdefault('skipped_sections', []).append(name)
        else:
            try:
                fn()
            except Exception as e:  # noqa: BLE001 - a section must never
                extra[name + '_error'] = repr(e)[:300]  # kill the report
        emit()

    def jax_metrics(prefix, *args, fn=_measure_jax):
        result = fn(*args)
        if 'error' in result and not os.environ.get('BENCH_JAX_PLATFORM'):
            # Chip/tunnel wedged mid-run despite a healthy probe: still
            # record the staging path on the CPU backend, marked as such —
            # and KEEP the CPU pin for every later section. One wedge means
            # the link is gone for the run (observed: hours), and unpinning
            # would make each remaining section re-burn a full subprocess
            # timeout against the dead link before its own retry.
            os.environ['BENCH_JAX_PLATFORM'] = 'cpu'
            extra['tpu_wedged_midrun'] = result['error']
            cpu_result = fn(*args)
            if 'error' not in cpu_result:
                extra['%s_device' % prefix] = 'cpu-fallback'
                extra['%s_tpu_error' % prefix] = result['error']
                result = cpu_result
        for k, v in result.items():
            if isinstance(v, float):
                # keep 4 significant digits: rates are O(10^3)+ but
                # steps/sec and losses are O(1) and would be erased by
                # fixed 1-decimal rounding
                v = float('%.4g' % v)
            extra['%s_%s' % (prefix, k)] = v

    img_state = {}

    def sec_hello_row():
        import statistics
        _build_hello_world(hello_url)
        # the PRIMARY metric: median of MEDIAN_RUNS like the other headline
        # rates (a single draw on the shared box risks recording a stall)
        rate = statistics.median(
            _measure_rows(hello_url) for _ in range(MEDIAN_RUNS))
        state['value'] = round(rate, 2)
        state['vs_baseline'] = round(rate / BASELINE_SAMPLES_PER_SEC, 3)
        # The reference's tool reports rate + RSS + CPU% together (its
        # published hello-world row carries 217 MB / 136%). Reuse this
        # repo's throughput tool with its fresh-process mode — measuring
        # RSS on THIS long-lived driver process would report harness +
        # dataset-build memory, not the reader's footprint (the same
        # reason the reference re-spawns, throughput.py:144-149). One
        # owner of the metric definition; optional: a failure never
        # touches the primary rate above.
        try:
            from petastorm_tpu.benchmark.throughput import reader_throughput
            r = reader_throughput(hello_url, warmup_cycles=WARMUP_SAMPLES,
                                  measure_cycles=MEASURE_SAMPLES,
                                  spawn_new_process=True)
            extra['hello_world_rss_mb'] = round(r.memory_rss_mb, 1)
            extra['hello_world_cpu_percent'] = round(r.cpu_percent, 1)
        except Exception as e:  # noqa: BLE001 - accounting is optional
            extra['hello_world_rss_error'] = repr(e)[:200]

    def sec_hello_batch():
        warm, meas = (100, 600) if SMOKE else (1000, 8000)
        batch_rate, _ = _measure_batch(hello_url, warm, meas)
        extra['hello_world_batch_rows_per_sec'] = round(batch_rate, 1)

    def sec_decoded_cache():
        # Decode-once serve-many (the 71% io+decode share in BENCH_r05):
        # epoch 1 fills the materialized decoded-row-group cache (Arrow
        # IPC, cache_type='decoded'), epoch 2 must serve from it — the
        # warm/cold ratio and hit share are the record. Full sweeps, no
        # warmup: a warmup pass would pre-fill the cache and erase the
        # cold number.
        from petastorm_tpu.reader import make_batch_reader
        from petastorm_tpu.telemetry import get_registry, pipeline_report
        cache_dir = os.path.join(tmp, 'decoded_cache')

        def one_pass(epochs):
            # rate over the iteration only (construction is identical on
            # both sides and would otherwise drown the 1k-row sweep)
            with make_batch_reader(hello_url, reader_pool_type='thread',
                                   num_epochs=epochs,
                                   shuffle_row_groups=False,
                                   cache_type='decoded',
                                   cache_location=cache_dir,
                                   cache_size_limit=2 * 10 ** 9) as reader:
                seen = 0
                start = time.monotonic()
                for batch in reader:
                    seen += len(batch.id)
                return seen / (time.monotonic() - start)

        # the cold pass is exactly ONE epoch: its epoch 2 would already
        # be warm; the warm pass sweeps more to amortize scheduling noise
        cold_rate = one_pass(1)
        mid = get_registry().snapshot()
        warm_rate = one_pass(1 if SMOKE else 3)
        report = pipeline_report(baseline=mid)
        extra['hello_world_cold_epoch_rows_per_sec'] = round(cold_rate, 1)
        extra['hello_world_warm_epoch_rows_per_sec'] = round(warm_rate, 1)
        extra['decoded_cache_warm_speedup'] = round(warm_rate / cold_rate, 3)
        cache = report.get('decoded_cache') or {}
        if cache:
            extra['cache_hit_share'] = cache['hit_rate']
            extra['decoded_cache_warm_verdict'] = cache['verdict']

    def sec_selective_read():
        """Query-shaped reads (ISSUE 12): a range predicate at ~1%/10%/50%
        selectivity over the sorted-id jpeg dataset, pruned+late-
        materialized vs the decode-everything-then-filter oracle
        (PETASTORM_TPU_PUSHDOWN=0). The rate is the EFFECTIVE scan rate —
        dataset rows / epoch wall — because a selective read's value is
        how fast it disposes of the rows it does NOT want; rowgroups
        pruned is recorded so the speedup is attributable to pruning,
        not caching."""
        from petastorm_tpu import pushdown
        from petastorm_tpu.filters import FiltersPredicate
        from petastorm_tpu.reader import make_batch_reader
        from petastorm_tpu.telemetry import get_registry

        _build_selective(selective_url)

        # rung -> knob overrides: full fast path / late materialization
        # without plan-time pruning (attribution) / the
        # decode-everything-then-filter full-scan oracle
        modes = {'pruned': {},
                 'late_only': {'PETASTORM_TPU_PUSHDOWN_PRUNE': '0'},
                 'unpruned': {'PETASTORM_TPU_PUSHDOWN': '0'}}

        def one_epoch(cutoff, mode):
            saved = {k: os.environ.get(k) for k in modes[mode]}
            os.environ.update(modes[mode])
            try:
                start = time.monotonic()
                with make_batch_reader(
                        selective_url, reader_pool_type='thread',
                        shuffle_row_groups=False,
                        predicate=FiltersPredicate(
                            [('id', '<', cutoff)])) as reader:
                    delivered = sum(len(b.id) for b in reader)
                return time.monotonic() - start, delivered
            finally:
                for k, v in saved.items():
                    if v is None:
                        os.environ.pop(k, None)
                    else:
                        os.environ[k] = v

        registry = get_registry()
        pruned_before = registry.counter_value(pushdown.ROWGROUPS_PRUNED)
        late_before = registry.counter_value(pushdown.LATE_MATERIALIZED_ROWS)
        for label, fraction in (('1pct', 0.01), ('10pct', 0.10),
                                ('50pct', 0.50)):
            cutoff = max(1, int(SELECTIVE_ROWS * fraction))
            # warm the page cache + footer memo so every rung compares
            # steady-state read paths, not first-touch I/O
            one_epoch(cutoff, 'pruned')
            seconds = {}
            for mode in modes:
                seconds[mode], delivered = one_epoch(cutoff, mode)
                assert delivered == cutoff, (mode, delivered, cutoff)
                key = ('selective_read_%s_rows_per_sec' % label
                       if mode == 'pruned'
                       else 'selective_read_%s_%s_rows_per_sec'
                       % (label, mode))
                extra[key] = round(SELECTIVE_ROWS / seconds[mode], 1)
            extra['selective_read_%s_speedup' % label] = \
                round(seconds['unpruned'] / seconds['pruned'], 3)
        extra['selective_read_rowgroups_pruned'] = int(
            registry.counter_value(pushdown.ROWGROUPS_PRUNED)
            - pruned_before)
        # delta over this section only (warm-ups + all rungs), like the
        # pruned count — the absolute counter would absorb any earlier
        # predicate reader in the process
        extra['selective_read_late_materialized_rows'] = int(
            registry.counter_value(pushdown.LATE_MATERIALIZED_ROWS)
            - late_before)

    def sec_io_overlap():
        """Wire-speed I/O plane (ISSUE 15): cold-read rows/s with
        coalesced readahead vs the PETASTORM_TPU_READAHEAD=0 blocking
        oracle, both behind the same injected-latency filesystem
        (_SlowFS: every read request pays one fixed round trip — the
        remote/cold-storage shape the plane exists to hide). The oracle
        serializes per-column-chunk requests inside each worker; the
        plane fetches coalesced ranges depth-ahead on its own threads,
        so storage latency overlaps decode. Parity is asserted, not
        assumed: both runs must deliver the identical row multiset."""
        import fsspec

        from petastorm_tpu import readahead
        from petastorm_tpu.reader import make_batch_reader
        from petastorm_tpu import telemetry
        from petastorm_tpu.telemetry import get_registry

        _build_io_overlap(io_overlap_url)
        base_fs, _ = fsspec.core.url_to_fs(io_overlap_url)

        section_env = {'PETASTORM_TPU_READAHEAD_THREADS': '4',
                       'PETASTORM_TPU_READAHEAD_DEPTH': '8'}

        def one_epoch(oracle):
            env = dict(section_env)
            # BOTH sides pin the knob: an ambient =0 in the operator's
            # environment must not silently measure oracle-vs-oracle and
            # record a phantom ~1.0 "speedup"
            env['PETASTORM_TPU_READAHEAD'] = '0' if oracle else '1'
            saved = {k: os.environ.get(k) for k in env}
            os.environ.update(env)
            telemetry.refresh()
            try:
                fs = _SlowFS(base_fs, IO_OVERLAP_READ_DELAY_S)
                with make_batch_reader(io_overlap_url,
                                       reader_pool_type='thread',
                                       workers_count=2,
                                       shuffle_row_groups=False,
                                       filesystem=fs) as reader:
                    # rate over the DATA plane only: construction
                    # (row-group enumeration footers) is identical on
                    # both sides and would only compress the contrast
                    start = time.monotonic()
                    ids = sorted(int(i) for b in reader for i in b.id)
                    return time.monotonic() - start, ids
            finally:
                for k, v in saved.items():
                    if v is None:
                        os.environ.pop(k, None)
                    else:
                        os.environ[k] = v
                telemetry.refresh()

        registry = get_registry()
        before = {name: registry.counter_value(name) for name in
                  (readahead.READAHEAD_HITS, readahead.READAHEAD_MISSES,
                   readahead.READAHEAD_BYTES,
                   readahead.READAHEAD_COALESCED_READS)}
        ahead_s, ahead_ids = one_epoch(oracle=False)
        delta = {name: registry.counter_value(name) - before[name]
                 for name in before}
        oracle_s, oracle_ids = one_epoch(oracle=True)
        assert ahead_ids == oracle_ids, 'io_overlap parity broke'
        extra['io_overlap_parity'] = True
        extra['io_overlap_readahead_rows_per_sec'] = round(
            IO_OVERLAP_ROWS / ahead_s, 1)
        extra['io_overlap_oracle_rows_per_sec'] = round(
            IO_OVERLAP_ROWS / oracle_s, 1)
        extra['io_overlap_speedup'] = round(oracle_s / ahead_s, 3)
        served = (delta[readahead.READAHEAD_HITS]
                  + delta[readahead.READAHEAD_MISSES])
        assert served > 0, 'io_overlap: readahead plane never engaged'
        extra['io_overlap_hit_share'] = round(
            delta[readahead.READAHEAD_HITS] / served, 4)
        reads = delta[readahead.READAHEAD_COALESCED_READS]
        extra['io_overlap_mean_coalesced_kb'] = round(
            delta[readahead.READAHEAD_BYTES] / reads / 1024, 2) if reads \
            else 0.0

    def sec_mixture_stream():
        """Streaming mixture engine (ISSUE 17): two token corpora mixed
        3:1 by the arithmetic interleave and packed to fixed
        MIXTURE_SEQ_LEN rows — packed-token throughput, fill ratio, the
        interleave's realized-ratio deviation against an RNG-draw
        baseline, and the readahead hit share on the mixture path, with
        the PETASTORM_TPU_READAHEAD=0 pass as the exact-parity oracle
        (identical packed rows, bit for bit)."""
        from petastorm_tpu import readahead, telemetry
        from petastorm_tpu.mixture import (InterleaveSchedule,
                                           MixtureSource, MixtureSpec,
                                           MixtureStream,
                                           realized_deviation)
        from petastorm_tpu.telemetry import get_registry

        _build_mixture_source(mix_a_url, MIXTURE_DOCS_A, seed=1)
        _build_mixture_source(mix_b_url, MIXTURE_DOCS_B, seed=2)
        weights = [3, 1]

        def spec():
            return MixtureSpec(
                [MixtureSource('web', weights[0], url=mix_a_url),
                 MixtureSource('code', weights[1], url=mix_b_url)],
                seed=0, seq_len=MIXTURE_SEQ_LEN)

        def one_pass(oracle):
            env = {'PETASTORM_TPU_READAHEAD': '0' if oracle else '1',
                   'PETASTORM_TPU_READAHEAD_DEPTH': '8',
                   'PETASTORM_TPU_READAHEAD_THREADS': '4'}
            saved = {k: os.environ.get(k) for k in env}
            os.environ.update(env)
            telemetry.refresh()
            try:
                stream = MixtureStream(spec(), reader_pool_type='thread',
                                       workers_count=2)
                try:
                    start = time.monotonic()
                    rows = list(stream)
                    elapsed = time.monotonic() - start
                    return elapsed, rows, stream.pack_stats
                finally:
                    stream.stop()
                    stream.join()
            finally:
                for k, v in saved.items():
                    if v is None:
                        os.environ.pop(k, None)
                    else:
                        os.environ[k] = v
                telemetry.refresh()

        registry = get_registry()
        before = {name: registry.counter_value(name) for name in
                  (readahead.READAHEAD_HITS, readahead.READAHEAD_MISSES)}
        ahead_s, ahead_rows, stats = one_pass(oracle=False)
        delta = {name: registry.counter_value(name) - before[name]
                 for name in before}
        oracle_s, oracle_rows, _ = one_pass(oracle=True)
        assert len(ahead_rows) == len(oracle_rows) and all(
            np.array_equal(a[k], b[k])
            for a, b in zip(ahead_rows, oracle_rows)
            for k in ('tokens', 'loss_mask', 'segment_ids')), \
            'mixture_stream readahead parity broke'
        extra['mixture_parity'] = True
        extra['mixture_packed_tokens_per_sec'] = round(
            stats['tokens'] / ahead_s, 1)
        extra['mixture_oracle_packed_tokens_per_sec'] = round(
            stats['tokens'] / oracle_s, 1)
        extra['mixture_fill_ratio'] = round(stats['fill_ratio'], 4)
        extra['mixture_rows'] = stats['rows']
        extra['mixture_split_doc_share'] = round(
            stats['split_docs'] / max(1, stats['docs']), 4)
        served = (delta[readahead.READAHEAD_HITS]
                  + delta[readahead.READAHEAD_MISSES])
        if served:
            extra['mixture_readahead_hit_share'] = round(
                delta[readahead.READAHEAD_HITS] / served, 4)
        # interleave-vs-RNG divergence: worst realized-ratio deviation
        # over 2k positions — the arithmetic schedule holds a hard O(1)
        # bound where RNG draws wander O(sqrt(n))
        k = 2000
        order = InterleaveSchedule.order(weights, seed=0, start=0, k=k)
        extra['mixture_interleave_deviation'] = round(
            realized_deviation(order, weights), 3)
        rng = np.random.RandomState(0)
        share = weights[0] / float(sum(weights))
        rng_order = [0 if draw < share else 1
                     for draw in rng.random_sample(k)]
        extra['mixture_rng_deviation'] = round(
            realized_deviation(rng_order, weights), 3)

    def sec_write_throughput():
        """Distributed write plane (ISSUE 18): commit-to-commit rows/s
        and MB/s for the local (pool=None, shards run inline) and fleet
        (ServicePool subprocess workers) backends over the same row
        stream — backend byte-parity asserted via the committed
        manifests, which carry no timestamps. Then the compaction
        story: small appended generations read before and after
        compact_dataset folds them, for the read-speed delta the
        re-shard service exists to buy."""
        from petastorm_tpu.reader import make_batch_reader
        from petastorm_tpu.service.service_pool import ServicePool
        from petastorm_tpu.write import compact_dataset
        from petastorm_tpu.write import manifest as wmanifest
        from petastorm_tpu.write import write_dataset_distributed

        schema = _write_bench_schema()
        rows = [{'id': i, 'payload': 'payload-%06d|' % i * 8}
                for i in range(WRITE_BENCH_ROWS)]

        def one_write(name, pool):
            url = 'file://' + write_bench_dir + '/' + name
            start = time.monotonic()
            writer = write_dataset_distributed(
                url, schema, rows, sort_by='id',
                shard_rows=WRITE_BENCH_SHARD_ROWS, pool=pool)
            elapsed = time.monotonic() - start
            nbytes = sum(e['bytes'] for e in writer.manifest['files'])
            return elapsed, nbytes, writer

        local_s, local_bytes, w_local = one_write('local', None)
        fleet_s, fleet_bytes, w_fleet = one_write(
            'fleet', ServicePool(spawn_local_workers=4,
                                 heartbeat_interval_s=0.2,
                                 liveness_timeout_s=2.0,
                                 connect_timeout_s=60,
                                 no_workers_timeout_s=30))
        assert wmanifest.dumps(w_local.manifest) == \
            wmanifest.dumps(w_fleet.manifest), 'write backend parity broke'
        extra['write_parity'] = True
        extra['write_rows_per_sec'] = round(WRITE_BENCH_ROWS / local_s, 1)
        extra['write_mb_per_sec'] = round(
            local_bytes / local_s / (1024 * 1024), 2)
        extra['write_fleet_rows_per_sec'] = round(
            WRITE_BENCH_ROWS / fleet_s, 1)
        extra['write_fleet_mb_per_sec'] = round(
            fleet_bytes / fleet_s / (1024 * 1024), 2)
        check = w_local.last_self_check
        if check:
            extra['write_selfcheck_prune_share'] = round(
                check['predicted_prune_share'], 4)
            extra['write_selfcheck_fits_window_share'] = round(
                check['coalesce']['fits_window_share'], 4)

        # compaction before/after: many small generations vs the fold
        compact_url = 'file://' + write_bench_dir + '/compact'
        for gen in range(WRITE_COMPACT_GENS):
            write_dataset_distributed(
                compact_url, schema,
                [{'id': i, 'payload': 'payload-%06d|' % i * 8}
                 for i in range(gen * WRITE_COMPACT_GEN_ROWS,
                                (gen + 1) * WRITE_COMPACT_GEN_ROWS)],
                sort_by='id', shard_rows=WRITE_COMPACT_GEN_ROWS // 4,
                append=(gen > 0))

        def read_s():
            best = None
            for _ in range(3):
                start = time.monotonic()
                with make_batch_reader(compact_url,
                                       shuffle_row_groups=False) as r:
                    total = sum(len(b.id) for b in r)
                elapsed = time.monotonic() - start
                assert total == WRITE_COMPACT_GENS * WRITE_COMPACT_GEN_ROWS
                best = elapsed if best is None else min(best, elapsed)
            return best

        before_files = len(wmanifest.load(
            *_write_bench_fs(compact_url))['files'])
        before_s = read_s()
        compacted = compact_dataset(compact_url, minimum=2)
        assert compacted is not None, 'write bench compaction planned nothing'
        after_s = read_s()
        extra['write_compact_files_before'] = before_files
        extra['write_compact_files_after'] = len(compacted['files'])
        extra['write_compact_read_speedup'] = round(before_s / after_s, 3)

    def sec_critpath():
        """Critical-path engine (ISSUE 19): a fully-traced hello-world
        read, then the sweep-line analysis over its flight recorder —
        the analysis' wall-clock share of the traced read (the <2%
        overhead budget the perf-marked test also gates), the
        critical-path bottleneck stage and the top what-if projection."""
        from petastorm_tpu import telemetry
        from petastorm_tpu.reader import make_reader
        from petastorm_tpu.telemetry import critpath, recorder

        if not os.path.isdir(tmp + '/hello_world'):
            _build_hello_world(hello_url)
        env = {'PETASTORM_TPU_TRACE': '1',
               'PETASTORM_TPU_TRACE_SAMPLE': '1'}
        saved = {k: os.environ.get(k) for k in env}
        os.environ.update(env)
        telemetry.refresh()
        try:
            start = time.monotonic()
            with make_reader(hello_url, reader_pool_type='thread',
                             workers_count=2, num_epochs=1,
                             shuffle_row_groups=False) as reader:
                rows = sum(1 for _ in reader)
            traced_s = time.monotonic() - start
            assert rows == HELLO_ROWS, rows
            start = time.monotonic()
            report = critpath.analyze()
            analyze_s = time.monotonic() - start
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
            telemetry.refresh()
            recorder.reset_recorder()
        assert report is not None, 'traced read recorded no stage events'
        extra['critpath_overhead_share'] = round(analyze_s / traced_s, 4)
        extra['critpath_bottleneck'] = report['bottleneck']
        extra['critpath_events'] = report['events']
        if report['what_if']:
            top = report['what_if'][0]
            extra['critpath_top_whatif'] = '%s => %+.1f%%' % (
                top['scenario'], top['epoch_delta_pct'])

    def sec_service():
        # Standing-service HA record (docs/service.md, "High
        # availability"): SIGKILL a subprocess primary mid-job with a
        # warm in-process standby attached and measure the delivery
        # blackout — kill to first post-promotion row at the client.
        # Then a second job with the identical decode fingerprint binds
        # against the promoted daemon's warm fleet for the
        # placement-hit share.
        import signal as _signal

        from petastorm_tpu.service.daemon import DaemonClientPool
        from petastorm_tpu.service.protocol import free_tcp_port
        from petastorm_tpu.service.standby import StandbyDaemon
        from petastorm_tpu.workers.worker_base import WorkerBase

        class _Echo(WorkerBase):  # shipped to the workers via dill
            def process(self, value):
                self.publish_func(value)

        repo = os.path.dirname(os.path.abspath(__file__))
        env = dict(os.environ, PYTHONPATH=repo, JAX_PLATFORMS='cpu')
        endpoint = 'tcp://127.0.0.1:%d' % free_tcp_port()
        procs = [subprocess.Popen(
            [sys.executable, '-m', 'petastorm_tpu.service',
             '--endpoint', endpoint, '--no-supervisor',
             '--heartbeat-interval', '0.2'], env=env)]
        procs += [subprocess.Popen(
            [sys.executable, '-m', 'petastorm_tpu.service.worker_server',
             '--endpoint', endpoint, '--heartbeat-interval', '0.2',
             '--ack-timeout', '2', '--parent-pid', str(os.getpid())],
            env=env) for _ in range(2)]
        standby = None
        pools = []
        try:
            pool = DaemonClientPool(endpoint, name='bench-ha',
                                    heartbeat_interval_s=0.2,
                                    ack_timeout_s=1.5,
                                    connect_timeout_s=60)
            pools.append(pool)
            pool.start(_Echo, worker_args={'placement_group': 'bench-ha'})
            standby = StandbyDaemon(endpoint, sync_interval_s=0.2,
                                    lapse_s=1.0, supervise=False,
                                    heartbeat_interval_s=0.2)
            standby.start()
            n = 50 if SMOKE else 200
            for i in range(n):
                pool.ventilate(i)
            got = [pool.get_results(timeout=60) for _ in range(n // 4)]
            t_kill = time.monotonic()
            os.kill(procs[0].pid, _signal.SIGKILL)
            procs[0].wait()
            # blackout at the SERVICE plane: kill → the first row
            # delivered through the promoted incarnation (client
            # re-registration and re-submission included) — the local
            # results buffer can't fake this number
            standby.wait_promoted(60)
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                promoted = standby.daemon
                if promoted is not None and promoted.dispatcher.health()[
                        'items_completed'] > 0:
                    break
                time.sleep(0.02)
            extra['service_failover_blackout_s'] = round(
                time.monotonic() - t_kill, 2)
            while len(got) < n:
                got.append(pool.get_results(timeout=60))
            extra['service_failover_exact'] = sorted(got) == list(range(n))
            pools.remove(pool)
            pool.stop()
            pool.join()
            second = DaemonClientPool(endpoint, name='bench-warm',
                                      heartbeat_interval_s=0.2,
                                      ack_timeout_s=1.5,
                                      connect_timeout_s=60)
            pools.append(second)
            second.start(_Echo,
                         worker_args={'placement_group': 'bench-ha'})
            for i in range(50):
                second.ventilate(i)
            for _ in range(50):
                second.get_results(timeout=60)
            health = standby.health()
            placed = (health.get('placement_hits', 0)
                      + health.get('placement_misses', 0))
            if placed:
                extra['service_placement_hit_share'] = round(
                    health['placement_hits'] / placed, 3)
        finally:
            for p in pools:
                p.stop()
                p.join()
            if standby is not None:
                standby.stop()
            for p in procs:
                if p.poll() is None:
                    p.terminate()
            for p in procs:
                try:
                    p.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    p.kill()
                    p.wait()

    def sec_peer_cache():
        # Fleet cache tier record (docs/service.md, "Fleet cache
        # tier"): two worker servers with DISJOINT host-local cache
        # directories over one hot dataset. Epoch 1 decodes each
        # row-group exactly once fleet-wide (cold fill with an injected
        # decode cost). Epoch 2 is a fresh job over the same keys: the
        # decoding host serves its own items from its disk tier and the
        # OTHER host's items arrive by peer fetch — so the headline is
        # the share of warm-epoch items served without a fresh decode.
        from petastorm_tpu.materialized_cache import (
            MaterializedRowGroupCache,
        )
        from petastorm_tpu.service.daemon import DaemonClientPool
        from petastorm_tpu.service.protocol import free_tcp_port
        from petastorm_tpu.workers.worker_base import WorkerBase

        class _FleetDecode(WorkerBase):  # shipped to the workers via dill
            def process(self, item):
                import time as _time

                import numpy as _np

                from petastorm_tpu.arrow_worker import ColumnBatch
                decoded = []

                def fill():
                    decoded.append(True)
                    _time.sleep(self.args['decode_s'])
                    cols = {'v': _np.full(256, item, dtype=_np.int64)}
                    return ColumnBatch(cols, 256)

                batch = self.args['cache'].get(('bench-peer', item), fill)
                self.publish_func((item, bool(decoded),
                                   int(batch.columns['v'][0])))

        repo = os.path.dirname(os.path.abspath(__file__))
        env = dict(os.environ, PYTHONPATH=repo, JAX_PLATFORMS='cpu')
        endpoint = 'tcp://127.0.0.1:%d' % free_tcp_port()
        procs = [subprocess.Popen(
            [sys.executable, '-m', 'petastorm_tpu.service',
             '--endpoint', endpoint, '--no-supervisor',
             '--heartbeat-interval', '0.2'], env=env)]
        procs += [subprocess.Popen(
            [sys.executable, '-m', 'petastorm_tpu.service.worker_server',
             '--endpoint', endpoint, '--heartbeat-interval', '0.2',
             '--ack-timeout', '2', '--parent-pid', str(os.getpid()),
             '--cache-dir', os.path.join(tmp, 'peer_host%d' % i)],
            env=env) for i in range(2)]
        cache = MaterializedRowGroupCache(
            os.path.join(tmp, 'peer_seed'), disk_limit_bytes=1 << 30,
            mem_limit_bytes=0)
        n = 24 if SMOKE else 48
        pools = []

        def one_epoch(name):
            pool = DaemonClientPool(endpoint, name=name,
                                    heartbeat_interval_s=0.2,
                                    ack_timeout_s=2,
                                    connect_timeout_s=60)
            pools.append(pool)
            pool.start(_FleetDecode,
                       worker_args={'cache': cache, 'decode_s': 0.02,
                                    'placement_group': 'bench-peer'})
            start = time.monotonic()
            for i in range(n):
                pool.ventilate(i)
            rows = [pool.get_results(timeout=60) for _ in range(n)]
            elapsed = time.monotonic() - start
            pools.remove(pool)
            pool.stop()
            pool.join()
            return rows, elapsed

        try:
            cold_rows, cold_s = one_epoch('bench-peer-cold')
            warm_rows, warm_s = one_epoch('bench-peer-warm')
            decodes = sum(1 for _, was_decoded, _v in warm_rows
                          if was_decoded)
            extra['peer_hit_share'] = round((n - decodes) / n, 3)
            extra['peer_cache_warm_decodes'] = decodes
            extra['peer_cache_cold_epoch_s'] = round(cold_s, 2)
            extra['peer_cache_warm_epoch_s'] = round(warm_s, 2)
            if warm_s > 0:
                extra['peer_cache_warm_speedup'] = round(cold_s / warm_s, 2)
            extra['peer_cache_exact'] = (
                sorted(v for _i, _d, v in warm_rows)
                == sorted(v for _i, _d, v in cold_rows)
                == list(range(n)))
        finally:
            for p in pools:
                p.stop()
                p.join()
            for p in procs:
                if p.poll() is None:
                    p.terminate()
            for p in procs:
                try:
                    p.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    p.kill()
                    p.wait()

    def sec_lm_tokens():
        _build_c4_like(c4_url)
        extra['lm_packed_tokens_per_sec'] = round(_measure_lm_tokens(c4_url),
                                                  1)

    def sec_imagenet():
        _build_imagenet_like(imagenet_url)
        img_bytes = int(np.prod(IMAGENET_SHAPE))
        # median of MEDIAN_RUNS: the shared box is noisy (single runs
        # swing +-10%) and this is the north-star rate
        img_runs = sorted(
            (_measure_batch(imagenet_url, IMAGENET_ROWS // 2,
                            IMAGENET_ROWS * 4, bytes_per_row=img_bytes)
             for _ in range(MEDIAN_RUNS)), key=lambda pair: pair[0])
        img_rate, img_mb = img_runs[MEDIAN_RUNS // 2]
        img_state['rate'] = img_rate
        extra['imagenet_batch_rows_per_sec'] = round(img_rate, 1)
        extra['imagenet_decoded_mb_per_sec'] = round(img_mb, 1)

    def sec_imagenet_python_decode():
        """Native C decoders OFF (pure-Python/cv2 fallback): the native
        layer's measured win on the same bytes (VERDICT r3 #8). The
        toggle is live per-call, so an in-process re-run measures the
        fallback path; the default (native on when built) is what the
        main imagenet section measured. Only a real comparison is
        reported: if the main run itself used the fallback (no built
        jpeg extension, or an ambient kill-switch) a 'speedup' would be
        ~1.0 noise posing as the native layer's win."""
        from petastorm_tpu.native import get_jpeg_module, native_disabled
        if native_disabled():
            extra['native_decode'] = 'disabled-by-env'
            return
        if get_jpeg_module() is None:
            extra['native_decode'] = 'unavailable'
            return
        img_bytes = int(np.prod(IMAGENET_SHAPE))
        saved = os.environ.get('PETASTORM_TPU_NATIVE')
        os.environ['PETASTORM_TPU_NATIVE'] = '0'
        try:
            py_rate, py_mb = _measure_batch(
                imagenet_url, IMAGENET_ROWS // 2, IMAGENET_ROWS * 4,
                bytes_per_row=img_bytes)
        finally:
            if saved is None:
                del os.environ['PETASTORM_TPU_NATIVE']
            else:
                os.environ['PETASTORM_TPU_NATIVE'] = saved
        extra['imagenet_python_decode_rows_per_sec'] = round(py_rate, 1)
        extra['imagenet_python_decode_mb_per_sec'] = round(py_mb, 1)
        if img_state.get('rate'):
            extra['native_decode_speedup'] = round(
                img_state['rate'] / py_rate, 3)

    def sec_fused_decode():
        """ISSUE 9's decode-granularity ladder at the 224² north-star
        shape, on the SAME jpeg bytes the imagenet sections read: python
        per-cell (cv2, the oracle), native per-cell (one C call per
        image — the old dispatch granularity whose win capped at ~1.15×),
        and native batched (ONE C call for the whole column, cells fanned
        across the internal pthread pool). The fused-into-slot rung is
        loader-level and lives in the imagenet_jax section — its
        ``fused_decode_mode`` key says whether fusion engaged there."""
        import glob
        import statistics

        import pyarrow.parquet as pq

        from petastorm_tpu.codecs import (
            CompressedImageCodec, image_decoder_threads,
        )
        from petastorm_tpu.native import get_jpeg_module, native_disabled
        from petastorm_tpu.unischema import UnischemaField

        root = imagenet_url[len('file://'):]
        cells = []
        for path in sorted(glob.glob(root + '/*.parquet')):
            cells.extend(
                bytes(c) for c in pq.read_table(path, columns=['image'])
                .column('image').to_pylist())
        cells = cells[:64 if SMOKE else 256]
        n = len(cells)
        codec = CompressedImageCodec('jpeg', quality=90)
        field = UnischemaField('image', np.uint8, IMAGENET_SHAPE, codec,
                               False)
        out = np.empty((n,) + IMAGENET_SHAPE, np.uint8)

        def rate(fn, reps=1 if SMOKE else 3):
            fn()  # warm (page-in, mode calibration, pool spin-up)
            samples = []
            for _ in range(reps):
                start = time.monotonic()
                fn()
                samples.append(time.monotonic() - start)
            return n / statistics.median(samples)

        py_rate = rate(lambda: [codec.decode(field, c) for c in cells])
        extra['fused_decode_per_image_rows_per_sec'] = round(py_rate, 1)
        if native_disabled() or get_jpeg_module() is None:
            extra['fused_decode_native'] = 'unavailable'
            return
        decode_fn = get_jpeg_module().decode_jpeg_batch
        # BOTH native rungs decode with the SAME chroma-upsampling mode —
        # the one the codec's calibration picked (what decode_batch below
        # uses) — so the batched-vs-per-cell ratio measures the batching
        # win alone, never a mode delta
        from petastorm_tpu.codecs import _jpeg_upsampling_mode
        mode = _jpeg_upsampling_mode(decode_fn, cells, IMAGENET_SHAPE)
        extra['fused_decode_jpeg_mode'] = mode

        def native_per_cell():
            for i in range(n):
                decode_fn(cells[i:i + 1], out[i:i + 1], mode, 1)

        cell_rate = rate(native_per_cell)
        batched_rate = rate(
            lambda: codec.decode_batch(field, cells, out=out))
        extra['fused_decode_native_per_cell_rows_per_sec'] = \
            round(cell_rate, 1)
        extra['fused_decode_batched_rows_per_sec'] = round(batched_rate, 1)
        extra['fused_decode_batched_vs_per_cell'] = \
            round(batched_rate / cell_rate, 3)
        extra['fused_decode_native_threads'] = image_decoder_threads()

    def sec_tfdata():
        # North star (BASELINE.json): ratio vs a tf.data+TFRecord pipeline
        # decoding the SAME jpeg bytes on the same machine. Target >= 0.9.
        tfrecord_path, build_error = _build_tfrecord(imagenet_url)
        if build_error:
            extra['tfdata_imagenet_error'] = build_error
            return
        runs = [_measure_tfdata(tfrecord_path, IMAGENET_ROWS // 2,
                                IMAGENET_ROWS * 4)
                for _ in range(TFDATA_RUNS)]
        os.unlink(tfrecord_path)
        ok_rates = sorted(r['rows_per_sec'] for r in runs
                          if 'rows_per_sec' in r)
        if ok_rates:
            import statistics
            tf_rate = statistics.median(ok_rates)
            extra['tfdata_imagenet_rows_per_sec'] = round(tf_rate, 1)
            if img_state.get('rate'):
                extra['vs_tfdata'] = round(img_state['rate'] / tf_rate, 3)
        else:
            extra['tfdata_imagenet_error'] = runs[-1].get('error', 'unknown')

    def sec_jax_hello():
        warm, meas = (128, 1024) if SMOKE else (1024, 8192)
        jax_metrics('hello_world_jax', hello_url, 256, warm, meas,
                    ['^id$', '^array_4d$', '^image1$'])

    def sec_jax_imagenet():
        jax_metrics('imagenet_jax', imagenet_url, IMAGENET_JAX_BATCH,
                    IMAGENET_ROWS // 2, IMAGENET_ROWS * 3, ['^image$'])
        # headline-named copy: the BENCH_r0x record must say whether the
        # imagenet H2D number rode the fused decode path or a fallback
        if 'imagenet_jax_fused_decode_mode' in extra:
            extra['fused_decode_mode'] = \
                extra['imagenet_jax_fused_decode_mode']
        # Attribution marker: when even a RAW device_put tight loop cannot
        # reach 1 GB/s, the H2D ceiling is the link (a degraded tunnel),
        # not the staging layer — h2d_efficiency (loader/raw) close to or
        # above 1.0 in the same run confirms staging adds nothing on top.
        # Only meaningful when a real device link was measured: the
        # cpu-fallback path records host-to-host rates.
        raw_gb = extra.get('imagenet_jax_raw_h2d_gb_per_sec')
        if (raw_gb is not None and raw_gb < 1.0
                and extra.get('imagenet_jax_device') != 'cpu-fallback'
                and not os.environ.get('BENCH_JAX_PLATFORM')):
            # (the env check covers probe-pinned and operator-forced CPU
            # runs, where no real device link was measured)
            extra['h2d_link_degraded'] = True

    def sec_jax_dummy():
        # VERDICT r4 #4: the same loader consumer over a DummyBatchReader
        # source (zero I/O, zero decode) decomposes the imagenet_jax
        # sec/row into framework-staging vs I/O+decode vs H2D-link. The
        # raw-H2D calibration from sec_jax_imagenet provides the
        # link-only term; shares are clamped at 0 (on a degraded tunnel
        # the loader overlaps H2D better than the raw loop, so the
        # staging term can measure negative — meaning it adds nothing).
        warm, meas = (128, 512) if SMOKE else (IMAGENET_ROWS // 2,
                                               IMAGENET_ROWS * 3)
        jax_metrics('imagenet_jax_dummy', IMAGENET_JAX_BATCH, warm, meas,
                    IMAGENET_SHAPE, fn=_measure_jax_dummy)
        if (extra.get('imagenet_jax_device')
                != extra.get('imagenet_jax_dummy_device')):
            # a mid-run wedge put the two runs on DIFFERENT devices (one
            # real, one cpu-fallback): subtracting their rates would mix
            # devices into a bogus headline decomposition
            extra['jax_share_skipped'] = 'device mismatch'
        else:
            shares = compute_staging_shares(
                extra.get('imagenet_jax_rows_per_sec'),
                extra.get('imagenet_jax_dummy_rows_per_sec'),
                extra.get('imagenet_jax_raw_h2d_mb_per_sec'),
                extra.get('imagenet_jax_staged_bytes_per_batch'),
                IMAGENET_JAX_BATCH)
            if shares:
                extra.update(shares)

    def sec_sharded_staging():
        # Shard-aware staging engine (ISSUE 14): global jax.Array batches
        # onto a data mesh over every visible device — aggregate GB/s,
        # per-host overlap share, staged-vs-raw h2d efficiency (chasing
        # the r05 0.035 on the staged path; the dummy source isolates
        # the staging layer from decode), and the autotuner's decision
        # log, so rounds are attributable when a knob moved mid-run.
        warm, meas = (128, 512) if SMOKE else (256, 3072)
        jax_metrics('sharded_staging', IMAGENET_JAX_BATCH, warm, meas,
                    IMAGENET_SHAPE, fn=_measure_sharded_staging)

    def sec_vit_train():
        # image-family silicon throughput (VERDICT r4 #7): ViT-Base-dims
        # train steps from in-HBM batches — steps/s, images/s, MFU
        jax_metrics('vit_train', fn=_measure_vit_train)

    def sec_lm_train():
        # end-to-end TRAINING throughput on the default device: Parquet →
        # packed batches → H2D → real transformer optimizer steps. Runs
        # immediately after the probe, so the chip's health is at most
        # one section old when the most valuable capture starts.
        jax_metrics('lm_train', c4_url, fn=_measure_lm_train)

    def sec_mfu_breakdown():
        # VERDICT r4 #3: where the non-MXU half of the flagship step
        # goes. Part-times from the subprocess + lm_train's own step
        # time and matmul calibration combine into shares of the COMPUTE
        # step (input wait reported separately from input_bound_util).
        jax_metrics('mfu_parts', fn=_measure_mfu_breakdown)
        shares = compute_mfu_breakdown(
            extra.get('lm_train_steps_per_sec'),
            extra.get('lm_train_input_bound_util'),
            extra.get('lm_train_measured_matmul_tflops'),
            {'attn_measured': extra.get('mfu_parts_attn_total_ms'),
             'norms_measured': extra.get('mfu_parts_norm_total_ms'),
             'loss_head_measured': extra.get('mfu_parts_loss_head_ms')})
        if shares:
            extra['lm_train_mfu_breakdown'] = shares

    def sec_lm_train_tuned():
        # VERDICT r4 #3: a separately-keyed tuned variant — flagship
        # DIMENSIONS untouched (cross-round MFU comparability lives in
        # lm_train); only execution levers move here. remat=True frees
        # the activation HBM that capped the flagship at batch 8, and the
        # larger per-core batch amortizes the non-MXU per-step work the
        # breakdown section quantifies.
        # bounded timeout: a pathological compile here must not starve
        # the sections that follow (lm_train's own 900s is for the single
        # most valuable capture; this is the experiment, not the record).
        # Ladder: if batch 16 under remat doesn't fit/compile on this
        # chip, batch 12 still tests the amortization hypothesis — an
        # error should cost one rung, not the whole experiment.
        def tuned(url):
            result = {'error': 'no tuned rung ran'}
            for b in (16, 12):
                result = _measure_lm_train(url, batch=b,
                                           overrides=dict(remat=True),
                                           timeout=420)
                if 'error' not in result:
                    result['batch'] = b
                    return result
                # setdefault: the CPU-fallback re-invocation must not
                # overwrite the TPU rung's diagnostic (OOM vs wedge)
                extra.setdefault('lm_train_tuned_b%d_error' % b,
                                 result['error'][:200])
                if 'timeout' in result['error']:
                    # a timed-out rung means a dead/wedged link, not a
                    # too-big batch — a second rung would re-burn 420s
                    # against it and starve the sections that follow
                    break
            return result

        jax_metrics('lm_train_tuned', c4_url, fn=tuned)

    def sec_lm_decode():
        # inference: KV-cache greedy decode rate on the same model family
        jax_metrics('lm_decode', fn=_measure_lm_decode)

    def sec_pp_bf16():
        # bf16 pipelined train step smoke — meaningful on the real chip
        # (the 1-stage shape happens to compile on current XLA:CPU too,
        # so a CPU run must be LABELED as such, not pass as validation)
        jax_metrics('pp_bf16', fn=_measure_pp_bf16)
        if (extra.get('pp_bf16_device_kind') == 'cpu'
                and 'pp_bf16_device' not in extra):
            extra['pp_bf16_device'] = 'cpu-fallback'

    try:
        # Cumulative emission means finished sections are never lost, so
        # the order IS the value ranking under budget pressure: the cheap
        # host sections that secure the primary metric (and build the
        # datasets later sections read), then the probe, then lm_train
        # FIRST among the expensive sections — the MFU / input-bound-util
        # capture is the single most valuable device number (VERDICT r3
        # #2) and must not queue behind tf.data subprocess startups that
        # can eat minutes each on a loaded box. tfdata (the north-star
        # ratio) follows, then the H2D story, decode, pp smoke.
        section('hello_row', 10, sec_hello_row)
        section('hello_batch', 5, sec_hello_batch)
        section('decoded_cache', 10, sec_decoded_cache)
        section('selective_read', 15, sec_selective_read)
        section('io_overlap', 10, sec_io_overlap)
        section('mixture_stream', 15, sec_mixture_stream)
        section('write_throughput', 15, sec_write_throughput)
        section('critpath', 10, sec_critpath)
        section('service', 20, sec_service)
        section('peer_cache', 15, sec_peer_cache)
        section('lm_tokens', 10, sec_lm_tokens)
        section('imagenet', 20, sec_imagenet)
        section('probe', 20, lambda: _probe_tpu(extra))
        section('lm_train', 60, sec_lm_train)
        section('tfdata', 30, sec_tfdata)
        section('imagenet_python_decode', 10, sec_imagenet_python_decode)
        section('fused_decode', 15, sec_fused_decode)
        section('jax_imagenet', 30, sec_jax_imagenet)
        # proven captures (decode/GQA) run before the round-5 sections
        # (vit/tuned/breakdown) — a new section's worst-case compile must
        # never squeeze a number the ledger already tracks
        section('jax_dummy', 20, sec_jax_dummy)
        section('sharded_staging', 25, sec_sharded_staging)
        section('lm_decode', 45, sec_lm_decode)
        section('vit_train', 45, sec_vit_train)
        section('lm_train_tuned', 60, sec_lm_train_tuned)
        section('mfu_breakdown', 60, sec_mfu_breakdown)
        section('jax_hello', 30, sec_jax_hello)
        section('pp_bf16', 30, sec_pp_bf16)
        extra['bench_elapsed_sec'] = round(time.monotonic() - _START, 1)
        emit()
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == '__main__':
    main()
