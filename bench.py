"""Round benchmark: hello-world dataset read rate vs the reference baseline.

Replicates the reference's only published absolute number — the
``petastorm-throughput.py`` hello-world read rate of 709.84 samples/sec with
3 thread workers (``docs/benchmarks_tutorial.rst:20-21``) — against this
framework's reader on an equivalent dataset (id + 128-float array + 32x32
png image per row, mirroring ``examples/hello_world``'s schema shape).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Deliberately host-only (no jax import): the read path is the benchmarked
surface, and touching an accelerator here could wedge on a busy chip.
"""

import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

BASELINE_SAMPLES_PER_SEC = 709.84  # reference: docs/benchmarks_tutorial.rst:20

WARMUP_SAMPLES = 300
MEASURE_SAMPLES = 3000


def _build_dataset(url):
    import numpy as np
    import pyarrow as pa

    from petastorm_tpu.codecs import (
        CompressedImageCodec, NdarrayCodec, ScalarCodec,
    )
    from petastorm_tpu.etl.dataset_metadata import write_dataset
    from petastorm_tpu.unischema import Unischema, UnischemaField

    schema = Unischema('HelloWorldSchema', [
        UnischemaField('id', np.int32, (), ScalarCodec(pa.int32()), False),
        UnischemaField('array_4d', np.uint8, (128,), NdarrayCodec(), False),
        UnischemaField('image1', np.uint8, (32, 32, 3),
                       CompressedImageCodec('png'), False),
    ])
    rng = np.random.RandomState(42)
    rows = [{
        'id': i,
        'array_4d': rng.randint(0, 255, (128,), dtype=np.uint8),
        'image1': rng.randint(0, 255, (32, 32, 3), dtype=np.uint8),
    } for i in range(1000)]
    write_dataset(url, schema, rows, rowgroup_size_rows=100, num_files=4)


def main():
    from petastorm_tpu.reader import make_reader

    tmp = tempfile.mkdtemp(prefix='petastorm_tpu_bench_')
    url = 'file://' + tmp + '/hello_world'
    try:
        _build_dataset(url)
        with make_reader(url, reader_pool_type='thread', workers_count=3,
                         num_epochs=None, shuffle_row_groups=True) as reader:
            for _ in range(WARMUP_SAMPLES):
                next(reader)
            start = time.monotonic()
            for _ in range(MEASURE_SAMPLES):
                next(reader)
            elapsed = time.monotonic() - start
        rate = MEASURE_SAMPLES / elapsed
        print(json.dumps({
            'metric': 'hello_world_read_rate',
            'value': round(rate, 2),
            'unit': 'samples/sec',
            'vs_baseline': round(rate / BASELINE_SAMPLES_PER_SEC, 3),
        }))
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == '__main__':
    main()
