"""pipeline_report acceptance: the dummy-reader benchmark attributes its
wall time to named stages, the CLI emits JSONL metrics, and the disk cache
counts hits/misses/evictions/bytes with sound size accounting."""

import json
import os
import pickle

import pytest

from petastorm_tpu import telemetry as T
from petastorm_tpu.cache import (
    CACHE_BYTES_EVICTED, CACHE_BYTES_WRITTEN, CACHE_EVICTIONS, CACHE_HITS,
    CACHE_MISSES, LocalDiskCache,
)


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    T.reset_for_tests()
    yield
    T.reset_for_tests()


# -- dummy-reader benchmark: the ≥95% wall-attribution gate ------------------


def test_dummy_benchmark_attributes_wall_time():
    """tier-1 smoke (ISSUE acceptance + CI satellite): one measure window
    of the dummy-reader benchmark; pipeline_report must attribute ≥95% of
    the measured wall to named stages, and the per-stage shares must sum
    to ~1.0 of the wall.

    One retry: a scheduler preemption landing in the handful of unclocked
    instructions between the wall clock and the span (a single-core CI box
    running the rest of the suite) can eat >5% of a small window; two
    consecutive such hits on independent windows would be a real
    attribution bug, not noise."""
    from petastorm_tpu.benchmark.throughput import reader_throughput
    report = None
    for _ in range(2):
        result = reader_throughput(None, reader_type='dummy',
                                   warmup_cycles=100, measure_cycles=50000,
                                   read_method='python', pool_type='dummy')
        report = result.pipeline
        assert report is not None
        assert report['wall_time_s'] == pytest.approx(result.elapsed_s)
        if report['attributed_fraction'] >= 0.95:
            break
    assert report['attributed_fraction'] >= 0.95, report
    share_sum = sum(s['share'] for s in report['stages'].values())
    assert 0.95 <= share_sum <= 1.05, report['stages']
    assert set(report['stages']) <= set(T.STAGES)
    # the rendering names every attributed stage
    text = T.format_pipeline_report(report)
    for stage in report['stages']:
        assert stage in text


def test_dummy_batch_benchmark_has_report_too():
    from petastorm_tpu.benchmark.throughput import reader_throughput
    result = reader_throughput(None, reader_type='dummy',
                               warmup_cycles=1000, measure_cycles=2000000,
                               read_method='batch', pool_type='dummy')
    report = result.pipeline
    assert report['attributed_fraction'] >= 0.9, report
    assert 'queue_wait' in report['stages']


def test_cli_metrics_out_writes_snapshot(tmp_path, capsys):
    """--metrics-out appends one parseable JSONL line carrying the full
    registry snapshot AND the measure window's pipeline report."""
    from petastorm_tpu.benchmark.cli import main
    out = str(tmp_path / 'metrics.jsonl')
    rc = main(['--reader', 'dummy', '-w', '100', '-m', '5000',
               '--pool', 'dummy', '--metrics-out', out])
    assert rc == 0
    (snap,) = T.read_jsonl_snapshots(out)
    assert snap['samples'] == 5000
    assert snap['pipeline_report']['attributed_fraction'] >= 0.9
    assert any(k.startswith('petastorm_tpu_stage_seconds_total')
               for k in snap['counters'])
    # stdout still carries the human rendering
    assert 'pipeline stages' in capsys.readouterr().out


# -- LocalDiskCache telemetry + eviction-size accounting ---------------------


def _fill(value):
    return lambda: value


def test_cache_counts_hits_misses_and_bytes(tmp_path):
    cache = LocalDiskCache(str(tmp_path / 'c'), size_limit_bytes=1 << 20)
    reg = T.get_registry()
    assert cache.get('k1', _fill('v1')) == 'v1'   # miss + store
    assert cache.get('k1', _fill('XX')) == 'v1'   # hit
    assert cache.get('k2', _fill('v2')) == 'v2'   # miss
    assert reg.counter_value(CACHE_HITS) == 1
    assert reg.counter_value(CACHE_MISSES) == 2
    assert reg.counter_value(CACHE_BYTES_WRITTEN) > 0
    assert reg.counter_value(CACHE_EVICTIONS) == 0
    # pipeline_report surfaces the cache section once traffic exists
    report = T.pipeline_report()
    assert report['cache']['hits'] == 1
    assert report['cache']['misses'] == 2
    assert report['cache']['hit_rate'] == pytest.approx(1 / 3, abs=1e-3)


def test_cache_eviction_counts_and_actual_sizes(tmp_path):
    # tiny limit: every new entry pushes the total over and evicts LRU
    cache = LocalDiskCache(str(tmp_path / 'c'), size_limit_bytes=400)
    payload = 'x' * 120  # ~130 pickled bytes per entry
    for i in range(8):
        cache.get('key-%d' % i, _fill(payload + str(i)))
    reg = T.get_registry()
    assert reg.counter_value(CACHE_EVICTIONS) >= 1
    assert reg.counter_value(CACHE_BYTES_EVICTED) > 0
    # running total must equal the actual on-disk footprint (the fix: size
    # measured at eviction time, and overwrites subtract the old bytes)
    actual = cache._scan_total()
    assert cache._total == actual
    assert actual <= 400 + 200  # limit + at most one entry of slack


def test_cache_overwrite_does_not_double_count(tmp_path):
    """Re-filling an existing entry (corrupt file) must replace its bytes
    in the running total, not add them again — the drift that used to
    cause premature evictions. Corruption keeps the file size so the
    invariant is exact: running total == on-disk total."""
    cache = LocalDiskCache(str(tmp_path / 'c'), size_limit_bytes=1 << 20)
    cache.get('k', _fill('A' * 100))
    entry = cache._entry_path('k')
    size = os.stat(entry).st_size
    with open(entry, 'wb') as f:
        f.write(b'z' * size)  # unpicklable, same size → next get re-fills
    for _ in range(3):  # repeated refills must not inflate the total
        with open(entry, 'wb') as f:
            f.write(b'z' * size)
        assert cache.get('k', _fill('A' * 100)) == 'A' * 100
    assert cache._total == cache._scan_total() == size
    with open(entry, 'rb') as f:
        assert pickle.load(f) == 'A' * 100


def test_cache_eviction_uses_size_at_eviction_time(tmp_path):
    """An entry re-written (larger) after insert must be accounted at its
    CURRENT size when evicted — the bytes-evicted clock and the running
    total both reflect eviction-time reality, not the insert-time size."""
    cache = LocalDiskCache(str(tmp_path / 'c'), size_limit_bytes=6000)
    cache.get('victim', _fill('v'))  # ~20 bytes at insert
    victim = cache._entry_path('victim')
    # grow the file behind the cache's back (another process re-wrote it;
    # atomic-rename sharing makes that a supported scenario)
    with open(victim, 'wb') as f:
        pickle.dump('W' * 5000, f)
    os.utime(victim, (1, 1))  # oldest access → first eviction candidate
    # this store pushes the RUNNING total past the limit → eviction pass
    cache.get('big', _fill('y' * 8000))
    reg = T.get_registry()
    assert reg.counter_value(CACHE_EVICTIONS) >= 1
    # evicted bytes reflect the GROWN victim (~5KB), not its ~20-byte
    # insert-time size
    assert reg.counter_value(CACHE_BYTES_EVICTED) >= 5000
    assert cache._total == cache._scan_total()


def test_cache_section_absent_without_traffic():
    assert 'cache' not in T.pipeline_report()


def test_jsonl_roundtrip_through_cli_snapshot(tmp_path):
    """A snapshot written by the exporter parses back to the exact
    registry state even after cache + span traffic."""
    cache = LocalDiskCache(str(tmp_path / 'c'), size_limit_bytes=1 << 20)
    cache.get('k', _fill(json.dumps({'a': 1})))
    with T.span('io'):
        pass
    path = str(tmp_path / 'm.jsonl')
    T.write_jsonl_snapshot(path)
    (snap,) = T.read_jsonl_snapshots(path)
    live = T.get_registry().snapshot()
    assert snap['counters'] == live['counters']
    assert snap['histograms'] == live['histograms']
