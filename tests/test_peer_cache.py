"""Fleet cache tier (docs/service.md, "Fleet cache tier"): adverts,
the dispatcher directory, wire-priced peer fetches, advisory global
eviction — and the acceptance drills: a two-worker fleet decodes each
row-group exactly once fleet-wide, stays exactly parity-preserving
against the ``PETASTORM_TPU_PEER_CACHE=0`` oracle, and degrades to
local decode (never an error) through peer loss chaos.

Worker servers are real CLI subprocesses over ``tcp://`` loopback with
DISJOINT ``--cache-dir`` directories (one per simulated host). Hangs
are bounded internally like test_service.py: every ``get_results``
carries a timeout and fleets are reaped in ``finally``.
"""

import contextlib
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from petastorm_tpu import telemetry as T
from petastorm_tpu.arrow_worker import ColumnBatch
from petastorm_tpu.materialized_cache import MaterializedRowGroupCache
from petastorm_tpu.service import peer_cache
from petastorm_tpu.service import protocol as proto
from petastorm_tpu.service.peer_cache import (
    PEER_CACHE_HITS, PEER_CACHE_MISSES, FleetCacheDirectory,
    PeerCacheClient, PeerCacheServer, digest_entry_path, entry_digest,
)
from petastorm_tpu.service.protocol import free_tcp_port
from petastorm_tpu.service.service_pool import ServicePool
from petastorm_tpu.workers.worker_base import WorkerBase

pytestmark = pytest.mark.service

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_FAST = dict(heartbeat_interval_s=0.15, liveness_timeout_s=0.75,
             connect_timeout_s=60, no_workers_timeout_s=20)


@pytest.fixture(autouse=True)
def fresh_telemetry():
    T.reset_for_tests()
    yield
    T.reset_for_tests()


def _cache(path, mem_mb=0):
    return MaterializedRowGroupCache(str(path), 10 ** 9,
                                     mem_limit_bytes=mem_mb * 2 ** 20)


def _fill_entry(cache, key, value, n=64):
    batch = cache.get(key, lambda: ColumnBatch(
        {'v': np.full(n, value, dtype=np.int64)}, n))
    return cache._entry_path(key), batch


# -- worker side: the serve socket -------------------------------------------


@contextlib.contextmanager
def _server(cache_dir):
    server = PeerCacheServer(str(cache_dir))
    try:
        yield server
    finally:
        server.close()


def _raw_fetch(endpoint, digest, timeout_s=5.0):
    import zmq
    context = zmq.Context()
    sock = context.socket(zmq.DEALER)
    try:
        sock.setsockopt(zmq.LINGER, 0)
        sock.connect(endpoint)
        sock.send_multipart([proto.MSG_PEER_FETCH, digest.encode()])
        assert sock.poll(int(timeout_s * 1000)), 'serve socket silent'
        return sock.recv_multipart()
    finally:
        sock.close(0)
        context.term()


class TestPeerCacheServer:
    def test_serves_entry_bytes_chunked_and_verified(self, tmp_path,
                                                     monkeypatch):
        # tiny chunks force real multipart streaming of one entry
        monkeypatch.setattr(peer_cache, '_CHUNK_BYTES', 1024)
        cache = _cache(tmp_path / 'a')
        entry, _ = _fill_entry(cache, 'k', 7, n=4096)
        digest = entry_digest(entry)
        with _server(cache.path) as server:
            frames = _raw_fetch(server.endpoint, digest)
            assert frames[0] == proto.MSG_PEER_ENTRY
            assert frames[1] == digest.encode()
            meta = proto.load_json_params(frames[2])
            blob = b''.join(frames[3:])
            assert len(frames) > 4  # actually chunked
            assert len(blob) == meta['size'] == os.path.getsize(entry)
            assert blob == open(entry, 'rb').read()
            assert server.served == 1
            assert server.health_snapshot()['entries'] == 1

    def test_unknown_digest_answers_honest_miss(self, tmp_path):
        with _server(tmp_path) as server:
            frames = _raw_fetch(server.endpoint, '0' * 40)
            assert frames[0] == proto.MSG_PEER_MISS

    def test_register_advert_scans_directory_durably(self, tmp_path):
        """Entries left by a PREVIOUS process are advertised from the
        startup scan — the fleet map survives worker restarts."""
        cache = _cache(tmp_path / 'a')
        entry, _ = _fill_entry(cache, 'k1', 1)
        _fill_entry(cache, 'k2', 2)
        with _server(cache.path) as server:
            full = server.full_advert()
            assert full['ep'] == server.endpoint
            digests = {row[0] for row in full['full']}
            assert digests == {entry_digest(cache._entry_path('k1')),
                               entry_digest(cache._entry_path('k2'))}
            assert all(row[1] > 0 for row in full['full'])

    def test_heartbeat_delta_carries_adds_and_removes(self, tmp_path):
        cache = _cache(tmp_path / 'a')
        with _server(cache.path) as server:
            server.full_advert()
            assert server.advert_delta() is None  # nothing changed
            entry, _ = _fill_entry(cache, 'k', 3)  # publish listener path
            delta = server.advert_delta()
            assert delta['ep'] == server.endpoint
            assert [row[0] for row in delta['add']] == [entry_digest(entry)]
            os.remove(entry)
            server._last_scan = 0.0  # bypass the rescan rate limit
            delta = server.advert_delta()
            assert delta['rm'] == [entry_digest(entry)]
            assert server.advert_delta() is None

    def test_evict_hint_declines_hot_entry_removes_cold(self, tmp_path):
        cache = _cache(tmp_path / 'a')
        entry, _ = _fill_entry(cache, 'k', 1)
        digest = entry_digest(entry)
        with _server(cache.path) as server:
            server.full_advert()
            # locally fresh: the hint is advisory and must be declined
            assert server.apply_evict_hints([digest]) == 0
            assert os.path.exists(entry)
            os.utime(entry, (1.0, 1.0))  # ancient: fleet hint wins
            assert server.apply_evict_hints([digest]) == 1
            assert not os.path.exists(entry)
            assert server.evicted_on_hint == 1
            assert server.apply_evict_hints(['not-a-digest', digest]) == 0


# -- worker side: the fetch path ---------------------------------------------


class TestPeerCacheClient:
    def _client(self, mirror=None, self_endpoint=None):
        # dispatcher endpoint nobody answers: DIRGET lookups time out
        client = PeerCacheClient(
            'tcp://127.0.0.1:%d' % free_tcp_port(),
            self_endpoint=self_endpoint)
        if mirror:
            client.update_directory(mirror)
        return client

    def test_fetch_serves_wire_parity_entry(self, tmp_path, monkeypatch):
        monkeypatch.setenv('PETASTORM_TPU_PEER_CACHE_TIMEOUT_S', '5')
        cache_a = _cache(tmp_path / 'a')
        entry_a, _ = _fill_entry(cache_a, 'k', 42)
        digest = entry_digest(entry_a)
        cache_b = _cache(tmp_path / 'b')
        entry_b = cache_b._entry_path('k')
        with _server(cache_a.path) as server:
            client = self._client(
                {digest: [[server.endpoint, os.path.getsize(entry_a)]]})
            try:
                served = client.fetch('k', entry_b, cache_b)
            finally:
                client.close()
        assert served is not None
        columns, length = served
        assert length == 64
        np.testing.assert_array_equal(
            columns['v'], np.full(64, 42, dtype=np.int64))
        # on disk the peer path is indistinguishable from a local fill
        assert open(entry_b, 'rb').read() == open(entry_a, 'rb').read()
        registry = T.get_registry()
        assert registry.counter_value(PEER_CACHE_HITS) == 1
        assert registry.counter_value('petastorm_tpu_stage_calls_total',
                                      stage='peer_fetch') == 1
        assert client.stats()['hits'] == 1
        assert client.stats()['budget_used'] == 0  # arena fully released

    def test_every_failure_degrades_with_its_reason(self, tmp_path,
                                                    monkeypatch):
        monkeypatch.setenv('PETASTORM_TPU_PEER_CACHE_TIMEOUT_S', '0.1')
        registry = T.get_registry()

        def reason(name):
            return registry.counter_value(PEER_CACHE_MISSES, reason=name)

        cache = _cache(tmp_path / 'b')
        entry = cache._entry_path('k')
        digest = entry_digest(entry)

        # no holder anywhere (and the DIRGET endpoint is dead too)
        client = self._client()
        try:
            assert client.fetch('k', entry, cache) is None
            assert reason('no_holder') == 1

            # a holder that stopped answering: timeout, then forgotten
            dead = 'tcp://127.0.0.1:%d' % free_tcp_port()
            client.update_directory({digest: [[dead, 128]]})
            assert client.fetch('k', entry, cache) is None
            assert reason('timeout') == 1
            assert client.stats()['mirror'] == 0  # holder forgotten
        finally:
            client.close()

        # the advertised holder evicted the entry: honest peer_miss
        with _server(tmp_path / 'empty') as server:
            client = self._client({digest: [[server.endpoint, 128]]})
            try:
                assert client.fetch('k', entry, cache) is None
                assert reason('peer_miss') == 1
            finally:
                client.close()

        # corrupt bytes are discarded, never published
        client = self._client()
        try:
            client.update_directory({digest: [['tcp://127.0.0.1:1', 3]]})
            monkeypatch.setattr(
                client, '_request',
                lambda endpoint, d: [proto.MSG_PEER_ENTRY, d.encode(),
                                     proto.dump_json_params(
                                         {'size': 3, 'sha1': '0' * 40}),
                                     b'abc'])
            assert client.fetch('k', entry, cache) is None
            assert reason('corrupt') == 1
            assert not os.path.exists(entry)
        finally:
            client.close()

        assert client.stats()['budget_used'] == 0

    def test_budget_exhaustion_declines_not_buffers(self, tmp_path,
                                                    monkeypatch):
        monkeypatch.setenv('PETASTORM_TPU_PEER_CACHE_BUDGET_MB', '1')
        cache = _cache(tmp_path / 'b')
        entry = cache._entry_path('k')
        digest = entry_digest(entry)
        client = self._client({digest: [['tcp://127.0.0.1:1', 2 << 20]]})
        try:
            assert client.fetch('k', entry, cache) is None
            assert T.get_registry().counter_value(
                PEER_CACHE_MISSES, reason='budget') == 1
        finally:
            client.close()

    def test_own_endpoint_is_never_fetched_from(self, tmp_path,
                                                monkeypatch):
        monkeypatch.setenv('PETASTORM_TPU_PEER_CACHE_TIMEOUT_S', '0.1')
        cache = _cache(tmp_path / 'b')
        entry = cache._entry_path('k')
        digest = entry_digest(entry)
        me = 'tcp://127.0.0.1:9'
        client = self._client({digest: [[me, 128]]}, self_endpoint=me)
        try:
            assert client.fetch('k', entry, cache) is None
            assert T.get_registry().counter_value(
                PEER_CACHE_MISSES, reason='no_holder') == 1
        finally:
            client.close()


# -- dispatcher side: the fleet directory ------------------------------------


_D1 = 'a' * 40
_D2 = 'b' * 40
_D3 = 'c' * 40


class TestFleetCacheDirectory:
    def test_fold_lookup_drop(self):
        directory = FleetCacheDirectory()
        directory.note_advert(b'w1', {'ep': 'tcp://h1:1',
                                      'full': [[_D1, 100, 50]]})
        directory.note_advert(b'w2', {'ep': 'tcp://h2:2',
                                      'add': [[_D1, 100, 90],
                                              [_D2, 200, 10]]})
        out = directory.lookup([_D1, _D2, _D3])
        assert out[_D1] == [['tcp://h2:2', 100], ['tcp://h1:1', 100]]
        assert out[_D2] == [['tcp://h2:2', 200]]
        assert out[_D3] == []  # unknown: the asker negative-caches
        assert directory.held_count(b'w2') == 2
        directory.drop(b'w2')
        assert directory.lookup([_D2])[_D2] == []
        assert directory.lookup([_D1])[_D1] == [['tcp://h1:1', 100]]
        assert directory.stats()['digests'] == 1

    def test_lookup_excludes_the_asker_and_touch_reorders(self):
        directory = FleetCacheDirectory()
        directory.note_advert(b'w1', {'ep': 'tcp://h1:1',
                                      'full': [[_D1, 100, 50]]})
        directory.note_advert(b'w2', {'ep': 'tcp://h2:2',
                                      'add': [[_D1, 100, 10]]})
        assert directory.lookup([_D1], exclude_identity=b'w1')[_D1] == \
            [['tcp://h2:2', 100]]
        directory.note_advert(b'w2', {'ep': 'tcp://h2:2',
                                      't': [[_D1, 99]]})
        assert directory.lookup([_D1])[_D1][0] == ['tcp://h2:2', 100]

    def test_delta_since_is_incremental_and_capped(self):
        directory = FleetCacheDirectory()
        version, delta = directory.delta_since(0)
        assert version == 0 and delta is None
        directory.note_advert(b'w1', {'ep': 'tcp://h1:1',
                                      'full': [[_D1, 100, 50]]})
        version, delta = directory.delta_since(0)
        assert delta == {_D1: [['tcp://h1:1', 100]]}
        assert directory.delta_since(version) == (version, None)
        # the advertising worker's own rows never ride back to it
        directory.note_advert(b'w2', {'ep': 'tcp://h2:2',
                                      'add': [[_D2, 200, 60]]})
        _, delta = directory.delta_since(version, exclude_identity=b'w2')
        assert delta is None or _D2 not in delta

    def test_malformed_adverts_are_swallowed(self):
        directory = FleetCacheDirectory()
        directory.note_advert(b'w1', None)
        directory.note_advert(b'w1', {'full': [[_D1, 1, 1]]})  # no ep
        directory.note_advert(b'w1', {'ep': 'tcp://h:1',
                                      'full': [['nope', 1, 1],
                                               [_D1, 'x', 'y']]})
        assert directory.lookup([_D1])[_D1] == []
        assert directory.stats()['digests'] == 0

    def test_global_eviction_hints_spare_the_freshest(self, monkeypatch):
        monkeypatch.setenv('PETASTORM_TPU_PEER_CACHE_COLD_S', '100')
        directory = FleetCacheDirectory()
        now = 10_000.0
        # replicated + fleet-cold: hint every holder except the freshest
        directory.note_advert(b'w1', {'ep': 'tcp://h1:1',
                                      'full': [[_D1, 100, now - 500]]})
        directory.note_advert(b'w2', {'ep': 'tcp://h2:2',
                                      'add': [[_D1, 100, now - 900]]})
        # single-copy entries are never hinted, however cold
        directory.note_advert(b'w2', {'ep': 'tcp://h2:2',
                                      'add': [[_D2, 100, now - 900]]})
        # replicated but fleet-hot: untouched
        directory.note_advert(b'w1', {'ep': 'tcp://h1:1',
                                      'add': [[_D3, 100, now - 10]]})
        directory.note_advert(b'w2', {'ep': 'tcp://h2:2',
                                      'add': [[_D3, 100, now - 900]]})
        directory.compute_evict_hints(now)
        assert directory.take_hints(b'w1') is None  # w1 is freshest on D1
        assert directory.take_hints(b'w2') == [_D1]
        assert directory.take_hints(b'w2') is None  # drained
        assert directory.stats()['hints_queued'] == 1

    def test_snapshot_seed_supersede_expire(self):
        primary = FleetCacheDirectory()
        primary.note_advert(b'w1', {'ep': 'tcp://h1:1',
                                    'full': [[_D1, 100, 50]]})
        snapshot = primary.snapshot()
        promoted = FleetCacheDirectory()
        promoted.seed(snapshot, now_mono=1000.0)
        assert promoted.lookup([_D1])[_D1] == [['tcp://h1:1', 100]]
        assert promoted.stats()['seeded']
        # the endpoint's first real advert supersedes its seed rows
        promoted.note_advert(b'w9', {'ep': 'tcp://h1:1',
                                     'full': [[_D2, 200, 60]]})
        assert promoted.lookup([_D1])[_D1] == []
        assert promoted.lookup([_D2])[_D2] == [['tcp://h1:1', 200]]
        assert not promoted.stats()['seeded']
        # unclaimed seeds age out
        expiring = FleetCacheDirectory()
        expiring.seed(snapshot, now_mono=1000.0)
        expiring.expire_seeds(1030.0)  # before the TTL: kept
        assert expiring.lookup([_D1])[_D1]
        expiring.expire_seeds(1061.0)
        assert expiring.lookup([_D1])[_D1] == []


def test_dispatcher_snapshot_replicates_directory_to_standby():
    """The failover path end-to-end at the state level: a primary
    dispatcher's standby snapshot seeds a promoted dispatcher whose
    DIRGET answers stay warm through the failover window."""
    from petastorm_tpu.service.dispatcher import Dispatcher
    primary = Dispatcher('tcp://127.0.0.1:0', None, None, threading.Event())
    primary._peer_dir.note_advert(b'w1', {'ep': 'tcp://h1:1',
                                          'full': [[_D1, 100, 50]]})
    state = primary.standby_snapshot()
    assert state['peer_directory'] == [[_D1, [['tcp://h1:1', 100, 50.0]]]]
    promoted = Dispatcher('tcp://127.0.0.1:0', None, None,
                          threading.Event(), seed_state=state)
    assert promoted._peer_dir.lookup([_D1])[_D1] == [['tcp://h1:1', 100]]


# -- acceptance: the two-worker decode-once drill ----------------------------


class FleetDecodeWorker(WorkerBase):
    """Publishes ``(item, was_decoded, value, pid)`` for one item served
    through the job's materialized cache — ``was_decoded`` is the
    fleet-wide decode-count probe."""

    def process(self, item, sleep_s=0.0):
        if sleep_s:
            time.sleep(sleep_s)
        decoded = []

        def fill():
            decoded.append(True)
            return ColumnBatch(
                {'v': np.full(16, item, dtype=np.int64)}, 16)

        batch = self.args['cache'].get(('peer-e2e', item), fill)
        self.publish_func((int(item), bool(decoded),
                           int(batch.columns['v'][0]), os.getpid()))


@contextlib.contextmanager
def _fleet(endpoint, tmp_path, count=2, env_extra=None):
    """CLI worker servers with DISJOINT --cache-dir dirs (one per
    simulated host)."""
    env = dict(os.environ,
               PYTHONPATH=os.pathsep.join(
                   [_REPO_ROOT, os.path.join(_REPO_ROOT, 'tests')]),
               JAX_PLATFORMS='cpu')
    env.update(env_extra or {})
    procs = [
        subprocess.Popen(
            [sys.executable, '-m', 'petastorm_tpu.service.worker_server',
             '--endpoint', endpoint,
             '--heartbeat-interval', '0.2',
             '--worker-id', str(i),
             '--cache-dir', str(tmp_path / ('host%d' % i)),
             '--parent-pid', str(os.getpid())],
            env=env)
        for i in range(count)
    ]
    try:
        yield procs
    finally:
        for p in procs:
            if p.poll() is None:
                p.terminate()
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait()


def _run_epoch(endpoint, cache, n, expected_workers=2, sleep_s=0.0,
               kill_after_first=None):
    pool = ServicePool(endpoint=endpoint, expected_workers=expected_workers,
                       **_FAST)
    pool.start(FleetDecodeWorker, worker_args={'cache': cache})
    try:
        for i in range(n):
            pool.ventilate(i, sleep_s=sleep_s)
        rows = [pool.get_results(timeout=60)]
        if kill_after_first is not None:
            os.kill(kill_after_first.pid, signal.SIGKILL)
        rows += [pool.get_results(timeout=60) for _ in range(n - 1)]
        return rows, dict(pool.diagnostics)
    finally:
        pool.stop()
        pool.join()


def _values(rows):
    return sorted(value for _item, _dec, value, _pid in rows)


def test_fleet_decodes_each_rowgroup_exactly_once(tmp_path):
    """The tentpole acceptance: epoch 1 decodes each item once
    fleet-wide; epoch 2 (a fresh job on the same fleet) serves EVERY
    item without a single decode — local disk hits on the decoding
    host, wire-priced peer fetches across hosts."""
    n = 24
    endpoint = 'tcp://127.0.0.1:%d' % free_tcp_port()
    cache = _cache(tmp_path / 'client')
    with _fleet(endpoint, tmp_path):
        cold, _ = _run_epoch(endpoint, cache, n)
        assert _values(cold) == list(range(n))
        assert sum(dec for _i, dec, _v, _p in cold) == n  # once each
        hosts = {pid for _i, _d, _v, pid in cold}
        assert len(hosts) == 2  # both simulated hosts participated

        warm, diag = _run_epoch(endpoint, cache, n)
        assert _values(warm) == list(range(n))
        redecoded = [i for i, dec, _v, _p in warm if dec]
        assert redecoded == [], \
            'fleet re-decoded row-groups %s' % redecoded
        assert diag['items_poisoned'] == 0


def test_exact_parity_with_peer_cache_disabled_oracle(tmp_path):
    """``PETASTORM_TPU_PEER_CACHE=0`` is the host-local oracle: the row
    multiset must be identical with the tier off — each host just pays
    its own decodes again."""
    n = 16
    endpoint = 'tcp://127.0.0.1:%d' % free_tcp_port()
    cache = _cache(tmp_path / 'client')
    oracle_env = {'PETASTORM_TPU_PEER_CACHE': '0'}
    os.environ['PETASTORM_TPU_PEER_CACHE'] = '0'  # dispatcher side too
    try:
        with _fleet(endpoint, tmp_path, env_extra=oracle_env):
            cold, _ = _run_epoch(endpoint, cache, n)
            warm, diag = _run_epoch(endpoint, cache, n)
    finally:
        del os.environ['PETASTORM_TPU_PEER_CACHE']
    assert _values(cold) == list(range(n))
    assert _values(warm) == list(range(n))
    assert diag['items_poisoned'] == 0
    registry = T.get_registry()
    assert registry.counter_value(PEER_CACHE_HITS) == 0


def test_peer_loss_mid_epoch_degrades_to_local_decode(tmp_path):
    """The chaos drill: SIGKILL the worker holding half the fleet's
    entries mid-warm-epoch. Its in-flight items re-ventilate to the
    survivor, fetches from the dead endpoint time out into local
    decode, and the epoch still delivers the exact row multiset with
    zero quarantines."""
    n = 24
    endpoint = 'tcp://127.0.0.1:%d' % free_tcp_port()
    cache = _cache(tmp_path / 'client')
    env_extra = {'PETASTORM_TPU_PEER_CACHE_TIMEOUT_S': '0.3'}
    with _fleet(endpoint, tmp_path, env_extra=env_extra) as procs:
        cold, _ = _run_epoch(endpoint, cache, n)
        assert _values(cold) == list(range(n))
        warm, diag = _run_epoch(endpoint, cache, n, sleep_s=0.05,
                                kill_after_first=procs[1])
        assert _values(warm) == list(range(n))
        assert diag['items_poisoned'] == 0
        assert diag['items_reventilated'] >= 1
        assert diag['workers_alive'] == 1


def test_injected_serve_drop_degrades_every_fetch(tmp_path):
    """Every peer serve drops its reply (armed ``zmq.peer_serve``
    faultpoint): fetchers time out into local decode — exact rows,
    zero quarantines, the tier is advisory under total peer loss."""
    n = 12
    endpoint = 'tcp://127.0.0.1:%d' % free_tcp_port()
    cache = _cache(tmp_path / 'client')
    env_extra = {'PETASTORM_TPU_FAULTS': 'zmq.peer_serve:drop:1',
                 'PETASTORM_TPU_PEER_CACHE_TIMEOUT_S': '0.3'}
    with _fleet(endpoint, tmp_path, env_extra=env_extra):
        cold, _ = _run_epoch(endpoint, cache, n)
        warm, diag = _run_epoch(endpoint, cache, n)
    assert _values(cold) == list(range(n))
    assert _values(warm) == list(range(n))
    assert diag['items_poisoned'] == 0


def test_peer_fetch_report_section_and_format(tmp_path, monkeypatch):
    """pipeline_report grows a ``peer_cache`` section (hit share +
    degrade reasons) only when the tier did something."""
    assert 'peer_cache' not in T.pipeline_report()
    monkeypatch.setenv('PETASTORM_TPU_PEER_CACHE_TIMEOUT_S', '1')
    cache_a = _cache(tmp_path / 'a')
    entry_a, _ = _fill_entry(cache_a, 'k', 1)
    cache_b = _cache(tmp_path / 'b')
    with _server(cache_a.path) as server:
        client = PeerCacheClient('tcp://127.0.0.1:%d' % free_tcp_port())
        client.update_directory({entry_digest(entry_a): [
            [server.endpoint, os.path.getsize(entry_a)]]})
        try:
            assert client.fetch('k', cache_b._entry_path('k'),
                                cache_b) is not None
            assert client.fetch('missing', cache_b._entry_path('missing'),
                                cache_b) is None
        finally:
            client.close()
    report = T.pipeline_report()
    section = report['peer_cache']
    assert section['hits'] == 1
    assert section['misses'] == 1
    assert section['hit_share'] == 0.5
    assert section['degraded'] == {'no_holder': 1}
    assert section['bytes_fetched'] == os.path.getsize(entry_a)
    text = T.format_pipeline_report(report)
    assert 'peer cache: 1 hit / 1 miss' in text
