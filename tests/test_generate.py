"""KV-cache greedy decoding vs the recompute-everything oracle."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from petastorm_tpu.models.generate import (
    greedy_generate, reference_greedy_generate,
)
from petastorm_tpu.models.transformer import (
    TransformerConfig, init_transformer_params,
)

pytestmark = pytest.mark.slow  # compile-heavy scan/jit tests


def _setup(**kw):
    base = dict(vocab_size=32, d_model=16, n_heads=2, n_layers=2,
                d_ff=32, max_seq_len=24, dtype=jnp.float32)
    base.update(kw)
    config = TransformerConfig(**base)
    params = init_transformer_params(jax.random.PRNGKey(0), config)
    return config, params


def test_matches_recompute_oracle_exactly():
    config, params = _setup()
    prompt = jnp.asarray(
        np.random.RandomState(0).randint(0, 32, (3, 5), np.int32))
    got = greedy_generate(params, prompt, config, max_new_tokens=8)
    want = reference_greedy_generate(params, prompt, config,
                                     max_new_tokens=8)
    assert got.shape == (3, 13)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_single_new_token():
    config, params = _setup()
    prompt = jnp.asarray(
        np.random.RandomState(1).randint(0, 32, (2, 4), np.int32))
    got = greedy_generate(params, prompt, config, max_new_tokens=1)
    want = reference_greedy_generate(params, prompt, config,
                                     max_new_tokens=1)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_whole_decode_is_jittable():
    config, params = _setup()
    prompt = jnp.asarray(
        np.random.RandomState(2).randint(0, 32, (2, 6), np.int32))
    jitted = jax.jit(lambda p, t: greedy_generate(p, t, config,
                                                  max_new_tokens=6))
    got = jitted(params, prompt)
    want = reference_greedy_generate(params, prompt, config,
                                     max_new_tokens=6)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_bf16_decode_runs():
    # bf16 cache/compute: exact argmax parity with the oracle is not
    # guaranteed under reassociation, but the decode must run and emit
    # in-vocab tokens
    config, params = _setup(dtype=jnp.bfloat16)
    prompt = jnp.asarray(
        np.random.RandomState(3).randint(0, 32, (2, 4), np.int32))
    got = np.asarray(greedy_generate(params, prompt, config,
                                     max_new_tokens=5))
    assert got.shape == (2, 9)
    assert ((got >= 0) & (got < 32)).all()


def test_top_k_one_equals_greedy():
    from petastorm_tpu.models.generate import sample_generate
    config, params = _setup()
    prompt = jnp.asarray(
        np.random.RandomState(4).randint(0, 32, (2, 4), np.int32))
    greedy = greedy_generate(params, prompt, config, max_new_tokens=6)
    sampled = sample_generate(params, prompt, config, max_new_tokens=6,
                              rng=jax.random.PRNGKey(0), temperature=0.5,
                              top_k=1)
    np.testing.assert_array_equal(np.asarray(sampled), np.asarray(greedy))


def test_sampling_is_seeded_and_in_vocab():
    from petastorm_tpu.models.generate import sample_generate
    config, params = _setup()
    prompt = jnp.asarray(
        np.random.RandomState(5).randint(0, 32, (2, 4), np.int32))
    a = sample_generate(params, prompt, config, max_new_tokens=8,
                        rng=jax.random.PRNGKey(1), temperature=1.5)
    b = sample_generate(params, prompt, config, max_new_tokens=8,
                        rng=jax.random.PRNGKey(1), temperature=1.5)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    got = np.asarray(a)
    assert got.shape == (2, 12)
    assert ((got >= 0) & (got < 32)).all()
    other = sample_generate(params, prompt, config, max_new_tokens=8,
                            rng=jax.random.PRNGKey(2), temperature=1.5)
    assert not np.array_equal(np.asarray(other), got), \
        'different seeds should (overwhelmingly) differ at T=1.5'


def test_top_k_beyond_vocab_is_full_vocab():
    from petastorm_tpu.models.generate import sample_generate
    config, params = _setup()
    prompt = jnp.asarray(
        np.random.RandomState(6).randint(0, 32, (1, 4), np.int32))
    full = sample_generate(params, prompt, config, max_new_tokens=4,
                           rng=jax.random.PRNGKey(0), temperature=1.0)
    clamped = sample_generate(params, prompt, config, max_new_tokens=4,
                              rng=jax.random.PRNGKey(0), temperature=1.0,
                              top_k=1000)
    np.testing.assert_array_equal(np.asarray(clamped), np.asarray(full))


def test_eos_freezes_finished_rows():
    # pick the token the model would greedily emit at step k as "EOS":
    # from then on that row must emit only EOS, while other rows continue
    config, params = _setup()
    prompt = jnp.asarray(
        np.random.RandomState(7).randint(0, 32, (2, 5), np.int32))
    free = np.asarray(greedy_generate(params, prompt, config,
                                      max_new_tokens=8))
    # an "EOS" row 0 emits but row 1 never does — lets the test pin both
    # the freeze AND per-row independence
    eos = next(int(t) for t in free[0, 5:]
               if t not in free[1, 5:].tolist())
    stopped = np.asarray(greedy_generate(params, prompt, config,
                                         max_new_tokens=8, eos_token=eos))
    row = stopped[0, 5:]
    hit = int(np.argmax(row == eos))
    assert row[hit] == eos
    assert (row[hit:] == eos).all(), 'row must freeze at EOS'
    # prefix before EOS matches the unconstrained decode
    np.testing.assert_array_equal(row[:hit], free[0, 5:5 + hit])
    # per-row independence: a bug collapsing the done mask across the
    # batch would freeze row 1 too
    np.testing.assert_array_equal(stopped[1], free[1])


def test_zero_new_tokens_rejected():
    config, params = _setup()
    with pytest.raises(ValueError, match='max_new_tokens'):
        greedy_generate(params, jnp.zeros((1, 4), jnp.int32), config, 0)


def test_zero_temperature_rejected():
    from petastorm_tpu.models.generate import sample_generate
    config, params = _setup()
    with pytest.raises(ValueError, match='temperature'):
        sample_generate(params, jnp.zeros((1, 4), jnp.int32), config, 2,
                        rng=jax.random.PRNGKey(0), temperature=0.0)


def test_overflow_rejected():
    config, params = _setup(max_seq_len=8)
    prompt = jnp.zeros((1, 5), jnp.int32)
    with pytest.raises(ValueError, match='exceeds'):
        greedy_generate(params, prompt, config, max_new_tokens=4)


def test_moe_and_seq_configs_rejected():
    config, params = _setup(n_experts=4)
    with pytest.raises(NotImplementedError, match='dense'):
        greedy_generate(params, jnp.zeros((1, 4), jnp.int32), config, 2)
    config2, _ = _setup(seq_axis='seq')
    with pytest.raises(NotImplementedError, match='dense'):
        greedy_generate(params, jnp.zeros((1, 4), jnp.int32), config2, 2)


def test_top_p_tiny_nucleus_equals_greedy():
    # a nucleus smaller than the top token's own probability keeps only
    # the argmax -> identical to greedy
    from petastorm_tpu.models.generate import sample_generate
    config, params = _setup()
    prompt = jnp.asarray(
        np.random.RandomState(8).randint(0, 32, (2, 4), np.int32))
    greedy = greedy_generate(params, prompt, config, max_new_tokens=6)
    nucleus = sample_generate(params, prompt, config, max_new_tokens=6,
                              rng=jax.random.PRNGKey(0), temperature=1.0,
                              top_p=1e-6)
    np.testing.assert_array_equal(np.asarray(nucleus), np.asarray(greedy))


def test_top_p_full_mass_equals_plain_sampling():
    from petastorm_tpu.models.generate import sample_generate
    config, params = _setup()
    prompt = jnp.asarray(
        np.random.RandomState(9).randint(0, 32, (2, 4), np.int32))
    plain = sample_generate(params, prompt, config, max_new_tokens=6,
                            rng=jax.random.PRNGKey(4), temperature=1.3)
    full = sample_generate(params, prompt, config, max_new_tokens=6,
                           rng=jax.random.PRNGKey(4), temperature=1.3,
                           top_p=1.0)
    np.testing.assert_array_equal(np.asarray(full), np.asarray(plain))


def test_top_p_out_of_range_rejected():
    from petastorm_tpu.models.generate import sample_generate
    config, params = _setup()
    with pytest.raises(ValueError, match='top_p'):
        sample_generate(params, jnp.zeros((1, 4), jnp.int32), config, 2,
                        rng=jax.random.PRNGKey(0), top_p=1.5)


def test_gqa_decode_matches_recompute_oracle_exactly():
    # GQA (2 query heads per shared K/V head): the grouped-einsum cache
    # path must equal the training forward's expanded-heads math token
    # for token
    config, params = _setup(n_heads=4, n_kv_heads=2)
    prompt = jnp.asarray(
        np.random.RandomState(1).randint(0, 32, (2, 5), np.int32))
    got = greedy_generate(params, prompt, config, max_new_tokens=8)
    want = reference_greedy_generate(params, prompt, config,
                                     max_new_tokens=8)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_mqa_decode_matches_recompute_oracle_exactly():
    # the n_kv_heads=1 extreme (multi-query attention)
    config, params = _setup(n_heads=4, n_kv_heads=1)
    prompt = jnp.asarray(
        np.random.RandomState(2).randint(0, 32, (2, 4), np.int32))
    got = greedy_generate(params, prompt, config, max_new_tokens=6)
    want = reference_greedy_generate(params, prompt, config,
                                     max_new_tokens=6)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_gqa_cache_is_kv_heads_sized():
    # the point of GQA: the qkv projection (and so the cache the decode
    # builds from it) carries n_kv_heads K/V head blocks, not n_heads
    config, params = _setup(n_heads=4, n_kv_heads=2)
    head_dim = config.d_model // config.n_heads
    expected = (config.n_heads + 2 * 2) * head_dim
    assert params['blocks'][0]['qkv'].shape == (config.d_model, expected)


def test_rope_decode_matches_recompute_oracle_exactly():
    # rope: the cache stores position-rotated keys; decode must equal the
    # training forward (which rotates per global position) token for token
    config, params = _setup(pos_encoding='rope')
    assert 'pos_embed' not in params
    prompt = jnp.asarray(
        np.random.RandomState(3).randint(0, 32, (2, 5), np.int32))
    got = greedy_generate(params, prompt, config, max_new_tokens=8)
    want = reference_greedy_generate(params, prompt, config,
                                     max_new_tokens=8)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_rope_gqa_decode_matches_recompute_oracle_exactly():
    # rope and GQA interact (rotation before the grouped-cache attend):
    # pin the combination, not just each feature alone
    config, params = _setup(n_heads=4, n_kv_heads=2, pos_encoding='rope')
    prompt = jnp.asarray(
        np.random.RandomState(4).randint(0, 32, (2, 6), np.int32))
    got = greedy_generate(params, prompt, config, max_new_tokens=7)
    want = reference_greedy_generate(params, prompt, config,
                                     max_new_tokens=7)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
