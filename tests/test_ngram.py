"""NGram unit + end-to-end tests (reference: ``petastorm/tests/test_ngram.py``
and ``test_ngram_end_to_end.py``)."""

import numpy as np
import pyarrow as pa
import pytest

from petastorm_tpu.arrow_worker import ColumnBatch
from petastorm_tpu.codecs import ScalarCodec
from petastorm_tpu.ngram import NGram
from petastorm_tpu.reader import make_reader
from petastorm_tpu.unischema import Unischema, UnischemaField

from tests.test_common import TestSchema

TsSchema = Unischema('TsSchema', [
    UnischemaField('ts', np.int64, (), ScalarCodec(pa.int64()), False),
    UnischemaField('value', np.int32, (), ScalarCodec(pa.int32()), False),
    UnischemaField('other', np.float64, (), ScalarCodec(pa.float64()), False),
])


def _batch(ts_values, values=None):
    ts = np.asarray(ts_values, dtype=np.int64)
    n = len(ts)
    vals = np.asarray(values if values is not None else np.arange(n), dtype=np.int32)
    other = np.arange(n, dtype=np.float64) * 0.5
    return ColumnBatch({'ts': ts, 'value': vals, 'other': other}, n)


def _resolved(fields, delta, overlap=True, timestamp='ts'):
    ngram = NGram(fields=fields, delta_threshold=delta, timestamp_field=timestamp,
                  timestamp_overlap=overlap)
    ngram.resolve_regex_field_names(TsSchema)
    return ngram


class TestNGramUnit:
    def test_length(self):
        assert _resolved({0: ['value'], 1: ['value']}, 1).length == 2
        assert _resolved({-1: ['value'], 1: ['value']}, 1).length == 3

    def test_validation(self):
        with pytest.raises(ValueError):
            NGram(fields=None, delta_threshold=1, timestamp_field='ts')
        with pytest.raises(ValueError):
            NGram(fields={0: 'not-a-list'}, delta_threshold=1, timestamp_field='ts')
        with pytest.raises(ValueError):
            NGram(fields={0: [5]}, delta_threshold=1, timestamp_field='ts')
        with pytest.raises(ValueError):
            NGram(fields={0: ['value']}, delta_threshold='x', timestamp_field='ts')
        with pytest.raises(ValueError):
            NGram(fields={0: ['value']}, delta_threshold=1, timestamp_field=7)
        with pytest.raises(ValueError):
            NGram(fields={0: ['value']}, delta_threshold=1, timestamp_field='ts',
                  timestamp_overlap='yes')
        with pytest.raises(ValueError):
            NGram(fields={0.5: ['value']}, delta_threshold=1, timestamp_field='ts')

    def test_regex_resolution(self):
        ngram = _resolved({0: ['va.*'], 1: [TsSchema.fields['other']]}, 1)
        assert ngram.get_field_names_at_timestep(0) == ['value']
        assert ngram.get_field_names_at_timestep(1) == ['other']
        assert ngram.get_field_names_at_timestep(9) == []

    def test_timestamp_regex_must_be_unique(self):
        ngram = NGram(fields={0: ['value']}, delta_threshold=1, timestamp_field='.*')
        with pytest.raises(ValueError, match='exactly one'):
            ngram.resolve_regex_field_names(TsSchema)

    def test_schema_at_timestep(self):
        ngram = _resolved({0: ['value'], 1: ['value', 'other']}, 1)
        view = ngram.get_schema_at_timestep(TsSchema, 1)
        assert set(view.fields) == {'value', 'other'}

    def test_loads_timestamp_field(self):
        ngram = _resolved({0: ['value']}, 1)
        names = {f.name for f in ngram.get_field_names_at_all_timesteps()}
        assert names == {'value', 'ts'}

    def test_equality(self):
        a = _resolved({0: ['value'], 1: ['other']}, 1)
        b = _resolved({0: ['value'], 1: ['other']}, 5)
        c = _resolved({0: ['value'], 1: ['value']}, 1)
        assert a == b  # delta not part of identity (reference semantics)
        assert a != c


class TestFormNGram:
    def test_dense_windows(self):
        ngram = _resolved({0: ['value'], 1: ['value', 'other']}, 1)
        windows = ngram.form_ngram(_batch([0, 1, 2, 3]), TsSchema)
        assert len(windows) == 3
        for w, start in zip(windows, range(3)):
            assert w[0]['value'] == start
            assert w[1]['value'] == start + 1
            assert w[1]['other'] == (start + 1) * 0.5
            assert 'other' not in w[0]

    def test_delta_threshold_gap(self):
        # Gaps > threshold drop windows spanning them (reference Case 2).
        ngram = _resolved({-1: ['value'], 0: ['value']}, 4)
        windows = ngram.form_ngram(_batch([0, 3, 8, 10, 11, 20, 30]), TsSchema)
        starts = [w[-1]['value'] for w in windows]
        assert starts == [0, 2, 3]

    def test_all_windows_dropped(self):
        ngram = _resolved({0: ['value'], 1: ['value']}, 5)
        assert ngram.form_ngram(_batch([0, 10, 20, 30]), TsSchema) == []

    def test_sparse_timestep_keys(self):
        # {-1, 1} → length 3; middle row consumed but projected to no fields.
        ngram = _resolved({-1: ['value'], 1: ['value']}, 1)
        windows = ngram.form_ngram(_batch([0, 1, 2, 3]), TsSchema)
        assert len(windows) == 2
        assert windows[0][-1]['value'] == 0
        assert windows[0][1]['value'] == 2
        assert set(windows[0]) == {-1, 1}

    def test_non_overlapping(self):
        ngram = _resolved({0: ['value'], 1: ['value'], 2: ['value']}, 1,
                          overlap=False)
        windows = ngram.form_ngram(_batch([0, 1, 2, 3, 4, 5]), TsSchema)
        assert [w[0]['value'] for w in windows] == [0, 3]

    def test_unsorted_raises(self):
        ngram = _resolved({0: ['value'], 1: ['value']}, 1)
        with pytest.raises(NotImplementedError, match='sorted'):
            ngram.form_ngram(_batch([3, 1, 2]), TsSchema)

    def test_short_batch(self):
        ngram = _resolved({0: ['value'], 1: ['value'], 2: ['value']}, 1)
        assert ngram.form_ngram(_batch([0, 1]), TsSchema) == []

    def test_make_namedtuple(self):
        ngram = _resolved({0: ['value'], 1: ['value', 'other']}, 1)
        nt = ngram.make_namedtuple(TsSchema, {0: {'value': 1},
                                              1: {'value': 2, 'other': 0.5}})
        assert nt[0].value == 1
        assert nt[1].other == 0.5


@pytest.mark.parametrize('pool_type', ['dummy', 'thread', 'process'])
class TestNGramEndToEnd:
    """Dataset fixture: ids 0..99 over 4 files, row-groups of ≤10 dense ids —
    windows form within each row-group only (reference ``ngram.py:85-91``)."""

    def _expected_window_count(self, window):
        # 4 files x 25 rows = rowgroups of (10, 10, 5) per file.
        return 4 * sum(max(0, n - window + 1) for n in (10, 10, 5))

    def test_basic(self, synthetic_dataset, pool_type):
        fields = {0: ['^id$', '^id2$'], 1: ['^id$', '^sensor_name$']}
        ngram = NGram(fields=fields, delta_threshold=1, timestamp_field='^id$')
        with make_reader(synthetic_dataset.url, ngram=ngram, num_epochs=1,
                         shuffle_row_groups=False, reader_pool_type=pool_type,
                         workers_count=2) as reader:
            windows = list(reader)
        assert len(windows) == self._expected_window_count(2)
        for w in windows:
            assert w[1].id == w[0].id + 1
            assert w[1].sensor_name[0] == 'sensor_%d' % w[1].id
            assert not hasattr(w[0], 'sensor_name')

    def test_length_three_with_decoded_image(self, synthetic_dataset, pool_type):
        fields = {0: ['^id$'], 1: ['^id$', '^image_png$'], 2: ['^id$']}
        ngram = NGram(fields=fields, delta_threshold=1, timestamp_field='^id$')
        with make_reader(synthetic_dataset.url, ngram=ngram, num_epochs=1,
                         shuffle_row_groups=False, reader_pool_type=pool_type,
                         workers_count=2) as reader:
            windows = list(reader)
        assert len(windows) == self._expected_window_count(3)
        by_start = {w[0].id: w for w in windows}
        expected = {r['id']: r for r in synthetic_dataset.data}
        some = by_start[min(by_start)]
        np.testing.assert_array_equal(some[1].image_png,
                                      expected[some[1].id]['image_png'])

    def test_shuffle_row_drop_overlap(self, synthetic_dataset, pool_type):
        fields = {0: ['^id$'], 1: ['^id$']}
        ngram = NGram(fields=fields, delta_threshold=1, timestamp_field='^id$')
        with make_reader(synthetic_dataset.url, ngram=ngram, num_epochs=1,
                         shuffle_row_groups=False, reader_pool_type=pool_type,
                         shuffle_row_drop_partitions=2,
                         workers_count=2) as reader:
            windows = list(reader)
        # Partition-boundary borrow keeps every within-rowgroup window alive.
        starts = sorted(w[0].id for w in windows)
        assert len(starts) == self._expected_window_count(2)

    def test_non_overlap_end_to_end(self, synthetic_dataset, pool_type):
        fields = {0: ['^id$'], 1: ['^id$']}
        ngram = NGram(fields=fields, delta_threshold=1, timestamp_field='^id$',
                      timestamp_overlap=False)
        with make_reader(synthetic_dataset.url, ngram=ngram, num_epochs=1,
                         shuffle_row_groups=False, reader_pool_type=pool_type,
                         workers_count=2) as reader:
            windows = list(reader)
        seen = [w[k].id for w in windows for k in (0, 1)]
        assert len(seen) == len(set(seen))


def _write_ts_dataset(tmp_path, ts_values, name='ts_ds'):
    from petastorm_tpu.etl.dataset_metadata import write_dataset
    url = 'file://' + str(tmp_path / name)
    rows = [{'ts': int(t), 'value': i, 'other': i * 0.5}
            for i, t in enumerate(ts_values)]
    write_dataset(url, TsSchema, rows, rowgroup_size_rows=len(rows))
    return url


@pytest.mark.parametrize('pool_type', ['dummy', 'thread', 'process'])
class TestNGramDeltaThresholdEndToEnd:
    """Sparse-timestamp datasets through a real reader (reference:
    ``test_ngram_end_to_end.py:332-440``)."""

    GAPPY = [0, 3, 8, 10, 11, 20, 23]

    def test_large_threshold_admits_all(self, tmp_path, pool_type):
        url = _write_ts_dataset(tmp_path, self.GAPPY)
        ngram = NGram(fields={0: ['^ts$'], 1: ['^ts$', '^value$']},
                      delta_threshold=100, timestamp_field='^ts$')
        with make_reader(url, ngram=ngram, shuffle_row_groups=False,
                         reader_pool_type=pool_type) as reader:
            windows = list(reader)
        assert len(windows) == len(self.GAPPY) - 1

    def test_threshold_rejects_gaps(self, tmp_path, pool_type):
        url = _write_ts_dataset(tmp_path, self.GAPPY)
        ngram = NGram(fields={0: ['^ts$'], 1: ['^ts$', '^value$']},
                      delta_threshold=4, timestamp_field='^ts$')
        with make_reader(url, ngram=ngram, shuffle_row_groups=False,
                         reader_pool_type=pool_type) as reader:
            starts = sorted(w[0].ts for w in reader)
        # admitted pairs: (0,3), (8,10), (10,11), (20,23)
        assert starts == [0, 8, 10, 20]

    def test_small_threshold_over_stride_yields_nothing(self, tmp_path,
                                                        pool_type):
        url = _write_ts_dataset(tmp_path, list(range(0, 100, 5)))
        ngram = NGram(fields={0: ['^ts$'], 1: ['^ts$']},
                      delta_threshold=2, timestamp_field='^ts$')
        with make_reader(url, ngram=ngram, shuffle_row_groups=False,
                         reader_pool_type=pool_type) as reader:
            assert list(reader) == []


def test_ngram_length_one(synthetic_dataset):
    # reference: test_ngram_length_1 (:495) — degenerate window = plain rows
    ngram = NGram(fields={0: ['^id$']}, delta_threshold=1,
                  timestamp_field='^id$')
    with make_reader(synthetic_dataset.url, ngram=ngram,
                     shuffle_row_groups=False) as reader:
        windows = list(reader)
    assert len(windows) == 100
    assert sorted(w[0].id for w in windows) == list(range(100))


@pytest.mark.parametrize('pool_type', ['dummy', 'thread'])
def test_ngram_field_order_irrelevant(tmp_path, pool_type):
    # reference: test_shuffled_fields (:521) — permuted field lists and
    # unordered timestep keys must produce identical windows
    url = _write_ts_dataset(tmp_path, list(range(12)))
    a = NGram(fields={1: ['^value$', '^ts$', '^other$'], 0: ['^ts$']},
              delta_threshold=1, timestamp_field='^ts$')
    b = NGram(fields={0: ['^ts$'], 1: ['^other$', '^ts$', '^value$']},
              delta_threshold=1, timestamp_field='^ts$')
    results = []
    for ngram in (a, b):
        with make_reader(url, ngram=ngram, shuffle_row_groups=False,
                         reader_pool_type=pool_type) as reader:
            results.append([(w[0].ts, w[1].ts, w[1].value, w[1].other)
                            for w in reader])
    assert results[0] == results[1] and len(results[0]) == 11


def test_ngram_tf_dataset_longer_window(synthetic_dataset):
    # reference: test_ngram_basic_longer_tf (:228) — 3-step windows through
    # the tf.data bridge keep per-timestep schemas and consecutive ids
    tf = pytest.importorskip('tensorflow')
    from petastorm_tpu.tf_utils import make_petastorm_dataset
    ngram = NGram(fields={0: ['^id$'], 1: ['^id$', '^id2$'], 2: ['^id$']},
                  delta_threshold=1, timestamp_field='^id$')
    with make_reader(synthetic_dataset.url, ngram=ngram,
                     shuffle_row_groups=False) as reader:
        dataset = make_petastorm_dataset(reader)
        seen = 0
        for window in dataset.take(20):
            assert int(window[1].id) == int(window[0].id) + 1
            assert int(window[2].id) == int(window[0].id) + 2
            assert set(window[1]._fields) == {'id', 'id2'}
            seen += 1
    assert seen == 20


def test_non_overlap_with_row_drop_rejected(synthetic_dataset):
    ngram = NGram(fields={0: ['^id$'], 1: ['^id$']}, delta_threshold=1,
                  timestamp_field='^id$', timestamp_overlap=False)
    with pytest.raises(NotImplementedError):
        make_reader(synthetic_dataset.url, ngram=ngram,
                    shuffle_row_drop_partitions=2)


def test_ngram_with_explicit_unischema_fields(synthetic_dataset):
    fields = {0: [TestSchema.fields['id']], 1: [TestSchema.fields['id']]}
    ngram = NGram(fields=fields, delta_threshold=1,
                  timestamp_field=TestSchema.fields['id'])
    with make_reader(synthetic_dataset.url, ngram=ngram, num_epochs=1,
                     shuffle_row_groups=False, reader_pool_type='dummy') as reader:
        w = next(reader)
    assert w[1].id == w[0].id + 1


def test_ngram_checkpoint_records_progress(synthetic_dataset):
    """Window consumption marks row-groups consumed, so state_dict resumes
    instead of silently replaying the whole epoch."""
    fields = {0: ['^id$'], 1: ['^id$']}
    ngram = NGram(fields=fields, delta_threshold=1, timestamp_field='^id$')
    reader = make_reader(synthetic_dataset.url, ngram=ngram,
                         shuffle_row_groups=False, reader_pool_type='dummy')
    # 12 row-groups of (10,10,5)x4; consume past the first two row-groups
    consumed_windows = [next(reader) for _ in range(25)]
    assert consumed_windows
    state = reader.state_dict()
    reader.stop()
    reader.join()
    assert state['consumed_items'], 'ngram consumption must record progress'

    resumed = make_reader(synthetic_dataset.url, ngram=ngram,
                          shuffle_row_groups=False, reader_pool_type='dummy')
    resumed.load_state_dict(state)
    rest_ids = {w[0].id for w in resumed}
    resumed.stop()
    resumed.join()
    seen = {w[0].id for w in consumed_windows}
    # union covers every possible window start (at-least-once resume)
    assert seen | rest_ids >= {i for i in range(100) if (i % 25) not in (9, 19, 24)}
