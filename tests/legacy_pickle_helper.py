"""Builds a pickle byte stream shaped exactly like the reference's pickled
Unischema (module paths ``petastorm.unischema`` / ``petastorm.codecs`` /
``pyspark.sql.types``) without importing petastorm or pyspark.

Strategy: register synthetic modules in sys.modules carrying classes whose
``__module__``/``__qualname__`` match the reference's, pickle an instance
graph, then remove the modules again.
"""

import pickle
import sys
import types
from collections import OrderedDict
from typing import NamedTuple, Any, Optional, Tuple

import numpy as np


def make_reference_style_pickle():
    mods = {}

    def new_module(name):
        m = types.ModuleType(name)
        mods[name] = m
        return m

    new_module('petastorm')
    new_module('pyspark')
    new_module('pyspark.sql')
    m_uni = new_module('petastorm.unischema')
    m_cod = new_module('petastorm.codecs')
    m_spark = new_module('pyspark.sql.types')

    class UnischemaField(NamedTuple):
        name: str
        numpy_dtype: Any
        shape: Tuple[Optional[int], ...]
        codec: Optional[Any] = None
        nullable: Optional[bool] = False

    UnischemaField.__module__ = 'petastorm.unischema'
    UnischemaField.__qualname__ = 'UnischemaField'
    m_uni.UnischemaField = UnischemaField

    class Unischema:
        pass

    Unischema.__module__ = 'petastorm.unischema'
    Unischema.__qualname__ = 'Unischema'
    m_uni.Unischema = Unischema

    class ScalarCodec:
        pass

    class CompressedImageCodec:
        pass

    for cls in (ScalarCodec, CompressedImageCodec):
        cls.__module__ = 'petastorm.codecs'
        cls.__qualname__ = cls.__name__
    m_cod.ScalarCodec = ScalarCodec
    m_cod.CompressedImageCodec = CompressedImageCodec

    class IntegerType:
        pass

    IntegerType.__module__ = 'pyspark.sql.types'
    IntegerType.__qualname__ = 'IntegerType'
    m_spark.IntegerType = IntegerType

    saved = {k: sys.modules.get(k) for k in mods}
    sys.modules.update(mods)
    try:
        int_type = IntegerType()
        scalar = ScalarCodec()
        scalar._spark_type = int_type
        image = CompressedImageCodec()
        image._image_codec = '.png'
        image._quality = 80

        fields = [
            UnischemaField('id', np.int32, (), scalar, False),
            UnischemaField('image', np.uint8, (None, None, 3), image, False),
        ]
        schema = Unischema()
        schema._name = 'LegacySchema'
        schema._fields = OrderedDict((f.name, f) for f in fields)
        for f in fields:
            setattr(schema, f.name, f)
        return pickle.dumps(schema, protocol=2)
    finally:
        for k, v in saved.items():
            if v is None:
                sys.modules.pop(k, None)
            else:
                sys.modules[k] = v
