"""Critical-path engine: sweep-line attribution, the slack model's
what-if projections, the autotuner cross-check, and the ground-truth
drill — a known injected decode slowdown whose measured epoch-time delta
the model must predict within ±25% (the ISSUE 19 acceptance bound)."""

import time

import pytest

from petastorm_tpu import faults
from petastorm_tpu import telemetry as T
from petastorm_tpu.telemetry import critpath


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    T.reset_for_tests()
    yield
    T.reset_for_tests()


def _ev(name, start_us, dur_us, trace_id='t1'):
    return {'ph': 'X', 'name': name, 'ts': float(start_us),
            'dur': float(dur_us), 'args': {'trace_id': trace_id}}


# -- sweep-line attribution --------------------------------------------------


def test_sweep_charges_overlap_to_the_priority_stage():
    # io 0..10, decode 5..15: the 5..10 overlap is decode's self-time
    # (productive compute outranks I/O); io keeps 0..5 as self.
    totals, self_us = critpath._sweep([
        (0.0, 10.0, 'io'), (5.0, 15.0, 'decode')])
    assert totals == {'io': 10.0, 'decode': 10.0}
    assert self_us == {'io': 5.0, 'decode': 10.0}


def test_sweep_waits_never_outrank_work():
    # queue_wait spanning everything only owns the instants where
    # nothing else runs
    totals, self_us = critpath._sweep([
        (0.0, 100.0, 'queue_wait'), (10.0, 30.0, 'decode'),
        (50.0, 60.0, 'io')])
    assert self_us['decode'] == 20.0
    assert self_us['io'] == 10.0
    assert self_us['queue_wait'] == 70.0


def test_attempt_and_instant_events_are_excluded():
    events = [_ev('decode', 0, 10),
              {'ph': 'X', 'name': 'attempt', 'ts': 0.0, 'dur': 50.0,
               'args': {'trace_id': 't1'}},
              {'ph': 'i', 'name': 'decode', 'ts': 5.0,
               'args': {'trace_id': 't1'}}]
    intervals = critpath._stage_intervals(events)
    assert intervals == [(0.0, 10.0, 'decode')]


def test_analyze_report_shape_and_bottleneck():
    events = [_ev('io', 0, 40_000), _ev('decode', 10_000, 100_000),
              _ev('queue_wait', 0, 110_000, trace_id='t2')]
    report = critpath.analyze(events)
    assert report['bottleneck'] == 'decode'
    assert report['items'] == 2
    assert report['events'] == 3
    assert report['span_s'] == pytest.approx(0.11)
    decode = report['stages']['decode']
    assert decode['self_s'] == pytest.approx(0.1)
    assert decode['overlap_s'] == pytest.approx(0.0)
    # io 0..10ms is self, 10..40ms hidden under decode
    io = report['stages']['io']
    assert io['self_s'] == pytest.approx(0.01)
    assert io['overlap_s'] == pytest.approx(0.03)
    # self times partition the busy span exactly (no double counting)
    total_self = sum(s['self_s'] for s in report['stages'].values())
    assert total_self == pytest.approx(report['span_s'])
    assert report['recommendation'].startswith('decode 2x faster')


def test_analyze_none_without_stage_events():
    assert critpath.analyze([]) is None
    assert critpath.critpath_section([]) is None


def test_what_if_math_and_readahead_scenario():
    # decode 100ms self, io 30ms self => decode 2x saves 50ms; readahead
    # hides min(io_self, compute_self) = 30ms
    events = [_ev('decode', 0, 100_000), _ev('io', 100_000, 30_000)]
    report = critpath.analyze(events)
    scenarios = {s['scenario']: s for s in report['what_if']}
    decode2x = scenarios['decode 2x faster']
    assert decode2x['saving_s'] == pytest.approx(0.05)
    assert decode2x['epoch_delta_pct'] == pytest.approx(-38.46, abs=0.05)
    ra = scenarios['readahead depth +4']
    assert ra['saving_s'] == pytest.approx(0.03)


def test_predict_speedup_matches_slack_model():
    events = [_ev('decode', 0, 100_000)]
    report = critpath.analyze(events)
    pred = critpath.predict_speedup('decode', 4.0, report=report)
    assert pred['saving_s'] == pytest.approx(0.075)
    assert pred['predicted_span_s'] == pytest.approx(0.025)
    assert critpath.predict_speedup('io', 2.0, report=report) is None


# -- autotuner cross-check ---------------------------------------------------


def test_crosscheck_scores_bottleneck_and_slack_actions():
    # h2d_ready-bound trace: deepen_slots (bottleneck on the h2d side)
    # agrees; deepen_readahead (bottleneck on the io side) disagrees;
    # shed_readahead (slack on the io side) agrees.
    events = [_ev('h2d_ready', 0, 100_000), _ev('io', 0, 10_000)]
    report = critpath.analyze(events)
    assert report['bottleneck'] == 'h2d_ready'
    verdicts = critpath.crosscheck_autotuner(
        report=report,
        decisions=[{'action': 'deepen_slots'},
                   {'action': 'deepen_readahead'},
                   {'action': 'shed_readahead'},
                   {'action': 'unknown_action'}])
    assert [v['verdict'] for v in verdicts] == \
        ['agree', 'disagree', 'agree']
    reg = T.get_registry()
    assert reg.counter_value(critpath.CRITPATH_AGREEMENT,
                             verdict='agree') == 2
    assert reg.counter_value(critpath.CRITPATH_AGREEMENT,
                             verdict='disagree') == 1


def test_crosscheck_none_without_decisions():
    events = [_ev('decode', 0, 1000)]
    report = critpath.analyze(events)
    assert critpath.crosscheck_autotuner(report=report,
                                         decisions=[]) is None


def test_critpath_section_carries_crosscheck_summary():
    events = [_ev('decode', 0, 100_000)]
    # patch decisions through the public seam: pass report via section?
    # section pulls live autotune decisions; with none loaded the
    # summary is simply absent
    section = critpath.critpath_section(events)
    assert section['bottleneck'] == 'decode'
    assert 'autotune_crosscheck' not in section


# -- ground truth: injected slowdown vs projected delta ----------------------


def _traced_epoch(url, monkeypatch, fault_spec=None):
    """One fully-traced single-worker epoch; returns (wall_s, report)."""
    from petastorm_tpu.reader import make_batch_reader
    monkeypatch.setenv('PETASTORM_TPU_TRACE', '1')
    monkeypatch.setenv('PETASTORM_TPU_TRACE_SAMPLE', '1')
    if fault_spec is None:
        monkeypatch.delenv('PETASTORM_TPU_FAULTS', raising=False)
    else:
        monkeypatch.setenv('PETASTORM_TPU_FAULTS', fault_spec)
    T.refresh()
    faults.refresh_faults()
    T.reset_recorder()
    start = time.monotonic()
    with make_batch_reader(url, reader_pool_type='thread',
                           workers_count=1, num_epochs=1,
                           shuffle_row_groups=False) as reader:
        rows = sum(len(batch.id) for batch in reader)
    wall = time.monotonic() - start
    assert rows == 80
    report = critpath.analyze()
    assert report is not None, 'traced epoch recorded no stage events'
    return wall, report


def test_ground_truth_injected_decode_delay_within_25pct(tmp_path,
                                                         monkeypatch):
    """Acceptance (ISSUE 19): slow decode by a KNOWN injected delay,
    then ask the slack model for the reverse what-if on the slowed trace
    — the projected epoch-time saving must match the measured delta
    within ±25%."""
    from tests.test_common import create_test_scalar_dataset
    url = 'file://' + str(tmp_path / 'gt')
    create_test_scalar_dataset(url, num_rows=80, num_files=8)

    wall_base, report_base = _traced_epoch(url, monkeypatch)
    wall_slow, report_slow = _traced_epoch(
        url, monkeypatch, fault_spec='decode.rowgroup:delay:1:ms=80')
    faults.refresh_faults()  # disarm before anything else runs

    measured_delta = wall_slow - wall_base
    # 8 row-groups x 80ms on one worker: the injected slowdown dwarfs
    # host noise, so the bound is meaningful
    assert measured_delta > 0.3, (wall_base, wall_slow)

    decode_base = report_base['stages'].get('decode', {}).get('self_s', 0.0)
    decode_slow = report_slow['stages']['decode']['self_s']
    assert decode_slow > decode_base, (decode_base, decode_slow)
    factor = decode_slow / max(decode_base, 1e-9)
    pred = critpath.predict_speedup('decode', factor, report=report_slow)
    # the projection of undoing the slowdown = the saving of making the
    # slowed decode factor-x faster
    assert pred['saving_s'] == pytest.approx(measured_delta,
                                             rel=0.25), (
        pred, measured_delta, factor)
    assert report_slow['bottleneck'] == 'decode'


# -- overhead budget (the bench critpath section's gate) ---------------------


@pytest.mark.perf
def test_analysis_overhead_share_under_budget(tmp_path, monkeypatch):
    """The sweep over a real traced epoch must cost <2% of the traced
    wall time — the same share bench.py's critpath section reports as
    critpath_overhead_share."""
    from tests.test_common import create_test_scalar_dataset
    url = 'file://' + str(tmp_path / 'ov')
    create_test_scalar_dataset(url, num_rows=80, num_files=8)
    wall, _ = _traced_epoch(url, monkeypatch)
    start = time.perf_counter()
    report = critpath.analyze()
    analyze_s = time.perf_counter() - start
    assert report is not None
    assert analyze_s / wall < 0.02, (analyze_s, wall)
